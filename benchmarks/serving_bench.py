"""Closed-loop serving benchmark: dynamic batcher vs serialized requests.

Drives a `serving.PlainSession` with a closed-loop offered-load sweep —
`c` client threads, each issuing its next request the moment the previous
one returns — at several concurrency levels, in two modes:

* **batched**: the session's `DynamicBatcher` coalesces concurrent
  requests into padded power-of-two key batches (the serving/ tentpole);
* **unbatched**: the same session class with `batching=False`, so every
  request pays its own `handle_plain_request` device step — the
  one-request-at-a-time baseline.

The batched sweep also reads the cost-model accuracy ledger
(`observability/costmodel.py`) the session populated while serving: a
report-only `cost_model_residual_p50` per workload (abs signed-ratio
error of the capacity model's device-ms price, lower is better) that
`main()` appends to `benchmarks/results/history.jsonl`, and a
`ledger_overhead` point measuring the q/s cost of the per-batch
predicted-vs-actual join against a short-circuited ledger (same <2%
review budget as the prober and digest points).

Every response is compared bit-for-bit against an oracle computed
upfront by a direct (no serving runtime) `DenseDpfPirServer`, so the
throughput claim carries an equal-correctness proof in the same run.
The report includes the batched session's full metrics export — batch
size histogram, padding waste, and the jit bucket compile/hit counters
that demonstrate the bounded-compilation property — plus two
report-only overhead points: `prober_overhead` measures the q/s cost
of running the blackbox verification prober (`serving/prober.py`)
alongside real traffic, and `digest_overhead` measures the q/s cost of
the v2 envelope's critical-path digest piggyback (Helper phase
waterfall + recv/send timestamps on every reply; pinned off via
`ServingConfig(helper_digest=False)`) on the encrypted Leader->Helper
path. Both ride a <2% budget reviewed from the report, not gated in
CI.

A **pipeline A/B leg** runs the same batched point at pipeline depth 1
(the serial pre-pipeline worker) and depth 2 (async dispatch with a
completion thread, the default), emitting the gated
`serving_qps_pipelined` history record (direction "higher") plus two
staging-side companions — `pipelined_staging_hidden_ms` (overlapped
H2D time a pipelined full staging hid behind host work) and
`rotation_prestage_bytes_saved` (bytes a ~1%-row delta rotation's
prestage kept off the bus) — and a report-only `pipeline_overhead`
percentage under the same <2% budget.

Run directly (one JSON report on stdout, also written to
``benchmarks/results/serving_bench.json``)::

    JAX_PLATFORMS=cpu python -m benchmarks.serving_bench

or through the headline harness (one bench-style JSON line)::

    BENCH_SERVING=1 BENCH_PLATFORM=cpu python bench.py

The bench closes with a **mesh stage**: the same closed-loop point
served by one logical server spread over a 2-D device mesh
(database-shard axis x key-batch axis, `parallel.ShardedServingPlan`),
bit-checked against the same oracle. It emits a
`serving_qps_{ndev}dev` history record (direction "higher") plus the
donation accounting — TransferLedger `selection_scratch` copies before
and after the timed loop, proving the donated scratch stages once, not
per request. When invoked directly on a single-device CPU host, the
bench forces `--xla_force_host_platform_device_count=8` before JAX
initializes so a record always lands; the forced-host CPU numbers gate
correctness and relayout accounting only, not throughput.

A **sparse ladder** closes the report: closed-loop string-keyed
(cuckoo key-value) traffic through a `SparsePlainSession` with the
batcher on, every masked response bit-checked against an unbatched
sparse oracle, then a ~1%-key write batch landed as a SnapshotManager
delta rotation on the live session. It emits two gated history records
— `sparse_qps` (direction "higher") and
`sparse_rotation_prestage_bytes_saved` (direction "higher", the bytes
the touched-row prestage kept off the bus).

Environment knobs: SERVING_BENCH_RECORDS (default 2048),
SERVING_BENCH_RECORD_BYTES (32), SERVING_BENCH_CONCURRENCY ("1,4,16"),
SERVING_BENCH_REQUESTS (total closed-loop requests per sweep point,
default 64), SERVING_BENCH_MAX_BATCH (16), SERVING_BENCH_PROBER_PERIOD_S
(cadence for the overhead point, default 5.0 — the prober default),
SERVING_BENCH_MESH ("0" skips the mesh stage),
SERVING_BENCH_SPARSE ("0" skips the sparse ladder),
SERVING_BENCH_SPARSE_KEYS (sparse ladder key count, default 512),
SERVING_BENCH_OUT (report path; empty string disables the file),
BENCH_HISTORY ("0" skips the history.jsonl residual append),
BENCH_HISTORY_PATH (append target, default
benchmarks/results/history.jsonl).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def force_host_devices(count: int = 8) -> None:
    """CPU fallback for the mesh stage: force `count` virtual host
    devices so a `serving_qps_{ndev}dev` record always lands, even on a
    1-CPU box. Only effective before JAX initializes (XLA reads the
    flag at backend creation), so a no-op when jax is already imported
    or a device count is already forced."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={count}".strip()
    )


def _log(msg: str) -> None:
    print(f"[serving-bench {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def workload_residual_summary(ledger_export, workload):
    """Collapse one workload's cost-ledger cells into a single
    report-line number: the samples-weighted mean of per-cell signed
    `residual_p50` and of its absolute value (the history metric —
    0 means the capacity model priced the work exactly).

    Shared with `heavy_hitters_bench` so both workloads report the
    same aggregate.
    """
    prefix = f"{workload}/"
    cells = {
        name: cell
        for name, cell in ledger_export.get("cells", {}).items()
        if name.startswith(prefix) and cell.get("residual_p50") is not None
    }
    total = sum(c["samples"] for c in cells.values())
    if not total:
        return {"workload": workload, "samples": 0, "cells": {},
                "residual_p50": None, "residual_p50_abs": None}
    signed = sum(
        c["residual_p50"] * c["samples"] for c in cells.values()
    ) / total
    absolute = sum(
        abs(c["residual_p50"]) * c["samples"] for c in cells.values()
    ) / total
    return {
        "workload": workload,
        "samples": total,
        "residual_p50": round(signed, 4),
        "residual_p50_abs": round(absolute, 4),
        "cells": {
            name: {
                "samples": c["samples"],
                "residual_p50": c["residual_p50"],
            }
            for name, c in cells.items()
        },
    }


def append_residual_history(summary, bench):
    """Best-effort: append the |residual_p50| aggregate for one
    workload to `benchmarks/results/history.jsonl` as metric
    `cost_model_residual_p50_<workload>` with explicit
    ``direction: "lower"`` — report-only in spirit (the regression
    gate needs 2 clean priors before it judges, and the record is
    plainly labeled), never fatal to the bench."""
    if summary["samples"] == 0 or summary["residual_p50_abs"] is None:
        return
    try:
        from benchmarks.regression_gate import append_record, git_rev

        append_record(
            {
                "metric": f"cost_model_residual_p50_{summary['workload']}",
                "value": float(summary["residual_p50_abs"]),
                "unit": "abs_ratio_error",
                "direction": "lower",
                "status": "ok",
                "vs_baseline": None,
                "git_rev": git_rev(),
                "device": os.environ.get("BENCH_PLATFORM", "cpu"),
                "bench": bench,
                "samples": summary["samples"],
            },
            path=os.environ.get(
                "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
            ),
        )
    except Exception as e:  # noqa: BLE001 - accounting never fails a bench
        _log(f"history append skipped: {e}")


def append_mesh_history(mesh_point, bench):
    """Best-effort: record the mesh-stage throughput as
    `serving_qps_{ndev}dev` (direction "higher" — the whole point of
    sharding is that this number scales with the device count) plus a
    `serving_mesh_donation_saved_copies` companion documenting the
    buffer-donation win (scratch copies the donated entry point did
    NOT re-stage, one per batch when donation works). Status is "ok"
    only when the mesh actually served (no tier-demotion fallback) and
    every response matched the single-device oracle."""
    if not mesh_point:
        return
    try:
        from benchmarks.regression_gate import append_record, git_rev

        path = os.environ.get(
            "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
        )
        ok = mesh_point["mesh_served"] and mesh_point["mismatches"] == 0
        rev = git_rev()
        device = os.environ.get("BENCH_PLATFORM", "cpu")
        append_record(
            {
                "metric": f"serving_qps_{mesh_point['devices']}dev",
                "value": float(mesh_point["qps"]),
                "unit": "queries/s",
                "direction": "higher",
                "status": "ok" if ok else "mesh_fallback",
                "vs_baseline": None,
                "git_rev": rev,
                "device": device,
                "bench": bench,
                "mesh_shape": mesh_point["mesh_shape"],
                "concurrency": mesh_point["concurrency"],
            },
            path=path,
        )
        append_record(
            {
                "metric": "serving_mesh_donation_saved_copies",
                "value": float(mesh_point["donation_saved_copies"]),
                "unit": "h2d_copies",
                "direction": "higher",
                "status": "ok" if ok else "mesh_fallback",
                "vs_baseline": None,
                "git_rev": rev,
                "device": device,
                "bench": bench,
                "scratch_copies_before": mesh_point["scratch_copies_before"],
                "scratch_copies_after": mesh_point["scratch_copies_after"],
                "batches": mesh_point["batches"],
            },
            path=path,
        )
    except Exception as e:  # noqa: BLE001 - accounting never fails a bench
        _log(f"mesh history append skipped: {e}")


def append_pipeline_history(point, bench):
    """Best-effort: append the three hot-path-pipelining records the
    regression gate locks in — `serving_qps_pipelined` (the depth-2
    closed-loop throughput), `pipelined_staging_hidden_ms` (overlapped
    H2D milliseconds a pipelined full staging hid behind host work),
    and `rotation_prestage_bytes_saved` (bytes a ~1%-row delta
    rotation's prestage kept off the bus) — all direction "higher".
    The depth-1-vs-2 `pipeline_overhead` percentage stays report-only
    (<2% budget reviewed from the report, not gated). Never fatal to
    the bench."""
    if not point:
        return
    try:
        from benchmarks.regression_gate import append_record, git_rev

        path = os.environ.get(
            "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
        )
        rev = git_rev()
        device = os.environ.get("BENCH_PLATFORM", "cpu")
        status = "ok" if point["mismatches"] == 0 else "mismatch"
        append_record(
            {
                "metric": "serving_qps_pipelined",
                "value": float(point["pipelined_qps"]),
                "unit": "queries/s",
                "direction": "higher",
                "status": status,
                "vs_baseline": None,
                "git_rev": rev,
                "device": device,
                "bench": bench,
                "concurrency": point["concurrency"],
                "serial_qps": point["serial_qps"],
                "overhead_pct": point["overhead_pct"],
            },
            path=path,
        )
        append_record(
            {
                "metric": "pipelined_staging_hidden_ms",
                "value": float(point["staging_hidden_ms"]),
                "unit": "ms",
                "direction": "higher",
                "status": status,
                "vs_baseline": None,
                "git_rev": rev,
                "device": device,
                "bench": bench,
            },
            path=path,
        )
        append_record(
            {
                "metric": "rotation_prestage_bytes_saved",
                "value": float(point["prestage_bytes_saved"]),
                "unit": "bytes",
                "direction": "higher",
                "status": status,
                "vs_baseline": None,
                "git_rev": rev,
                "device": device,
                "bench": bench,
                "rows_touched": point["prestage_rows_touched"],
                "bytes_full_image": point["prestage_bytes_full_image"],
                "prestage_mode": point["prestage_mode"],
            },
            path=path,
        )
    except Exception as e:  # noqa: BLE001 - accounting never fails a bench
        _log(f"pipeline history append skipped: {e}")


def append_utilization_history(point, bench):
    """Best-effort: append the two device-seconds-ledger records the
    regression gate locks in — `device_duty_cycle_pct` (the fraction of
    tracked worker time spent feeding the device, direction "higher")
    and `pipeline_bubble_ms_p99` (the tail of the typed idle-bubble
    reservoir, direction "lower"). The off-vs-on `overhead_pct` stays
    report-only (<2% budget reviewed from the report, not gated).
    Never fatal to the bench."""
    if not point or point.get("duty_cycle_pct") is None:
        return
    try:
        from benchmarks.regression_gate import append_record, git_rev

        path = os.environ.get(
            "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
        )
        rev = git_rev()
        device = os.environ.get("BENCH_PLATFORM", "cpu")
        status = "ok" if point["mismatches"] == 0 else "mismatch"
        append_record(
            {
                "metric": "device_duty_cycle_pct",
                "value": float(point["duty_cycle_pct"]),
                "unit": "pct",
                "direction": "higher",
                "status": status,
                "vs_baseline": None,
                "git_rev": rev,
                "device": device,
                "bench": bench,
                "concurrency": point["concurrency"],
                "overhead_pct": point["overhead_pct"],
            },
            path=path,
        )
        if point.get("bubble_ms_p99") is not None:
            append_record(
                {
                    "metric": "pipeline_bubble_ms_p99",
                    "value": float(point["bubble_ms_p99"]),
                    "unit": "ms",
                    "direction": "lower",
                    "status": status,
                    "vs_baseline": None,
                    "git_rev": rev,
                    "device": device,
                    "bench": bench,
                    "bubbles": point["bubbles"],
                    "bubble_causes": point["bubble_causes"],
                },
                path=path,
            )
    except Exception as e:  # noqa: BLE001 - accounting never fails a bench
        _log(f"utilization history append skipped: {e}")


def append_sparse_history(point, bench):
    """Best-effort: append the two sparse-serving records the
    regression gate locks in — `sparse_qps` (closed-loop key-value
    throughput through the batched session, direction "higher") and
    `sparse_rotation_prestage_bytes_saved` (bytes a ~1%-key write
    batch's delta prestage kept off the bus, direction "higher").
    Never fatal to the bench."""
    if not point:
        return
    try:
        from benchmarks.regression_gate import append_record, git_rev

        path = os.environ.get(
            "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
        )
        rev = git_rev()
        device = os.environ.get("BENCH_PLATFORM", "cpu")
        status = "ok" if point["mismatches"] == 0 else "mismatch"
        append_record(
            {
                "metric": "sparse_qps",
                "value": float(point["qps"]),
                "unit": "queries/s",
                "direction": "higher",
                "status": status,
                "vs_baseline": None,
                "git_rev": rev,
                "device": device,
                "bench": bench,
                "num_keys": point["num_keys"],
                "num_buckets": point["num_buckets"],
                "concurrency": point["concurrency"],
            },
            path=path,
        )
        append_record(
            {
                "metric": "sparse_rotation_prestage_bytes_saved",
                "value": float(point["prestage_bytes_saved"]),
                "unit": "bytes",
                "direction": "higher",
                "status": status,
                "vs_baseline": None,
                "git_rev": rev,
                "device": device,
                "bench": bench,
                "keys_touched": point["rotation_keys_touched"],
                "bytes_full_image": point["prestage_bytes_full_image"],
                "prestage_mode": point["prestage_mode"],
            },
            path=path,
        )
    except Exception as e:  # noqa: BLE001 - accounting never fails a bench
        _log(f"sparse history append skipped: {e}")


def _closed_loop(handle, requests, concurrency):
    """Run `requests` through `handle` from `concurrency` closed-loop
    client threads; returns (wall_seconds, latencies_ms, responses)."""
    next_idx = [0]
    lock = threading.Lock()
    latencies = [0.0] * len(requests)
    responses = [None] * len(requests)
    errors = []

    def client():
        while True:
            with lock:
                i = next_idx[0]
                if i >= len(requests):
                    return
                next_idx[0] = i + 1
            t0 = time.perf_counter()
            try:
                responses[i] = handle(requests[i])
            except Exception as e:  # noqa: BLE001 - collected, not raised
                with lock:
                    errors.append(f"request {i}: {e}")
                return
            latencies[i] = (time.perf_counter() - t0) * 1e3
    threads = [
        threading.Thread(target=client, name=f"closed-loop-{t}")
        for t in range(concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError("; ".join(errors[:3]))
    return wall, latencies, responses


def run_serving_bench():
    """Build the database, sweep (mode x concurrency), return the report
    dict (also written to SERVING_BENCH_OUT unless empty)."""
    from distributed_point_functions_tpu.pir import messages
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.observability import tracing
    from distributed_point_functions_tpu.pir.server import (
        DenseDpfPirServer,
        set_tier_floor,
        tier_floor,
    )
    from distributed_point_functions_tpu.serving import (
        PlainSession,
        ServingConfig,
        bucket_size,
    )

    # Stage spans accumulate process-wide; reset so the report's span
    # summary covers exactly this sweep.
    tracing.reset_stages()

    num_records = int(os.environ.get("SERVING_BENCH_RECORDS", 2048))
    record_bytes = int(os.environ.get("SERVING_BENCH_RECORD_BYTES", 32))
    num_requests = int(os.environ.get("SERVING_BENCH_REQUESTS", 64))
    max_batch = int(os.environ.get("SERVING_BENCH_MAX_BATCH", 16))
    concurrency_levels = [
        int(c)
        for c in os.environ.get("SERVING_BENCH_CONCURRENCY", "1,4,16")
        .split(",")
        if c.strip()
    ]

    _log(
        f"database: {num_records} x {record_bytes}B, "
        f"{num_requests} requests/point, max_batch={max_batch}, "
        f"concurrency sweep {concurrency_levels}"
    )
    record_list = [
        (b"serve-%06d:" % i).ljust(record_bytes, b".")[:record_bytes]
        for i in range(num_records)
    ]
    builder = DenseDpfPirDatabase.Builder()
    for r in record_list:
        builder.insert(r)
    database = builder.build()

    # Request pool: one single-key plain request per closed-loop request,
    # generated up front so key generation never sits inside the timed
    # loop. The oracle answers each request alone on a bare server — the
    # ground truth both modes must match bit-for-bit.
    import numpy as np

    rng = np.random.default_rng(11)
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    requests = [
        client.create_plain_requests([int(i)])[0]
        for i in rng.integers(0, num_records, num_requests)
    ]
    oracle_server = DenseDpfPirServer.create_plain(database)
    _log("computing oracle responses (and warming per-shape jit entries)")
    t0 = time.perf_counter()
    oracle = [
        oracle_server.handle_plain_request(r).dpf_pir_response.masked_response
        for r in requests
    ]
    # Warm every power-of-two bucket the batcher can form, so the sweep
    # measures steady-state serving rather than first-shape compiles (the
    # module-level jit cache is shared across server instances). Warm at
    # every planner tier, not just the default: a forced-tier blackbox
    # probe running alongside traffic (the prober_overhead point below)
    # momentarily demotes concurrent batches too — the floor only
    # demotes — and an unwarmed (tier x bucket) shape would charge its
    # compile to the probed leg.
    for tier in ("materialized", "streaming", "chunked"):
        prev = tier_floor()
        set_tier_floor(tier)
        try:
            b = 1
            while b <= max_batch:
                oracle_server.handle_plain_request(
                    messages.PirRequest(
                        plain_request=messages.PlainRequest(
                            dpf_keys=list(
                                requests[0].plain_request.dpf_keys
                            ) * b
                        )
                    )
                )
                b *= 2
        finally:
            set_tier_floor(prev)
    _log(f"oracle + warmup done in {time.perf_counter() - t0:.1f}s")

    def sweep_mode(batching):
        config = ServingConfig(
            max_batch_size=max_batch,
            max_wait_ms=2.0,
            max_queue=max(256, 4 * num_requests),
            batching=batching,
        )
        points = []
        with PlainSession(database, config) as session:
            for concurrency in concurrency_levels:
                wall, lats, resps = _closed_loop(
                    session.handle_request, requests, concurrency
                )
                mismatches = sum(
                    1
                    for got, want in zip(resps, oracle)
                    if got.dpf_pir_response.masked_response != want
                )
                lats.sort()
                qps = len(requests) / wall
                points.append({
                    "mode": "batched" if batching else "unbatched",
                    "concurrency": concurrency,
                    "qps": round(qps, 2),
                    "wall_s": round(wall, 3),
                    "p50_ms": round(_percentile(lats, 0.50), 3),
                    "p95_ms": round(_percentile(lats, 0.95), 3),
                    "p99_ms": round(_percentile(lats, 0.99), 3),
                    "mismatches": mismatches,
                })
                _log(
                    f"{points[-1]['mode']:>9} c={concurrency:<3} "
                    f"{qps:8.1f} q/s  p50 {points[-1]['p50_ms']:.1f} ms  "
                    f"p95 {points[-1]['p95_ms']:.1f} ms  "
                    f"mismatches={mismatches}"
                )
            metrics = session.metrics.export()
        return points, metrics

    unbatched_points, _ = sweep_mode(batching=False)
    batched_points, batched_metrics = sweep_mode(batching=True)

    # Prober overhead: the same batched point at the highest concurrency,
    # measured back to back on one session without and with a background
    # blackbox prober at its default (bounded) duty cycle. Report-only:
    # on a noisy CPU host the delta sits inside run-to-run variance, so
    # the <2% q/s budget is reviewed from the report, not gated in CI.
    def prober_overhead_point():
        from distributed_point_functions_tpu.serving.prober import Prober

        concurrency = concurrency_levels[-1]
        period_s = float(
            os.environ.get("SERVING_BENCH_PROBER_PERIOD_S", 5.0)
        )
        config = ServingConfig(
            max_batch_size=max_batch,
            max_wait_ms=2.0,
            max_queue=max(256, 4 * num_requests),
            batching=True,
        )
        # Replay the request pool until each leg spans ~2 probe periods
        # at the q/s the sweep just measured — a window shorter than a
        # period would charge one probe cycle's full cost to the whole
        # leg instead of amortizing it at the configured cadence.
        est_qps = max(
            p["qps"]
            for p in batched_points
            if p["concurrency"] == concurrency
        )
        copies = min(
            512,
            1 + int(est_qps * 2.0 * period_s / max(1, len(requests))),
        )
        reqs = requests * copies
        want_all = oracle * copies
        with PlainSession(database, config) as session:
            prober = Prober(session, record_list, period_s=period_s)
            # One cycle outside the timing so probe-shape jit entries
            # (the forced-tier variants) are compiled before either leg.
            prober.run_cycle()
            wall_base, _, _ = _closed_loop(
                session.handle_request, reqs, concurrency
            )
            with prober:
                wall_probed, _, resps = _closed_loop(
                    session.handle_request, reqs, concurrency
                )
            mismatches = sum(
                1
                for got, want in zip(resps, want_all)
                if got.dpf_pir_response.masked_response != want
            )
            base_qps = len(reqs) / wall_base
            probed_qps = len(reqs) / wall_probed
            return {
                "concurrency": concurrency,
                "period_s": period_s,
                "requests_per_leg": len(reqs),
                "baseline_wall_s": round(wall_base, 2),
                "probed_wall_s": round(wall_probed, 2),
                "baseline_qps": round(base_qps, 2),
                "probed_qps": round(probed_qps, 2),
                "overhead_pct": round(
                    100.0 * (base_qps - probed_qps) / base_qps, 2
                ),
                "prober_cycles": prober.export()["cycles"],
                "mismatches": mismatches,
            }

    prober_overhead = prober_overhead_point()
    _log(
        f"prober overhead c={prober_overhead['concurrency']}: "
        f"{prober_overhead['baseline_qps']:.1f} -> "
        f"{prober_overhead['probed_qps']:.1f} q/s "
        f"({prober_overhead['overhead_pct']:+.1f}%, "
        f"{prober_overhead['prober_cycles']} probe cycles)"
    )

    # Digest piggyback overhead: the encrypted Leader->Helper path,
    # back to back with the critical-path digest pinned off (v1
    # envelope: no phase waterfall, no recv/send timestamps, no skew
    # merge on the Leader) and on (the v2 default). Report-only, same
    # rationale as the prober point: the <2% q/s budget is reviewed
    # from the report because the delta sits inside CPU-host variance.
    def digest_overhead_point():
        from distributed_point_functions_tpu.serving import (
            HelperSession,
            InProcessTransport,
            LeaderSession,
        )
        from distributed_point_functions_tpu.testing import encrypt_decrypt

        concurrency = concurrency_levels[-1]
        e2e_client = DenseDpfPirClient.create(
            num_records, encrypt_decrypt.encrypt
        )
        indices = [
            int(i) for i in rng.integers(0, num_records, num_requests)
        ]
        pool = [e2e_client.create_request([i]) for i in indices]

        def leg(helper_digest):
            config = ServingConfig(
                max_batch_size=max_batch,
                max_wait_ms=2.0,
                max_queue=max(256, 4 * num_requests),
                batching=True,
                helper_digest=helper_digest,
            )
            helper = HelperSession(
                database, encrypt_decrypt.decrypt, config
            )
            leader = LeaderSession(
                database, InProcessTransport(helper.handle_wire), config
            )
            with helper, leader:
                # One warm request outside the timing: the envelope
                # probe settles and the leader-share jit shapes warm.
                leader.handle_request(pool[0][0])
                wall, _, resps = _closed_loop(
                    leader.handle_request,
                    [r for r, _ in pool],
                    concurrency,
                )
            bad = 0
            for (_, state), idx, resp in zip(pool, indices, resps):
                got = e2e_client.handle_response(resp, state)
                if got != [record_list[idx]]:
                    bad += 1
            return len(pool) / wall, bad

        base_qps, base_bad = leg(helper_digest=False)
        digest_qps, digest_bad = leg(helper_digest=True)
        return {
            "concurrency": concurrency,
            "requests_per_leg": len(pool),
            "baseline_qps": round(base_qps, 2),
            "digest_qps": round(digest_qps, 2),
            "overhead_pct": round(
                100.0 * (base_qps - digest_qps) / base_qps, 2
            ),
            "mismatches": base_bad + digest_bad,
        }

    digest_overhead = digest_overhead_point()
    _log(
        f"digest overhead c={digest_overhead['concurrency']}: "
        f"{digest_overhead['baseline_qps']:.1f} -> "
        f"{digest_overhead['digest_qps']:.1f} q/s "
        f"({digest_overhead['overhead_pct']:+.1f}%)"
    )

    # Cost-ledger overhead: the same batched point at the highest
    # concurrency, back to back on two fresh sessions — one bound to a
    # ledger whose `observe` is short-circuited (the join never runs),
    # one bound to a real `CostLedger` — so the delta is exactly the
    # per-batch predicted-vs-actual join. Report-only, same <2% q/s
    # budget and CPU-variance rationale as the prober/digest points.
    def ledger_overhead_point():
        from distributed_point_functions_tpu.observability import (
            costmodel as costmodel_mod,
        )

        class _NullLedger(costmodel_mod.CostLedger):
            def observe(self, *args, **kwargs):  # noqa: D401 - no-op
                return None

        concurrency = concurrency_levels[-1]
        config = ServingConfig(
            max_batch_size=max_batch,
            max_wait_ms=2.0,
            max_queue=max(256, 4 * num_requests),
            batching=True,
        )
        prev = costmodel_mod.default_cost_ledger()

        def leg(ledger):
            costmodel_mod.set_default_cost_ledger(ledger)
            with PlainSession(database, config) as session:
                wall, _, resps = _closed_loop(
                    session.handle_request, requests, concurrency
                )
            bad = sum(
                1
                for got, want in zip(resps, oracle)
                if got.dpf_pir_response.masked_response != want
            )
            return len(requests) / wall, bad

        try:
            base_qps, base_bad = leg(_NullLedger())
            measured = costmodel_mod.CostLedger()
            ledger_qps, ledger_bad = leg(measured)
        finally:
            costmodel_mod.set_default_cost_ledger(prev)
        return {
            "concurrency": concurrency,
            "requests_per_leg": len(requests),
            "baseline_qps": round(base_qps, 2),
            "ledger_qps": round(ledger_qps, 2),
            "overhead_pct": round(
                100.0 * (base_qps - ledger_qps) / base_qps, 2
            ),
            "ledger_samples": measured.export()["total_samples"],
            "mismatches": base_bad + ledger_bad,
        }

    ledger_overhead = ledger_overhead_point()
    _log(
        f"ledger overhead c={ledger_overhead['concurrency']}: "
        f"{ledger_overhead['baseline_qps']:.1f} -> "
        f"{ledger_overhead['ledger_qps']:.1f} q/s "
        f"({ledger_overhead['overhead_pct']:+.1f}%, "
        f"{ledger_overhead['ledger_samples']} joined batches)"
    )

    # Pipeline A/B: the same batched point at the highest concurrency,
    # back to back on two fresh sessions — the serial depth-1 worker
    # (pre-pipeline behavior, bit-for-bit) vs the default depth-2
    # pipelined dispatch (bucket N dispatches while bucket N-1
    # completes). The depth-2 q/s is the gated `serving_qps_pipelined`
    # history record (direction "higher"); `overhead_pct` is the
    # report-only cost of the pipeline machinery relative to depth 1,
    # budgeted <2% and reviewed from the report (on a CPU host the
    # delta sits inside run-to-run variance, same rationale as the
    # prober/digest/ledger points). The point also captures the two
    # staging-side pipelining numbers: `staging_hidden_ms` (overlapped
    # H2D milliseconds from a pipelined full staging of the same
    # records) and `prestage_bytes_saved` (bytes a ~1%-row delta
    # rotation's prestage keeps off the bus vs its full image).
    def pipeline_point():
        from distributed_point_functions_tpu.observability.device import (
            default_telemetry,
        )

        concurrency = concurrency_levels[-1]

        def leg(depth):
            config = ServingConfig(
                max_batch_size=max_batch,
                max_wait_ms=2.0,
                max_queue=max(256, 4 * num_requests),
                batching=True,
                pipeline_depth=depth,
            )
            with PlainSession(database, config) as session:
                wall, _, resps = _closed_loop(
                    session.handle_request, requests, concurrency
                )
            bad = sum(
                1
                for got, want in zip(resps, oracle)
                if got.dpf_pir_response.masked_response != want
            )
            return len(requests) / wall, bad

        serial_qps, serial_bad = leg(1)
        pipelined_qps, pipelined_bad = leg(2)

        # Hidden transfer time: stage a fresh build of the same records
        # through the pipelined path and read the ledger's overlapped
        # delta — the milliseconds of host work performed while H2D
        # copies were already in flight.
        ledger = default_telemetry().transfers
        fresh_builder = DenseDpfPirDatabase.Builder()
        for r in record_list:
            fresh_builder.insert(r)
        fresh = fresh_builder.build()
        hidden_before = ledger.overlapped_ms("db_staging")
        _ = fresh.db_words
        hidden_ms = ledger.overlapped_ms("db_staging") - hidden_before

        # Delta-rotation savings: a build_from generation touching ~1%
        # of rows prestaged against the bench database's resident
        # staging ships only the touched rows plus the index vector.
        touched = max(1, num_records // 100)
        delta_builder = DenseDpfPirDatabase.Builder()
        for i in range(touched):
            delta_builder.update(
                i, bytes(b ^ 0x5A for b in record_list[i])
            )
        delta = delta_builder.build_from(database)
        delta.prestage()
        stats = delta.last_prestage_stats or {}

        return {
            "concurrency": concurrency,
            "requests_per_leg": len(requests),
            "serial_qps": round(serial_qps, 2),
            "pipelined_qps": round(pipelined_qps, 2),
            "overhead_pct": round(
                100.0 * (serial_qps - pipelined_qps) / serial_qps, 2
            ),
            "staging_hidden_ms": round(hidden_ms, 3),
            "prestage_mode": stats.get("mode"),
            "prestage_rows_touched": touched,
            "prestage_bytes_staged": int(stats.get("bytes_staged", 0)),
            "prestage_bytes_saved": int(stats.get("bytes_saved", 0)),
            "prestage_bytes_full_image": int(
                stats.get("bytes_full_image", 0)
            ),
            "mismatches": serial_bad + pipelined_bad,
        }

    pipeline_overhead = pipeline_point()
    _log(
        f"pipeline A/B c={pipeline_overhead['concurrency']}: depth-1 "
        f"{pipeline_overhead['serial_qps']:.1f} -> depth-2 "
        f"{pipeline_overhead['pipelined_qps']:.1f} q/s "
        f"({pipeline_overhead['overhead_pct']:+.1f}% overhead), staging "
        f"hid {pipeline_overhead['staging_hidden_ms']:.1f} ms, delta "
        f"prestage saved {pipeline_overhead['prestage_bytes_saved']} of "
        f"{pipeline_overhead['prestage_bytes_full_image']} bytes"
    )

    # Utilization A/B: the same batched point back to back with the
    # device-seconds ledger off (`ServingConfig(utilization=False)`,
    # the batcher never sees a tracker) vs on (the default). The
    # on-leg's duty cycle and bubble p99 become the gated
    # `device_duty_cycle_pct` (direction "higher") and
    # `pipeline_bubble_ms_p99` (direction "lower") history records;
    # `overhead_pct` — the throughput cost of bracketing every
    # worker/completion interval — stays report-only under the same
    # <2% budget as the other always-on telemetry points.
    def utilization_point():
        from distributed_point_functions_tpu.observability.utilization import (
            default_utilization_tracker,
        )

        concurrency = concurrency_levels[-1]
        tracker = default_utilization_tracker()

        def leg(enabled):
            tracker.reset()
            config = ServingConfig(
                max_batch_size=max_batch,
                max_wait_ms=2.0,
                max_queue=max(256, 4 * num_requests),
                batching=True,
                utilization=enabled,
            )
            with PlainSession(database, config) as session:
                wall, _, resps = _closed_loop(
                    session.handle_request, requests, concurrency
                )
            bad = sum(
                1
                for got, want in zip(resps, oracle)
                if got.dpf_pir_response.masked_response != want
            )
            return len(requests) / wall, bad

        baseline_qps, baseline_bad = leg(False)
        utilization_qps, utilization_bad = leg(True)
        totals = tracker.export()["totals"]

        return {
            "concurrency": concurrency,
            "requests_per_leg": len(requests),
            "baseline_qps": round(baseline_qps, 2),
            "utilization_qps": round(utilization_qps, 2),
            "overhead_pct": round(
                100.0 * (baseline_qps - utilization_qps) / baseline_qps,
                2,
            ),
            "duty_cycle_pct": totals["duty_cycle_pct"],
            "bubble_ms_p99": round(totals["bubble_ms_p99"], 3)
            if totals["bubble_ms_p99"] is not None
            else None,
            "bubble_causes": sorted(totals["idle_s"]),
            "bubbles": totals["bubbles"],
            "mismatches": baseline_bad + utilization_bad,
        }

    utilization_overhead = utilization_point()
    _log(
        f"utilization A/B c={utilization_overhead['concurrency']}: off "
        f"{utilization_overhead['baseline_qps']:.1f} -> on "
        f"{utilization_overhead['utilization_qps']:.1f} q/s "
        f"({utilization_overhead['overhead_pct']:+.1f}% overhead), duty "
        f"cycle {utilization_overhead['duty_cycle_pct']}%, bubble p99 "
        f"{utilization_overhead['bubble_ms_p99']} ms over "
        f"{utilization_overhead['bubbles']} bubbles"
    )

    # Mesh stage: the same closed-loop point served from a 2-D device
    # mesh (shard x key axes) behind the identical serving surface,
    # bit-checked against the same oracle. Also the donation proof:
    # TransferLedger `selection_scratch` copies before/after the timed
    # loop — with the donated scratch pool the delta is 0 while
    # `key_staging` grows by one per dispatched batch, i.e. donation
    # saves one h2d copy per steady-state batch.
    def mesh_stage_point():
        import jax

        from distributed_point_functions_tpu.observability.device import (
            default_telemetry,
        )
        from distributed_point_functions_tpu.parallel.sharded import (
            make_mesh2d,
        )

        ndev = len(jax.devices())
        if ndev < 2:
            _log(
                f"mesh stage skipped: {ndev} device(s); run directly "
                "(python -m benchmarks.serving_bench) or set XLA_FLAGS="
                "--xla_force_host_platform_device_count=8 for the CPU "
                "fallback"
            )
            return None
        key_devices = 2 if ndev % 2 == 0 else 1
        mesh = make_mesh2d(ndev // key_devices, key_devices)
        concurrency = concurrency_levels[-1]
        config = ServingConfig(
            max_batch_size=max_batch,
            max_wait_ms=2.0,
            max_queue=max(256, 4 * num_requests),
            batching=True,
        )
        ledger = default_telemetry().transfers
        with PlainSession(database, config, mesh=mesh) as session:
            # Warm outside the timing: compiles the mesh shard_map
            # entry and stages the one pooled scratch buffer.
            session.handle_request(requests[0])
            mesh_served = session.server._mesh_plan is not None
            scratch_before = ledger.copies("selection_scratch")
            keys_before = ledger.copies("key_staging")
            wall, lats, resps = _closed_loop(
                session.handle_request, requests, concurrency
            )
            scratch_after = ledger.copies("selection_scratch")
            batches = ledger.copies("key_staging") - keys_before
            mismatches = sum(
                1
                for got, want in zip(resps, oracle)
                if got.dpf_pir_response.masked_response != want
            )
            mesh_served = (
                mesh_served and session.server._mesh_plan is not None
            )
            mesh_export = session.server.mesh_export()
        lats.sort()
        qps = len(requests) / wall
        scratch_delta = scratch_after - scratch_before
        return {
            "devices": ndev,
            "mesh_shape": mesh_export.get("shape"),
            "concurrency": concurrency,
            "qps": round(qps, 2),
            "p50_ms": round(_percentile(lats, 0.50), 3),
            "p95_ms": round(_percentile(lats, 0.95), 3),
            "mismatches": mismatches,
            "mesh_served": mesh_served,
            "fallback_error": mesh_export.get("fallback_error"),
            "batches": batches,
            # Donation accounting: scratch copies staged during the
            # timed loop (0 = the donated buffer recycled every batch)
            # and the per-batch copies that recycling saved.
            "scratch_copies_before": scratch_before,
            "scratch_copies_after": scratch_after,
            "scratch_copies_during_loop": scratch_delta,
            "donation_saved_copies": max(0, batches - scratch_delta),
            "plan": mesh_export.get("plan"),
        }

    mesh_point = None
    if os.environ.get("SERVING_BENCH_MESH", "1") != "0":
        mesh_point = mesh_stage_point()
    if mesh_point:
        _log(
            f"mesh {mesh_point['mesh_shape']} c="
            f"{mesh_point['concurrency']}: {mesh_point['qps']:.1f} q/s  "
            f"p50 {mesh_point['p50_ms']:.1f} ms  "
            f"mismatches={mesh_point['mismatches']}  donation saved "
            f"{mesh_point['donation_saved_copies']} scratch copies over "
            f"{mesh_point['batches']} batches"
        )

    def sparse_point():
        """Sparse (cuckoo key-value) ladder: closed-loop string-keyed
        traffic through a `SparsePlainSession` (batcher on), every
        masked response bit-checked against an unbatched sparse oracle,
        then one ~1%-key write batch landed as a SnapshotManager delta
        rotation (prestage stats read off the staged generation)."""
        from distributed_point_functions_tpu.pir.cuckoo_database import (
            CuckooHashedDpfPirDatabase,
        )
        from distributed_point_functions_tpu.pir.sparse_client import (
            CuckooHashingSparseDpfPirClient,
            KeyNotFound,
        )
        from distributed_point_functions_tpu.pir.sparse_server import (
            CuckooHashingSparseDpfPirServer,
        )
        from distributed_point_functions_tpu.serving import (
            SnapshotManager,
            SparsePlainSession,
            make_sparse_client,
            sparse_lookup_plain,
        )

        num_keys = int(os.environ.get("SERVING_BENCH_SPARSE_KEYS", 512))
        touched = max(1, num_keys // 100)
        # Fixed-width keys/values: a delta rotation preserves the
        # packed row width of each parallel dense store, so the write
        # batch below must stay in-width to prestage as a delta.
        records = {
            b"skey-%06d" % i: (b"sval-%06d:" % i).ljust(
                record_bytes, b"."
            )[:record_bytes]
            for i in range(num_keys)
        }
        params = CuckooHashingSparseDpfPirServer.generate_params(
            num_keys, seed=b"0123456789abcdef"
        )
        builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
        for kv in records.items():
            builder.insert(kv)
        sparse_db = builder.build()

        sparse_client = CuckooHashingSparseDpfPirClient.create(
            params, lambda pt, ci: pt
        )
        key_list = sorted(records)
        sparse_requests = [
            sparse_client.create_plain_requests(
                [key_list[int(i)]]
            )[0]
            for i in rng.integers(0, num_keys, num_requests)
        ]
        sparse_oracle_server = (
            CuckooHashingSparseDpfPirServer.create_plain(
                params, sparse_db
            )
        )
        sparse_oracle = [
            sparse_oracle_server.handle_plain_request(
                r
            ).dpf_pir_response.masked_response
            for r in sparse_requests
        ]

        concurrency = concurrency_levels[-1]
        config = ServingConfig(
            max_batch_size=max_batch,
            max_wait_ms=2.0,
            max_queue=max(256, 4 * num_requests),
            batching=True,
        )
        with SparsePlainSession(params, sparse_db, config) as session:
            # Warm pass: compiles every bucket shape the closed loop
            # can form and makes the gen-0 stagings resident (the
            # prerequisite for the rotation below to prestage as a
            # delta rather than a full image).
            for r in sparse_requests:
                session.handle_request(r)
            wall, lats, resps = _closed_loop(
                session.handle_request, sparse_requests, concurrency
            )
            mismatches = sum(
                1
                for got, want in zip(resps, sparse_oracle)
                if got.dpf_pir_response.masked_response != want
            )

            # Write batch: rewrite ~1% of the keys (new in-width
            # values) plus one brand-new key, landed as a delta
            # rotation while the session stays live.
            manager = SnapshotManager(session)
            delta = CuckooHashedDpfPirDatabase.Builder()
            rewritten = key_list[:touched]
            for key in rewritten:
                delta.insert(
                    (key, records[key][::-1])  # same width, new bytes
                )
            new_key = b"snew-%06d" % num_keys
            delta.insert(
                (new_key, (b"sval-new---:").ljust(
                    record_bytes, b"."
                )[:record_bytes])
            )
            db1 = delta.build_from(sparse_db)
            staged_bytes = manager.stage(db1)
            manager.flip(timeout=120.0)
            stats = db1.last_prestage_stats or {}

            lookup_client = make_sparse_client(session)
            out = sparse_lookup_plain(
                session,
                lookup_client,
                [rewritten[0], new_key, b"skey-no-such"],
            )
            lookup_mismatches = 0
            if out[0] != records[rewritten[0]][::-1]:
                lookup_mismatches += 1
            if out[1] != (b"sval-new---:").ljust(
                record_bytes, b"."
            )[:record_bytes]:
                lookup_mismatches += 1
            if not isinstance(out[2], KeyNotFound):
                lookup_mismatches += 1
            generation = manager.serving_generation()

        lats.sort()
        return {
            "num_keys": num_keys,
            "num_buckets": params.num_buckets,
            "num_hash_functions": params.num_hash_functions,
            "concurrency": concurrency,
            "qps": round(len(sparse_requests) / wall, 2),
            "p50_ms": round(_percentile(lats, 0.50), 3),
            "p95_ms": round(_percentile(lats, 0.95), 3),
            "mismatches": mismatches + lookup_mismatches,
            "rotation_keys_touched": touched + 1,
            "rotation_staged_bytes": staged_bytes,
            "prestage_mode": stats.get("mode"),
            "prestage_bytes_saved": stats.get("bytes_saved", 0),
            "prestage_bytes_staged": stats.get("bytes_staged", 0),
            "prestage_bytes_full_image": stats.get(
                "bytes_full_image", 0
            ),
            "serving_generation": generation,
        }

    sparse_point_r = None
    if os.environ.get("SERVING_BENCH_SPARSE", "1") != "0":
        sparse_point_r = sparse_point()
    if sparse_point_r:
        _log(
            f"sparse {sparse_point_r['num_keys']} keys c="
            f"{sparse_point_r['concurrency']}: "
            f"{sparse_point_r['qps']:.1f} q/s  p50 "
            f"{sparse_point_r['p50_ms']:.1f} ms  mismatches="
            f"{sparse_point_r['mismatches']}  rotation "
            f"{sparse_point_r['prestage_mode']} saved "
            f"{sparse_point_r['prestage_bytes_saved']} of "
            f"{sparse_point_r['prestage_bytes_full_image']} bytes"
        )

    # Cost-model accuracy: the default ledger joined every terminal
    # batch the sweeps served against its admission-time price. The
    # aggregate is the samples-weighted mean of per-cell |residual_p50|
    # (signed ratio error, 0 = perfectly priced) — report-only, and
    # appended to history.jsonl by main() with direction "lower".
    from distributed_point_functions_tpu.observability import (
        costmodel as costmodel_mod,
    )

    cost_model_residual = workload_residual_summary(
        costmodel_mod.default_cost_ledger().export(), "pir"
    )
    if cost_model_residual["cells"]:
        _log(
            f"cost-model residual (pir): "
            f"|p50| {cost_model_residual['residual_p50_abs']:.3f} over "
            f"{cost_model_residual['samples']} batches in "
            f"{len(cost_model_residual['cells'])} cells"
        )

    best_batched = max(p["qps"] for p in batched_points)
    best_unbatched = max(p["qps"] for p in unbatched_points)
    correctness_ok = (
        all(
            p["mismatches"] == 0
            for p in batched_points + unbatched_points
        )
        and prober_overhead["mismatches"] == 0
        and digest_overhead["mismatches"] == 0
        and ledger_overhead["mismatches"] == 0
        and pipeline_overhead["mismatches"] == 0
        and utilization_overhead["mismatches"] == 0
        and (mesh_point is None or mesh_point["mismatches"] == 0)
        and (
            sparse_point_r is None
            or sparse_point_r["mismatches"] == 0
        )
    )
    compiles = batched_metrics["counters"].get(
        "plain.batcher.jit_bucket_compiles", 0
    )
    report = {
        "config": {
            "num_records": num_records,
            "record_bytes": record_bytes,
            "num_requests": num_requests,
            "max_batch_size": max_batch,
            "concurrency_levels": concurrency_levels,
            "jit_bucket_bound": bucket_size(max_batch).bit_length(),
        },
        "sweep": unbatched_points + batched_points,
        "best_batched_qps": best_batched,
        "best_unbatched_qps": best_unbatched,
        "batched_speedup": round(best_batched / best_unbatched, 2)
        if best_unbatched
        else None,
        "correctness_ok": correctness_ok,
        "prober_overhead": prober_overhead,
        "digest_overhead": digest_overhead,
        "ledger_overhead": ledger_overhead,
        "pipeline_overhead": pipeline_overhead,
        "utilization_overhead": utilization_overhead,
        "mesh": mesh_point,
        "sparse": sparse_point_r,
        "cost_model_residual_p50": cost_model_residual,
        "jit_bucket_compiles": compiles,
        "batched_metrics": batched_metrics,
        # Per-stage span summary (queue wait / batch assembly / device
        # compute / evaluate_* percentiles) and the planner-tier
        # counters, so the report decomposes where the q/s went.
        "stage_spans": tracing.stage_summary(),
        "runtime_counters": tracing.runtime_counters.export(),
    }
    _log(
        f"best batched {best_batched:.1f} q/s vs unbatched "
        f"{best_unbatched:.1f} q/s ({report['batched_speedup']}x), "
        f"{compiles} jit buckets, correctness "
        f"{'ok' if correctness_ok else 'FAILED'}"
    )

    out = os.environ.get(
        "SERVING_BENCH_OUT", "benchmarks/results/serving_bench.json"
    )
    if out:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
        _log(f"report written to {out}")
    return report


def main():
    # Must run before anything imports jax: on a CPU-only host the
    # mesh stage needs >1 device, which XLA only fakes at init time.
    force_host_devices()
    report = run_serving_bench()
    print(json.dumps(report, indent=2))
    if os.environ.get("BENCH_HISTORY", "1") != "0":
        append_residual_history(
            report["cost_model_residual_p50"], bench="serving_bench"
        )
        append_mesh_history(report["mesh"], bench="serving_bench")
        append_pipeline_history(
            report["pipeline_overhead"], bench="serving_bench"
        )
        append_utilization_history(
            report["utilization_overhead"], bench="serving_bench"
        )
        append_sparse_history(report["sparse"], bench="serving_bench")
    if not report["correctness_ok"]:
        raise SystemExit("serving bench FAILED correctness")


if __name__ == "__main__":
    main()
