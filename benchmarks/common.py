"""Shared benchmark plumbing: timing + one-JSON-line-per-result reporting."""

from __future__ import annotations

import json
import os
import time
from typing import Callable


def setup_compilation_cache() -> None:
    """Point jax at the shared persistent compile cache (same location as
    bench.py): the reference-mirroring sweeps compile many large
    multi-level programs, and on the tunneled TPU each cold compile costs
    minutes — a cache hit across runs/retries is the difference between a
    sweep finishing and hitting its window timeout."""
    import jax

    cache_dir = os.environ.get(
        "BENCH_CACHE_DIR", os.path.expanduser("~/.cache/jax_bench")
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def run_timed(
    name: str,
    fn: Callable[[], object],
    *,
    iters: int = 3,
    warmup: int = 1,
    items: int = 1,
    unit: str = "s",
    label: str = "",
) -> dict:
    """Times `fn` (which must block until done) and prints one JSON line.

    `items` scales the result to a per-item rate (e.g. leaves, points,
    queries); with items > 1 the reported value is items/second.
    """
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    elapsed = (time.perf_counter() - t0) / iters
    result = {
        "benchmark": name,
        "time_s": round(elapsed, 6),
    }
    if items > 1:
        result["items_per_s"] = round(items / elapsed, 2)
        result["ns_per_item"] = round(elapsed / items * 1e9, 3)
    if label:
        result["label"] = label
    print(json.dumps(result), flush=True)
    return result
