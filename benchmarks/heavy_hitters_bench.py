"""Heavy-hitters sweep benchmark: per-level latency, prune ratio, reuse.

Drives the full two-server sweep (`heavy_hitters.session` over an
in-process transport) across a `clients x domain-bits x threshold`
grid, and measures the tentpole claim directly: each grid point also
runs a *from-root* sweep — identical rounds, but the aggregator's
cut-state cache is dropped before every level so evaluation re-expands
from the root — giving the cut-state-reuse speedup as the report's
`vs_baseline` analog.

Every point's private answer is checked against the plaintext oracle,
so the throughput claim carries an equal-correctness proof in the same
run, exactly like `serving_bench`. Metric definitions:

* **lane** — one (key, prefix) evaluation inside a fused level batch;
  `lanes_per_sec` is total lanes over the measured sweep wall clock,
  the sweep's q/s-equivalent.
* **prune_ratio** — per round, the fraction of the candidate frontier
  the threshold killed.
* **cut-state hit rate** — prefixes served from cached cuts over total
  prefixes evaluated (from the `hh.cut_resume_prefixes` /
  `hh.root_eval_prefixes` counters).

Run directly (one JSON report on stdout, also written to
``benchmarks/results/heavy_hitters_bench.json``)::

    JAX_PLATFORMS=cpu python -m benchmarks.heavy_hitters_bench

or through the headline harness (one bench-style JSON line)::

    BENCH_HEAVY_HITTERS=1 BENCH_PLATFORM=cpu python bench.py

Environment knobs: HH_BENCH_CLIENTS (default 48), HH_BENCH_DOMAIN_BITS
("16"), HH_BENCH_LEVEL_BITS (4), HH_BENCH_THRESHOLDS ("2,4"),
HH_BENCH_OUT (report path; empty string disables the file),
BENCH_HISTORY ("0" skips the history.jsonl residual append).

The report also carries `cost_model_residual_p50` for the "hh"
workload — the cost ledger's samples-weighted |residual_p50| over the
measured sweeps' folded levels, appended to history.jsonl with
direction "lower" (report-only; same shape as serving_bench's pir
aggregate).
"""

from __future__ import annotations

import json
import os
import random
import sys
import time


def _log(msg: str) -> None:
    print(f"[hh-bench {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def _skewed_values(num_clients: int, domain_bits: int, seed: int):
    """Zipf-ish population: a few hot values, a long random tail."""
    rng = random.Random(seed)
    hot = [rng.randrange(1 << domain_bits) for _ in range(4)]
    weights = [num_clients // 4, num_clients // 6, num_clients // 8,
               num_clients // 10]
    values = []
    for v, w in zip(hot, weights):
        values.extend([v] * max(1, w))
    while len(values) < num_clients:
        values.append(rng.randrange(1 << domain_bits))
    rng.shuffle(values)
    return values[:num_clients]


def _sweep_leader_helper(config, keys0, keys1, metrics):
    """One full Leader/Helper sweep over an in-process transport;
    returns (result, wall_s)."""
    from distributed_point_functions_tpu import heavy_hitters as hh
    from distributed_point_functions_tpu.serving.transport import (
        InProcessTransport,
    )

    s0 = hh.HeavyHittersServer(config, keys0, metrics=metrics)
    s1 = hh.HeavyHittersServer(config, keys1, metrics=metrics)
    leader = hh.HeavyHittersLeader(
        s0, InProcessTransport(hh.HeavyHittersHelper(s1).handle_wire),
        metrics=metrics,
    )
    t0 = time.perf_counter()
    result = leader.run()
    return result, time.perf_counter() - t0


def _sweep_from_root(config, keys0, keys1):
    """The same rounds with the cut-state cache dropped before every
    level — the re-expand-from-root baseline; returns (result, wall_s)."""
    from distributed_point_functions_tpu import heavy_hitters as hh

    s0 = hh.HeavyHittersServer(config, keys0)
    s1 = hh.HeavyHittersServer(config, keys1)
    sweep = hh.FrontierSweep(config)
    t0 = time.perf_counter()
    while not sweep.done:
        r, frontier = sweep.round_index, sweep.frontier
        s0.aggregator.reset()
        s1.aggregator.reset()
        counts = hh.reconstruct_counts(
            s0.aggregator.evaluate_level(r, frontier),
            s1.aggregator.evaluate_level(r, frontier),
            config.count_bits,
        )
        sweep.observe_counts(counts)
    wall = time.perf_counter() - t0
    return (
        hh.HeavyHittersResult(
            heavy_hitters=sweep.result, rounds=sweep.rounds
        ),
        wall,
    )


def run_heavy_hitters_bench():
    """Sweep the grid, check each point against the oracle, return the
    report dict (also written to HH_BENCH_OUT unless empty)."""
    from distributed_point_functions_tpu import heavy_hitters as hh
    from distributed_point_functions_tpu.observability import tracing
    from distributed_point_functions_tpu.serving.metrics import (
        MetricsRegistry,
    )

    tracing.reset_stages()

    num_clients = int(os.environ.get("HH_BENCH_CLIENTS", 48))
    level_bits = int(os.environ.get("HH_BENCH_LEVEL_BITS", 4))
    domain_bits_list = [
        int(b)
        for b in os.environ.get("HH_BENCH_DOMAIN_BITS", "16").split(",")
        if b.strip()
    ]
    thresholds = [
        int(t)
        for t in os.environ.get("HH_BENCH_THRESHOLDS", "2,4").split(",")
        if t.strip()
    ]

    metrics = MetricsRegistry()
    points = []
    correctness_ok = True
    for domain_bits in domain_bits_list:
        for threshold in thresholds:
            config = hh.HeavyHittersConfig(
                domain_bits=domain_bits,
                level_bits=level_bits,
                threshold=threshold,
            )
            values = _skewed_values(num_clients, domain_bits, seed=13)
            client = hh.HeavyHittersClient(config)
            pairs = [client.generate_report(v) for v in values]
            keys0 = [p[0] for p in pairs]
            keys1 = [p[1] for p in pairs]

            # Warm run compiles every jit shape bucket the sweep needs;
            # the measured run then reflects steady-state level latency.
            _sweep_leader_helper(config, keys0, keys1, MetricsRegistry())
            metrics.reset()
            result, wall_s = _sweep_leader_helper(
                config, keys0, keys1, metrics
            )
            snap = metrics.snapshot()

            want = hh.plaintext_heavy_hitters(values, config)
            ok = result.as_dict() == want
            correctness_ok = correctness_ok and ok

            # Warm the from-root shapes too (each level's full-depth
            # walk is a distinct program) so the speedup compares
            # steady-state sweeps, not resume vs cold compiles.
            _sweep_from_root(config, keys0, keys1)
            root_result, root_wall_s = _sweep_from_root(
                config, keys0, keys1
            )
            ok_root = root_result.as_dict() == want
            correctness_ok = correctness_ok and ok_root

            lanes = sum(
                st.frontier_width * num_clients for st in result.rounds
            )
            resume = snap["counters"].get("hh.cut_resume_prefixes", 0)
            root = snap["counters"].get("hh.root_eval_prefixes", 0)
            point = {
                "num_clients": num_clients,
                "domain_bits": domain_bits,
                "level_bits": level_bits,
                "threshold": threshold,
                "num_rounds": len(result.rounds),
                "heavy_hitters": len(result.heavy_hitters),
                "sweep_wall_s": round(wall_s, 4),
                "from_root_wall_s": round(root_wall_s, 4),
                "resume_speedup": round(root_wall_s / wall_s, 2)
                if wall_s
                else None,
                "lanes": lanes,
                "lanes_per_sec": round(lanes / wall_s, 1) if wall_s else 0.0,
                "cut_state_hit_rate": round(
                    resume / (resume + root), 4
                ) if (resume + root) else 0.0,
                "rounds": [
                    {
                        "round": st.round_index,
                        "bit_width": st.bit_width,
                        "frontier_width": st.frontier_width,
                        "survivors": st.survivors,
                        "prune_ratio": round(st.prune_ratio, 4),
                        "wall_ms": round(st.wall_ms, 2),
                        "bytes_on_wire": st.bytes_sent + st.bytes_received,
                    }
                    for st in result.rounds
                ],
                "correctness_ok": ok and ok_root,
            }
            points.append(point)
            _log(
                f"d={domain_bits} t={threshold}: "
                f"{point['lanes_per_sec']:.0f} lanes/s over "
                f"{point['num_rounds']} rounds, resume speedup "
                f"{point['resume_speedup']}x, hit rate "
                f"{point['cut_state_hit_rate']}, "
                f"correct={'ok' if point['correctness_ok'] else 'FAILED'}"
            )

    # Cost-model accuracy: every folded level in the measured sweeps
    # joined its admission-time frontier price against the measured
    # fold in the default cost ledger. Same aggregate (samples-weighted
    # mean |residual_p50|) and history metric shape as serving_bench's
    # pir workload — report-only, direction "lower".
    from benchmarks.serving_bench import workload_residual_summary
    from distributed_point_functions_tpu.observability import (
        costmodel as costmodel_mod,
    )

    cost_model_residual = workload_residual_summary(
        costmodel_mod.default_cost_ledger().export(), "hh"
    )
    if cost_model_residual["cells"]:
        _log(
            f"cost-model residual (hh): "
            f"|p50| {cost_model_residual['residual_p50_abs']:.3f} over "
            f"{cost_model_residual['samples']} folded levels in "
            f"{len(cost_model_residual['cells'])} cells"
        )

    best = max(p["lanes_per_sec"] for p in points)
    speedups = [p["resume_speedup"] for p in points if p["resume_speedup"]]
    report = {
        "config": {
            "num_clients": num_clients,
            "level_bits": level_bits,
            "domain_bits": domain_bits_list,
            "thresholds": thresholds,
        },
        "sweep": points,
        "best_lanes_per_sec": best,
        "resume_speedup": round(sum(speedups) / len(speedups), 2)
        if speedups
        else None,
        "correctness_ok": correctness_ok,
        "cost_model_residual_p50": cost_model_residual,
        # Sweep-wide span summary (helper_evaluate / leader_own_share /
        # reconstruct / round percentiles) and the final measured
        # point's metrics snapshot.
        "stage_spans": tracing.stage_summary(),
        "metrics_snapshot": snap,
    }

    out = os.environ.get(
        "HH_BENCH_OUT", "benchmarks/results/heavy_hitters_bench.json"
    )
    if out:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
        _log(f"report written to {out}")
    return report


def main():
    report = run_heavy_hitters_bench()
    print(json.dumps(report, indent=2))
    if os.environ.get("BENCH_HISTORY", "1") != "0":
        from benchmarks.serving_bench import append_residual_history

        append_residual_history(
            report["cost_model_residual_p50"], bench="heavy_hitters_bench"
        )
    if not report["correctness_ok"]:
        raise SystemExit("heavy-hitters bench FAILED correctness")


if __name__ == "__main__":
    main()
