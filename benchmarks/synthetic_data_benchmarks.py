"""End-to-end hierarchical-evaluation benchmark on synthetic sparse
histograms — the equivalent of the reference's experiments harness
(`experiments/synthetic_data_benchmarks.cc:45-308`).

One DPF key for a random nonzero index is expanded hierarchically: at each
configured hierarchy level only the prefixes that are "live" in the
synthetic workload (plus the expansion-factor cap) are evaluated, mirroring
the heavy-hitters evaluation strategy of `experiments/README.md:18-24`.

Flags mirror the reference's absl flags:
  --input PATH               CSV whose first column holds the nonzero
                             indices (`synthetic_data_benchmarks.cc:121-144`;
                             the reference's checked-in CSVs are git-lfs
                             stubs, so --distribution synthesizes equivalent
                             workloads when no file is given)
  --distribution {uniform,powerlaw10,powerlaw50}
  --log_domain_size N        total domain bits (default 32)
  --log_num_nonzeros N       synthetic workload size (default 14)
  --levels_to_evaluate a,b,c hierarchy levels (default auto: every 2 bits
                             from log_num_nonzeros+1)
  --max_expansion_factor F   cap on per-level expansion (default 4)
  --only_nonzeros            batched single-point evaluation at the nonzero
                             indices instead of hierarchical evaluation
                             (`synthetic_data_benchmarks.cc:55-58,299-302`)
  --num_iterations N
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root


def synthesize_nonzeros(distribution: str, log_domain_size: int, n: int,
                        rng: np.random.Generator) -> np.ndarray:
    """Random nonzero indices with the reference's workload shapes
    (`experiments/README.md:35-48`): uniform, or power-law with 90% of mass
    in the first 10%/50% of the domain.

    Returns uint64[m, 2] (hi, lo) limb pairs, sorted and deduplicated —
    domains up to 2^128 (`experiments/README.md:72-108`) exceed any numpy
    integer dtype. For log_domain_size <= 64, hi is identically 0.
    """
    def draw(bits, k):
        hi_bits = max(0, bits - 64)
        lo_bits = min(bits, 64)
        hi = (
            _rand_bits(rng, hi_bits, k)
            if hi_bits
            else np.zeros(k, dtype=np.uint64)
        )
        return hi, _rand_bits(rng, lo_bits, k)

    if distribution == "uniform":
        hi, lo = draw(log_domain_size, n)
    else:
        head = rng.random(n) < 0.9
        frac = 0.1 if distribution == "powerlaw10" else 0.5
        if log_domain_size <= 64:
            # Exact head bound (the reference's 10%/50% of the domain);
            # frac < 1 keeps the bound within uint64 even at lds = 64.
            bound = max(1, int((1 << log_domain_size) * frac))
            h_lo = rng.integers(0, bound, n, dtype=np.uint64)
            h_hi = np.zeros(n, dtype=np.uint64)
        else:
            # Beyond numpy's integer range: power-of-two head bound
            # (domain/8 ~ 12.5% for powerlaw10, domain/2 exact for 50%).
            frac_bits = log_domain_size - (3 if frac == 0.1 else 1)
            h_hi, h_lo = draw(frac_bits, n)
        t_hi, t_lo = draw(log_domain_size, n)
        hi = np.where(head, h_hi, t_hi)
        lo = np.where(head, h_lo, t_lo)
    return np.unique(np.stack([hi, lo], axis=1), axis=0)


def _rand_bits(rng: np.random.Generator, bits: int, k: int) -> np.ndarray:
    """k random uint64 values of `bits` (<= 64) random low bits."""
    if bits <= 0:
        return np.zeros(k, dtype=np.uint64)
    vals = rng.integers(0, 1 << min(bits, 63), k, dtype=np.uint64)
    if bits == 64:
        vals = (vals << np.uint64(1)) | rng.integers(
            0, 2, k, dtype=np.uint64
        )
    return vals


def _pairs_to_ints(pairs: np.ndarray) -> list:
    """uint64[m, 2] (hi, lo) -> python ints (arbitrary precision)."""
    return [(int(h) << 64) | int(l) for h, l in pairs]


def _unique_prefixes(pairs: np.ndarray, shift: int) -> list:
    """Distinct `x >> shift` over (hi, lo) pairs, as python ints."""
    hi = pairs[:, 0]
    lo = pairs[:, 1]
    if shift >= 64:
        p = np.unique(hi >> np.uint64(shift - 64))
        return [int(x) for x in p]
    if shift == 0:
        u = np.unique(pairs, axis=0)
        return _pairs_to_ints(u)
    u = np.unique(
        np.stack([hi, lo >> np.uint64(shift)], axis=1), axis=0
    )
    return [(int(h) << (64 - shift)) | int(l) for h, l in u]


def read_unique_values_from_file(path: str) -> list:
    """Unique integers in the first CSV column (sorted python ints —
    values may exceed 64 bits), like the reference's
    `ReadUniqueValuesFromFile` (`synthetic_data_benchmarks.cc:121-144`)."""
    values = set()
    with open(path) as f:
        for line_number, line in enumerate(f):
            fields = [x.strip() for x in line.split(",") if x.strip()]
            if not fields:
                raise ValueError(f"Line {line_number} is empty")
            values.add(int(fields[0]))
    return sorted(values)


def main():
    from benchmarks.common import setup_compilation_cache

    setup_compilation_cache()
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    parser = argparse.ArgumentParser()
    parser.add_argument("--input", default="",
                        help="CSV of nonzero indices (first column)")
    parser.add_argument("--distribution", default="powerlaw10",
                        choices=["uniform", "powerlaw10", "powerlaw50"])
    parser.add_argument("--log_domain_size", type=int, default=32)
    parser.add_argument("--log_num_nonzeros", type=int, default=14)
    parser.add_argument("--levels_to_evaluate", default="")
    parser.add_argument("--max_expansion_factor", type=float, default=4.0)
    parser.add_argument("--only_nonzeros", action="store_true",
                        help="batched point eval at the nonzeros instead of "
                        "hierarchical evaluation (requires --input or "
                        "--distribution synthesis)")
    parser.add_argument("--num_iterations", type=int, default=1)
    args = parser.parse_args()

    import jax

    from distributed_point_functions_tpu.dpf import (
        DistributedPointFunction,
        DpfParameters,
    )
    from distributed_point_functions_tpu.value_types import IntType

    lds = args.log_domain_size
    if args.levels_to_evaluate:
        levels = [int(x) for x in args.levels_to_evaluate.split(",")]
    else:
        levels = list(range(args.log_num_nonzeros + 1, lds, 2)) + [lds]
    assert levels[-1] == lds, "last level must be the full domain"

    rng = np.random.default_rng(42)
    if args.input:
        values = read_unique_values_from_file(args.input)
        if not values:
            raise ValueError(f"--input {args.input} contains no values")
        if values[-1] >= (1 << lds):
            raise ValueError(
                f"nonzero {values[-1]} out of range for domain 2^{lds}"
            )
        nonzeros = np.array(
            [[v >> 64, v & ((1 << 64) - 1)] for v in values],
            dtype=np.uint64,
        )
    else:
        nonzeros = synthesize_nonzeros(
            args.distribution, lds, 1 << args.log_num_nonzeros, rng
        )

    params = [
        DpfParameters(log_domain_size=l, value_type=IntType(32))
        for l in levels
    ]
    dpf = DistributedPointFunction.create_incremental(params)
    mid = nonzeros[len(nonzeros) // 2]
    alpha = (int(mid[0]) << 64) | int(mid[1])
    k0, _ = dpf.generate_keys_incremental(alpha, [1] * len(levels))

    max_prefixes = int(args.max_expansion_factor * len(nonzeros))

    def one_iteration():
        ctx = dpf.create_evaluation_context(k0)
        total_evaluated = 0
        prefixes: list = []
        for i, level_bits in enumerate(levels):
            out = dpf.evaluate_until(i, prefixes, ctx)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
            size = int(np.asarray(out).shape[0])
            total_evaluated += size
            if i + 1 < len(levels):
                # Keep the live prefixes of the workload at this level
                # (the server knows which buckets are nonzero), capped at
                # the expansion factor like the reference harness.
                live = _unique_prefixes(nonzeros, lds - level_bits)
                prefixes = live[:max_prefixes]
        return total_evaluated

    if args.only_nonzeros:
        # Batched single-point evaluation at the nonzero indices
        # (`RunBatchedSinglePointEvaluation`,
        # `synthetic_data_benchmarks.cc:299-302`).
        points = _pairs_to_ints(nonzeros)
        last_level = len(levels) - 1

        def one_iteration():
            out = dpf.evaluate_at(k0, last_level, points)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
            return len(points)

    total = one_iteration()  # warmup + size probe
    t0 = time.perf_counter()
    for _ in range(args.num_iterations):
        one_iteration()
    elapsed = (time.perf_counter() - t0) / args.num_iterations

    print(
        json.dumps(
            {
                "benchmark": (
                    "synthetic_only_nonzeros"
                    if args.only_nonzeros
                    else "synthetic_hierarchical_eval"
                ),
                "distribution": "file" if args.input else args.distribution,
                "log_domain_size": lds,
                "num_nonzeros": len(nonzeros),
                "levels": levels,
                "leaves_evaluated": total,
                "time_s": round(elapsed, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
