"""End-to-end hierarchical-evaluation benchmark on synthetic sparse
histograms — the equivalent of the reference's experiments harness
(`experiments/synthetic_data_benchmarks.cc:45-308`).

One DPF key for a random nonzero index is expanded hierarchically: at each
configured hierarchy level only the prefixes that are "live" in the
synthetic workload (plus the expansion-factor cap) are evaluated, mirroring
the heavy-hitters evaluation strategy of `experiments/README.md:18-24`.

Flags mirror the reference's absl flags:
  --input PATH               CSV whose first column holds the nonzero
                             indices (`synthetic_data_benchmarks.cc:121-144`;
                             the reference's checked-in CSVs are git-lfs
                             stubs, so --distribution synthesizes equivalent
                             workloads when no file is given)
  --distribution {uniform,powerlaw10,powerlaw50}
  --log_domain_size N        total domain bits (default 32)
  --log_num_nonzeros N       synthetic workload size (default 14)
  --levels_to_evaluate a,b,c hierarchy levels (default auto: every 2 bits
                             from log_num_nonzeros+1)
  --max_expansion_factor F   cap on per-level expansion (default 4)
  --only_nonzeros            batched single-point evaluation at the nonzero
                             indices instead of hierarchical evaluation
                             (`synthetic_data_benchmarks.cc:55-58,299-302`)
  --num_iterations N
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def synthesize_nonzeros(distribution: str, log_domain_size: int, n: int,
                        rng: np.random.Generator) -> np.ndarray:
    """Random nonzero indices with the reference's workload shapes
    (`experiments/README.md:35-48`): uniform, or power-law with 90% of mass
    in the first 10%/50% of the domain."""
    domain = 1 << log_domain_size
    if distribution == "uniform":
        vals = rng.integers(0, domain, n, dtype=np.uint64)
    else:
        frac = 0.1 if distribution == "powerlaw10" else 0.5
        head = rng.random(n) < 0.9
        vals = np.where(
            head,
            rng.integers(0, max(1, int(domain * frac)), n, dtype=np.uint64),
            rng.integers(0, domain, n, dtype=np.uint64),
        )
    return np.unique(vals)


def read_unique_values_from_file(path: str) -> np.ndarray:
    """Unique integers in the first CSV column, like the reference's
    `ReadUniqueValuesFromFile` (`synthetic_data_benchmarks.cc:121-144`)."""
    values = set()
    with open(path) as f:
        for line_number, line in enumerate(f):
            fields = [x.strip() for x in line.split(",") if x.strip()]
            if not fields:
                raise ValueError(f"Line {line_number} is empty")
            values.add(int(fields[0]))
    return np.array(sorted(values), dtype=np.uint64)


def main():
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    parser = argparse.ArgumentParser()
    parser.add_argument("--input", default="",
                        help="CSV of nonzero indices (first column)")
    parser.add_argument("--distribution", default="powerlaw10",
                        choices=["uniform", "powerlaw10", "powerlaw50"])
    parser.add_argument("--log_domain_size", type=int, default=32)
    parser.add_argument("--log_num_nonzeros", type=int, default=14)
    parser.add_argument("--levels_to_evaluate", default="")
    parser.add_argument("--max_expansion_factor", type=float, default=4.0)
    parser.add_argument("--only_nonzeros", action="store_true",
                        help="batched point eval at the nonzeros instead of "
                        "hierarchical evaluation (requires --input or "
                        "--distribution synthesis)")
    parser.add_argument("--num_iterations", type=int, default=1)
    args = parser.parse_args()

    import jax

    from distributed_point_functions_tpu.dpf import (
        DistributedPointFunction,
        DpfParameters,
    )
    from distributed_point_functions_tpu.value_types import IntType

    lds = args.log_domain_size
    if args.levels_to_evaluate:
        levels = [int(x) for x in args.levels_to_evaluate.split(",")]
    else:
        levels = list(range(args.log_num_nonzeros + 1, lds, 2)) + [lds]
    assert levels[-1] == lds, "last level must be the full domain"

    rng = np.random.default_rng(42)
    if args.input:
        nonzeros = read_unique_values_from_file(args.input)
        if not len(nonzeros):
            raise ValueError(f"--input {args.input} contains no values")
        if int(nonzeros[-1]) >= (1 << lds):
            raise ValueError(
                f"nonzero {int(nonzeros[-1])} out of range for domain "
                f"2^{lds}"
            )
    else:
        nonzeros = synthesize_nonzeros(
            args.distribution, lds, 1 << args.log_num_nonzeros, rng
        )

    params = [
        DpfParameters(log_domain_size=l, value_type=IntType(32))
        for l in levels
    ]
    dpf = DistributedPointFunction.create_incremental(params)
    alpha = int(nonzeros[len(nonzeros) // 2])
    k0, _ = dpf.generate_keys_incremental(alpha, [1] * len(levels))

    max_prefixes = int(args.max_expansion_factor * len(nonzeros))

    def one_iteration():
        ctx = dpf.create_evaluation_context(k0)
        total_evaluated = 0
        prefixes: list = []
        for i, level_bits in enumerate(levels):
            out = dpf.evaluate_until(i, prefixes, ctx)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
            size = int(np.asarray(out).shape[0])
            total_evaluated += size
            if i + 1 < len(levels):
                # Keep the live prefixes of the workload at this level
                # (the server knows which buckets are nonzero), capped at
                # the expansion factor like the reference harness.
                shift = lds - level_bits
                live = np.unique(nonzeros >> np.uint64(shift)).astype(
                    np.uint64
                )
                if len(live) > max_prefixes:
                    live = live[:max_prefixes]
                prefixes = [int(x) for x in live]
        return total_evaluated

    if args.only_nonzeros:
        # Batched single-point evaluation at the nonzero indices
        # (`RunBatchedSinglePointEvaluation`,
        # `synthetic_data_benchmarks.cc:299-302`).
        points = [int(x) for x in nonzeros]
        last_level = len(levels) - 1

        def one_iteration():
            out = dpf.evaluate_at(k0, last_level, points)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
            return len(points)

    total = one_iteration()  # warmup + size probe
    t0 = time.perf_counter()
    for _ in range(args.num_iterations):
        one_iteration()
    elapsed = (time.perf_counter() - t0) / args.num_iterations

    print(
        json.dumps(
            {
                "benchmark": (
                    "synthetic_only_nonzeros"
                    if args.only_nonzeros
                    else "synthetic_hierarchical_eval"
                ),
                "distribution": "file" if args.input else args.distribution,
                "log_domain_size": lds,
                "num_nonzeros": len(nonzeros),
                "levels": levels,
                "leaves_evaluated": total,
                "time_s": round(elapsed, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
