#!/usr/bin/env bash
# Round-2 follow-up TPU queue (after the kernel-legality and self-check
# fixes): smoke the kernels first, A/B the inner product, headline at
# growing query batches, then the remaining reference sweeps. Each stage
# is its own process under `timeout` so a mid-stage tunnel stall never
# kills the queue.
set -u -o pipefail
cd "$(dirname "$0")/.."
mkdir -p benchmarks/results
stamp=$(date +%Y%m%d_%H%M%S)

echo "=== kernel smoke (tiny shapes, fast compiles) ==="
timeout 1500 python benchmarks/kernel_smoke.py \
    2>benchmarks/results/kernel_smoke_${stamp}.log \
    | tee benchmarks/results/kernel_smoke_${stamp}.json
tail -3 benchmarks/results/kernel_smoke_${stamp}.log

echo "=== inner-product kernel A/B (v1 vs v2 variants) ==="
timeout 2400 python benchmarks/ip_ab.py \
    2>benchmarks/results/ip_ab_${stamp}.log \
    | tee benchmarks/results/ip_ab_${stamp}.json
tail -3 benchmarks/results/ip_ab_${stamp}.log

echo "=== headline at larger query batches (v2 tier auto) ==="
for q in 128 256 64; do
    timeout 1500 env BENCH_QUERIES=$q BENCH_SKIP_NSLEAF=1 BENCH_ITERS=8 \
        BENCH_TIMEOUT=1400 python bench.py \
        2>benchmarks/results/bench_q${q}_${stamp}.log \
        | tee benchmarks/results/bench_q${q}_${stamp}.json
    tail -4 benchmarks/results/bench_q${q}_${stamp}.log
done

echo "=== inner-product A/B at 256 queries ==="
timeout 1800 env BENCH_QUERIES=256 python benchmarks/ip_ab.py \
    2>benchmarks/results/ip_ab_q256_${stamp}.log \
    | tee benchmarks/results/ip_ab_q256_${stamp}.json

echo "=== expansion stage profile ==="
timeout 1800 python benchmarks/expand_profile.py \
    2>benchmarks/results/expand_profile_${stamp}.log \
    | tee benchmarks/results/expand_profile_${stamp}.json

echo "=== BASELINE large configs (fixed kernels + native cuckoo build) ==="
timeout 3600 python benchmarks/baseline_suite.py --scale full \
    --suite dense_big \
    2>&1 | tee benchmarks/results/dense_big_${stamp}.json
timeout 3600 python benchmarks/baseline_suite.py --scale full \
    --suite sparse_big \
    2>&1 | tee benchmarks/results/sparse_big_${stamp}.json

echo "=== remaining reference sweeps (compile cache on) ==="
timeout 3600 python benchmarks/run_benchmarks.py \
    --suite dpf,dcf,mic,inner_product,int_mod_n --big \
    2>&1 | tee benchmarks/results/sweeps_${stamp}.json

echo "=== synthetic configs (2^32 and 2^128) ==="
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --num_iterations 3 \
    2>&1 | tee benchmarks/results/synthetic_${stamp}.json
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --only_nonzeros \
    --num_iterations 3 \
    2>&1 | tee benchmarks/results/only_nonzeros_${stamp}.json
timeout 3600 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 128 --log_num_nonzeros 20 --num_iterations 2 \
    2>&1 | tee benchmarks/results/synthetic128_${stamp}.json
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 128 --log_num_nonzeros 20 --only_nonzeros \
    --num_iterations 3 \
    2>&1 | tee benchmarks/results/only_nonzeros128_${stamp}.json

echo "followup done: benchmarks/results/*_${stamp}.*"
git add benchmarks/results >/dev/null 2>&1
git commit -q -m "Record TPU window results (automated capture)" \
    >/dev/null 2>&1 || true
echo "results committed"
