#!/usr/bin/env bash
# Round-4 capture queue, take 2. Lessons from window3's morning run are
# baked in: (a) the forced tail/tailhead A/B legs are GONE — a doomed
# fused-tail compile hangs tpu_compile_helper 20+ minutes and wedges the
# single-client tunnel for every following process (their data point is
# banked: hang == fail); (b) the auto headline now banks the XLA-levels
# candidate first and persists kernel verdicts, so one stage both warms
# the driver's compile cache and maps the kernel tiers; (c) the kernel
# probe isolates every case in a subprocess with a hard timeout, walk
# cases first. Stages commit as they go; TPU_WATCH_DEADLINE guards the
# driver's bench window.
set -u -o pipefail
cd "$(dirname "$0")/.."
mkdir -p benchmarks/results
stamp=$(date +%Y%m%d_%H%M%S)
rcs=""
fail=0

stage_fits() {
    local deadline=${TPU_WATCH_DEADLINE:-0}
    [ "$deadline" -le 0 ] && return 0
    local now margin=2700
    now=$(date +%s)
    if [ $((now + $1)) -ge $((deadline - margin)) ]; then
        echo "deadline margin: skipping remaining stages" >&2
        return 1
    fi
    return 0
}

commit_stage() {
    rcs="${rcs}${rcs:+ }$1=$2"
    [ "$2" -ne 0 ] && fail=1
    git add benchmarks/results >/dev/null 2>&1
    git commit -q -m "TPU window4 capture: stage $1 rc=$2 (${stamp})" \
        -- benchmarks/results >/dev/null 2>&1 || true
}

finish() {
    echo "window4 done (${stamp}): $rcs (fail=$fail)"
    git add benchmarks/results >/dev/null 2>&1
    git commit -q -m "TPU window4 capture (${stamp}): $rcs" \
        -- benchmarks/results >/dev/null 2>&1 || true
    exit $fail
}

# Tunnel probe: one trivial device op in a bounded subprocess (a wedged
# remote-compile helper hangs init indefinitely).
tunnel_ok() {
    timeout 90 python - <<'EOF' >/dev/null 2>&1
import jax
import jax.numpy as jnp
assert jax.devices()[0].platform != "cpu"
print(jnp.add(jnp.uint32(1), jnp.uint32(2)))
EOF
}

# Wait (up to ~50 min) for the tunnel before a stage: a mid-window
# outage or compile-wedge must PAUSE the queue, not cascade every
# remaining stage into an init-hang death (window3's fate).
wait_tunnel() {
    for i in $(seq 1 15); do
        tunnel_ok && return 0
        echo "tunnel not answering (attempt $i); sleeping 120s" >&2
        sleep 120
    done
    return 1
}

echo "=== 0. tunnel gate ==="
if ! wait_tunnel; then
    echo '{"gate": "tunnel never answered"}' \
        > benchmarks/results/window4_gate_${stamp}.json
    commit_stage gate 1
    finish
fi
echo "tunnel ok"

# Probe BEFORE the headline: each case is subprocess-bounded, and the
# failure verdicts it records protect the headline's in-process compile
# attempts from known-doomed (possibly wedging) programs.
stage_fits 3800 || finish
echo "=== 1. per-shape kernel probe (subprocess-isolated, walk first) ==="
timeout 3800 python benchmarks/level_kernel_probe.py \
    2>benchmarks/results/level_probe_${stamp}.log \
    | tee benchmarks/results/level_probe_${stamp}.json
commit_stage level_probe $?

{ wait_tunnel && stage_fits 1900; } || finish
echo "=== 2. headline (auto: banks planes_xla first, maps kernel tiers) ==="
timeout 1900 env BENCH_ITERS=16 BENCH_INIT_BUDGET=120 BENCH_TIMEOUT=1800 \
    BENCH_XPROF=benchmarks/results/xprof_w4_${stamp} python bench.py \
    2>benchmarks/results/bench_q128_${stamp}.log \
    | tee benchmarks/results/bench_q128_${stamp}.json
commit_stage headline $?
tail -5 benchmarks/results/bench_q128_${stamp}.log

echo "=== 3. batch sweep (q64 / q256 / q512, auto) ==="
# BENCH_NO_VET: the headline stage already vetted the kernel mode and
# persisted verdicts; re-vetting per sweep shape would burn a child
# compile per q against the same single-client tunnel.
for q in 64 256 512; do
    { wait_tunnel && stage_fits 1300; } || finish
    rm -f benchmarks/results/bench_extra.json
    timeout 1300 env BENCH_QUERIES=$q BENCH_ITERS=8 BENCH_NO_VET=1 \
        BENCH_INIT_BUDGET=120 BENCH_TIMEOUT=1200 python bench.py \
        2>benchmarks/results/bench_q${q}_${stamp}.log \
        | tee benchmarks/results/bench_q${q}_${stamp}.json
    rc=$?
    cp benchmarks/results/bench_extra.json \
        benchmarks/results/bench_extra_q${q}_${stamp}.json 2>/dev/null
    commit_stage q$q $rc
done

{ wait_tunnel && stage_fits 3000; } || finish
echo "=== 4. ns/leaf at log-domain 20 and 24 ==="
for ld in 20 24; do
    timeout 1500 env BENCH_ONLY_NSLEAF=1 BENCH_NSLEAF_LD=$ld \
        BENCH_INIT_BUDGET=120 BENCH_TIMEOUT=1400 python bench.py \
        2>benchmarks/results/bench_nsleaf_ld${ld}_${stamp}.log \
        | tee benchmarks/results/bench_nsleaf_ld${ld}_${stamp}.json
    commit_stage nsleaf_ld$ld $?
done

{ wait_tunnel && stage_fits 3600; } || finish
echo "=== 5. DCF/MIC reference sweeps on TPU ==="
timeout 3600 python benchmarks/run_benchmarks.py --suite dcf,mic --big \
    2>benchmarks/results/dcf_mic_tpu_${stamp}.log \
    | tee benchmarks/results/dcf_mic_tpu_${stamp}.jsonl
commit_stage dcf_mic $?

{ wait_tunnel && stage_fits 3600; } || finish
echo "=== 6. sparse PIR re-capture (native builder + batched queries) ==="
timeout 3600 python benchmarks/baseline_suite.py --scale full \
    --suite sparse_big \
    2>&1 | tee benchmarks/results/sparse_big_${stamp}.json
commit_stage sparse_big $?

{ wait_tunnel && stage_fits 2700; } || finish
echo "=== 6b. dense_big via the v2 gather-free serving path ==="
timeout 2700 env DPF_TPU_EXPANSION=v2 python benchmarks/baseline_suite.py \
    --scale full --suite dense_big \
    2>&1 | tee benchmarks/results/dense_big_v2_${stamp}.json
commit_stage dense_big_v2 $?

{ wait_tunnel && stage_fits 2700; } || finish
echo "=== 7. synthetic hierarchical (reference experiments configs) ==="
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --num_iterations 3 \
    2>&1 | tee benchmarks/results/synthetic_${stamp}.json
commit_stage synthetic32 $?
{ wait_tunnel && stage_fits 2700; } || finish
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --only_nonzeros \
    --num_iterations 3 \
    2>&1 | tee benchmarks/results/only_nonzeros_${stamp}.json
commit_stage direct32 $?
{ wait_tunnel && stage_fits 3600; } || finish
timeout 3600 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 128 --log_num_nonzeros 20 --num_iterations 2 \
    2>&1 | tee benchmarks/results/synthetic128_${stamp}.json
commit_stage synthetic128 $?

{ wait_tunnel && stage_fits 1800; } || finish
echo "=== 8. inner-product tile matrix ==="
timeout 1800 python benchmarks/ip_ab.py \
    2>benchmarks/results/ip_ab_${stamp}.log \
    | tee benchmarks/results/ip_ab_${stamp}.json
commit_stage ip_ab $?

{ wait_tunnel && stage_fits 3600; } || finish
echo "=== 9. remaining sweeps (dpf/inner_product/int_mod_n) ==="
timeout 3600 python benchmarks/run_benchmarks.py \
    --suite dpf,inner_product,int_mod_n --big \
    2>&1 | tee benchmarks/results/sweeps_${stamp}.json
commit_stage sweeps $?

{ wait_tunnel && stage_fits 1800; } || finish
echo "=== 10. kernel smoke (shape envelope) ==="
timeout 1800 python benchmarks/kernel_smoke.py \
    2>benchmarks/results/kernel_smoke_${stamp}.log \
    | tee benchmarks/results/kernel_smoke_${stamp}.json
commit_stage kernel_smoke $?

finish
