"""Rotation benchmark: snapshot staleness and q/s dip under live traffic.

Drives a two-party Leader/Helper pair (in-process transport, each side
with its own `SnapshotManager`) with closed-loop client threads, then
rotates the database through the `RotationCoordinator` several times
while the traffic keeps flowing. Two headline numbers come out:

- ``rotation_staleness_ms`` — the Helper-first/Leader-last flip window
  measured by the coordinator (worst rotation of the run). During this
  window the Leader refuses cross-generation pairs with a typed
  `SnapshotMismatch` and retries, so it is the interval in which
  queries can pay a retry, never the interval in which they can be
  wrong.
- ``rotation_qps_dip_pct`` — completed-query throughput in the window
  around the worst rotation, relative to the steady-state baseline.

Every completed response is compared bit-for-bit against the oracle of
*some single* generation (each generation's records differ from every
other generation at every byte, so a cross-generation XOR can match
nothing): the run fails if any response mixes generations.

Run directly (one JSON report on stdout, also written to
``benchmarks/results/rotation_bench.json``; appends the two records
above — both ``direction: lower`` — to the regression-gate history)::

    JAX_PLATFORMS=cpu python -m benchmarks.rotation_bench

Environment knobs: ROTATION_BENCH_RECORDS (default 512),
ROTATION_BENCH_RECORD_BYTES (32), ROTATION_BENCH_THREADS (4),
ROTATION_BENCH_ROTATIONS (3), ROTATION_BENCH_BASELINE_S (steady-state
measurement window, 1.5), ROTATION_BENCH_SETTLE_S (gap between
rotations, 0.5), ROTATION_BENCH_FLIP_DELAY_MS (arm a
``snapshot.flip`` delay failpoint to stretch the window, 0 = off),
ROTATION_BENCH_OUT (report path; empty string disables the file).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def _log(msg: str) -> None:
    print(f"[rotation-bench {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


# Per-generation XOR masks: any two differ, so any two generations'
# records differ at every byte and a torn (cross-generation) XOR can
# never equal either oracle.
_GEN_MASKS = [0x00, 0xA5, 0x3C, 0x5A, 0xC3, 0x69, 0x96, 0x0F, 0xF0]


def _records_for_generation(base, gen):
    mask = _GEN_MASKS[gen % len(_GEN_MASKS)]
    if mask == 0:
        return list(base)
    return [bytes(b ^ mask for b in r) for r in base]


def run_rotation_bench():
    """Build the two-party pair, run closed-loop traffic across several
    rotations, return the report dict (also written to
    ROTATION_BENCH_OUT unless empty)."""
    import numpy as np

    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.robustness import failpoints
    from distributed_point_functions_tpu.serving import (
        HelperSession,
        InProcessTransport,
        LeaderSession,
        RotationCoordinator,
        ServingConfig,
        SnapshotManager,
        SnapshotMismatch,
    )
    from distributed_point_functions_tpu.testing import encrypt_decrypt

    num_records = int(os.environ.get("ROTATION_BENCH_RECORDS", 512))
    record_bytes = int(os.environ.get("ROTATION_BENCH_RECORD_BYTES", 32))
    num_threads = int(os.environ.get("ROTATION_BENCH_THREADS", 4))
    num_rotations = int(os.environ.get("ROTATION_BENCH_ROTATIONS", 3))
    baseline_s = float(os.environ.get("ROTATION_BENCH_BASELINE_S", 1.5))
    settle_s = float(os.environ.get("ROTATION_BENCH_SETTLE_S", 0.5))
    flip_delay_ms = float(
        os.environ.get("ROTATION_BENCH_FLIP_DELAY_MS", 0.0)
    )

    _log(
        f"database: {num_records} x {record_bytes}B, {num_threads} "
        f"closed-loop threads, {num_rotations} rotations, baseline "
        f"{baseline_s}s, settle {settle_s}s, flip delay "
        f"{flip_delay_ms:.0f} ms"
    )

    rng = np.random.default_rng(12)
    base_records = [
        bytes(rng.integers(0, 256, record_bytes, dtype=np.uint8))
        for _ in range(num_records)
    ]
    oracles = {0: _records_for_generation(base_records, 0)}

    def build_full(records):
        builder = DenseDpfPirDatabase.Builder()
        for r in records:
            builder.insert(r)
        return builder.build()

    # Warm every jit bucket up front (sizes 1..max_batch). A cold XLA
    # compile mid-run would otherwise hold a batch in flight for longer
    # than the flip timeout and turn the rotation into a false failure.
    from distributed_point_functions_tpu.pir import messages
    from distributed_point_functions_tpu.pir.server import DenseDpfPirServer

    max_batch = 8
    _log("warming jit buckets")
    t0 = time.perf_counter()
    warm_server = DenseDpfPirServer.create_plain(build_full(oracles[0]))
    warm_keys = list(
        DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
        .create_plain_requests([0])[0]
        .plain_request.dpf_keys
    )
    b = 1
    while b <= max_batch:
        warm_server.handle_plain_request(
            messages.PirRequest(
                plain_request=messages.PlainRequest(dpf_keys=warm_keys * b)
            )
        )
        b *= 2
    _log(f"warmup done in {time.perf_counter() - t0:.1f}s")

    config = ServingConfig(max_batch_size=max_batch, max_wait_ms=2.0)
    helper = HelperSession(
        build_full(oracles[0]), encrypt_decrypt.decrypt, config
    )
    leader = LeaderSession(
        build_full(oracles[0]), InProcessTransport(helper.handle_wire),
        config,
    )
    leader_mgr = SnapshotManager(leader)
    helper_mgr = SnapshotManager(helper)
    coordinator = RotationCoordinator(leader_mgr, helper_mgr)

    client = DenseDpfPirClient.create(num_records, encrypt_decrypt.encrypt)
    probe_indices = [int(i) for i in rng.integers(0, num_records, 16)]

    lock = threading.Lock()
    stats = {"completed": 0, "torn": 0, "refusals": 0, "other_errors": 0}
    completion_times = []
    stop = threading.Event()

    def worker(tid):
        i = tid
        while not stop.is_set():
            idx = probe_indices[i % len(probe_indices)]
            i += num_threads
            try:
                request, state = client.create_request([idx])
                response = leader.handle_request(request)
                got = client.handle_response(response, state)[0]
                now = time.monotonic()
                with lock:
                    ok = any(
                        got == recs[idx] for recs in oracles.values()
                    )
                    stats["completed"] += 1
                    if not ok:
                        stats["torn"] += 1
                    completion_times.append(now)
            except SnapshotMismatch:
                # Typed refusal that out-lasted the leader's own retry
                # budget: counted, re-issued by the closed loop.
                with lock:
                    stats["refusals"] += 1
            except Exception:  # noqa: BLE001 - counted, bench continues
                with lock:
                    stats["other_errors"] += 1

    threads = [
        threading.Thread(target=worker, args=(t,), name=f"load-{t}")
        for t in range(num_threads)
    ]
    for t in threads:
        t.start()

    # Steady-state baseline window before the first rotation.
    t_base0 = time.monotonic()
    time.sleep(baseline_s)
    t_base1 = time.monotonic()

    if flip_delay_ms > 0:
        failpoints.default_failpoints().arm(
            "snapshot.flip", "delay",
            times=2 * num_rotations, delay_ms=flip_delay_ms,
        )

    rotations = []
    try:
        for _ in range(num_rotations):
            prev = leader.server.database
            next_gen = prev.generation + 1
            next_records = _records_for_generation(base_records, next_gen)
            with lock:
                oracles[next_gen] = next_records
            delta = DenseDpfPirDatabase.Builder()
            for i, r in enumerate(next_records):
                delta.update(i, r)
            leader_db = delta.build_from(prev)
            helper_delta = DenseDpfPirDatabase.Builder()
            for i, r in enumerate(next_records):
                helper_delta.update(i, r)
            helper_db = helper_delta.build_from(helper.server.database)

            t_rot0 = time.monotonic()
            report = coordinator.rotate(leader_db, helper_db)
            t_rot1 = time.monotonic()
            rotations.append({
                "to_generation": report["to_generation"],
                "staleness_ms": report["staleness_ms"],
                "rotate_wall_ms": round((t_rot1 - t_rot0) * 1e3, 3),
                "window": (t_rot0, t_rot1),
            })
            _log(
                f"rotation -> generation {report['to_generation']}: "
                f"staleness {report['staleness_ms']:.2f} ms, wall "
                f"{(t_rot1 - t_rot0) * 1e3:.2f} ms"
            )
            # Older generations can no longer answer; keeping only the
            # two live oracles keeps the torn-check meaningful.
            with lock:
                for g in list(oracles):
                    if g < next_gen - 1:
                        del oracles[g]
            time.sleep(settle_s)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        failpoints.default_failpoints().clear()

    def qps_in(t0, t1):
        with lock:
            n = sum(1 for t in completion_times if t0 <= t < t1)
        return n / max(t1 - t0, 1e-9)

    baseline_qps = qps_in(t_base0, t_base1)
    # Measure each rotation over a window at least as long as one
    # baseline-granularity slice so a handful of fast flips doesn't
    # produce a noisy zero-sample dip.
    dips = []
    for rot in rotations:
        t0, t1 = rot.pop("window")
        span = max(t1 - t0, 0.25)
        rot_qps = qps_in(t0, t0 + span)
        dip = max(0.0, (baseline_qps - rot_qps) / baseline_qps * 100.0) \
            if baseline_qps > 0 else 0.0
        rot["window_qps"] = round(rot_qps, 2)
        rot["qps_dip_pct"] = round(dip, 2)
        dips.append(dip)

    worst_staleness = max(
        (r["staleness_ms"] for r in rotations), default=0.0
    )
    worst_dip = max(dips, default=0.0)
    correctness_ok = (
        stats["torn"] == 0 and stats["other_errors"] == 0
        and len(rotations) == num_rotations
    )
    counters = leader.metrics.export()["counters"]
    report = {
        "config": {
            "num_records": num_records,
            "record_bytes": record_bytes,
            "threads": num_threads,
            "rotations": num_rotations,
            "baseline_s": baseline_s,
            "flip_delay_ms": flip_delay_ms,
        },
        "baseline_qps": round(baseline_qps, 2),
        "rotations": rotations,
        "rotation_staleness_ms": round(worst_staleness, 3),
        "rotation_qps_dip_pct": round(worst_dip, 2),
        "traffic": dict(stats),
        "correctness_ok": correctness_ok,
        "handshake_counters": {
            k: v for k, v in counters.items() if "snapshot" in k
        },
        "snapshots": leader_mgr.export(),
    }
    _log(
        f"baseline {baseline_qps:.1f} q/s; worst staleness "
        f"{worst_staleness:.2f} ms, worst dip {worst_dip:.1f}%; "
        f"{stats['completed']} completed, {stats['refusals']} refusals, "
        f"{stats['torn']} torn, correctness "
        f"{'ok' if correctness_ok else 'FAILED'}"
    )

    out = os.environ.get(
        "ROTATION_BENCH_OUT", "benchmarks/results/rotation_bench.json"
    )
    if out:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
        _log(f"report written to {out}")
    return report


def _append_history_records(report):
    """Two records for the regression gate — staleness and q/s dip,
    both explicit `direction: lower`. Best-effort like every history
    append."""
    try:
        from benchmarks.regression_gate import append_record, git_rev

        path = os.environ.get(
            "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
        )
        status = "ok" if report["correctness_ok"] else "error"
        rev = git_rev()
        device = os.environ.get("BENCH_PLATFORM", "cpu")
        append_record({
            "metric": "rotation_staleness_ms",
            "value": report["rotation_staleness_ms"],
            "unit": "ms",
            "direction": "lower",
            "vs_baseline": None,
            "status": status,
            "git_rev": rev,
            "device": device,
        }, path=path)
        append_record({
            "metric": "rotation_qps_dip_pct",
            "value": report["rotation_qps_dip_pct"],
            "unit": "percent",
            "direction": "lower",
            "vs_baseline": None,
            "status": status,
            "git_rev": rev,
            "device": device,
        }, path=path)
    except Exception as e:  # noqa: BLE001 - history must not break a bench
        _log(f"history append failed (non-fatal): {e}")


def main():
    report = run_rotation_bench()
    if os.environ.get("BENCH_HISTORY", "1") != "0":
        _append_history_records(report)
    print(json.dumps(report, indent=2))
    if not report["correctness_ok"]:
        raise SystemExit("rotation bench FAILED correctness")


if __name__ == "__main__":
    main()
