"""Realistic workload generation: Zipfian keys, diurnal + bursty
arrivals, mixed tenants — and the predictive-plane A/B bench.

Every scale claim before this module was made against uniform synthetic
load (ROADMAP item 5 calls it out). This module is the corrective, in
three parts:

* **Profiles** — named `WorkloadProfile`s composing a key-popularity
  distribution (uniform or Zipf with a declared ground-truth exponent),
  an arrival process (sinusoidal-diurnal base rate with Poisson bursts
  layered on), and a tenant mix with realistic per-tenant deadlines.
  The `uniform` profile reproduces `overload_bench`'s original request
  pool **byte-for-byte** (same numpy seed, same draw order) so the
  existing `serving_overload_goodput_queries_per_sec` history stays
  comparable across the retirement of the old inline generator.
* **Generators** — `key_pool()` (indices for a request pool),
  `arrival_times()` (one deterministic arrival schedule; what the
  sketch tests and the forecast smoke feed through a
  `WorkloadObservatory`), and `drive()` (the closed-loop multi-tenant
  load driver with bit-identity oracle checks, shared with
  `overload_bench`).
* **The A/B main** — `python -m benchmarks.workload_gen` runs the
  mixed profile at 2x saturation twice — predictive governor ON
  (forecaster over the live TSDB tightening tenant buckets) and OFF —
  and appends gated `goodput_2x_predictive_on` / `_off` history
  records, plus a *report-only* `workload_observatory_overhead` record
  (observatory attached vs detached at low concurrency, where the q/s
  delta is the hook's cost rather than GIL-contention noise; budget <2%
  of q/s, recorded with `status: report_only` so the regression gate
  never fails on it).

Environment knobs: WORKLOAD_BENCH_RECORDS (default 4096),
WORKLOAD_BENCH_RECORD_BYTES (256), WORKLOAD_BENCH_BASE_THREADS (48 —
the 1x saturation point; the A/B runs 2x), WORKLOAD_BENCH_SECONDS
(3.0 per leg), WORKLOAD_BENCH_BUDGET_MS (2000), WORKLOAD_BENCH_PROFILE
(mixed), WORKLOAD_BENCH_OUT (report path; empty disables the file).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple


def _log(msg: str) -> None:
    print(f"[workload-gen {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


@dataclasses.dataclass(frozen=True)
class TenantMix:
    """One tenant's slice of the offered load. `burst` bounds the
    token bucket's headroom (None = the admission default of a full
    second of tokens — effectively unmetered over short legs)."""

    name: str
    weight: float = 1.0  # share of requests
    deadline_ms: float = 1000.0
    rate_qps: Optional[float] = None  # admission policy rate (None = unmetered)
    burst: Optional[float] = None
    priority: int = 1


@dataclasses.dataclass(frozen=True)
class WorkloadProfile:
    """A named traffic shape. `zipf_s` is the ground-truth popularity
    exponent (None = uniform); the arrival process is a sinusoidal
    diurnal envelope (`diurnal_amplitude` of the base rate over
    `diurnal_period_s`) with Poisson bursts of `burst_size` extra
    back-to-back arrivals at `burst_rate_per_s`."""

    name: str
    zipf_s: Optional[float] = None
    diurnal_period_s: float = 0.0  # 0 = flat
    diurnal_amplitude: float = 0.0
    burst_rate_per_s: float = 0.0
    burst_size: int = 0
    tenants: Tuple[TenantMix, ...] = (TenantMix("default"),)
    pool_size: int = 32
    seed: int = 8


PROFILES: Dict[str, WorkloadProfile] = {
    # Byte-identical to the retired inline generator (seed 8, one
    # integers() draw of 32): history continuity for the overload gate.
    "uniform": WorkloadProfile(name="uniform"),
    "zipf": WorkloadProfile(name="zipf", zipf_s=1.1, pool_size=64),
    "diurnal": WorkloadProfile(
        name="diurnal", zipf_s=1.1, pool_size=64,
        diurnal_period_s=60.0, diurnal_amplitude=0.6,
    ),
    "bursty": WorkloadProfile(
        name="bursty", zipf_s=1.1, pool_size=64,
        burst_rate_per_s=2.0, burst_size=8,
    ),
    # Deadlines tight relative to queue wait at 2x and bucket bursts of
    # ~100 ms, so overload manifests as deadline burn unless admission
    # tightens — the regime the predictive governor exists for.
    "mixed": WorkloadProfile(
        name="mixed", zipf_s=1.1, pool_size=64,
        diurnal_period_s=60.0, diurnal_amplitude=0.5,
        burst_rate_per_s=1.0, burst_size=6,
        tenants=(
            TenantMix("interactive", weight=3.0, deadline_ms=60.0,
                      rate_qps=2000.0, burst=200.0, priority=2),
            TenantMix("standard", weight=2.0, deadline_ms=150.0,
                      rate_qps=1000.0, burst=100.0, priority=1),
            TenantMix("batch", weight=1.0, deadline_ms=500.0,
                      rate_qps=500.0, burst=50.0, priority=0),
        ),
    ),
}


def key_pool(
    profile: WorkloadProfile, num_records: int,
    size: Optional[int] = None,
) -> List[int]:
    """The request-pool key indices for `profile` over a `num_records`
    database. Uniform reproduces the legacy overload_bench pool
    exactly; Zipf draws rank-popularity `rank^-s` over a deterministic
    permutation of the record space (so hot keys are not clustered at
    index 0, which a sorted database layout could otherwise mask)."""
    import numpy as np

    rng = np.random.default_rng(profile.seed)
    n = size if size is not None else profile.pool_size
    if profile.zipf_s is None:
        return [int(i) for i in rng.integers(0, num_records, n)]
    ranks = np.arange(1, num_records + 1, dtype=np.float64)
    probs = ranks ** -float(profile.zipf_s)
    probs /= probs.sum()
    perm = rng.permutation(num_records)
    draws = rng.choice(num_records, size=n, p=probs)
    return [int(perm[r]) for r in draws]


def zipf_stream(
    profile: WorkloadProfile, num_records: int, n: int,
    seed: Optional[int] = None,
) -> List[int]:
    """`n` key draws from the profile's popularity distribution (the
    sketch-correctness tests feed these through the observatory and
    compare the fitted exponent to `profile.zipf_s`)."""
    import numpy as np

    rng = np.random.default_rng(profile.seed if seed is None else seed)
    if profile.zipf_s is None:
        return [int(i) for i in rng.integers(0, num_records, n)]
    ranks = np.arange(1, num_records + 1, dtype=np.float64)
    probs = ranks ** -float(profile.zipf_s)
    probs /= probs.sum()
    return [int(i) for i in rng.choice(num_records, size=n, p=probs)]


def arrival_times(
    profile: WorkloadProfile,
    duration_s: float,
    base_rate_qps: float,
    seed: int = 0,
) -> List[float]:
    """One deterministic arrival schedule: a non-homogeneous Poisson
    process whose instantaneous rate rides the diurnal envelope, with
    `burst_size` extra back-to-back arrivals injected at
    `burst_rate_per_s`. Sorted offsets in `[0, duration_s)`."""
    rng = random.Random(seed)
    out: List[float] = []
    t = 0.0
    while True:
        rate = base_rate_qps
        if profile.diurnal_period_s > 0:
            rate *= 1.0 + profile.diurnal_amplitude * math.sin(
                2.0 * math.pi * t / profile.diurnal_period_s
            )
        rate = max(1e-3, rate)
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        out.append(t)
        if (
            profile.burst_rate_per_s > 0
            and rng.random() < profile.burst_rate_per_s / rate
        ):
            out.extend([t] * profile.burst_size)
    out.sort()
    return out


def pick_tenant(profile: WorkloadProfile, rng: random.Random) -> TenantMix:
    total = sum(t.weight for t in profile.tenants)
    x = rng.random() * total
    for tenant in profile.tenants:
        x -= tenant.weight
        if x <= 0:
            return tenant
    return profile.tenants[-1]


def build_request_pool(num_records: int, indices: Sequence[int]):
    """(requests, oracle_answers, oracle_server) for `indices` — every
    driver below compares responses bit-for-bit against these."""
    from distributed_point_functions_tpu.pir import messages
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.server import DenseDpfPirServer

    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    requests = [
        client.create_plain_requests([int(i)])[0] for i in indices
    ]
    return requests, messages, DenseDpfPirServer


def build_database(num_records: int, record_bytes: int):
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )

    builder = DenseDpfPirDatabase.Builder()
    for i in range(num_records):
        builder.insert(
            (b"load-%06d:" % i).ljust(record_bytes, b".")[:record_bytes]
        )
    return builder.build()


def drive(
    session,
    requests,
    oracle,
    profile: WorkloadProfile,
    num_threads: int,
    duration_s: float,
    observatory=None,
    key_indices: Optional[Sequence[int]] = None,
    governor=None,
    governor_period_s: float = 0.25,
    sampler=None,
    seed: int = 0,
) -> dict:
    """Closed-loop multi-tenant load against `session` for
    `duration_s`: each worker draws a tenant from the profile mix,
    applies that tenant's deadline, retries sheds after the server's
    hint, and bit-checks every completed response against `oracle`.

    `observatory` (with `key_indices`, the pool's public indices — the
    generator legitimately knows them) feeds the workload plane;
    `sampler` gets a `sample_once()` and `governor` an `update()` every
    `governor_period_s` from a pacer thread, so the predictive loop
    runs exactly as it would in production. Returns the point stats
    (same shape as overload_bench's ladder points)."""
    from distributed_point_functions_tpu.serving import Overloaded

    lock = threading.Lock()
    stats = {
        "completed": 0, "shed": 0, "deadline_missed": 0,
        "mismatches": 0, "other_errors": 0,
    }
    per_tenant: Dict[str, int] = {}
    stop = time.monotonic() + duration_s

    def worker(tid):
        rng = random.Random((seed << 8) | tid)
        i = tid
        while time.monotonic() < stop:
            request, want = requests[i % len(requests)], (
                oracle[i % len(requests)]
            )
            index = (
                key_indices[i % len(requests)]
                if key_indices is not None else None
            )
            i += num_threads
            tenant = pick_tenant(profile, rng)
            deadline_s = tenant.deadline_ms / 1e3
            if observatory is not None:
                observatory.observe(
                    num_keys=len(request.plain_request.dpf_keys),
                    tenant=tenant.name,
                    key_indices=[index] if index is not None else None,
                    deadline_s=deadline_s,
                )
            try:
                response = session.handle_request(
                    request,
                    deadline=time.monotonic() + deadline_s,
                    tenant=tenant.name,
                )
                ok = (
                    response.dpf_pir_response.masked_response == want
                )
                with lock:
                    stats["completed"] += 1
                    per_tenant[tenant.name] = (
                        per_tenant.get(tenant.name, 0) + 1
                    )
                    if not ok:
                        stats["mismatches"] += 1
            except Overloaded as e:
                with lock:
                    stats["shed"] += 1
                time.sleep(min(max(e.retry_after_s, 1e-3), 0.05))
            except TimeoutError:
                with lock:
                    stats["deadline_missed"] += 1
            except Exception:  # noqa: BLE001 - counted, bench continues
                with lock:
                    stats["other_errors"] += 1

    def pacer():
        while time.monotonic() < stop:
            if sampler is not None:
                sampler.sample_once()
            if governor is not None:
                governor.update()
            time.sleep(governor_period_s)

    threads = [
        threading.Thread(target=worker, args=(t,), name=f"load-{t}")
        for t in range(num_threads)
    ]
    if sampler is not None or governor is not None:
        threads.append(
            threading.Thread(target=pacer, name="predictive-pacer")
        )
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    stats["threads"] = num_threads
    stats["profile"] = profile.name
    stats["wall_s"] = round(wall, 3)
    stats["goodput_qps"] = round(stats["completed"] / wall, 2)
    offered = stats["completed"] + stats["shed"] + stats["deadline_missed"]
    stats["offered_qps"] = round(offered / wall, 2)
    stats["shed_ratio"] = round(
        stats["shed"] / offered, 4) if offered else 0.0
    stats["per_tenant"] = dict(sorted(per_tenant.items()))
    return stats


def _make_session(database, budget_ms: float, profile: WorkloadProfile,
                  max_batch: int):
    from distributed_point_functions_tpu.capacity import TenantPolicy
    from distributed_point_functions_tpu.serving import (
        PlainSession,
        ServingConfig,
    )

    config = ServingConfig(
        max_batch_size=max_batch,
        max_wait_ms=2.0,
        admission_enabled=True,
        admission_queue_budget_ms=budget_ms,
    )
    session = PlainSession(database, config)
    for tenant in profile.tenants:
        session.set_tenant(
            tenant.name,
            TenantPolicy(
                weight=tenant.weight,
                rate_qps=tenant.rate_qps,
                burst=tenant.burst,
                priority=tenant.priority,
            ),
        )
    return session


def _depth_source(session):
    """Extra-source callable exposing the admission controller's
    outstanding queue-cost estimate as a TSDB series. In a closed loop
    the arrival rate saturates at capacity for *any* concurrency, so
    queue depth — not rate — is the signal that separates 1x from 2x."""
    admission = session.admission
    return lambda: {
        "admission.outstanding_ms": float(
            admission.export()["outstanding_ms"]
        )
    }


def _make_sampler(session, observatory):
    """Sampler over a private store. Registry sampling stays off
    (registry=None): the session registry has far more series than a
    small store holds, and rings are granted first-come — the
    observatory/admission series must not lose that race."""
    from distributed_point_functions_tpu.observability import (
        MetricsSampler,
        TimeSeriesStore,
    )

    extra = [_depth_source(session)]
    if observatory is not None:
        extra.append(observatory.gauge_source)
    store = TimeSeriesStore(tiers=((0.2, 300),), max_series=32)
    return MetricsSampler(
        store=store, registry=None, period_s=0.2, extra_sources=extra
    )


def _mean_depth_ms(sampler, window_s: float = 30.0) -> Optional[float]:
    """Mean of the sampled queue-depth series over the trailing
    window (the measured 1x operating point)."""
    now = time.monotonic()
    _, grid = sampler.store.query_range(
        "admission.outstanding_ms", now - window_s, now, now=now
    )
    values = [v for _, v in grid if v is not None]
    return sum(values) / len(values) if values else None


def _predictive_plane(session, sampler, queue_ceiling_ms: float):
    """Forecaster + governor: the admission queue-depth series is
    forecast against `queue_ceiling_ms` (calibrated between the 1x and
    2x operating points); as predicted time-to-breach shrinks, the
    governor tightens every tenant's token-bucket refill.

    Fast-reacting settings: the bench legs are seconds long, so the
    forecast must become actionable after ~1s of samples (production
    deployments run the 10s tier and minutes-scale horizons)."""
    from distributed_point_functions_tpu.capacity import PredictiveGovernor
    from distributed_point_functions_tpu.observability import Forecaster

    forecaster = Forecaster(
        sampler.store,
        window_s=10.0,
        horizon_s=30.0,
        page_horizon_s=10.0,
        min_points=6,
        registry=session.metrics,
    )
    forecaster.watch(
        "admission.outstanding_ms",
        ceiling=queue_ceiling_ms,
        label="admission queue depth",
    )
    governor = PredictiveGovernor(
        session.admission,
        lambda: forecaster.min_time_to_breach_s(),
        horizon_s=8.0,
        floor=0.45,
        metrics=session.metrics,
    )
    return forecaster, governor


def run_ab_bench() -> dict:
    """The predictive-plane A/B: mixed profile at 1x (overhead leg)
    and 2x (governor on vs off). Returns the report dict."""
    num_records = int(os.environ.get("WORKLOAD_BENCH_RECORDS", 4096))
    record_bytes = int(os.environ.get("WORKLOAD_BENCH_RECORD_BYTES", 256))
    base_threads = int(os.environ.get("WORKLOAD_BENCH_BASE_THREADS", 48))
    duration_s = float(os.environ.get("WORKLOAD_BENCH_SECONDS", 3.0))
    profile = PROFILES[os.environ.get("WORKLOAD_BENCH_PROFILE", "mixed")]
    # Deliberately loose queue budget: the A/B isolates the predictive
    # governor's contribution, not the reactive queue-cost shedding.
    budget_ms = float(os.environ.get("WORKLOAD_BENCH_BUDGET_MS", 2000.0))

    from distributed_point_functions_tpu.observability import (
        WorkloadObservatory,
    )

    _log(
        f"profile {profile.name}: {num_records} x {record_bytes}B, "
        f"base {base_threads} threads, {duration_s}s/leg"
    )
    database = build_database(num_records, record_bytes)
    indices = key_pool(profile, num_records)
    requests, messages, server_cls = build_request_pool(
        num_records, indices
    )
    oracle_server = server_cls.create_plain(database)
    oracle = [
        oracle_server.handle_plain_request(r).dpf_pir_response
        .masked_response
        for r in requests
    ]
    max_batch = 16
    b = 1
    while b <= max_batch:
        oracle_server.handle_plain_request(
            messages.PirRequest(
                plain_request=messages.PlainRequest(
                    dpf_keys=list(requests[0].plain_request.dpf_keys) * b
                )
            )
        )
        b *= 2

    warmup_s = float(os.environ.get("WORKLOAD_BENCH_WARMUP_S", 1.0))
    legs: Dict[str, dict] = {}

    def run_leg(label, threads, leg_profile, *, with_observatory,
                with_sampler=False, queue_ceiling_ms=None):
        with _make_session(database, budget_ms, leg_profile, max_batch) as s:
            observatory = key_idx = None
            if with_observatory:
                observatory = WorkloadObservatory()
                s.attach_workload(observatory)
                key_idx = indices
            sampler = governor = None
            if with_sampler or queue_ceiling_ms is not None:
                sampler = _make_sampler(s, observatory)
            if queue_ceiling_ms is not None:
                _forecaster, governor = _predictive_plane(
                    s, sampler, queue_ceiling_ms
                )
            if warmup_s > 0:  # pay compile + allocator churn off-ledger
                drive(s, requests, oracle, leg_profile, threads, warmup_s,
                      observatory=observatory, key_indices=key_idx,
                      governor=governor, sampler=sampler)
            legs[label] = drive(
                s, requests, oracle, leg_profile, threads, duration_s,
                observatory=observatory, key_indices=key_idx,
                governor=governor, sampler=sampler,
            )
            if with_observatory:
                legs[label]["workload"] = observatory.export()
            legs[label]["admission"] = s.admission.export()
            if sampler is not None:
                depth = _mean_depth_ms(sampler, window_s=duration_s)
                if depth is not None:
                    legs[label]["mean_queue_depth_ms"] = round(depth, 3)
        _log(f"{label}: {legs[label]['goodput_qps']:.1f} q/s")
        return legs[label]

    # -- saturation leg: measure the 1x operating point (throughput and
    # admission queue depth) with deadlines and buckets out of the way --
    relaxed = dataclasses.replace(profile, tenants=tuple(
        dataclasses.replace(
            t, deadline_ms=30_000.0, rate_qps=None, burst=None
        )
        for t in profile.tenants
    ))
    sat_leg = run_leg(
        "saturation_1x", base_threads, relaxed,
        with_observatory=False, with_sampler=True,
    )
    saturation = max(sat_leg["goodput_qps"], 1.0)
    queue_1x_ms = sat_leg.get("mean_queue_depth_ms")

    # -- overhead legs: observatory attached vs detached, low concurrency --
    # (measures the hook's per-request cost; at full saturation every
    # q/s delta is GIL-contention noise, not observatory cost)
    overhead_threads = min(base_threads, 8)
    run_leg("observatory_off", overhead_threads, relaxed,
            with_observatory=False)
    run_leg("observatory_on", overhead_threads, relaxed,
            with_observatory=True)

    qps_off = legs["observatory_off"]["goodput_qps"]
    qps_on = legs["observatory_on"]["goodput_qps"]
    overhead_pct = (
        round((qps_off - qps_on) / qps_off * 100.0, 2) if qps_off else 0.0
    )

    # -- A/B legs: 2x overload, predictive governor on vs off ---------------
    # Deadlines derive from the *measured* saturation so the off leg
    # burns on any machine: at 2x the closed-loop queue wait is
    # 2*threads/saturation, and the tightest tenant's deadline lands at
    # 75% of that — doomed unless admission keeps the queue short.
    # Tenant rates scale to 1.75x saturation split by weight, so the
    # governor's floor (0.45) throttles admitted load to ~0.8x capacity.
    queue_2x_ms = 2.0 * base_threads / saturation * 1e3
    min_dl = min(t.deadline_ms for t in profile.tenants)
    weight_sum = sum(t.weight for t in profile.tenants)
    ab_profile = dataclasses.replace(profile, tenants=tuple(
        dataclasses.replace(
            t,
            deadline_ms=0.75 * queue_2x_ms * (t.deadline_ms / min_dl),
            rate_qps=1.75 * saturation * (t.weight / weight_sum),
            burst=max(8.0, 0.0875 * saturation * (t.weight / weight_sum)),
        )
        for t in profile.tenants
    ))
    ceiling_ms = 1.3 * queue_1x_ms if queue_1x_ms else 0.5 * queue_2x_ms
    run_leg("predictive_off", base_threads * 2, ab_profile,
            with_observatory=True, with_sampler=True)
    run_leg("predictive_on", base_threads * 2, ab_profile,
            with_observatory=True, queue_ceiling_ms=ceiling_ms)

    correctness_ok = all(
        leg["mismatches"] == 0 and leg["other_errors"] == 0
        for leg in legs.values()
    )
    report = {
        "config": {
            "profile": profile.name,
            "num_records": num_records,
            "record_bytes": record_bytes,
            "base_threads": base_threads,
            "seconds_per_leg": duration_s,
        },
        "legs": legs,
        "goodput_2x_predictive_on": legs["predictive_on"]["goodput_qps"],
        "goodput_2x_predictive_off": legs["predictive_off"]["goodput_qps"],
        "workload_observatory_overhead": {
            "qps_off": qps_off,
            "qps_on": qps_on,
            "overhead_pct": overhead_pct,
            "budget_pct": 2.0,
            "within_budget": overhead_pct <= 2.0,
        },
        "correctness_ok": correctness_ok,
    }
    _log(
        f"predictive on/off at 2x: "
        f"{report['goodput_2x_predictive_on']:.1f} / "
        f"{report['goodput_2x_predictive_off']:.1f} q/s; observatory "
        f"overhead {overhead_pct:+.2f}% (budget 2%); correctness "
        f"{'ok' if correctness_ok else 'FAILED'}"
    )

    out = os.environ.get(
        "WORKLOAD_BENCH_OUT", "benchmarks/results/workload_bench.json"
    )
    if out:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
        _log(f"report written to {out}")
    return report


def _append_history_records(report) -> None:
    """Two gated goodput records (direction higher) plus the
    report-only overhead record. The overhead record carries
    `status: report_only`, which the regression gate classifies as
    infra (never a failure) — it is tracked, not enforced."""
    try:
        from benchmarks.regression_gate import append_record, git_rev

        path = os.environ.get(
            "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
        )
        common = {
            "unit": "queries/s",
            "git_rev": git_rev(),
            "device": os.environ.get("BENCH_PLATFORM", "cpu"),
        }
        for metric, key in (
            ("goodput_2x_predictive_on", "goodput_2x_predictive_on"),
            ("goodput_2x_predictive_off", "goodput_2x_predictive_off"),
        ):
            append_record({
                "metric": metric,
                "value": report[key],
                "direction": "higher",
                "status": "ok" if report["correctness_ok"] else "error",
                **common,
            }, path=path)
        overhead = report["workload_observatory_overhead"]
        append_record({
            "metric": "workload_observatory_overhead",
            "value": overhead["overhead_pct"],
            "unit": "percent",
            "direction": "lower",
            "status": "report_only",
            "error": (
                "report-only observability overhead record "
                "(budget 2%; never gates)"
            ),
            "within_budget": overhead["within_budget"],
            **{k: v for k, v in common.items() if k != "unit"},
        }, path=path)
    except Exception as e:  # noqa: BLE001 - history must not break a bench
        _log(f"history append failed (non-fatal): {e}")


def main():
    report = run_ab_bench()
    if os.environ.get("BENCH_HISTORY", "1") != "0":
        _append_history_records(report)
    print(json.dumps(report, indent=2))
    if not report["correctness_ok"]:
        raise SystemExit("workload bench FAILED correctness")


if __name__ == "__main__":
    main()
