"""Fleet benchmark: front-door throughput and fleet-wide rotation
staleness over an in-process N-replica fleet.

Builds N two-party Leader/Helper replicas (each side with its own
`SnapshotManager`), registers them in a `ReplicaSet`, and drives
closed-loop tenants through the `FleetRouter` front door — each
tenant sticks to one replica and checks every reconstruction
bit-for-bit against the oracle of *some single* generation
(generations differ at every byte, so a torn XOR matches nothing; a
torn pair inside a replica is refused as `SnapshotMismatch`, never
answered). Mid-run the `FleetRotationCoordinator` rotates the whole
fleet through quorum several times. Two headline numbers:

- ``fleet_qps_3rep`` — steady-state completed reconstructions/second
  through the front door (direction: higher).
- ``fleet_rotation_staleness_ms`` — the worst per-replica
  helper-first/leader-last flip window across all fleet rotations
  (direction: lower).
- ``fleet_routable_replicas_min`` — the smallest routable-replica
  count the fleet telemetry plane observed once attached (through the
  rotations; direction: higher — a clean run never dips below N).

Plus a report-only A/B leg, ``fleet_telemetry_overhead``: the q/s
window is measured once with no fleet telemetry attached and again
with every replica scoped (`FleetTelemetry.scope` per replica, a
sampler thread driving `sample()` continuously). The overhead budget
is <2% of front-door q/s; the report flags ``overhead_within_budget``
but the gate does not block on it (two short windows on a shared CI
box are too noisy to gate — the number is for trend eyes).

Run directly (JSON report on stdout, also written to
``benchmarks/results/fleet_bench.json``; appends both records to the
regression-gate history)::

    JAX_PLATFORMS=cpu python -m benchmarks.fleet_bench

Environment knobs: FLEET_BENCH_RECORDS (default 256),
FLEET_BENCH_RECORD_BYTES (32), FLEET_BENCH_REPLICAS (3),
FLEET_BENCH_THREADS (4), FLEET_BENCH_ROTATIONS (2),
FLEET_BENCH_BASELINE_S (1.5), FLEET_BENCH_SETTLE_S (0.5),
FLEET_BENCH_SAMPLE_PERIOD_S (1.0, the telemetry sampling cadence in
the A/B leg), FLEET_BENCH_OUT (report path; empty string disables the
file).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def _log(msg: str) -> None:
    print(f"[fleet-bench {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


# Per-generation XOR masks: any two generations differ at every byte.
_GEN_MASKS = [0x00, 0xA5, 0x3C, 0x5A, 0xC3, 0x69, 0x96, 0x0F, 0xF0]


def _records_for_generation(base, gen):
    mask = _GEN_MASKS[gen % len(_GEN_MASKS)]
    if mask == 0:
        return list(base)
    return [bytes(b ^ mask for b in r) for r in base]


def run_fleet_bench():
    import numpy as np

    from distributed_point_functions_tpu.fleet import (
        FleetRotationCoordinator,
        FleetRouter,
        FleetTelemetry,
        Replica,
        ReplicaSet,
    )
    from distributed_point_functions_tpu.serving.metrics import (
        MetricsRegistry,
    )
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.serving import (
        HelperSession,
        InProcessTransport,
        LeaderSession,
        ServingConfig,
        SnapshotManager,
        SnapshotMismatch,
    )
    from distributed_point_functions_tpu.testing import encrypt_decrypt

    num_records = int(os.environ.get("FLEET_BENCH_RECORDS", 256))
    record_bytes = int(os.environ.get("FLEET_BENCH_RECORD_BYTES", 32))
    num_replicas = int(os.environ.get("FLEET_BENCH_REPLICAS", 3))
    num_threads = int(os.environ.get("FLEET_BENCH_THREADS", 4))
    num_rotations = int(os.environ.get("FLEET_BENCH_ROTATIONS", 2))
    baseline_s = float(os.environ.get("FLEET_BENCH_BASELINE_S", 1.5))
    settle_s = float(os.environ.get("FLEET_BENCH_SETTLE_S", 0.5))
    sample_period_s = float(
        os.environ.get("FLEET_BENCH_SAMPLE_PERIOD_S", 1.0)
    )

    _log(
        f"fleet: {num_replicas} replicas x ({num_records} x "
        f"{record_bytes}B), {num_threads} closed-loop tenants, "
        f"{num_rotations} quorum rotations"
    )

    rng = np.random.default_rng(21)
    base_records = [
        bytes(rng.integers(0, 256, record_bytes, dtype=np.uint8))
        for _ in range(num_records)
    ]
    oracles = {0: _records_for_generation(base_records, 0)}

    def build_full(records):
        builder = DenseDpfPirDatabase.Builder()
        for r in records:
            builder.insert(r)
        return builder.build()

    config = ServingConfig(max_batch_size=8, max_wait_ms=2.0)
    replica_set = ReplicaSet()
    replicas = []
    for i in range(num_replicas):
        helper = HelperSession(
            build_full(oracles[0]), encrypt_decrypt.decrypt, config
        )
        leader = LeaderSession(
            build_full(oracles[0]),
            InProcessTransport(helper.handle_wire),
            config,
        )
        replica = Replica(
            f"r{i}",
            leader,
            helper,
            leader_snapshots=SnapshotManager(leader),
            helper_snapshots=SnapshotManager(helper),
        )
        replicas.append(replica_set.add(replica))
    fleet_registry = MetricsRegistry()
    router = FleetRouter(replica_set, metrics=fleet_registry)
    coordinator = FleetRotationCoordinator(replica_set)

    client = DenseDpfPirClient.create(num_records, encrypt_decrypt.encrypt)
    probe_indices = [int(i) for i in rng.integers(0, num_records, 16)]

    # Warm every jit bucket (batch sizes 1..max) up front: the jit
    # cache is keyed by shape, so one throwaway server warms the whole
    # fleet. A cold compile mid-window would zero the baseline or hold
    # a pin past the flip timeout.
    from distributed_point_functions_tpu.pir import messages
    from distributed_point_functions_tpu.pir.server import DenseDpfPirServer

    _log("warming jit buckets")
    t0 = time.perf_counter()
    warm_server = DenseDpfPirServer.create_plain(build_full(oracles[0]))
    warm_client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    warm_keys = list(
        warm_client.create_plain_requests([0])[0].plain_request.dpf_keys
    )
    b = 1
    while b <= 8:
        warm_server.handle_plain_request(
            messages.PirRequest(
                plain_request=messages.PlainRequest(dpf_keys=warm_keys * b)
            )
        )
        b *= 2
    warm_request, warm_state = client.create_request([0])
    for r in replicas:
        client.handle_response(
            r.leader.handle_request(warm_request), warm_state
        )
    _log(f"warmup done in {time.perf_counter() - t0:.1f}s")

    lock = threading.Lock()
    stats = {
        "completed": 0, "torn": 0, "sheds": 0, "refusals": 0,
        "other_errors": 0,
    }
    completion_times = []
    stop = threading.Event()

    def worker(tid):
        from distributed_point_functions_tpu.serving.batcher import (
            Overloaded,
        )

        tenant = f"tenant-{tid}"
        i = tid
        while not stop.is_set():
            idx = probe_indices[i % len(probe_indices)]
            i += num_threads
            try:
                # Front door picks the replica (sticky per tenant); the
                # Leader pairs with its own Helper at ONE generation —
                # a torn pair is refused as `SnapshotMismatch`, never
                # answered.
                replica = router.pick(tenant)
                request, state = client.create_request([idx])
                response = replica.leader.handle_request(request)
                got = client.handle_response(response, state)[0]
                now = time.monotonic()
                with lock:
                    ok = any(
                        got == recs[idx] for recs in oracles.values()
                    )
                    stats["completed"] += 1
                    if not ok:
                        stats["torn"] += 1
                    completion_times.append(now)
            except Overloaded:
                with lock:
                    stats["sheds"] += 1
                time.sleep(0.005)
            except SnapshotMismatch:
                # Typed refusal that out-lasted the leader's own retry
                # budget: counted, re-issued by the closed loop.
                with lock:
                    stats["refusals"] += 1
            except Exception:  # noqa: BLE001 - counted, bench continues
                with lock:
                    stats["other_errors"] += 1

    threads = [
        threading.Thread(target=worker, args=(t,), name=f"tenant-{t}")
        for t in range(num_threads)
    ]
    for t in threads:
        t.start()

    # Window A: steady state with NO fleet telemetry attached.
    t_base0 = time.monotonic()
    time.sleep(baseline_s)
    t_base1 = time.monotonic()

    # A/B leg: scope every replica into the fleet telemetry plane and
    # drive `sample()` continuously from a sampler thread, then measure
    # the same window again. The delta is the plane's whole cost:
    # scoped journals, per-registry samplers, derived-gauge refresh,
    # SLO grading.
    _log("attaching fleet telemetry plane for the A/B window")
    telemetry = FleetTelemetry(
        replica_set, router=router, registry=fleet_registry
    )
    for r in replicas:
        telemetry.scope(r)
    coordinator.set_telemetry(telemetry)
    min_routable = [None]
    sample_stop = threading.Event()

    def sample_loop():
        while not sample_stop.is_set():
            try:
                routable = telemetry.sample()["routable"]
                if min_routable[0] is None or routable < min_routable[0]:
                    min_routable[0] = routable
            except Exception:  # noqa: BLE001 - sampling must not kill bench
                pass
            sample_stop.wait(sample_period_s)

    sampler_thread = threading.Thread(
        target=sample_loop, name="fleet-sampler", daemon=True
    )
    sampler_thread.start()

    # Window B: same duration, telemetry plane on.
    t_ab0 = time.monotonic()
    time.sleep(baseline_s)
    t_ab1 = time.monotonic()

    rotations = []
    try:
        for _ in range(num_rotations):
            next_gen = replicas[0].serving_generation() + 1
            next_records = _records_for_generation(base_records, next_gen)
            with lock:
                oracles[next_gen] = next_records

            def next_dbs(replica):
                def delta_from(db):
                    builder = DenseDpfPirDatabase.Builder()
                    for i, r in enumerate(next_records):
                        builder.update(i, r)
                    return builder.build_from(db)

                return (
                    delta_from(replica.leader.server.database),
                    delta_from(replica.helper.server.database),
                )

            t_rot0 = time.monotonic()
            report = coordinator.rotate(next_dbs)
            t_rot1 = time.monotonic()
            rotations.append({
                "to_generation": report["to_generation"],
                "staleness_ms": report["staleness_ms"],
                "laggards": report["laggards"],
                "rotate_wall_ms": round((t_rot1 - t_rot0) * 1e3, 3),
            })
            _log(
                f"fleet rotation -> generation {report['to_generation']}"
                f": worst staleness {report['staleness_ms']:.2f} ms, "
                f"wall {(t_rot1 - t_rot0) * 1e3:.2f} ms, laggards "
                f"{report['laggards'] or 'none'}"
            )
            with lock:
                for g in list(oracles):
                    if g < next_gen - 1:
                        del oracles[g]
            time.sleep(settle_s)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        # One last sample so the post-rotation fleet state (all
        # replicas back to serving) is in the min-routable record, then
        # stop the sampler.
        try:
            routable = telemetry.sample()["routable"]
            if min_routable[0] is None or routable < min_routable[0]:
                min_routable[0] = routable
        except Exception:  # noqa: BLE001
            pass
        sample_stop.set()
        sampler_thread.join(timeout=10.0)

    def qps_in(t0, t1):
        with lock:
            n = sum(1 for t in completion_times if t0 <= t < t1)
        return n / max(t1 - t0, 1e-9)

    baseline_qps = qps_in(t_base0, t_base1)
    telemetry_qps = qps_in(t_ab0, t_ab1)
    overhead_pct = (
        round((baseline_qps - telemetry_qps) / baseline_qps * 100.0, 2)
        if baseline_qps > 0
        else None
    )
    worst_staleness = max(
        (r["staleness_ms"] for r in rotations), default=0.0
    )
    correctness_ok = (
        stats["torn"] == 0 and stats["other_errors"] == 0
        and len(rotations) == num_rotations
        and all(not r["laggards"] for r in rotations)
    )
    report = {
        "config": {
            "num_records": num_records,
            "record_bytes": record_bytes,
            "replicas": num_replicas,
            "threads": num_threads,
            "rotations": num_rotations,
            "baseline_s": baseline_s,
        },
        "fleet_qps": round(baseline_qps, 2),
        "rotations": rotations,
        "fleet_rotation_staleness_ms": round(worst_staleness, 3),
        # Report-only A/B leg: the cost of the whole telemetry plane.
        "fleet_telemetry_overhead": {
            "qps_off": round(baseline_qps, 2),
            "qps_on": round(telemetry_qps, 2),
            "overhead_pct": overhead_pct,
            "budget_pct": 2.0,
            "within_budget": (
                overhead_pct is not None and overhead_pct < 2.0
            ),
            "samples": telemetry.export()["samples"],
            "series_count": telemetry.export()["timeseries"][
                "series_count"
            ],
        },
        "fleet_routable_replicas_min": min_routable[0],
        "traffic": dict(stats),
        "correctness_ok": correctness_ok,
        "router": router.export(),
        "fleet": replica_set.export(),
        "rotation_coordinator": coordinator.export(),
    }
    _log(
        f"front door {baseline_qps:.1f} q/s across {num_replicas} "
        f"replicas; worst rotation staleness {worst_staleness:.2f} ms; "
        f"{stats['completed']} completed, {stats['sheds']} sheds, "
        f"{stats['refusals']} refusals, {stats['torn']} torn, "
        f"correctness {'ok' if correctness_ok else 'FAILED'}"
    )
    _log(
        f"telemetry A/B: {baseline_qps:.1f} q/s off -> "
        f"{telemetry_qps:.1f} q/s on ({overhead_pct}% overhead, "
        f"budget 2%); min routable {min_routable[0]}"
    )

    out = os.environ.get(
        "FLEET_BENCH_OUT", "benchmarks/results/fleet_bench.json"
    )
    if out:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
        _log(f"report written to {out}")

    for r in replicas:
        r.leader.close()
        if r.helper is not None:
            r.helper.close()
    return report


def _append_history_records(report):
    """Records for the regression gate: front-door throughput
    (higher), fleet rotation staleness (lower), and the minimum
    routable-replica count the telemetry plane observed (higher).
    Best-effort like every history append."""
    try:
        from benchmarks.regression_gate import append_record, git_rev

        path = os.environ.get(
            "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
        )
        status = "ok" if report["correctness_ok"] else "error"
        rev = git_rev()
        device = os.environ.get("BENCH_PLATFORM", "cpu")
        append_record({
            "metric": "fleet_qps_3rep",
            "value": report["fleet_qps"],
            "unit": "queries_per_sec",
            "direction": "higher",
            "vs_baseline": None,
            "status": status,
            "git_rev": rev,
            "device": device,
        }, path=path)
        append_record({
            "metric": "fleet_rotation_staleness_ms",
            "value": report["fleet_rotation_staleness_ms"],
            "unit": "ms",
            "direction": "lower",
            "vs_baseline": None,
            "status": status,
            "git_rev": rev,
            "device": device,
        }, path=path)
        # Gated: the telemetry plane must keep seeing a fully routable
        # fleet through rotations (healthy() counts staging, so a clean
        # rotation never dips this).
        if report.get("fleet_routable_replicas_min") is not None:
            append_record({
                "metric": "fleet_routable_replicas_min",
                "value": float(report["fleet_routable_replicas_min"]),
                "unit": "replicas",
                "direction": "higher",
                "vs_baseline": None,
                "status": status,
                "git_rev": rev,
                "device": device,
            }, path=path)
    except Exception as e:  # noqa: BLE001 - history must not break a bench
        _log(f"history append failed (non-fatal): {e}")


def main():
    report = run_fleet_bench()
    if os.environ.get("BENCH_HISTORY", "1") != "0":
        _append_history_records(report)
    print(json.dumps(report, indent=2))
    if not report["correctness_ok"]:
        raise SystemExit("fleet bench FAILED correctness")


if __name__ == "__main__":
    main()
