"""Overload benchmark: goodput vs offered load through admission control.

Drives a `serving.PlainSession` with cost-aware admission enabled
(`capacity/admission.py`) through an offered-load ladder — closed-loop
client threads at 1x the measured saturation concurrency, then the
over-capacity points — and reports **goodput**: requests completed
within their deadline, per second. The claim under test is the PR 8
overload contract: past saturation the excess is shed at admission with
a `RetryAfter` hint (costing the server almost nothing), so goodput
stays flat instead of collapsing into queue-drain timeouts.

Every completed response is compared bit-for-bit against an oracle
computed upfront on a bare `DenseDpfPirServer`, so the goodput claim
carries the usual equal-correctness proof.

Run directly (one JSON report on stdout, also written to
``benchmarks/results/overload_bench.json``; appends one
``serving_overload_goodput_queries_per_sec`` record — ``direction:
higher`` — to the regression-gate history)::

    JAX_PLATFORMS=cpu python -m benchmarks.overload_bench

or through the headline harness (one bench-style JSON line)::

    BENCH_OVERLOAD=1 BENCH_PLATFORM=cpu python bench.py

The request pool comes from `benchmarks/workload_gen.py` profiles
(``--profile`` / OVERLOAD_BENCH_PROFILE). The default ``uniform``
reproduces the retired inline generator byte-for-byte so the goodput
history stays comparable; other profiles (``zipf``, ``diurnal``,
``bursty``, ``mixed``) record their own suffixed history series.

Environment knobs: OVERLOAD_BENCH_RECORDS (default 1024),
OVERLOAD_BENCH_RECORD_BYTES (32), OVERLOAD_BENCH_BASE_THREADS (8),
OVERLOAD_BENCH_MULTIPLIERS ("1,2"), OVERLOAD_BENCH_SECONDS (2.0 per
point), OVERLOAD_BENCH_DEADLINE_MS (1000), OVERLOAD_BENCH_BUDGET_MS
(admission queue cost budget, 250), OVERLOAD_BENCH_PROFILE (uniform),
OVERLOAD_BENCH_OUT (report path; empty string disables the file).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def _log(msg: str) -> None:
    print(f"[overload-bench {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def _load_point(session, requests, oracle, num_threads, duration_s,
                deadline_s):
    """Closed-loop threads hammering `session` for `duration_s`; sheds
    retry after the server's hint. Returns the point stats."""
    from distributed_point_functions_tpu.serving import Overloaded

    lock = threading.Lock()
    stats = {
        "completed": 0, "shed": 0, "deadline_missed": 0,
        "mismatches": 0, "other_errors": 0,
    }
    stop = time.monotonic() + duration_s

    def worker(tid):
        i = tid
        while time.monotonic() < stop:
            request, want = requests[i % len(requests)], (
                oracle[i % len(requests)]
            )
            i += num_threads
            try:
                response = session.handle_request(
                    request, deadline=time.monotonic() + deadline_s
                )
                ok = (
                    response.dpf_pir_response.masked_response == want
                )
                with lock:
                    stats["completed"] += 1
                    if not ok:
                        stats["mismatches"] += 1
            except Overloaded as e:
                with lock:
                    stats["shed"] += 1
                time.sleep(min(max(e.retry_after_s, 1e-3), 0.05))
            except TimeoutError:
                with lock:
                    stats["deadline_missed"] += 1
            except Exception:  # noqa: BLE001 - counted, bench continues
                with lock:
                    stats["other_errors"] += 1

    threads = [
        threading.Thread(target=worker, args=(t,), name=f"load-{t}")
        for t in range(num_threads)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    stats["threads"] = num_threads
    stats["wall_s"] = round(wall, 3)
    stats["goodput_qps"] = round(stats["completed"] / wall, 2)
    offered = stats["completed"] + stats["shed"] + stats["deadline_missed"]
    stats["offered_qps"] = round(offered / wall, 2)
    stats["shed_ratio"] = round(
        stats["shed"] / offered, 4) if offered else 0.0
    return stats


def run_overload_bench():
    """Build the database, walk the offered-load ladder, return the
    report dict (also written to OVERLOAD_BENCH_OUT unless empty)."""
    from distributed_point_functions_tpu.pir import messages
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.server import DenseDpfPirServer
    from distributed_point_functions_tpu.serving import (
        PlainSession,
        ServingConfig,
    )

    num_records = int(os.environ.get("OVERLOAD_BENCH_RECORDS", 1024))
    record_bytes = int(os.environ.get("OVERLOAD_BENCH_RECORD_BYTES", 32))
    base_threads = int(os.environ.get("OVERLOAD_BENCH_BASE_THREADS", 8))
    multipliers = [
        float(m)
        for m in os.environ.get("OVERLOAD_BENCH_MULTIPLIERS", "1,2")
        .split(",")
        if m.strip()
    ]
    duration_s = float(os.environ.get("OVERLOAD_BENCH_SECONDS", 2.0))
    deadline_s = (
        float(os.environ.get("OVERLOAD_BENCH_DEADLINE_MS", 1000.0)) / 1e3
    )
    budget_ms = float(os.environ.get("OVERLOAD_BENCH_BUDGET_MS", 250.0))

    profile_name = os.environ.get("OVERLOAD_BENCH_PROFILE", "uniform")
    _log(
        f"database: {num_records} x {record_bytes}B, base "
        f"{base_threads} threads, multipliers {multipliers}, "
        f"{duration_s}s/point, deadline {deadline_s * 1e3:.0f} ms, "
        f"cost budget {budget_ms:.0f} ms, profile {profile_name}"
    )
    builder = DenseDpfPirDatabase.Builder()
    for i in range(num_records):
        builder.insert(
            (b"load-%06d:" % i).ljust(record_bytes, b".")[:record_bytes]
        )
    database = builder.build()

    from benchmarks import workload_gen

    profile = workload_gen.PROFILES[profile_name]
    # The `uniform` profile reproduces this bench's retired inline pool
    # byte-for-byte (numpy seed 8, one integers() draw of 32), so the
    # goodput history stays comparable across the generator handoff.
    indices = workload_gen.key_pool(profile, num_records)
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    requests = [
        client.create_plain_requests([int(i)])[0] for i in indices
    ]
    oracle_server = DenseDpfPirServer.create_plain(database)
    _log("computing oracle responses and warming jit buckets")
    t0 = time.perf_counter()
    oracle = [
        oracle_server.handle_plain_request(r).dpf_pir_response.masked_response
        for r in requests
    ]
    max_batch = 16
    b = 1
    while b <= max_batch:
        oracle_server.handle_plain_request(
            messages.PirRequest(
                plain_request=messages.PlainRequest(
                    dpf_keys=list(requests[0].plain_request.dpf_keys) * b
                )
            )
        )
        b *= 2
    _log(f"oracle + warmup done in {time.perf_counter() - t0:.1f}s")

    config = ServingConfig(
        max_batch_size=max_batch,
        max_wait_ms=2.0,
        admission_enabled=True,
        admission_queue_budget_ms=budget_ms,
    )
    points = []
    with PlainSession(database, config) as session:
        for mult in multipliers:
            threads = max(1, int(round(base_threads * mult)))
            point = _load_point(
                session, requests, oracle, threads, duration_s, deadline_s
            )
            point["offered_multiplier"] = mult
            points.append(point)
            _log(
                f"x{mult:<4} ({threads:>3} threads): goodput "
                f"{point['goodput_qps']:8.1f} q/s, offered "
                f"{point['offered_qps']:8.1f} q/s, shed "
                f"{point['shed_ratio'] * 100:5.1f}%, "
                f"mismatches={point['mismatches']}"
            )
        admission_export = session.admission.export()
        metrics = session.metrics.export()

    saturation = points[0]["goodput_qps"] if points else 0.0
    worst = min((p["goodput_qps"] for p in points), default=0.0)
    correctness_ok = all(
        p["mismatches"] == 0 and p["other_errors"] == 0 for p in points
    )
    report = {
        "config": {
            "profile": profile.name,
            "num_records": num_records,
            "record_bytes": record_bytes,
            "base_threads": base_threads,
            "multipliers": multipliers,
            "seconds_per_point": duration_s,
            "deadline_ms": deadline_s * 1e3,
            "queue_budget_ms": budget_ms,
        },
        "ladder": points,
        "saturation_goodput_qps": saturation,
        "overloaded_goodput_qps": points[-1]["goodput_qps"]
        if points else 0.0,
        "goodput_retention": round(worst / saturation, 4)
        if saturation else 0.0,
        "correctness_ok": correctness_ok,
        "admission": admission_export,
        "shed_counters": {
            k: v
            for k, v in metrics["counters"].items()
            if "shed" in k or "expired" in k
        },
    }
    _log(
        f"goodput retention at x{multipliers[-1] if multipliers else '?'}: "
        f"{report['goodput_retention'] * 100:.1f}% of saturation "
        f"({report['overloaded_goodput_qps']:.1f} / {saturation:.1f} q/s), "
        f"correctness {'ok' if correctness_ok else 'FAILED'}"
    )

    out = os.environ.get(
        "OVERLOAD_BENCH_OUT", "benchmarks/results/overload_bench.json"
    )
    if out:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
        _log(f"report written to {out}")
    return report


def _append_history_record(report):
    """One goodput-under-overload record for the regression gate.
    Explicit `direction: higher` (goodput dropping is the regression,
    whatever the unit inference says). Best-effort like every history
    append."""
    try:
        from benchmarks.regression_gate import append_record, git_rev

        metric = "serving_overload_goodput_queries_per_sec"
        profile = report.get("config", {}).get("profile", "uniform")
        if profile != "uniform":
            # Non-uniform profiles track their own history series; the
            # uniform rolling median must not drift on a zipf run.
            metric = f"{metric}_{profile}"
        append_record({
            "metric": metric,
            "value": report["overloaded_goodput_qps"],
            "unit": "queries/s",
            "direction": "higher",
            "vs_baseline": report["goodput_retention"],
            "status": "ok" if report["correctness_ok"] else "error",
            "git_rev": git_rev(),
            "device": os.environ.get("BENCH_PLATFORM", "cpu"),
        }, path=os.environ.get(
            "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
        ))
    except Exception as e:  # noqa: BLE001 - history must not break a bench
        _log(f"history append failed (non-fatal): {e}")


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--profile",
        default=os.environ.get("OVERLOAD_BENCH_PROFILE", "uniform"),
        choices=sorted(_profile_names()),
        help="workload_gen profile for the request pool "
             "(uniform = the pre-profile history-compatible pool)",
    )
    args = parser.parse_args(argv)
    os.environ["OVERLOAD_BENCH_PROFILE"] = args.profile
    report = run_overload_bench()
    if os.environ.get("BENCH_HISTORY", "1") != "0":
        _append_history_record(report)
    print(json.dumps(report, indent=2))
    if not report["correctness_ok"]:
        raise SystemExit("overload bench FAILED correctness")


def _profile_names():
    from benchmarks import workload_gen

    return workload_gen.PROFILES.keys()


if __name__ == "__main__":
    main()
