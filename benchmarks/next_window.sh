#!/usr/bin/env bash
# Priority queue for the next TPU tunnel window: the new-kernel A/Bs
# first (cheap, high information), then the remaining reference sweeps
# that the 2026-07-30 15:49 stall cut off. Run via tpu_watch-style
# polling or directly when the tunnel answers.
set -u -o pipefail
cd "$(dirname "$0")/.."
mkdir -p benchmarks/results
stamp=$(date +%Y%m%d_%H%M%S)

echo "=== kernel smoke (tiny shapes, fast compiles) ==="
timeout 1500 python benchmarks/kernel_smoke.py \
    2>benchmarks/results/kernel_smoke_${stamp}.log \
    | tee benchmarks/results/kernel_smoke_${stamp}.json

echo "=== inner-product kernel A/B (v1 vs v2 variants) ==="
timeout 1800 python benchmarks/ip_ab.py \
    2>benchmarks/results/ip_ab_${stamp}.log \
    | tee benchmarks/results/ip_ab_${stamp}.json
tail -3 benchmarks/results/ip_ab_${stamp}.log

echo "=== inner-product A/B at 256 queries (query-tile variants) ==="
timeout 1800 env BENCH_QUERIES=256 python benchmarks/ip_ab.py \
    2>benchmarks/results/ip_ab_q256_${stamp}.log \
    | tee benchmarks/results/ip_ab_q256_${stamp}.json

echo "=== headline at larger query batches (v2 tier auto) ==="
for q in 64 128 256; do
    timeout 1200 env BENCH_QUERIES=$q BENCH_SKIP_NSLEAF=1 BENCH_ITERS=8 \
        BENCH_TIMEOUT=1100 python bench.py \
        2>benchmarks/results/bench_q${q}_${stamp}.log \
        | tee benchmarks/results/bench_q${q}_${stamp}.json
done

echo "=== level-kernel ablation (planes expansion, XLA levels) ==="
timeout 1200 env BENCH_QUERIES=64 BENCH_SKIP_NSLEAF=1 BENCH_ITERS=8 \
    BENCH_TIMEOUT=1100 BENCH_EXPANSION=planes DPF_TPU_LEVEL_KERNEL=xla \
    python bench.py \
    2>benchmarks/results/bench_levelxla_${stamp}.log \
    | tee benchmarks/results/bench_levelxla_${stamp}.json

echo "=== expansion stage profile ==="
timeout 1800 python benchmarks/expand_profile.py \
    2>benchmarks/results/expand_profile_${stamp}.log \
    | tee benchmarks/results/expand_profile_${stamp}.json

echo "=== remaining reference sweeps ==="
timeout 3600 python benchmarks/run_benchmarks.py \
    --suite dpf,dcf,mic,inner_product,int_mod_n --big \
    2>&1 | tee benchmarks/results/sweeps_${stamp}.json

echo "=== synthetic configs (2^32 and 2^128) ==="
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --num_iterations 3 \
    2>&1 | tee benchmarks/results/synthetic_${stamp}.json
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --only_nonzeros \
    --num_iterations 3 \
    2>&1 | tee benchmarks/results/only_nonzeros_${stamp}.json
timeout 3600 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 128 --log_num_nonzeros 20 --num_iterations 2 \
    2>&1 | tee benchmarks/results/synthetic128_${stamp}.json
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 128 --log_num_nonzeros 20 --only_nonzeros \
    --num_iterations 3 \
    2>&1 | tee benchmarks/results/only_nonzeros128_${stamp}.json

echo "next_window done: benchmarks/results/*_${stamp}.*"

# Persist whatever this window captured even if no operator is watching.
git add benchmarks/results >/dev/null 2>&1
git commit -q -m "Record TPU window results (automated capture)" \
    >/dev/null 2>&1 || true
echo "results committed"
