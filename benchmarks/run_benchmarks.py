"""Benchmark runner mirroring the reference's benchmark binaries.

Suites (select with --suite, comma-separated; default all):

* ``dpf``           — full-domain expansion per value type and keygen /
                      batch point eval sweeps
                      (`dpf/distributed_point_function_benchmark.cc`)
* ``dcf``           — `batch_evaluate` sweep
                      (`dcf/distributed_comparison_function_benchmark.cc`)
* ``mic``           — batched MIC gate eval
                      (`dcf/fss_gates/multiple_interval_containment_benchmark.cc`)
* ``inner_product`` — database XOR inner product
                      (`pir/dense_dpf_pir_database_benchmark.cc`)
* ``int_mod_n``     — modular sampling throughput
                      (`dpf/int_mod_n_benchmark.cc`)

Each result prints as one JSON line. Scale knobs default small enough to run
on one chip in minutes; pass --big for the reference-sized sweeps.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

from benchmarks.common import run_timed  # noqa: E402


def bench_dpf(big: bool):
    import jax

    from distributed_point_functions_tpu.dpf import (
        DistributedPointFunction,
        DpfParameters,
    )
    from distributed_point_functions_tpu.value_types import (
        IntType,
        TupleType,
        XorType,
    )

    log_domains = [12, 16, 20] if big else [12, 14]
    value_types = {
        "uint32": IntType(32),
        "uint64": IntType(64),
        "uint128": IntType(128),
        "xor128": XorType(128),
        "tuple_u32x2": TupleType([IntType(32), IntType(32)]),
    }
    for lds in log_domains:
        for name, vt in value_types.items():
            dpf = DistributedPointFunction.create(
                DpfParameters(log_domain_size=lds, value_type=vt)
            )
            k0, _ = dpf.generate_keys(3, vt.zero())
            leaves = 1 << lds

            def full_eval():
                ctx = dpf.create_evaluation_context(k0)
                out = dpf.evaluate_next([], ctx)
                jax.tree_util.tree_map(
                    lambda x: x.block_until_ready(), out
                )

            run_timed(
                f"dpf_full_domain_eval_2^{lds}_{name}",
                full_eval,
                items=leaves,
                unit="leaves/s",
            )

    # Key generation sweep (1..128 levels analog: bitwise hierarchies).
    for levels in [16, 64, 128] if big else [16, 32]:
        params = [
            DpfParameters(log_domain_size=i + 1, value_type=IntType(64))
            for i in range(levels)
        ]
        dpf = DistributedPointFunction.create_incremental(params)
        betas = [1] * levels

        run_timed(
            f"dpf_keygen_{levels}_levels",
            lambda: dpf.generate_keys_incremental(0, betas),
            iters=3,
        )

    # Batch point evaluation (400k points in the reference; scaled).
    n_points = 400_000 if big else 50_000
    lds = 32
    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain_size=lds, value_type=IntType(64))
    )
    k0, _ = dpf.generate_keys(12345, 1)
    rng = np.random.default_rng(0)
    points = [int(x) for x in rng.integers(0, 1 << lds, n_points)]

    def point_eval():
        out = dpf.evaluate_at(k0, 0, points)
        out.block_until_ready()

    run_timed(
        f"dpf_batch_point_eval_{n_points}pts_2^{lds}",
        point_eval,
        items=n_points,
    )


def bench_dcf(big: bool):
    import jax

    from distributed_point_functions_tpu.dcf import (
        DistributedComparisonFunction,
    )
    from distributed_point_functions_tpu.value_types import IntType

    import time as _time

    # big includes the BASELINE.json config: 2^32 domain x 256 keys
    # (`dcf/distributed_comparison_function_benchmark.cc:31-74`).
    for lds in [32, 64] if big else [16, 32]:
        for batch in [64, 256, 1024] if big else [16, 256]:
            import random as _random

            dcf = DistributedComparisonFunction.create(lds, IntType(64))
            k0, k1 = dcf.generate_keys(3, 1)
            # Python randrange: domains beyond 2^63 overflow numpy int64.
            _r = _random.Random(0)
            xs = [_r.randrange(1 << lds) for _ in range(batch)]
            keys = [k0 if i % 2 == 0 else k1 for i in range(batch)]

            # Key staging is a one-time cost per batch; report it
            # separately so the eval number is pure device time.
            t0 = _time.perf_counter()
            staged = dcf.stage_keys(keys)
            jax.block_until_ready(staged.cw_seeds)
            stage_s = _time.perf_counter() - t0

            def batch_eval():
                out = dcf.batch_evaluate(None, xs, staged=staged)
                jax.tree_util.tree_map(
                    lambda x: x.block_until_ready(), out
                )

            run_timed(
                f"dcf_batch_eval_2^{lds}_batch{batch}",
                batch_eval,
                items=batch,
                label=f"stage_s={stage_s:.4f}",
            )


def bench_mic(big: bool):
    from distributed_point_functions_tpu.fss_gates import (
        Interval,
        MicParameters,
        MultipleIntervalContainmentGate,
    )

    log_group = 20
    num_intervals = 10 if big else 4
    num_keys = 16 if big else 4
    intervals = [
        Interval(i * 100, i * 100 + 50) for i in range(num_intervals)
    ]
    gate = MultipleIntervalContainmentGate.create(
        MicParameters(log_group, intervals)
    )
    k0, _ = gate.gen(7, [0] * num_intervals)
    xs = list(range(num_keys))

    run_timed(
        f"mic_batch_eval_{num_keys}keys_{num_intervals}intervals",
        lambda: gate.batch_eval([k0] * num_keys, xs),
        items=num_keys * num_intervals,
    )


def bench_inner_product(big: bool):
    import jax

    from distributed_point_functions_tpu.ops.inner_product import (
        xor_inner_product,
    )
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        permute_db_bitmajor,
        xor_inner_product_pallas_staged,
    )

    rng = np.random.default_rng(0)
    configs = (
        [(1 << 16, 80), (1 << 16, 256), (1 << 20, 80), (1 << 20, 256)]
        if big
        else [(1 << 16, 80), (1 << 16, 256)]
    )
    # The reference benches batch 1-2 (`dense_dpf_pir_database_benchmark
    # .cc:92-135`); the TPU design amortizes the database pass over a
    # whole query batch, so the 64-query point is the one that matters.
    for num_records, record_bytes in configs:
        num_padded = ((num_records + 127) // 128) * 128
        words = (record_bytes + 3) // 4
        db = jax.device_put(
            rng.integers(0, 1 << 32, (num_padded, words), dtype=np.uint32)
        )
        try:
            db_perm = jax.block_until_ready(permute_db_bitmajor(db))
        except Exception as e:  # noqa: BLE001
            db_perm = None
            print(f"# pallas staging skipped: {e}", flush=True)
        for nq in [1, 64] if big else [1]:
            sels = jax.device_put(
                rng.integers(
                    0, 1 << 32, (nq, num_padded // 128, 4), dtype=np.uint32
                )
            )

            run_timed(
                f"inner_product_jnp_{num_records}x{record_bytes}B_q{nq}",
                lambda: xor_inner_product(db, sels).block_until_ready(),
                items=num_records * nq,
            )
            if db_perm is None:
                continue
            try:
                run_timed(
                    f"inner_product_pallas_{num_records}x{record_bytes}B"
                    f"_q{nq}",
                    lambda: xor_inner_product_pallas_staged(
                        db_perm, sels
                    ).block_until_ready(),
                    items=num_records * nq,
                )
            except Exception as e:  # noqa: BLE001 - CPU backend has no Mosaic
                print(f"# pallas inner product skipped: {e}", flush=True)
        del db_perm


def bench_int_mod_n(big: bool):
    import jax

    from distributed_point_functions_tpu.value_types import IntModNType
    from distributed_point_functions_tpu.ops import limb

    vt = IntModNType(base_bits=32, modulus=1000003)
    n = (1 << 20) if big else (1 << 16)
    rng = np.random.default_rng(0)
    blocks = jax.device_put(
        rng.integers(0, 1 << 32, (n, 4), dtype=np.uint32)
    )

    def sample():
        q, r = limb.divmod_const(blocks, vt.modulus, 4)
        r.block_until_ready()

    run_timed(f"int_mod_n_sample_{n}", sample, items=n)


SUITES = {
    "dpf": bench_dpf,
    "dcf": bench_dcf,
    "mic": bench_mic,
    "inner_product": bench_inner_product,
    "int_mod_n": bench_int_mod_n,
}


def main():
    from benchmarks.common import setup_compilation_cache

    setup_compilation_cache()
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # The environment's sitecustomize pins the remote-TPU platform; the
        # config update (pre-backend-init) restores the requested one.
        import jax

        jax.config.update("jax_platforms", "cpu")
    parser = argparse.ArgumentParser()
    parser.add_argument("--suite", default=",".join(SUITES))
    parser.add_argument("--big", action="store_true",
                        help="reference-sized sweeps")
    args = parser.parse_args()
    for name in args.suite.split(","):
        SUITES[name.strip()](args.big)


if __name__ == "__main__":
    main()
