#!/usr/bin/env bash
# TPU window queue after the 2026-07-31 03:16-04:00 window: that window
# captured the fixed-kernel headline (q128 6601.9 q/s = 412.6x), the
# v2 inner-product A/Bs, and the expansion profile, and died during
# dense_big. This queue leads with the headline level-kernel A/B (the
# round's key number — the chunked kernels' first serving shot), then
# the shape probe, the remaining large configs, and reference sweeps.
set -u -o pipefail
cd "$(dirname "$0")/.."
mkdir -p benchmarks/results
stamp=$(date +%Y%m%d_%H%M%S)

echo "=== headline A/B: fused level kernels vs XLA levels ==="
for lk in pallas xla; do
    timeout 1500 env DPF_TPU_LEVEL_KERNEL=$lk BENCH_SKIP_NSLEAF=1 \
        BENCH_ITERS=8 BENCH_TIMEOUT=1400 python bench.py \
        2>benchmarks/results/bench_lk_${lk}_${stamp}.log \
        | tee benchmarks/results/bench_lk_${lk}_${stamp}.json
    tail -4 benchmarks/results/bench_lk_${lk}_${stamp}.log
done

echo "=== level-kernel shape probe ==="
timeout 2400 python benchmarks/level_kernel_probe.py \
    2>benchmarks/results/level_probe_${stamp}.log \
    | tee benchmarks/results/level_probe_${stamp}.json

echo "=== ns/leaf with fused kernels ==="
timeout 1500 env BENCH_ITERS=8 BENCH_TIMEOUT=1400 \
    BENCH_ONLY_NSLEAF=1 python bench.py \
    2>benchmarks/results/bench_nsleaf_${stamp}.log \
    | tee benchmarks/results/bench_nsleaf_${stamp}.json || true

echo "=== expansion stage profile (chunked kernels) ==="
timeout 1800 python benchmarks/expand_profile.py \
    2>benchmarks/results/expand_profile_${stamp}.log \
    | tee benchmarks/results/expand_profile_${stamp}.json

echo "=== BASELINE large configs ==="
timeout 3600 python benchmarks/baseline_suite.py --scale full \
    --suite dense_big \
    2>&1 | tee benchmarks/results/dense_big_${stamp}.json
timeout 3600 python benchmarks/baseline_suite.py --scale full \
    --suite sparse_big \
    2>&1 | tee benchmarks/results/sparse_big_${stamp}.json

echo "=== remaining reference sweeps (compile cache on) ==="
timeout 3600 python benchmarks/run_benchmarks.py \
    --suite dpf,dcf,mic,inner_product,int_mod_n --big \
    2>&1 | tee benchmarks/results/sweeps_${stamp}.json

echo "=== synthetic configs (2^32 and 2^128) ==="
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --num_iterations 3 \
    2>&1 | tee benchmarks/results/synthetic_${stamp}.json
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --only_nonzeros \
    --num_iterations 3 \
    2>&1 | tee benchmarks/results/only_nonzeros_${stamp}.json
timeout 3600 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 128 --log_num_nonzeros 20 --num_iterations 2 \
    2>&1 | tee benchmarks/results/synthetic128_${stamp}.json

echo "window2 done: benchmarks/results/*_${stamp}.*"
git add benchmarks/results >/dev/null 2>&1
git commit -q -m "Record TPU window results (automated capture)" \
    >/dev/null 2>&1 || true
echo "results committed"
