"""Hardware A/B of the Pallas inner-product kernels (v1 vs v2 variants).

Times each candidate at the headline config (2^20 records x 256 B, 64
queries by default) on the live chip, verifying every candidate's output
bit-identity against the jnp XOR path on a small instance first and
against v1 on the full instance. Prints one JSON line per candidate to
stdout; run after `capture_tpu.sh` so the timings don't contend.

Reference semantics: `pir/internal/inner_product_hwy.cc:157-258`.
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root
import time

import numpy as np


def log(msg):
    print(f"[ip_ab {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def slope(fn, iters=16, reps=3):
    def timed(n):
        out = None
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn()
        np.asarray(out)
        return time.perf_counter() - t0

    t1 = min(timed(1) for _ in range(reps))
    tn = min(timed(1 + iters) for _ in range(reps))
    return (tn - t1) / iters if tn > t1 else None


def main():
    num_records = int(os.environ.get("BENCH_RECORDS", 1 << 20))
    record_bytes = int(os.environ.get("BENCH_RECORD_BYTES", 256))
    nq = int(os.environ.get("BENCH_QUERIES", 64))

    import jax

    from benchmarks.common import setup_compilation_cache

    setup_compilation_cache()

    from distributed_point_functions_tpu.ops.inner_product import (
        xor_inner_product,
    )
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        permute_db_bitmajor,
        xor_inner_product_pallas2_staged,
        xor_inner_product_pallas_staged,
    )

    log(f"devices: {jax.devices()}")
    rng = np.random.default_rng(11)
    num_words = record_bytes // 4

    # Every candidate pins ALL three tile knobs explicitly. (The r02
    # sweep's labels named defaults that were never passed — rows tagged
    # tg32_j8 actually ran tg128_j32, so two rows were the same config
    # measured twice, 2.7 vs 3.4 ms: that's the noise band. Hence
    # min-of-3 reps per candidate now, and honest labels.) The kernel
    # clamps tq = min(tile_queries, nq, vmem cap).
    candidates = {"v1": xor_inner_product_pallas_staged}
    seen_effective = set()
    for tg in (32, 64, 128):
        for jc in (8, 32):
            for tq in (64, 128):
                # The kernel clamps tq = min(tile_queries, nq, VMEM
                # cap): distinct requested tiles can collapse to one
                # effective config (the r02 duplicate-label bug) —
                # dedupe on the effective tuple (cap formula mirrors
                # _ip_pallas_staged_v2) so every row is a distinct
                # kernel.
                tq_cap = max(8, (2 << 20) // (32 * num_words * 4) // 8 * 8)
                eff_tq = min(tq, nq, tq_cap)
                eff = (tg, jc, eff_tq)
                if eff in seen_effective:
                    continue
                seen_effective.add(eff)
                candidates[f"v2_int8_tg{tg}_j{jc}_tq{eff_tq}"] = (
                    functools.partial(
                        xor_inner_product_pallas2_staged, int8=True,
                        tile_groups=tg, j_chunk=jc, tile_queries=tq,
                    )
                )
    candidates["v2_bf16_tg64_j32_tq64"] = functools.partial(
        xor_inner_product_pallas2_staged, int8=False, tile_groups=64,
        j_chunk=32, tile_queries=64,
    )

    # Small-instance verification vs the jnp XOR path.
    sdb = jax.device_put(
        rng.integers(0, 1 << 32, (4096, num_words), dtype=np.uint32)
    )
    ssel = jax.device_put(
        rng.integers(0, 1 << 32, (8, 32, 4), dtype=np.uint32)
    )
    sperm = permute_db_bitmajor(sdb)
    want = np.asarray(xor_inner_product(sdb, ssel))
    ok = {}
    for name, fn in candidates.items():
        try:
            got = np.asarray(fn(sperm, ssel))
            if not np.array_equal(got, want):
                raise RuntimeError("mismatch vs jnp")
            ok[name] = fn
            log(f"{name}: verified")
        except Exception as e:  # noqa: BLE001
            log(f"{name}: FAILED ({str(e).splitlines()[0]})")
            print(json.dumps({"candidate": name, "error":
                              str(e).splitlines()[0][:200]}), flush=True)

    # Full-instance staging and timing.
    db = jax.device_put(
        rng.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)
    )
    db_perm = jax.block_until_ready(permute_db_bitmajor(db))
    nblocks = num_records // 128
    sel = jax.device_put(
        rng.integers(0, 1 << 32, (nq, nblocks, 4), dtype=np.uint32)
    )
    outs = {}
    for name, fn in ok.items():
        try:
            t0 = time.perf_counter()
            outs[name] = np.asarray(fn(db_perm, sel))
            compile_s = time.perf_counter() - t0
            per = slope(lambda f=fn: f(db_perm, sel))
            ms = per * 1e3 if per else None
            gbps = (num_records * num_words * 4 / per / 1e9) if per else None
            line = {
                "candidate": name,
                "ms": round(ms, 3) if ms else None,
                "gbps": round(gbps, 1) if gbps else None,
                "compile_s": round(compile_s, 1),
                "config": f"{num_records}x{record_bytes}B_{nq}q",
            }
            print(json.dumps(line), flush=True)
            log(line)
        except Exception as e:  # noqa: BLE001
            log(f"{name}: big-run FAILED ({str(e).splitlines()[0]})")
            print(json.dumps({"candidate": name, "error":
                              str(e).splitlines()[0][:200]}), flush=True)
    ref = outs.get("v1")
    if ref is not None:
        for name, got in outs.items():
            if not np.array_equal(got, ref):
                log(f"WARNING: {name} differs from v1 on the full instance")
                print(json.dumps({"candidate": name,
                                  "error": "full-instance mismatch vs v1"}),
                      flush=True)


if __name__ == "__main__":
    main()
