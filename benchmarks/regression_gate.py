"""Automated perf-regression gate over the bench trajectory.

`bench.py` (and the satellite benches it hosts) append one structured
record per run to `benchmarks/results/history.jsonl`; this module turns
that write-only trail into an enforced contract. The newest record per
metric is compared against a **rolling median** of the last `--window`
clean runs with a **noise band** (`--band`, default 15%), and the gate
distinguishes three very different kinds of bad:

* ``regression``  — a clean run measured outside the band on the bad
                    side of the median. The only verdict that exits
                    nonzero.
* ``infra_error`` — the newest record says the *harness* failed
                    (`status != "ok"`: hung backend init, watchdog
                    stall, tunnel outage). BENCH_r05 taught the
                    lesson: a hung TPU init used to emit a bare
                    ``value: 0.0`` indistinguishable from a
                    catastrophic real regression. Infra errors never
                    fail the gate and never pollute the median.
* ``first_run``   — not enough clean history to form a median yet.

Good news is graded too: ``ok`` (inside the band or better) and
``improved`` (outside the band on the *good* side — worth a look, but
never a failure).

History record schema (one JSON object per line; unknown fields pass
through):

    {"ts_unix": 1754380800.0,            # when the run finished
     "metric": "dense_pir_queries_per_sec_chip_1048576x256B",
     "value": 7203.53, "unit": "queries/s",
     "status": "ok",                      # "ok" | "infra_error" | "error"
     "vs_baseline": 450.2,
     "git_rev": "6cfabdc",                # best-effort
     "device": "tpu", "topology": "1x1",  # backend + device count
     "jax_version": "0.4.35",             # optional; stack stamp
     "backend": "tpu",                    # optional; stack stamp
     "error": "...",                      # failure paths only
     "last_good": 7203.53,                # failure paths: prior capture
     "p99": 12.4, "samples": 512,         # latency records only
     "direction": "higher"}               # optional; inferred from unit

Direction (is bigger better?) is inferred from the unit — throughput
units (`queries/s`, `lanes/s`, `GB/s`) are higher-is-better, time
units (`ns/leaf`, `ms`, `s`) lower-is-better — and can be pinned per
record with `direction`. Verdicts honor it on both sides: the
median comparison flips which band edge is "worse", and `vs_baseline`
(passed through to the verdict) reads as an improvement below 1.0 for
lower-is-better metrics.

Stack stamps (`jax_version`, `backend`) group the rolling median: a
prior record with a *different* stamp never enters the newest run's
median (a JAX upgrade or a CPU run must not mask a TPU regression).
Records missing a stamp — all pre-stamp history — match any stack, so
existing history keeps judging.

CLI (``python -m benchmarks.regression_gate``): exits 0 unless a real
regression is present. ``--check-only`` is the presubmit mode: same
verdicts, but an empty/missing history is "nothing to check" (exit 0)
instead of a configuration error, so the gate can run on CPU against
the committed fixture before any TPU capture exists.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import statistics
import subprocess
import sys
import time
from typing import Dict, List, Optional

DEFAULT_HISTORY = os.path.join(
    os.path.dirname(__file__), "results", "history.jsonl"
)
DEFAULT_WINDOW = 5
DEFAULT_BAND = 0.15
MIN_HISTORY = 2  # clean prior runs needed before the gate judges

_HIGHER_UNITS = ("queries/s", "lanes/s", "GB/s", "GiB/s", "ops/s", "x")
_LOWER_UNITS = ("ns/leaf", "ns", "ms", "s", "bytes")


# ---------------------------------------------------------------------------
# History store
# ---------------------------------------------------------------------------


def append_record(record: dict, path: str = DEFAULT_HISTORY) -> None:
    """Append one run record (adds `ts_unix` if missing). Creates the
    store on first write. Best-effort callers (bench.py's emit path)
    wrap this in try/except — the history must never break a bench."""
    record = dict(record)
    record.setdefault("ts_unix", round(time.time(), 3))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(record, sort_keys=True) + "\n")


def load_history(path: str) -> tuple:
    """(records, skipped_lines). Malformed lines are skipped and
    counted, never fatal — a half-written line from a killed bench
    must not take the gate down with it."""
    records: List[dict] = []
    skipped = 0
    try:
        with open(path) as f:
            lines = f.readlines()
    except FileNotFoundError:
        return [], 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            skipped += 1
            continue
        if not isinstance(rec, dict) or "metric" not in rec:
            skipped += 1
            continue
        records.append(rec)
    return records, skipped


def git_rev() -> Optional[str]:
    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip()
            or None
        )
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Verdicts
# ---------------------------------------------------------------------------


def direction_of(record: dict) -> str:
    """'higher' or 'lower' — explicit field wins, else inferred from
    the unit, defaulting to higher-is-better (every headline metric in
    this repo is a throughput)."""
    explicit = record.get("direction")
    if explicit in ("higher", "lower"):
        return explicit
    unit = str(record.get("unit", ""))
    if unit in _LOWER_UNITS:
        return "lower"
    if unit in _HIGHER_UNITS:
        return "higher"
    return "higher"


def _is_clean(record: dict) -> bool:
    status = record.get("status", "ok")
    value = record.get("value")
    return (
        status == "ok"
        and isinstance(value, (int, float))
        and math.isfinite(float(value))
    )


_STACK_KEYS = ("jax_version", "backend")


def _same_stack(record: dict, stack: dict) -> bool:
    """Whether `record` may enter a median for a run stamped `stack`.
    A missing stamp on the record is a wildcard (pre-stamp history);
    a present-but-different stamp excludes it."""
    return all(record.get(k) in (None, v) for k, v in stack.items())


def judge_metric(
    records: List[dict],
    window: int = DEFAULT_WINDOW,
    band: float = DEFAULT_BAND,
) -> dict:
    """Verdict for one metric's records (oldest -> newest). The newest
    record is the run under judgment; the rolling median forms over the
    `window` most recent *clean* records before it."""
    newest = records[-1]
    verdict = {
        "metric": newest.get("metric"),
        "value": newest.get("value"),
        "unit": newest.get("unit"),
        "status": newest.get("status", "ok"),
        "git_rev": newest.get("git_rev"),
        "n_records": len(records),
    }
    if newest.get("vs_baseline") is not None:
        verdict["vs_baseline"] = newest["vs_baseline"]
        verdict["vs_baseline_direction"] = direction_of(newest)
    for k in _STACK_KEYS:
        if newest.get(k) is not None:
            verdict[k] = newest[k]
    if not _is_clean(newest):
        # Harness failure, not a measurement: report, carry the
        # last-good context forward, never fail the gate.
        verdict.update(
            verdict="infra_error",
            reason=str(
                newest.get("error", "run reported a non-ok status")
            )[:300],
            last_good=newest.get("last_good"),
        )
        return verdict
    stack = {
        k: newest.get(k) for k in _STACK_KEYS
        if newest.get(k) is not None
    }
    prior_clean = [
        r for r in records[:-1]
        if _is_clean(r) and _same_stack(r, stack)
    ][-window:]
    if len(prior_clean) < MIN_HISTORY:
        verdict.update(
            verdict="first_run",
            reason=(
                f"only {len(prior_clean)} clean prior run(s)"
                + (" on this stack" if stack else "")
                + f"; need {MIN_HISTORY} to judge"
            ),
        )
        return verdict
    median = statistics.median(float(r["value"]) for r in prior_clean)
    value = float(newest["value"])
    direction = direction_of(newest)
    verdict.update(
        median=round(median, 4),
        band=band,
        window=len(prior_clean),
        direction=direction,
    )
    if median == 0:
        verdict.update(
            verdict="ok", reason="zero median; nothing to compare against"
        )
        return verdict
    ratio = value / median
    delta_pct = round((ratio - 1.0) * 100, 2)
    verdict["delta_pct"] = delta_pct
    worse = ratio < (1.0 - band) if direction == "higher" else ratio > (
        1.0 + band
    )
    better = ratio > (1.0 + band) if direction == "higher" else ratio < (
        1.0 - band
    )
    if worse:
        verdict.update(
            verdict="regression",
            reason=(
                f"{value} vs rolling median {round(median, 2)} "
                f"({delta_pct:+}% with a ±{band:.0%} noise band, "
                f"{direction} is better)"
            ),
        )
    elif better:
        verdict.update(
            verdict="improved",
            reason=f"{delta_pct:+}% vs rolling median {round(median, 2)}",
        )
    else:
        verdict.update(
            verdict="ok",
            reason=f"{delta_pct:+}% within the ±{band:.0%} noise band",
        )
    return verdict


def gate(
    records: List[dict],
    window: int = DEFAULT_WINDOW,
    band: float = DEFAULT_BAND,
    metrics: Optional[List[str]] = None,
) -> List[dict]:
    """One verdict per metric present in `records` (filtered to
    `metrics` when given), judging each metric's newest record."""
    by_metric: Dict[str, List[dict]] = {}
    for rec in records:
        name = str(rec.get("metric"))
        if metrics and name not in metrics:
            continue
        by_metric.setdefault(name, []).append(rec)
    return [
        judge_metric(recs, window=window, band=band)
        for _, recs in sorted(by_metric.items())
    ]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="benchmarks.regression_gate", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--history", default=DEFAULT_HISTORY,
        help=f"history.jsonl path (default: {DEFAULT_HISTORY})",
    )
    ap.add_argument(
        "--window", type=int, default=DEFAULT_WINDOW,
        help="rolling-median window of clean prior runs (default 5)",
    )
    ap.add_argument(
        "--band", type=float, default=DEFAULT_BAND,
        help="relative noise band around the median (default 0.15)",
    )
    ap.add_argument(
        "--metric", action="append", default=None,
        help="judge only this metric (repeatable; default: all)",
    )
    ap.add_argument(
        "--check-only", action="store_true",
        help="presubmit mode: an absent/empty history is 'nothing to "
        "check' (exit 0) instead of a configuration error",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the verdict table as one JSON document",
    )
    args = ap.parse_args(argv)

    records, skipped = load_history(args.history)
    if skipped:
        print(
            f"regression_gate: WARNING skipped {skipped} malformed "
            f"line(s) in {args.history}",
            file=sys.stderr,
        )
    if not records:
        if args.check_only:
            print(
                f"regression_gate: no history at {args.history}; "
                "nothing to check (check-only mode)"
            )
            return 0
        print(
            f"regression_gate: no usable history at {args.history}",
            file=sys.stderr,
        )
        return 2

    verdicts = gate(
        records, window=args.window, band=args.band, metrics=args.metric
    )
    if args.metric:
        missing = set(args.metric) - {v["metric"] for v in verdicts}
        for name in sorted(missing):
            print(
                f"regression_gate: WARNING metric {name!r} has no "
                "history records",
                file=sys.stderr,
            )

    if args.as_json:
        print(json.dumps({"verdicts": verdicts}, indent=2))
    else:
        for v in verdicts:
            print(
                f"regression_gate: {v['verdict']:<10} {v['metric']} "
                f"value={v['value']} {v.get('reason', '')}"
            )

    regressions = [v for v in verdicts if v["verdict"] == "regression"]
    infra = [v for v in verdicts if v["verdict"] == "infra_error"]
    summary = (
        f"regression_gate: {len(verdicts)} metric(s) judged — "
        f"{len(regressions)} regression(s), {len(infra)} infra error(s)"
    )
    print(summary, file=sys.stderr if regressions else sys.stdout)
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
