"""The BASELINE.json named configurations, runnable end to end.

Runs the two large configs that bench.py's headline metric does not
cover, mirroring the discipline of the reference's flag-driven harness
(`experiments/synthetic_data_benchmarks.cc:45-61`):

* ``dense_big``  — batched dense PIR: 2^22 records x 1024 concurrent
  queries on one chip (BASELINE config 3).
* ``sparse_big`` — cuckoo-hashed sparse PIR over 2^24 string keys
  (BASELINE config 5): measures build and serving separately.

``--scale smoke`` shrinks both (2^16 records / 2^14 keys) so the full
path runs on CPU in CI; ``--scale full`` is the benchmark configuration
(needs a TPU and a few GB of host RAM for the build).

HBM budget at full scale (v5e, 16 GB): dense 2^22 x 256 B = 1 GB
row-major + 1 GB bit-major staged copy + 0.5 GB packed selections for
1024 queries; sparse ~ 0.7 GB across the two bucket databases. Both fit
without chunking; beyond ~2^25 x 256 B the database would need the
chunked-expansion path instead (SURVEY.md §5 long-context notes).

Each result prints as one JSON line.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root


def _emit(**kv):
    print(json.dumps(kv), flush=True)


def _slope(fn, iters=4, reps=2):
    """Per-call seconds via slope timing (see bench.py)."""
    def timed(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn()
        np.asarray(out)
        return time.perf_counter() - t0

    t1 = min(timed(1) for _ in range(reps))
    tn = min(timed(1 + iters) for _ in range(reps))
    if tn <= t1:
        return None
    return (tn - t1) / iters


def bench_dense_big(scale: str):
    import jax

    from distributed_point_functions_tpu.ops.inner_product import (
        xor_inner_product,
        xor_inner_product_bitplane,
    )
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        permute_db_bitmajor,
        xor_inner_product_pallas2_staged,
        xor_inner_product_pallas_staged,
    )
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.dense_eval import (
        evaluate_selection_blocks,
        stage_keys,
    )

    if scale == "full":
        num_records, record_bytes, num_queries = 1 << 22, 256, 1024
    else:
        num_records, record_bytes, num_queries = 1 << 16, 64, 64

    rng = np.random.default_rng(11)
    num_words = record_bytes // 4
    db_host = rng.integers(
        0, 1 << 32, (num_records, num_words), dtype=np.uint32
    )
    t0 = time.perf_counter()
    on_tpu = jax.default_backend() == "tpu"
    ip_name = "jnp"
    if on_tpu:
        db = jax.block_until_ready(
            permute_db_bitmajor(jax.device_put(db_host))
        )
        # Same tier order as the serving path: v2 Pallas, v1 Pallas,
        # then the pure-jnp bit-plane MXU path (all consume the staged
        # layout).
        inner_product, ip_name = xor_inner_product_bitplane, "bitplane"
        for cand_name, cand in (
            ("pallas2", xor_inner_product_pallas2_staged),
            ("pallas", xor_inner_product_pallas_staged),
        ):
            try:
                jax.block_until_ready(
                    cand(db, np.zeros((8, db.shape[1], 4), np.uint32))
                )
                inner_product, ip_name = cand, cand_name
                break
            except Exception as e:  # noqa: BLE001
                print(f"# {cand_name} unavailable: {e}", flush=True)
    else:
        db = jax.device_put(db_host)
        inner_product = xor_inner_product
    stage_db_s = time.perf_counter() - t0

    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in rng.integers(0, num_records, num_queries)]
    t0 = time.perf_counter()
    keys0, _ = client._generate_key_pairs(indices)
    keygen_s = time.perf_counter() - t0
    staged = stage_keys(keys0)

    num_blocks = num_records // 128
    total_levels = max(0, math.ceil(math.log2(num_records)))
    expand_levels = min((num_blocks - 1).bit_length(), total_levels)
    walk_levels = total_levels - expand_levels

    @jax.jit
    def step(s0, c0, cs, cl, cr, vc, dbx):
        sel = evaluate_selection_blocks(
            s0, c0, cs, cl, cr, vc,
            walk_levels=walk_levels,
            expand_levels=expand_levels,
            num_blocks=num_blocks,
        )
        return inner_product(dbx, sel)

    t0 = time.perf_counter()
    jax.block_until_ready(step(*staged, db))
    compile_s = time.perf_counter() - t0
    per_batch = _slope(lambda: step(*staged, db))
    _emit(
        benchmark=f"dense_pir_{num_records}x{record_bytes}B_{num_queries}q",
        queries_per_s=(
            round(num_queries / per_batch, 2) if per_batch else None
        ),
        per_batch_ms=round(per_batch * 1e3, 3) if per_batch else None,
        compile_s=round(compile_s, 1),
        stage_db_s=round(stage_db_s, 2),
        keygen_s=round(keygen_s, 2),
        backend=jax.default_backend(),
        inner_product=ip_name,
    )


def bench_sparse_big(scale: str):
    import jax

    from distributed_point_functions_tpu.pir.cuckoo_database import (
        CuckooHashedDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.sparse_client import (
        CuckooHashingSparseDpfPirClient,
    )
    from distributed_point_functions_tpu.pir.sparse_server import (
        CuckooHashingSparseDpfPirServer,
    )

    num_keys = (1 << 24) if scale == "full" else (1 << 14)
    value_bytes = 16
    query_counts = [
        int(q)
        for q in os.environ.get("BENCH_SPARSE_QUERIES", "64,128").split(",")
    ]

    rng = np.random.default_rng(13)
    t0 = time.perf_counter()
    params = CuckooHashingSparseDpfPirServer.generate_params(
        num_keys, seed=b"0123456789abcdef"
    )
    builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
    for i in range(num_keys):
        builder.insert(
            (b"k%012d" % i, rng.integers(0, 256, value_bytes,
                                          dtype=np.uint8).tobytes())
        )
    db = builder.build()
    build_s = time.perf_counter() - t0

    server = CuckooHashingSparseDpfPirServer.create_plain(params, db)
    client = CuckooHashingSparseDpfPirClient.create_from_public_params(
        server.get_public_params().SerializeToString(), lambda pt, ci: pt
    )
    for num_queries in query_counts:
        queries = [b"k%012d" % int(i) for i in
                   rng.integers(0, num_keys, num_queries)]

        t0 = time.perf_counter()
        req0, _req1 = client.create_plain_requests(queries)
        resp = server.handle_request(req0)
        first_s = time.perf_counter() - t0
        assert len(resp.dpf_pir_response.masked_response) == (
            2 * num_queries * params.num_hash_functions
        )

        # handle_request blocks internally (the inner product is read
        # back to host bytes), so wall-clock per call is the honest
        # serving time.
        per_batch = _slope(lambda: server.handle_request(req0), iters=3)
        _emit(
            benchmark=f"sparse_pir_{num_keys}keys_{num_queries}q",
            queries_per_s=(
                round(num_queries / per_batch, 2) if per_batch else None
            ),
            per_batch_ms=round(per_batch * 1e3, 3) if per_batch else None,
            build_s=round(build_s, 1),
            first_request_s=round(first_s, 1),
            num_buckets=params.num_buckets,
            backend=jax.default_backend(),
        )


def main():
    from benchmarks.common import setup_compilation_cache

    setup_compilation_cache()
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # The environment's sitecustomize pins the remote-TPU platform;
        # the config update (pre-backend-init) restores the requested one.
        import jax

        jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["smoke", "full"], default="smoke")
    ap.add_argument(
        "--suite", default="dense_big,sparse_big",
        help="comma-separated: dense_big,sparse_big",
    )
    args = ap.parse_args()
    suites = {"dense_big": bench_dense_big, "sparse_big": bench_sparse_big}
    for name in args.suite.split(","):
        suites[name.strip()](args.scale)


if __name__ == "__main__":
    main()
