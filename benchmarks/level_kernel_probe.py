"""Isolate the Mosaic compile failure of the fused level kernel at
large group counts (expand_profile: level 9, G=2048, q64 -> kg=2 crashes
tpu_compile_helper; levels <= 7 with the same kg succeed).

Runs the level kernel compiled at a sweep of (G, kg) shapes and reports
ok/crash per shape, then the same for the value-hash kernel. Each case
is its own jit cache entry; crashes surface as INTERNAL remote_compile
errors. Run on the real chip between capture stages.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def main() -> None:
    from benchmarks.common import setup_compilation_cache

    setup_compilation_cache()
    import jax  # noqa: E402
    import jax.numpy as jnp  # noqa: E402

    print(f"devices: {jax.devices()}", file=sys.stderr)

    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        expand_level_planes_pallas,
        value_hash_planes_pallas,
    )

    rng = np.random.default_rng(11)

    def case(g: int, kg: int, which: str, tile: int | None = None) -> dict:
        state = jnp.asarray(
            rng.integers(0, 1 << 32, (16, 8, g), dtype=np.uint32)
        )
        ctrl = jnp.asarray(rng.integers(0, 1 << 32, (g,), dtype=np.uint32))
        cwp = jnp.asarray(
            rng.integers(0, 1 << 32, (16, 8, kg), dtype=np.uint32)
        )
        cwb = jnp.asarray(rng.integers(0, 1 << 32, (kg,), dtype=np.uint32))
        tag = {"kernel": which, "g": g, "kg": kg}
        if tile is not None:
            tag["tile"] = tile
        t0 = time.perf_counter()
        try:
            if which == "level":
                out = expand_level_planes_pallas(
                    state, ctrl, cwp, cwb, cwb, tile_lanes=tile
                )
                jax.block_until_ready(out)
            else:
                out = value_hash_planes_pallas(state, ctrl, cwp)
                jax.block_until_ready(out)
            return {**tag, "ok": True,
                    "compile_s": round(time.perf_counter() - t0, 1)}
        except Exception as e:  # noqa: BLE001
            return {**tag, "ok": False, "error": str(e).splitlines()[0][:160]}

    # The 2026-07-31 expand_profile found the level kernel fine through
    # G=1024 (one grid step) and crashing tpu_compile_helper at G=2048
    # (the first multi-step lane grid). The kernels now chunk in XLA
    # (grid-(1,) pallas_call per lane slice); this probe validates the
    # chunked design at the serving widths and maps the single-block
    # VMEM ceiling.
    cases = [
        # two chunks at a size known-good as one:
        ("level", 1024, 2, 512),
        # one big block at the size that used to crash as a 2-step grid:
        ("level", 2048, 2, 2048),
        # chunked defaults at the previously-crashing widths:
        ("level", 2048, 2, None),
        ("level", 16384, 2, None),
        # single-block VMEM ceiling:
        ("level", 4096, 2, 4096),
        # wide correction sources (small in-kernel repeat factors):
        ("level", 2048, 128, None),
        ("level", 8192, 128, None),
        # value-hash kernel at the bench's real leaf width:
        ("value", 2048, 2, None),
        ("value", 16384, 2, None),
    ]
    for which, g, kg, tile in cases:
        print(json.dumps(case(g, kg, which, tile)), flush=True)

    # Fused tail kernel (last r levels + value hash per subtree tile):
    # map the VMEM ceiling over (entry width, r, tile). q128 serving is
    # kg=4, g0=2048, r=4; q64 is kg=2, g0=1024.
    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        expand_tail_planes_pallas,
    )

    def tail_case(g0: int, kg: int, r: int, tile: int) -> dict:
        state = jnp.asarray(
            rng.integers(0, 1 << 32, (16, 8, g0), dtype=np.uint32)
        )
        ctrl = jnp.asarray(
            rng.integers(0, 1 << 32, (g0,), dtype=np.uint32)
        )
        cwp = jnp.asarray(
            rng.integers(0, 1 << 32, (r, 16, 8, kg), dtype=np.uint32)
        )
        cwb = jnp.asarray(
            rng.integers(0, 1 << 32, (r, kg), dtype=np.uint32)
        )
        vc = jnp.asarray(
            rng.integers(0, 1 << 32, (16, 8, kg), dtype=np.uint32)
        )
        tag = {"kernel": "tail", "g0": g0, "kg": kg, "r": r, "tile": tile,
               "out_lanes": tile << r}
        t0 = time.perf_counter()
        try:
            out = expand_tail_planes_pallas(
                state, ctrl, cwp, cwb, cwb, vc, tile_lanes=tile
            )
            jax.block_until_ready(out)
            # Per-call time after compile (whole-width launch set).
            t1 = time.perf_counter()
            jax.block_until_ready(
                expand_tail_planes_pallas(
                    state, ctrl, cwp, cwb, cwb, vc, tile_lanes=tile
                )
            )
            return {**tag, "ok": True,
                    "compile_s": round(t1 - t0, 1),
                    "run_ms": round((time.perf_counter() - t1) * 1e3, 2)}
        except Exception as e:  # noqa: BLE001
            return {**tag, "ok": False,
                    "error": str(e).splitlines()[0][:160]}

    tail_cases = [
        # q128 serving split (kg=4): vary tile -> out_lanes 2048..8192
        (2048, 4, 4, 128),
        (2048, 4, 4, 256),
        (2048, 4, 4, 512),
        # q64 serving (kg=2), deeper tails from a smaller split:
        (1024, 2, 4, 128),
        (512, 2, 5, 128),
        (256, 2, 6, 128),
        # VMEM ceiling: out 16384 lanes (8 MB) in one call
        (2048, 4, 4, 1024),
    ]
    for g0, kg, r, tile in tail_cases:
        print(json.dumps(tail_case(g0, kg, r, tile)), flush=True)

    # Fused head kernel (first r levels in ONE launch from a narrow
    # entry): Mosaic legality at the naturally narrow entry widths and
    # compile cost vs depth. q128 serving is kg=4 entry, r=9 to the
    # 2048-lane cap; hierarchical single-key is kg=1 entry.
    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        expand_head_planes_pallas,
    )

    def head_case(g0: int, kg: int, r: int) -> dict:
        state = jnp.asarray(
            rng.integers(0, 1 << 32, (16, 8, g0), dtype=np.uint32)
        )
        ctrl = jnp.asarray(
            rng.integers(0, 1 << 32, (g0,), dtype=np.uint32)
        )
        cwp = jnp.asarray(
            rng.integers(0, 1 << 32, (r, 16, 8, kg), dtype=np.uint32)
        )
        cwb = jnp.asarray(
            rng.integers(0, 1 << 32, (r, kg), dtype=np.uint32)
        )
        tag = {"kernel": "head", "g0": g0, "kg": kg, "r": r,
               "out_lanes": g0 << r}
        t0 = time.perf_counter()
        try:
            out = expand_head_planes_pallas(state, ctrl, cwp, cwb, cwb)
            jax.block_until_ready(out)
            t1 = time.perf_counter()
            jax.block_until_ready(
                expand_head_planes_pallas(state, ctrl, cwp, cwb, cwb)
            )
            return {**tag, "ok": True,
                    "compile_s": round(t1 - t0, 1),
                    "run_ms": round((time.perf_counter() - t1) * 1e3, 2)}
        except Exception as e:  # noqa: BLE001
            return {**tag, "ok": False,
                    "error": str(e).splitlines()[0][:160]}

    head_cases = [
        (4, 4, 9),    # q128 serving head: 4 -> 2048 lanes
        (2, 2, 10),   # q64 serving head: 2 -> 2048 lanes
        (8, 8, 8),    # q256 serving head: 8 -> 2048 lanes
        (4, 4, 5),    # shallower split (compile-cost scaling point)
        (1, 1, 11),   # hierarchical single-key entry: 1 -> 2048 lanes
        (4, 4, 10),   # cap probe: 4 -> 4096 lanes (~12 MB working set)
    ]
    for g0, kg, r in head_cases:
        print(json.dumps(head_case(g0, kg, r)), flush=True)


if __name__ == "__main__":
    main()
