"""Per-shape Mosaic legality/timing probe of the fused expansion kernels.

Runs each kernel family at a sweep of serving-geometry shapes and
reports ok/crash (+compile seconds, +per-call ms) per shape, one JSON
line each. Families: the fixed-width walk-descent (the doubling-free
redesign), the per-level kernel, the value-hash kernel, the fused tail,
and the fused head. Crashes surface as INTERNAL remote_compile errors.

Each case runs in its OWN SUBPROCESS under a hard timeout: on the
2026-08-01 toolchain a doomed fused-tail compile HANGS tpu_compile_helper
for 20+ minutes (it never errors) and wedges the single-client tunnel
for following processes — an in-process sweep would lose every case
after the first hang. `--one <idx>` runs a single case (the child
mode); the default parent mode spawns children sequentially, ordered
walk first (the redesign needs data most) and the hang-prone tail/head
last so their timeouts cannot starve the rest.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

# Seconds a child may spend on one case (init + compile + two runs).
# Legal compiles take <= ~120 s cold; a hang means Mosaic is stuck, and
# killing the child is the only way the rest of the sweep survives.
CASE_TIMEOUT = float(os.environ.get("PROBE_CASE_TIMEOUT", "420"))

# (kind, params). Walk: the fixed-width descent at q128/q64 serving
# geometries (kg=4 / kg=2), the head-replacement single launch, and the
# whole-expansion-as-fixed-tiles upper bound. Level/value: the chunked
# per-level design at serving widths. Tail/head: the doubling designs
# that fail on the 2026-08-01 toolchain — kept to map WHERE they fail,
# but last in line.
CASES = [
    ("walk", dict(g0=8192, kg=4, r=2, tile=2048, value=True)),
    ("walk", dict(g0=2048, kg=4, r=4, tile=2048, value=True)),
    ("walk", dict(g0=4, kg=4, r=9, tile=2048, value=False)),
    ("walk", dict(g0=4, kg=4, r=13, tile=2048, value=True)),
    ("walk", dict(g0=2, kg=2, r=10, tile=2048, value=False)),
    ("walk", dict(g0=1024, kg=2, r=4, tile=1024, value=True)),
    # fori_loop body (one AES body regardless of depth): the program-
    # size insurance if the unrolled deep instances fail/hang.
    ("walk", dict(g0=4, kg=4, r=9, tile=2048, value=False,
                  unroll=False)),
    ("walk", dict(g0=2048, kg=4, r=4, tile=2048, value=True,
                  unroll=False)),
    # compact entry (in-kernel replication, no full-width HBM staging):
    # the big-domain variant — replication traffic is ~0.7 ms at ld24.
    # Matched pairs against the plain cases above (same unroll flag) so
    # the A/B isolates the replication traffic, not codegen.
    ("walk", dict(g0=2048, kg=4, r=4, tile=2048, value=True,
                  compact=True)),
    ("walk", dict(g0=2048, kg=4, r=4, tile=2048, value=True,
                  compact=True, unroll=False)),
    ("walk", dict(g0=4, kg=4, r=9, tile=2048, value=False,
                  compact=True)),
    ("level", dict(g=2048, kg=2, tile=2048)),
    ("level", dict(g=2048, kg=4, tile=None)),
    ("level", dict(g=8192, kg=4, tile=None)),
    ("level", dict(g=16384, kg=2, tile=None)),
    ("value", dict(g=16384, kg=2)),
    ("value", dict(g=32768, kg=4)),
    # Known hang-prone doubling designs: one canary each (a hang costs
    # a full CASE_TIMEOUT, so the sweep carries no more than two).
    ("tail", dict(g0=2048, kg=4, r=4, tile=512)),
    ("head", dict(g0=4, kg=4, r=9)),
]


def run_one(idx: int) -> dict:
    """Child mode: full backend init + one case. Returns the result tag."""
    kind, p = CASES[idx]
    from benchmarks.common import setup_compilation_cache

    setup_compilation_cache()
    import jax
    import jax.numpy as jnp

    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        expand_head_planes_pallas,
        expand_level_planes_pallas,
        expand_tail_planes_pallas,
        value_hash_planes_pallas,
        walk_descend_planes_pallas,
    )

    rng = np.random.default_rng(11 + idx)

    def u32(*shape):
        return jnp.asarray(
            rng.integers(0, 1 << 32, shape, dtype=np.uint32)
        )

    tag = {"kernel": kind, **{k: v for k, v in p.items()}}
    # Backend init OUTSIDE the kernel-attributable region: a tunnel-down
    # init failure must never count as kernel evidence (the family
    # verdict below demotes kernels cross-process on attributable
    # errors only).
    try:
        jax.devices()
        jax.device_put(np.zeros(4, np.uint32)).block_until_ready()
    except Exception as e:  # noqa: BLE001
        return {**tag, "ok": False, "attributable": False,
                "error": "backend init failed: "
                f"{str(e).splitlines()[0][:140]}"}
    t0 = time.perf_counter()
    try:
        if kind == "level":
            g, kg, tile = p["g"], p["kg"], p["tile"]
            out = expand_level_planes_pallas(
                u32(16, 8, g), u32(g), u32(16, 8, kg), u32(kg), u32(kg),
                tile_lanes=tile,
            )
            jax.block_until_ready(out)
            return {**tag, "ok": True,
                    "compile_s": round(time.perf_counter() - t0, 1)}
        if kind == "value":
            g, kg = p["g"], p["kg"]
            out = value_hash_planes_pallas(
                u32(16, 8, g), u32(g), u32(16, 8, kg)
            )
            jax.block_until_ready(out)
            return {**tag, "ok": True,
                    "compile_s": round(time.perf_counter() - t0, 1)}
        if kind == "tail":
            g0, kg, r, tile = p["g0"], p["kg"], p["r"], p["tile"]
            args = (u32(16, 8, g0), u32(g0), u32(r, 16, 8, kg),
                    u32(r, kg), u32(r, kg), u32(16, 8, kg))

            def call():
                return expand_tail_planes_pallas(*args, tile_lanes=tile)
        elif kind == "head":
            g0, kg, r = p["g0"], p["kg"], p["r"]
            args = (u32(16, 8, g0), u32(g0), u32(r, 16, 8, kg),
                    u32(r, kg), u32(r, kg))

            def call():
                return expand_head_planes_pallas(*args)
        else:  # walk
            g0, kg, r = p["g0"], p["kg"], p["r"]
            tile, value = p["tile"], p["value"]
            unroll = p.get("unroll", True)
            compact = p.get("compact", False)
            args = (u32(16, 8, g0), u32(g0), u32(r, 16, 8, kg),
                    u32(r, kg), u32(r, kg),
                    u32(16, 8, kg) if value else None)

            def call():
                return walk_descend_planes_pallas(
                    *args, r=r, tile_lanes=tile, value_hash=value,
                    unroll=unroll, compact_entry=compact,
                )

        jax.block_until_ready(call())
        t1 = time.perf_counter()
        jax.block_until_ready(call())
        return {**tag, "ok": True,
                "compile_s": round(t1 - t0, 1),
                "run_ms": round((time.perf_counter() - t1) * 1e3, 2)}
    except Exception as e:  # noqa: BLE001
        # The backend answered and this specific program failed: that IS
        # kernel-attributable evidence.
        return {**tag, "ok": False, "attributable": True,
                "error": str(e).splitlines()[0][:160]}


def main() -> None:
    import signal

    # A SIGTERM to this parent (the stage's outer `timeout` expiring)
    # must not orphan a live child onto the single-client tunnel.
    active = {"proc": None}

    def _reap(signum, frame):
        p = active["proc"]
        if p is not None:
            try:
                p.kill()
            except Exception:  # noqa: BLE001
                pass
        sys.exit(1)

    signal.signal(signal.SIGTERM, _reap)
    signal.signal(signal.SIGINT, _reap)

    consecutive_timeouts = 0
    results = []
    for i, (kind, p) in enumerate(CASES):
        if consecutive_timeouts >= 3:
            print(json.dumps({"kernel": kind, **p, "ok": False,
                              "error": "skipped: tunnel wedged "
                              "(3 consecutive case timeouts)"}),
                  flush=True)
            continue
        proc = subprocess.Popen(
            [sys.executable, __file__, "--one", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        active["proc"] = proc
        try:
            stdout, stderr = proc.communicate(timeout=CASE_TIMEOUT)
            active["proc"] = None
            out = (stdout or "").strip().splitlines()
            if out:
                print(out[-1], flush=True)
                consecutive_timeouts = 0
                try:
                    results.append(json.loads(out[-1]))
                except ValueError:
                    pass
            else:
                # A child that died without reporting (init hang killed
                # by the runtime, OOM, tunnel drop) is NOT kernel
                # evidence.
                err = (stderr or "").strip().splitlines()
                print(json.dumps({"kernel": kind, **p, "ok": False,
                                  "attributable": False,
                                  "error": "child died rc="
                                  f"{proc.returncode}: "
                                  f"{err[-1][:120] if err else ''}"}),
                      flush=True)
                results.append({"kernel": kind, **p, "ok": False,
                                "attributable": False})
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            active["proc"] = None
            consecutive_timeouts += 1
            # A timeout is ambiguous (hung Mosaic compile OR wedged/
            # down tunnel): never family-demote on it — r04's outage
            # would have persisted _WALK_KERNEL_FAILED cross-process on
            # zero kernel evidence (ADVICE r04).
            print(json.dumps({"kernel": kind, **p, "ok": False,
                              "attributable": False,
                              "error": f"timeout {CASE_TIMEOUT:.0f}s "
                              "(hung Mosaic compile or wedged tunnel)"}),
                  flush=True)
            results.append({"kernel": kind, **p, "ok": False,
                            "attributable": False})
            # A hung compile may leave the tunnel wedged for a while;
            # wait for it to answer again (bounded) so the NEXT case
            # gets a fair run instead of burning the 3-strikes guard
            # on the same wedge.
            for _ in range(5):
                try:
                    ok = subprocess.run(
                        [sys.executable, "-c",
                         "import jax, jax.numpy as jnp; "
                         "assert jax.devices()[0].platform != 'cpu'; "
                         "jnp.add(jnp.uint32(1), jnp.uint32(2))"
                         ".block_until_ready()"],
                        timeout=90, capture_output=True,
                    ).returncode == 0
                except Exception:  # noqa: BLE001
                    ok = False
                if ok:
                    consecutive_timeouts = 0
                    break
                time.sleep(120)

    # Persist failure verdicts so serving/bench processes skip the
    # doomed compiles this sweep just paid for. Failures only — the
    # probe checks compile/run, not bit identity, so it must never set
    # a VERIFIED flag — and only with ATTRIBUTION: a family is demoted
    # when no case succeeded AND at least one case produced a real
    # compile/run error (timeouts and child/init deaths are tunnel-
    # ambiguous and never count). Compact-entry walk cases form their
    # own family (their serving gate has its own flag). Runs in a
    # bounded child (recording needs a backend init, which hangs when
    # the tunnel is wedged).
    fams = {}
    for res in results:
        fam = res.get("kernel")
        if fam == "walk" and res.get("compact"):
            fam = "walk_compact"
        fams.setdefault(fam, []).append(res)
    failed = [
        k for k in ("walk", "walk_compact", "tail", "head")
        if k in fams
        and not any(r.get("ok") for r in fams[k])
        and any(r.get("attributable") for r in fams[k])
    ]
    if failed:
        try:
            subprocess.run(
                [sys.executable, __file__, "--record",
                 ",".join(failed)],
                timeout=120, capture_output=True,
            )
            print(json.dumps({"recorded_failures": failed}), flush=True)
        except Exception:  # noqa: BLE001
            pass


def record_failures(families: list) -> None:
    """Child mode: persist FAILED flags for whole kernel families whose
    every probed case failed with kernel-attributable evidence (see the
    verdict cache in dense_eval_planes — serving skips known-doomed
    Mosaic compiles)."""
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    flag_for = {
        "walk": "_WALK_KERNEL_FAILED",
        "walk_compact": "_WALK_COMPACT_FAILED",
        "tail": "_TAIL_KERNEL_FAILED",
        "head": "_HEAD_KERNEL_FAILED",
    }
    for fam in families:
        setattr(dep, flag_for[fam], True)
    dep.record_kernel_verdicts()


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--one":
        print(json.dumps(run_one(int(sys.argv[2]))), flush=True)
    elif len(sys.argv) >= 3 and sys.argv[1] == "--record":
        record_failures(sys.argv[2].split(","))
    else:
        main()
