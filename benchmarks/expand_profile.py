"""Stage-by-stage timing of the plane-resident dense-PIR expansion.

The headline split shows ~8 ms of expansion per 64-query batch where the
bitsliced-AES gate count alone prices at ~0.7 ms of VPU time — this
script localizes the gap by timing each stage as its own jitted program:
the limb-space walk prologue, each [all-left; all-right] plane level at
its true width, the leaf value hash, and the exit transpose + bit-reversal
gather. Prints one JSON line per stage.

Run on the live chip after `capture_tpu.sh` (contention-free).
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root
import time

import numpy as np


def log(msg):
    print(f"[prof {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def slope(fn, iters=32, reps=3):
    def timed(n):
        out = None
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn()
        for leaf in jax_tree_leaves(out):
            np.asarray(leaf)
        return time.perf_counter() - t0

    t1 = min(timed(1) for _ in range(reps))
    tn = min(timed(1 + iters) for _ in range(reps))
    return (tn - t1) / iters if tn > t1 else None


def jax_tree_leaves(x):
    import jax

    return jax.tree_util.tree_leaves(x)


def main():
    num_records = int(os.environ.get("BENCH_RECORDS", 1 << 20))
    nq = int(os.environ.get("BENCH_QUERIES", 64))

    import jax
    import jax.numpy as jnp

    from benchmarks.common import setup_compilation_cache

    setup_compilation_cache()
    log(f"devices: {jax.devices()}")

    from distributed_point_functions_tpu import keys as fk
    from distributed_point_functions_tpu.ops.aes_bitslice import (
        limbs_to_planes,
        mmo_hash_planes,
        planes_to_limbs,
    )
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.dense_eval import (
        _walk_zeros,
        stage_keys,
    )
    from distributed_point_functions_tpu.pir.dense_eval_planes import (
        bitrev_permutation,
        expand_level_planes,
        pack_key_bits,
        pack_key_planes,
        _tile_keys,
    )

    num_blocks = num_records // 128
    total_levels = max(0, math.ceil(math.log2(num_records)))
    expand_levels = min((num_blocks - 1).bit_length(), total_levels)
    walk_levels = total_levels - expand_levels

    rng = np.random.default_rng(5)
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in rng.integers(0, num_records, nq)]
    keys0, _ = client._generate_key_pairs(indices)
    seeds0, control0, cw_seeds, cw_left, cw_right, last_vc = stage_keys(keys0)

    # Plane layout wants the key axis padded to a multiple of 32 (the
    # serving wrapper pads the same way).
    pad = (-seeds0.shape[0]) % 32
    if pad:
        seeds0 = jnp.pad(seeds0, ((0, pad), (0, 0)))
        control0 = jnp.pad(control0, ((0, pad),))
        cw_seeds = jnp.pad(cw_seeds, ((0, 0), (0, pad), (0, 0)))
        cw_left = jnp.pad(cw_left, ((0, 0), (0, pad)))
        cw_right = jnp.pad(cw_right, ((0, 0), (0, pad)))
        last_vc = jnp.pad(last_vc, ((0, pad), (0, 0)))

    results = {}

    def report(stage, per):
        ms = per * 1e3 if per is not None else None
        results[stage] = ms
        print(json.dumps({"stage": stage,
                          "ms": round(ms, 4) if ms else None}), flush=True)

    # Stage 1: limb-space walk prologue.
    walk = jax.jit(
        lambda s, c: _walk_zeros(
            s, c, cw_seeds[:walk_levels], cw_left[:walk_levels]
        )
    )
    seeds_w, control_w = jax.block_until_ready(walk(seeds0, control0))
    report("walk_prologue", slope(lambda: walk(seeds0, control0)))

    # Stage 2: entry transpose + packing.
    enter = jax.jit(
        lambda s, c: (limbs_to_planes(s), pack_key_bits(c.astype(jnp.uint32)))
    )
    state0, ctrl0 = jax.block_until_ready(enter(seeds_w, control_w))
    report("enter_planes", slope(lambda: enter(seeds_w, control_w)))

    # Stage 3: each expansion level at its true width — the XLA level
    # and (on TPU) the fused Pallas kernel side by side.
    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        expand_level_planes_pallas,
    )

    try_kernel = jax.default_backend() == "tpu"
    states = [(state0, ctrl0)]
    for i in range(expand_levels):
        lvl = walk_levels + i
        groups2 = 2 * states[-1][0].shape[-1]

        def level_fn(s, c, lvl=lvl, groups2=groups2):
            return expand_level_planes(
                s,
                c,
                _tile_keys(pack_key_planes(cw_seeds[lvl]), groups2),
                _tile_keys(pack_key_bits(cw_left[lvl]), groups2 // 2),
                _tile_keys(pack_key_bits(cw_right[lvl]), groups2 // 2),
            )

        level = jax.jit(level_fn)
        s_in, c_in = states[-1]
        states.append(jax.block_until_ready(level(s_in, c_in)))
        report(f"level_{i:02d}_groups{groups2}",
               slope(lambda l=level, s=s_in, c=c_in: l(s, c)))
        if try_kernel:
            def kernel_fn(s, c, lvl=lvl):
                return expand_level_planes_pallas(
                    s,
                    c,
                    pack_key_planes(cw_seeds[lvl]),
                    pack_key_bits(cw_left[lvl]),
                    pack_key_bits(cw_right[lvl]),
                )

            try:
                klevel = jax.jit(kernel_fn)
                jax.block_until_ready(klevel(s_in, c_in))
                report(
                    f"level_{i:02d}_groups{groups2}_kernel",
                    slope(lambda l=klevel, s=s_in, c=c_in: l(s, c)),
                )
            except Exception as e:  # noqa: BLE001
                log(f"kernel level {i} failed: {str(e).splitlines()[0]}")
                try_kernel = False

    state_f, ctrl_f = states[-1]

    # Stage 4: leaf value hash + correction.
    def value_fn(s, c):
        v = mmo_hash_planes(fk.RK_VALUE, s)
        vc_p = _tile_keys(pack_key_planes(last_vc), v.shape[-1])
        return v ^ (vc_p & c[None, None, :])

    value = jax.jit(value_fn)
    values = jax.block_until_ready(value(state_f, ctrl_f))
    report("value_hash", slope(lambda: value(state_f, ctrl_f)))

    # Stage 5: exit transpose + bitrev gather + truncation.
    nkp = seeds0.shape[0]
    perm = jnp.asarray(bitrev_permutation(expand_levels))

    def exit_fn(v):
        w = 1 << expand_levels
        out = planes_to_limbs(v).reshape(w, nkp, 4)
        out = jnp.moveaxis(out, 0, 1)
        return out[:, perm, :][:, :num_blocks, :]

    exitp = jax.jit(exit_fn)
    jax.block_until_ready(exitp(values))
    report("exit_planes_bitrev", slope(lambda: exitp(values)))

    # Same exit without the bit-reversal gather (what serving would pay
    # with a bitrev-staged database, `bitrev_leaves=True`): if the delta
    # is material, wiring the block-bitrev into database staging is the
    # next win; if not, the refactor isn't worth its complexity.
    def exit_nogather_fn(v):
        w = 1 << expand_levels
        out = planes_to_limbs(v).reshape(w, nkp, 4)
        return jnp.moveaxis(out, 0, 1)

    exitng = jax.jit(exit_nogather_fn)
    jax.block_until_ready(exitng(values))
    report("exit_planes_nogather", slope(lambda: exitng(values)))

    total = sum(
        v for k, v in results.items() if v and not k.endswith("_kernel")
    )
    print(json.dumps({"stage": "sum_of_stages_xla", "ms": round(total, 3)}),
          flush=True)


if __name__ == "__main__":
    main()
