"""Tiny-shape hardware smoke of every new Pallas kernel (fast compiles).

First thing to run in a TPU tunnel window: one JSON line per kernel with
ok/fail + compile seconds + bit-identity vs the XLA twin, so a short
window still tells us which kernels Mosaic accepts on this hardware.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root
import time

import numpy as np


def log(msg):
    print(f"[smoke {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def check(name, fn):
    t0 = time.perf_counter()
    try:
        fn()
        line = {"kernel": name, "ok": True,
                "compile_s": round(time.perf_counter() - t0, 1)}
    except Exception as e:  # noqa: BLE001
        line = {"kernel": name, "ok": False,
                "error": str(e).splitlines()[0][:300]}
    print(json.dumps(line), flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from benchmarks.common import setup_compilation_cache

    setup_compilation_cache()
    log(f"devices: {jax.devices()}")

    from distributed_point_functions_tpu.ops.inner_product import (
        xor_inner_product,
        pack_selection_bits_np,
    )
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        permute_db_bitmajor,
        xor_inner_product_pallas2_staged,
        xor_inner_product_pallas_staged,
    )

    rng = np.random.default_rng(3)
    db = jnp.asarray(rng.integers(0, 1 << 32, (8192, 8), dtype=np.uint32))
    bits = rng.integers(0, 2, (8, 8192), dtype=np.uint32)
    sel = jnp.asarray(pack_selection_bits_np(bits))
    db_perm = permute_db_bitmajor(db)
    want_ip = np.asarray(xor_inner_product(db, sel))

    def smoke_ip(fn, **kw):
        got = np.asarray(fn(db_perm, sel, **kw))
        assert np.array_equal(got, want_ip), "bit mismatch vs jnp"

    check("ip_pallas_v1", lambda: smoke_ip(xor_inner_product_pallas_staged))
    check("ip_pallas2_int8",
          lambda: smoke_ip(xor_inner_product_pallas2_staged, int8=True))
    check("ip_pallas2_bf16",
          lambda: smoke_ip(xor_inner_product_pallas2_staged, int8=False))

    # Level kernels vs XLA twins.
    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        expand_level_planes_pallas,
        value_hash_planes_pallas,
    )
    from distributed_point_functions_tpu import keys as fixed_keys
    from distributed_point_functions_tpu.ops.aes_bitslice import (
        mmo_hash_planes,
        pack_select_bits,
    )
    from distributed_point_functions_tpu.pir.dense_eval_planes import (
        _tile_keys,
        expand_level_planes,
        pack_key_bits,
        pack_key_planes,
    )

    g, nk = 64, 64
    kgp = pack_key_planes(
        jnp.asarray(rng.integers(0, 1 << 32, (nk, 4), dtype=np.uint32))
    )
    kgl = pack_key_bits(
        jnp.asarray(rng.integers(0, 2, (nk,), dtype=np.uint32))
    )
    kgr = pack_key_bits(
        jnp.asarray(rng.integers(0, 2, (nk,), dtype=np.uint32))
    )
    state = jnp.asarray(
        rng.integers(0, 1 << 32, (16, 8, g), dtype=np.uint32)
    )
    ctrl = jnp.asarray(rng.integers(0, 1 << 32, (g,), dtype=np.uint32))

    def smoke_level():
        want_s, want_c = expand_level_planes(
            state, ctrl, _tile_keys(kgp, 2 * g), _tile_keys(kgl, g),
            _tile_keys(kgr, g),
        )
        got_s, got_c = expand_level_planes_pallas(
            state, ctrl, kgp, kgl, kgr
        )
        assert np.array_equal(np.asarray(got_s), np.asarray(want_s))
        assert np.array_equal(np.asarray(got_c), np.asarray(want_c))

    check("level_expand_pallas", smoke_level)

    def smoke_value():
        want = mmo_hash_planes(fixed_keys.RK_VALUE, state) ^ (
            _tile_keys(kgp, g) & ctrl[None, None, :]
        )
        got = value_hash_planes_pallas(state, ctrl, kgp)
        assert np.array_equal(np.asarray(got), np.asarray(want))

    check("value_hash_pallas", smoke_value)

    def smoke_path():
        from distributed_point_functions_tpu import dpf as dpf_mod

        sel_bits = pack_select_bits(
            jnp.asarray(rng.integers(0, 2, (32 * g,), dtype=np.uint32))
        )
        # Differential via the full walk (shared-cw mode, one level).
        n = 32 * g
        seeds = jnp.asarray(
            rng.integers(0, 1 << 32, (n, 4), dtype=np.uint32)
        )
        control = jnp.asarray(rng.integers(0, 2, (n,), dtype=np.uint32))
        paths = jnp.asarray(
            rng.integers(0, 1 << 32, (n, 4), dtype=np.uint32)
        )
        cw_seeds = jnp.asarray(
            rng.integers(0, 1 << 32, (2, 1, 4), dtype=np.uint32)
        )
        cw_l = jnp.asarray(rng.integers(0, 2, (2, 1), dtype=np.uint32))
        cw_r = jnp.asarray(rng.integers(0, 2, (2, 1), dtype=np.uint32))
        bidx = jnp.asarray(np.array([1, 0], dtype=np.uint32))
        want = dpf_mod._eval_paths_limb(
            seeds, control, paths, cw_seeds, cw_l, cw_r, bidx
        )
        got = dpf_mod._eval_paths_planes(
            seeds, control, paths, cw_seeds, cw_l, cw_r, bidx,
            level_kernel=True,
        )
        for w, gg in zip(want, got):
            assert np.array_equal(np.asarray(gg), np.asarray(w))
        del sel_bits

    check("path_level_pallas", smoke_path)

    def smoke_walk(unroll):
        from distributed_point_functions_tpu.ops.expand_planes_pallas import (
            tail_node_permutation,
            walk_descend_planes_pallas,
        )

        nk, r = 64, 2
        kg = nk // 32
        g0 = 4 * kg
        st = jnp.asarray(
            rng.integers(0, 1 << 32, (16, 8, g0), dtype=np.uint32)
        )
        ct = jnp.asarray(
            rng.integers(0, 1 << 32, (g0,), dtype=np.uint32)
        )
        cwp = jnp.asarray(
            rng.integers(0, 1 << 32, (r, 16, 8, kg), dtype=np.uint32)
        )
        cwl = jnp.asarray(
            rng.integers(0, 1 << 32, (r, kg), dtype=np.uint32)
        )
        cwr = jnp.asarray(
            rng.integers(0, 1 << 32, (r, kg), dtype=np.uint32)
        )
        vc = jnp.asarray(
            rng.integers(0, 1 << 32, (16, 8, kg), dtype=np.uint32)
        )
        s, c = st, ct
        for i in range(r):
            g2 = 2 * s.shape[-1]
            s, c = expand_level_planes(
                s, c, _tile_keys(cwp[i], g2), _tile_keys(cwl[i], g2 // 2),
                _tile_keys(cwr[i], g2 // 2),
            )
        want = mmo_hash_planes(fixed_keys.RK_VALUE, s) ^ (
            _tile_keys(vc, s.shape[-1]) & c[None, None, :]
        )
        n_entry = g0 // kg
        pos_of_leaf = tail_node_permutation(
            np.arange(n_entry), r, n_entry
        )[1]
        lanes = (
            pos_of_leaf[:, None] * kg + np.arange(kg)[None, :]
        ).reshape(-1)
        got_v, got_c = walk_descend_planes_pallas(
            st, ct, cwp, cwl, cwr, vc, r=r, tile_lanes=g0 << r,
            value_hash=True, unroll=unroll,
        )
        assert np.array_equal(
            np.asarray(got_v), np.asarray(want)[:, :, lanes]
        )
        assert np.array_equal(np.asarray(got_c), np.asarray(c)[lanes])

    check("walk_descend_pallas", lambda: smoke_walk(True))
    check("walk_descend_pallas_loop", lambda: smoke_walk(False))


if __name__ == "__main__":
    main()
