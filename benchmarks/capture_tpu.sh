#!/usr/bin/env bash
# One-shot TPU perf capture for the round: headline bench (+ns/leaf +
# expansion/IP split), BASELINE large configs, and the DCF/MIC/dpf sweeps.
# Results land in benchmarks/results/.
set -u
cd "$(dirname "$0")/.."
mkdir -p benchmarks/results
stamp=$(date +%Y%m%d_%H%M%S)

echo "=== headline bench (2^20 x 256B) ==="
python bench.py 2>benchmarks/results/bench_${stamp}.log \
    | tee benchmarks/results/bench_${stamp}.json
tail -20 benchmarks/results/bench_${stamp}.log

echo "=== BASELINE large configs ==="
python benchmarks/baseline_suite.py --scale full --suite dense_big \
    2>&1 | tee benchmarks/results/dense_big_${stamp}.json
python benchmarks/baseline_suite.py --scale full --suite sparse_big \
    2>&1 | tee benchmarks/results/sparse_big_${stamp}.json

echo "=== reference-mirroring sweeps (big) ==="
python benchmarks/run_benchmarks.py --suite dcf,mic,inner_product --big \
    2>&1 | tee benchmarks/results/sweeps_${stamp}.json

echo "=== synthetic hierarchical eval (reference experiments config) ==="
python benchmarks/synthetic_data_benchmarks.py --log_domain_size 32 \
    --log_num_nonzeros 20 --num_iterations 3 \
    2>&1 | tee benchmarks/results/synthetic_${stamp}.json

echo "done: benchmarks/results/*_${stamp}.*"
