#!/usr/bin/env bash
# One-shot TPU perf capture for the round: headline bench (+ns/leaf +
# expansion/IP split), BASELINE large configs, and the DCF/MIC/dpf sweeps.
# Results land in benchmarks/results/.
set -u -o pipefail
cd "$(dirname "$0")/.."
mkdir -p benchmarks/results
stamp=$(date +%Y%m%d_%H%M%S)
fail=0

# Every suite runs under `timeout`: the observed tunnel-stall mode blocks
# inside block_until_ready where no Python-level watchdog can be relied
# on, and a hung suite would kill the watcher's recovery loop.

# Pass 0 — minimal headline first. The tunnel has come up for windows as
# short as ~4 minutes; one single-config compile (planes: ~150-320 s cold,
# seconds when the persistent compile cache is warm) plus a short
# measurement maximizes the chance a brief window still yields the
# round's gating number before the full A/B + sweeps below. The budget
# must cover init (90 s fast-fail here) + a cold planes compile +
# the limb-fallback recompile bench.py runs when planes is unusable.
echo "=== quick headline (planes single-config, no secondary metrics) ==="
timeout 1000 env BENCH_ITERS=8 BENCH_INIT_BUDGET=90 \
    BENCH_TIMEOUT=900 python bench.py \
    2>benchmarks/results/bench_quick_${stamp}.log \
    | tee benchmarks/results/bench_quick_${stamp}.json
tail -5 benchmarks/results/bench_quick_${stamp}.log

echo "=== headline bench (2^20 x 256B, expansion A/B + ns/leaf) ==="
rm -f benchmarks/results/bench_extra.json
timeout 2700 env BENCH_EXPANSION=both BENCH_NSLEAF=1 BENCH_TIMEOUT=2600 \
    BENCH_INIT_BUDGET=120 \
    python bench.py 2>benchmarks/results/bench_${stamp}.log \
    | tee benchmarks/results/bench_${stamp}.json || fail=1
tail -20 benchmarks/results/bench_${stamp}.log
# The capture "really happened" iff a positive headline value was
# measured (the watchdog may emit a valid qps plus an error field when
# only a late-stage secondary metric stalled — that still counts).
python - benchmarks/results/bench_${stamp}.json <<'EOF' || fail=1
import json, sys
with open(sys.argv[1]) as f:
    line = f.read().strip()
sys.exit(0 if line and json.loads(line).get("value", 0) > 0 else 1)
EOF
# Preserve this run's secondary metrics before a later run overwrites
# the fixed path.
[ -f benchmarks/results/bench_extra.json ] && \
    cp benchmarks/results/bench_extra.json \
       benchmarks/results/bench_extra_${stamp}.json

echo "=== BASELINE large configs ==="
timeout 3600 python benchmarks/baseline_suite.py --scale full \
    --suite dense_big \
    2>&1 | tee benchmarks/results/dense_big_${stamp}.json || fail=1
timeout 3600 python benchmarks/baseline_suite.py --scale full \
    --suite sparse_big \
    2>&1 | tee benchmarks/results/sparse_big_${stamp}.json || fail=1

echo "=== reference-mirroring sweeps (big) ==="
timeout 3600 python benchmarks/run_benchmarks.py \
    --suite dpf,dcf,mic,inner_product,int_mod_n --big \
    2>&1 | tee benchmarks/results/sweeps_${stamp}.json || fail=1

echo "=== synthetic hierarchical eval (reference experiments config) ==="
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --num_iterations 3 \
    2>&1 | tee benchmarks/results/synthetic_${stamp}.json || fail=1

echo "=== synthetic direct eval at 2^20 nonzeros (CPU baseline: 0.67s) ==="
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --only_nonzeros \
    --num_iterations 3 \
    2>&1 | tee benchmarks/results/only_nonzeros_${stamp}.json || fail=1

echo "=== domain 2^128 (CPU baselines: 32.7s hierarchical, 3.1s direct) ==="
timeout 3600 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 128 --log_num_nonzeros 20 --num_iterations 2 \
    2>&1 | tee benchmarks/results/synthetic128_${stamp}.json || fail=1
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 128 --log_num_nonzeros 20 --only_nonzeros \
    --num_iterations 3 \
    2>&1 | tee benchmarks/results/only_nonzeros128_${stamp}.json || fail=1

echo "done (fail=$fail): benchmarks/results/*_${stamp}.*"
exit $fail
