#!/usr/bin/env bash
# Poll the axon TPU tunnel; the moment it answers, run the full perf
# capture (benchmarks/capture_tpu.sh). Writes a heartbeat log so a stalled
# tunnel is distinguishable from a stalled capture.
set -u
cd "$(dirname "$0")/.."
log=benchmarks/results/tpu_watch.log
mkdir -p benchmarks/results

probe() {
    timeout 75 python - <<'EOF' >/dev/null 2>&1
import numpy as np
import jax
# sitecustomize sets jax_platforms="axon,cpu": a fast axon init failure
# silently falls back to CPU, so assert the device really is the TPU.
assert jax.devices()[0].platform == "tpu", jax.devices()
x = jax.device_put(np.zeros(8, np.uint32))
x.block_until_ready()
jax.jit(lambda a: a ^ np.uint32(3))(x).block_until_ready()
EOF
}

# Deadline (epoch seconds, env TPU_WATCH_DEADLINE): no capture *starts*
# within 45 min of it, and polling stops at it, to keep watcher captures
# clear of the round's driver-run bench on the single-client tunnel. (A
# healthy capture finishes well inside 45 min; only a mid-capture tunnel
# stall runs longer, and then the driver bench would be stalled anyway.)
deadline=${TPU_WATCH_DEADLINE:-0}
margin=2700

while true; do
    if [ "$deadline" -gt 0 ] && \
       [ "$(date +%s)" -ge "$((deadline - margin))" ]; then
        echo "$(date -u +%H:%M:%S) deadline margin reached - exiting" >>"$log"
        exit 0
    fi
    if probe; then
        echo "$(date -u +%H:%M:%S) tunnel ALIVE - launching capture" >>"$log"
        bash "${CAPTURE_SCRIPT:-benchmarks/capture_tpu.sh}" >>"$log" 2>&1
        rc=$?
        echo "$(date -u +%H:%M:%S) capture exited rc=$rc" >>"$log"
        if [ "$rc" -eq 0 ]; then
            exit 0
        fi
        # Capture died (tunnel dropped mid-run?): go back to polling.
    else
        echo "$(date -u +%H:%M:%S) tunnel down" >>"$log"
    fi
    # 1-vCPU machine: each probe costs ~30s of CPU (jax import), so poll
    # sparingly to leave the core free for the build.
    sleep 180
done
