#!/usr/bin/env bash
# Round-3 TPU capture queue, ordered by VERDICT r02 priority: the gating
# headline number first (single-config, compile-cache-friendly), then the
# level-kernel A/B (the expansion bottleneck), the batch-size sweep +
# xprof trace, ns/leaf at two domains, DCF/MIC on TPU, sparse re-capture,
# and the synthetic hierarchical configs. Results commit after every
# stage with the stage's exit code recorded, so a mid-window tunnel stall
# neither loses earlier results nor forges a "window succeeded" commit.
set -u -o pipefail
cd "$(dirname "$0")/.."
mkdir -p benchmarks/results
stamp=$(date +%Y%m%d_%H%M%S)
rcs=""
fail=0

# Stage guard: with TPU_WATCH_DEADLINE exported (epoch secs, the round
# driver's bench time), refuse to START a stage whose nominal timeout
# could still be running inside the driver's 45-min margin — the tunnel
# serves one client and a late capture must not contend with the
# round's own bench.
stage_fits() {
    # stage_fits <nominal_timeout_secs>
    local deadline=${TPU_WATCH_DEADLINE:-0}
    [ "$deadline" -le 0 ] && return 0
    local now margin=2700
    now=$(date +%s)
    if [ $((now + $1)) -ge $((deadline - margin)) ]; then
        echo "deadline margin: skipping remaining stages" >&2
        return 1
    fi
    return 0
}

commit_stage() {
    # commit_stage <name> <rc>; commits ONLY the results pathspec so a
    # pre-staged unrelated change can't be swept into a capture commit.
    rcs="${rcs}${rcs:+ }$1=$2"
    [ "$2" -ne 0 ] && fail=1
    git add benchmarks/results >/dev/null 2>&1
    git commit -q -m "TPU window3 capture: stage $1 rc=$2 (${stamp})" \
        -- benchmarks/results >/dev/null 2>&1 || true
}

finish() {
    # Always land the summary commit, whether the queue completed or a
    # deadline guard cut it short; the per-stage rc list tells which.
    echo "window3 done (${stamp}): $rcs (fail=$fail)"
    git add benchmarks/results >/dev/null 2>&1
    git commit -q -m "TPU window3 capture (${stamp}): $rcs" \
        -- benchmarks/results >/dev/null 2>&1 || true
    exit $fail
}

stage_fits 1000 || finish
echo "=== 1. headline (planes single-config, q128) ==="
timeout 1000 env BENCH_ITERS=16 BENCH_INIT_BUDGET=90 BENCH_TIMEOUT=900 \
    BENCH_XPROF=benchmarks/results/xprof_${stamp} python bench.py \
    2>benchmarks/results/bench_q128_${stamp}.log \
    | tee benchmarks/results/bench_q128_${stamp}.json
commit_stage headline $?

# Stage 1 doubles as the driver-cache warmer: it compiles the exact
# driver-config programs into ~/.cache/jax_bench (same shapes, same
# cache dir), so the driver's own run hits warm compiles. Stage 1b then
# measures what a truly COLD driver run would cost, against a throwaway
# cache, so BENCH_TIMEOUT is set from data instead of hope (VERDICT r03
# weak #6). Low priority order cost: one extra headline run.
# Skipped (not finish) when it doesn't fit: this stage is lower
# priority than the A/B legs after it, which may still fit.
if stage_fits 2100; then
    echo "=== 1b. cold-path wall clock (fresh compile cache) ==="
    cold_cache=$(mktemp -d)
    cold_t0=$(date +%s)
    timeout 2000 env BENCH_CACHE_DIR="$cold_cache" BENCH_ITERS=8 \
        BENCH_INIT_BUDGET=90 BENCH_TIMEOUT=1900 python bench.py \
        2>benchmarks/results/bench_cold_${stamp}.log \
        | tee benchmarks/results/bench_cold_${stamp}.json
    rc=$?
    cold_secs=$(( $(date +%s) - cold_t0 ))
    rm -rf "$cold_cache"
    echo "{\"cold_path_wall_secs\": ${cold_secs}, \"rc\": ${rc}}" \
        | tee benchmarks/results/cold_path_${stamp}.json
    commit_stage cold_path $rc
fi

echo "=== 2. level-kernel A/B (head+tail / tail / pallas / XLA) ==="
# Explicit head counts: forced DPF_TPU_LEVEL_KERNEL legs skip the
# self-checks, so the auto head would silently stay off. 9 levels fills
# the 2048-lane cap at the headline kg=4.
for leg in "tailhead tail 9" "tail tail 0" "pallas pallas 0" \
           "xla xla 0"; do
    set -- $leg
    name=$1; lk=$2; head=$3
    stage_fits 1500 || finish
    timeout 1500 env DPF_TPU_LEVEL_KERNEL=$lk DPF_TPU_HEAD_LEVELS=$head \
        BENCH_ITERS=8 \
        BENCH_INIT_BUDGET=90 BENCH_TIMEOUT=1400 python bench.py \
        2>benchmarks/results/bench_lk_${name}_${stamp}.log \
        | tee benchmarks/results/bench_lk_${name}_${stamp}.json
    rc=$?
    tail -4 benchmarks/results/bench_lk_${name}_${stamp}.log
    commit_stage lk_$name $rc
done

stage_fits 2400 || finish
echo "=== 2b. level/tail kernel shape probe ==="
timeout 2400 python benchmarks/level_kernel_probe.py \
    2>benchmarks/results/level_probe_${stamp}.log \
    | tee benchmarks/results/level_probe_${stamp}.json
commit_stage level_probe $?

echo "=== 3. batch sweep (q64..q512; both expansions at q256 cliff) ==="
for q in 64 256 512; do
    stage_fits 1200 || finish
    mode=planes
    [ "$q" = 256 ] && mode=both
    rm -f benchmarks/results/bench_extra.json
    timeout 1200 env BENCH_QUERIES=$q BENCH_EXPANSION=$mode \
        BENCH_ITERS=8 BENCH_INIT_BUDGET=90 BENCH_TIMEOUT=1100 \
        python bench.py \
        2>benchmarks/results/bench_q${q}_${stamp}.log \
        | tee benchmarks/results/bench_q${q}_${stamp}.json
    rc=$?
    cp benchmarks/results/bench_extra.json \
        benchmarks/results/bench_extra_q${q}_${stamp}.json 2>/dev/null
    commit_stage q$q $rc
done

stage_fits 1800 || finish
echo "=== 3b. inner-product tile matrix (honest labels, min-of-3) ==="
timeout 1800 python benchmarks/ip_ab.py \
    2>benchmarks/results/ip_ab_${stamp}.log \
    | tee benchmarks/results/ip_ab_${stamp}.json
commit_stage ip_ab $?

stage_fits 3000 || finish
echo "=== 4. ns/leaf at log-domain 20 and 24 ==="
for ld in 20 24; do
    timeout 1500 env BENCH_ONLY_NSLEAF=1 BENCH_NSLEAF_LD=$ld \
        BENCH_INIT_BUDGET=90 BENCH_TIMEOUT=1400 python bench.py \
        2>benchmarks/results/bench_nsleaf_ld${ld}_${stamp}.log \
        | tee benchmarks/results/bench_nsleaf_ld${ld}_${stamp}.json
    commit_stage nsleaf_ld$ld $?
done

stage_fits 3600 || finish
echo "=== 5. DCF/MIC reference sweeps on TPU ==="
timeout 3600 python benchmarks/run_benchmarks.py --suite dcf,mic --big \
    2>benchmarks/results/dcf_mic_tpu_${stamp}.log \
    | tee benchmarks/results/dcf_mic_tpu_${stamp}.jsonl
commit_stage dcf_mic $?

stage_fits 3600 || finish
echo "=== 6. sparse PIR re-capture (native builder + batched queries) ==="
timeout 3600 python benchmarks/baseline_suite.py --scale full \
    --suite sparse_big \
    2>&1 | tee benchmarks/results/sparse_big_${stamp}.json
commit_stage sparse_big $?

stage_fits 2700 || finish
echo "=== 7. synthetic hierarchical (reference experiments configs) ==="
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --num_iterations 3 \
    2>&1 | tee benchmarks/results/synthetic_${stamp}.json
commit_stage synthetic32 $?
stage_fits 2700 || finish
timeout 2700 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 32 --log_num_nonzeros 20 --only_nonzeros \
    --num_iterations 3 \
    2>&1 | tee benchmarks/results/only_nonzeros_${stamp}.json
commit_stage direct32 $?
stage_fits 3600 || finish
timeout 3600 python benchmarks/synthetic_data_benchmarks.py \
    --log_domain_size 128 --log_num_nonzeros 20 --num_iterations 2 \
    2>&1 | tee benchmarks/results/synthetic128_${stamp}.json
commit_stage synthetic128 $?

stage_fits 3600 || finish
echo "=== 8. remaining sweeps (dpf/inner_product/int_mod_n) ==="
timeout 3600 python benchmarks/run_benchmarks.py \
    --suite dpf,inner_product,int_mod_n --big \
    2>&1 | tee benchmarks/results/sweeps_${stamp}.json
commit_stage sweeps $?

stage_fits 1800 || finish
echo "=== 9. kernel smoke (shape envelope) ==="
timeout 1800 python benchmarks/kernel_smoke.py \
    2>benchmarks/results/kernel_smoke_${stamp}.log \
    | tee benchmarks/results/kernel_smoke_${stamp}.json
commit_stage kernel_smoke $?

# Nonzero when any stage failed so tpu_watch keeps re-polling the window.
finish
