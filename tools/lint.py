"""Stdlib-only lint for the repo (no flake8/ruff in this image).

Checks, per Python file: syntax (ast.parse), unused imports, trailing
whitespace, tabs in indentation, CRLF line endings, and accidental
`print(` in library code (the package logs via utils/runtime or
logging; benchmarks/tests/tools may print).

Mirrors the role of the reference CI's compiler-warning gate
(`.bazelci/presubmit.yml:15-34`) at the level a Python codebase needs.
Exit code 0 = clean.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# Directories scanned; _pb2 files are generated and exempt.
SCAN_DIRS = ["distributed_point_functions_tpu", "tests", "benchmarks", "tools"]
TOP_LEVEL = ["bench.py", "__graft_entry__.py"]
PRINT_OK_DIRS = {"tests", "benchmarks", "tools", "examples"}


def _iter_files():
    for d in SCAN_DIRS:
        root = REPO / d
        if root.is_dir():
            yield from sorted(root.rglob("*.py"))
    for f in TOP_LEVEL:
        p = REPO / f
        if p.exists():
            yield p


def _unused_imports(tree: ast.AST, src: str) -> list[tuple[int, str]]:
    imported: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                imported[a.asname or a.name] = node.lineno
    if not imported:
        return []
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # the base Name is walked separately
    # __all__ reexports and doctest-style usage count as used.
    for name in list(imported):
        if name in used or f'"{name}"' in src or f"'{name}'" in src:
            imported.pop(name)
    return [(line, name) for name, line in sorted(imported.items())]


def main() -> int:
    problems: list[str] = []
    for path in _iter_files():
        rel = path.relative_to(REPO)
        if path.name.endswith("_pb2.py"):
            continue
        reexport_ok = path.name == "__init__.py"
        raw = path.read_bytes()
        if b"\r\n" in raw:
            problems.append(f"{rel}: CRLF line endings")
        src = raw.decode("utf-8")
        try:
            tree = ast.parse(src, filename=str(rel))
        except SyntaxError as e:
            problems.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
            continue
        if not reexport_ok:
            for lineno, name in _unused_imports(tree, src):
                problems.append(f"{rel}:{lineno}: unused import '{name}'")
        lib_code = rel.parts[0] not in PRINT_OK_DIRS and not any(
            part in ("examples",) for part in rel.parts
        )
        for i, line in enumerate(src.splitlines(), 1):
            if line.rstrip() != line:
                problems.append(f"{rel}:{i}: trailing whitespace")
            if line[: len(line) - len(line.lstrip())].count("\t"):
                problems.append(f"{rel}:{i}: tab in indentation")
        if lib_code and str(rel) not in TOP_LEVEL:
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                ):
                    problems.append(
                        f"{rel}:{node.lineno}: print() in library code "
                        "(use logging)"
                    )
    for p in problems:
        print(p)
    print(f"lint: {len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
