#!/usr/bin/env python3
"""Layer-DAG and import-cycle presubmit check (stdlib AST, no deps).

Three rules over `distributed_point_functions_tpu/`:

1. **Layer DAG** — `fleet -> heavy_hitters -> serving -> pir ->
   capacity -> ops -> observability -> robustness`, never the reverse,
   with restricted
   layers: the serving runtime may only be imported by
   `heavy_hitters/` (the one in-library session kind built on it),
   the prober, and `fleet/` (the replica composition layer: registry,
   price-aware router, quorum rotation — the topmost leaf), and
   `heavy_hitters` itself is application-facing — no library layer
   imports it (applications — examples/, bench.py, benchmarks/ — may
   import anything). `observability` sits near the bottom on purpose:
   every layer may instrument itself (spans, runtime counters,
   compile/HBM telemetry), but observability — `device.py`, `slo.py`,
   `critical_path.py`, `utilization.py`, and `timeseries.py`
   included — imports only `utils/`, stdlib, and
   `robustness/` — never pir/ops/serving — so telemetry can never
   create an upward edge (serving pushes busy/idle intervals into the
   utilization tracker through duck-typed hooks, same as
   `default_telemetry`). `capacity` (the shared byte/throughput
   model plus admission and brownout policy) sits below every
   workload: pir, serving, and heavy_hitters all consume it, and it
   may instrument itself via observability but never import a
   workload back. `robustness` (fault injection, circuit
   breaker, checkpoints) is the true bottom: stdlib-only, so even the
   device dispatch bracket can host a failpoint. Checked over ALL
   imports, including function-level ones, because a reversed
   dependency is wrong wherever the import statement sits.

2. **No module-level import cycles** — the repo's sanctioned idiom for
   breaking genuine cycles is the function-level import, so only
   imports that execute at module import time participate in the cycle
   graph.

3. **Library never imports applications** — `bench.py`, `benchmarks/`
   (the regression gate and its history store), `examples/`, and
   `tools/` sit *outside* the package and may import any layer
   (`benchmarks/` imports observability for exposition); no package
   module may import them back. In particular the regression gate
   depends on observability, never the reverse.

Exit 0 on success; prints each violation and exits 1 otherwise.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

PACKAGE = "distributed_point_functions_tpu"
ROOT = Path(__file__).resolve().parent.parent

# Layer order, outermost first: a module may import same-or-lower
# layers only. Subpackages not listed are unconstrained by rule 1
# (but still cycle-checked by rule 2).
LAYERS = {
    "fleet": 9,
    "prober": 8,
    "heavy_hitters": 7,
    "serving": 6,
    "pir": 5,
    "capacity": 4,
    "ops": 3,
    "observability": 2,
    "robustness": 1,
}

# Individual modules promoted out of their directory's layer. The
# blackbox prober lives in serving/ for discoverability but *drives*
# both serving and heavy_hitters (it replays golden queries through
# them), so it gets its own top layer; `serving/__init__.py`
# deliberately does not export it — that import would be serving ->
# prober, an upward edge.
MODULE_LAYERS = {f"{PACKAGE}.serving.prober": "prober"}

# Restricted layers: importable only from the listed source layers
# (plus themselves). serving stays a near-leaf — its in-library
# consumers are the heavy_hitters session, the prober, and the fleet
# composition layer; heavy_hitters is a true leaf only applications
# (and the prober) may import; the prober may additionally be consumed
# by fleet/ (the registry hands `CrossReplicaProbe` the replicas);
# fleet itself is the topmost true leaf.
RESTRICTED = {
    "serving": {"heavy_hitters", "prober", "fleet"},
    "heavy_hitters": {"prober"},
    "prober": {"fleet"},
    "fleet": set(),
}

# Application namespaces living outside the package: they may import
# any layer, but no package module may import them (rule 3). Keeps
# benchmarks/ -> observability a one-way edge.
APPLICATIONS = {"bench", "benchmarks", "examples", "tools"}


def module_name(path: Path) -> str:
    rel = path.relative_to(ROOT).with_suffix("")
    parts = list(rel.parts)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def resolve_relative(module: str, node: ast.ImportFrom, is_pkg: bool) -> str:
    """Absolute dotted name for a (possibly relative) import-from."""
    if node.level == 0:
        return node.module or ""
    base = module.split(".")
    # A package's __init__ resolves level-1 against itself.
    up = node.level - (1 if is_pkg else 0)
    if up:
        base = base[:-up]
    return ".".join(base + ([node.module] if node.module else []))


def collect(path: Path):
    """Returns (all_imports, module_level_imports) as absolute names."""
    tree = ast.parse(path.read_text(), filename=str(path))
    module = module_name(path)
    is_pkg = path.name == "__init__.py"
    all_imports, top_imports = [], []

    def visit(node, top):
        for child in ast.iter_child_nodes(node):
            inner_top = top and not isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
            )
            if isinstance(child, ast.Import):
                names = [a.name for a in child.names]
            elif isinstance(child, ast.ImportFrom):
                base = resolve_relative(module, child, is_pkg)
                names = [
                    f"{base}.{a.name}" if base else a.name
                    for a in child.names
                ]
            else:
                visit(child, inner_top)
                continue
            all_imports.extend(names)
            if top:
                top_imports.extend(names)

    visit(tree, top=True)
    return all_imports, top_imports


def layer_of(module: str):
    for name, layer in MODULE_LAYERS.items():
        if module == name or module.startswith(name + "."):
            return layer
    parts = module.split(".")
    if len(parts) >= 2 and parts[0] == PACKAGE and parts[1] in LAYERS:
        return parts[1]
    return None


def find_cycle(graph):
    """First module-level import cycle found via iterative DFS, or None."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {m: WHITE for m in graph}
    for start in sorted(graph):
        if color[start] != WHITE:
            continue
        stack = [(start, iter(sorted(graph[start])))]
        color[start] = GRAY
        path = [start]
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if nxt not in graph:
                    continue
                if color[nxt] == GRAY:
                    return path[path.index(nxt):] + [nxt]
                if color[nxt] == WHITE:
                    color[nxt] = GRAY
                    path.append(nxt)
                    stack.append((nxt, iter(sorted(graph[nxt]))))
                    advanced = True
                    break
            if not advanced:
                color[node] = BLACK
                path.pop()
                stack.pop()
    return None


def main() -> int:
    pkg_root = ROOT / PACKAGE
    violations = []
    graph = {}
    for path in sorted(pkg_root.rglob("*.py")):
        module = module_name(path)
        try:
            all_imports, top_imports = collect(path)
        except SyntaxError as e:
            violations.append(f"{path}: unparsable ({e})")
            continue
        src_layer = layer_of(module)
        for name in all_imports:
            if name.split(".")[0] in APPLICATIONS:
                violations.append(
                    f"{module}: imports {name} — library modules must "
                    f"never import application code (bench/benchmarks/"
                    f"examples/tools); the dependency runs the other way"
                )
                continue
            tgt_layer = layer_of(name)
            if tgt_layer is None or src_layer == tgt_layer:
                continue
            if (
                tgt_layer in RESTRICTED
                and src_layer not in RESTRICTED[tgt_layer]
            ):
                allowed = ", ".join(sorted(RESTRICTED[tgt_layer])) or (
                    "applications"
                )
                violations.append(
                    f"{module}: imports {name} — only {allowed} (and "
                    f"applications) may depend on the {tgt_layer} layer"
                )
            elif (
                src_layer is not None
                and LAYERS[tgt_layer] > LAYERS[src_layer]
            ):
                # Unlayered support modules (dpf, crypto, prng, ...) may
                # import ops freely; only the ranked layers constrain
                # their upward edges.
                violations.append(
                    f"{module}: imports {name} — reverses the "
                    f"heavy_hitters -> serving -> pir -> capacity -> "
                    f"ops -> observability -> robustness layer DAG"
                )
        graph[module] = {
            n for imp in top_imports
            if (n := _owning_module(imp)) and n.startswith(PACKAGE)
        }

    cycle = find_cycle(graph)
    if cycle:
        violations.append(
            "module-level import cycle: " + " -> ".join(cycle)
        )
    for v in violations:
        print(f"check_layers: {v}")
    if not violations:
        print(f"check_layers: OK ({len(graph)} modules, no cycles, "
              "layer DAG holds)")
    return 1 if violations else 0


def _owning_module(imported: str):
    """Trim `pkg.mod.symbol` to the module part we know about."""
    parts = imported.split(".")
    for cut in range(len(parts), 0, -1):
        candidate = ".".join(parts[:cut])
        if (ROOT / Path(*parts[:cut])).with_suffix(".py").exists() or (
            ROOT / Path(*parts[:cut]) / "__init__.py"
        ).exists():
            return candidate
    return None


if __name__ == "__main__":
    sys.exit(main())
