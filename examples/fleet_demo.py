"""Replica fleet demo: N Leader/Helper pairs behind one front door.

In-process walkthrough of the `fleet/` layer — the composition that
turns one proven Leader/Helper pair into a serving fleet:

1. Build N two-party replicas (each side with its own
   `SnapshotManager`) and register them in a `ReplicaSet`.
2. Route tenants through the price-aware `FleetRouter` front door:
   each tenant sticks to one replica; placement follows the live
   `CapacityModel` price times admission-queue depth.
3. Run one fleet-wide quorum rotation with the
   `FleetRotationCoordinator` — stage generation N+1 everywhere, flip
   on quorum ack (Helper first per pair) — optionally killing one
   replica mid-stage with a failpoint to show the laggard path: shed,
   re-converged party by party, readmitted.
4. Verify cross-replica consistency with `CrossReplicaProbe`: the
   same golden pair reconstructs bit-identically on every replica at
   the same generation.
5. Serve `/fleetz` from an `AdminServer` and print the fleet view.

Run it::

    JAX_PLATFORMS=cpu python examples/fleet_demo.py
    JAX_PLATFORMS=cpu python examples/fleet_demo.py --replicas 5 \
        --kill-mid-stage
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

NUM_RECORDS = 128
RECORD_BYTES = 24


def build_records(generation: int):
    base = [
        (b"record-%04d:" % i).ljust(RECORD_BYTES, b".")
        for i in range(NUM_RECORDS)
    ]
    if generation == 0:
        return base
    mask = [0x00, 0xA5, 0x3C][generation % 3]
    return [bytes(b ^ mask for b in r) for r in base]


def build_db(records, prev=None):
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )

    builder = DenseDpfPirDatabase.Builder()
    if prev is None:
        for r in records:
            builder.insert(r)
        return builder.build()
    for i, r in enumerate(records):
        builder.update(i, r)
    return builder.build_from(prev)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--replicas", type=int, default=3)
    parser.add_argument(
        "--kill-mid-stage",
        action="store_true",
        help="fail one replica's staging to demo the laggard path",
    )
    args = parser.parse_args()

    from distributed_point_functions_tpu.fleet import (
        FleetRotationCoordinator,
        FleetRouter,
        Replica,
        ReplicaSet,
    )
    from distributed_point_functions_tpu.observability import AdminServer
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.robustness import failpoints
    from distributed_point_functions_tpu.serving import (
        HelperSession,
        InProcessTransport,
        LeaderSession,
        ServingConfig,
        SnapshotManager,
    )
    from distributed_point_functions_tpu.serving.prober import (
        CrossReplicaProbe,
    )
    from distributed_point_functions_tpu.testing import encrypt_decrypt

    records0 = build_records(0)
    config = ServingConfig(max_batch_size=8, max_wait_ms=2.0)

    print(f"building {args.replicas} Leader/Helper replicas ...")
    replica_set = ReplicaSet()
    replicas = []
    for i in range(args.replicas):
        helper = HelperSession(
            build_db(records0), encrypt_decrypt.decrypt, config
        )
        leader = LeaderSession(
            build_db(records0),
            InProcessTransport(helper.handle_wire),
            config,
        )
        replica = Replica(
            f"r{i}",
            leader,
            helper,
            leader_snapshots=SnapshotManager(leader),
            helper_snapshots=SnapshotManager(helper),
        )
        replicas.append(replica_set.add(replica))

    router = FleetRouter(replica_set)
    client = DenseDpfPirClient.create(
        NUM_RECORDS, encrypt_decrypt.encrypt
    )

    # -- price-aware front door ---------------------------------------------
    print("\nrouting 4 tenants through the front door:")
    for tenant in ("alice", "bob", "carol", "dave"):
        replica = router.pick(tenant)
        request, state = client.create_request([7, 42])
        response = replica.leader.handle_request(request)
        values = client.handle_response(response, state)
        assert values == [records0[7], records0[42]]
        print(
            f"  tenant {tenant!r} -> {replica.replica_id} "
            f"(device_ms {replica.price()['device_ms']:.3f}, "
            f"queue {replica.queue_depth():.0f}) : "
            f"{values[0][:14].decode()}..."
        )

    # -- fleet-wide quorum rotation -----------------------------------------
    records1 = build_records(1)
    if args.kill_mid_stage:
        print("\narming failpoint: r1 dies mid-stage (once)")
        failpoints.default_failpoints().arm(
            "fleet.stage.r1", "error", times=1
        )

    def next_dbs(replica):
        return (
            build_db(records1, replica.leader.server.database),
            build_db(records1, replica.helper.server.database),
        )

    print("rotating the fleet to generation 1 (quorum "
          f"{len(replicas) // 2 + 1}/{len(replicas)}) ...")
    report = FleetRotationCoordinator(replica_set).rotate(next_dbs)
    failpoints.default_failpoints().clear()
    print(
        f"  acked {sorted(report['acked'])}, laggards "
        f"{report['laggards'] or 'none'}, worst staleness "
        f"{report['staleness_ms']:.2f} ms"
    )
    for replica in replicas:
        assert replica.serving_generation() == 1

    request, state = client.create_request([7])
    replica = router.pick("alice")
    values = client.handle_response(
        replica.leader.handle_request(request), state
    )
    assert values == [records1[7]]
    print(f"  post-flip lookup via {replica.replica_id}: "
          f"{values[0][:8].hex()}... (generation 1, masked bytes)")

    # -- cross-replica consistency ------------------------------------------
    probe = CrossReplicaProbe(
        replicas,
        records1,
        records_provider=lambda gen: records1 if gen == 1 else None,
    )
    result = probe.run_cycle()
    print(
        f"\ncross-replica probe: {result['status']} "
        f"(generations {result['generations']}, "
        f"{len(result['divergences'])} divergences)"
    )
    assert result["status"] == "pass"

    # -- /fleetz --------------------------------------------------------------
    with AdminServer(fleet=replica_set) as admin:
        url = f"http://127.0.0.1:{admin.port}/fleetz"
        state = json.loads(
            urllib.request.urlopen(url, timeout=10).read()
        )
    print(f"\n/fleetz: counts {state['counts']}, "
          f"sheds {state['sheds']}, readmissions {state['readmissions']}")
    for rid, row in state["replicas"].items():
        print(f"  {rid}: {row['state']} at generation "
              f"{row['serving_generation']} ({row['reason']})")

    for replica in replicas:
        replica.leader.close()
        replica.helper.close()
    print("\nfleet demo: OK")


if __name__ == "__main__":
    main()
