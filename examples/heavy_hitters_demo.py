"""Two-server private heavy hitters — the sweep end to end.

Thin CLI over `distributed_point_functions_tpu/heavy_hitters/`: clients
secret-share their string values as incremental DPF key pairs, the two
servers sweep the prefix hierarchy level by level (batched evaluation
from cached cut states, threshold pruning), and only the heavy-hitter
strings and their counts emerge. Neither server ever sees a value.

Modes:

    python examples/heavy_hitters_demo.py --demo
        In-process: both servers and the Leader/Helper wire protocol
        (`InProcessTransport`) in one process, with a plaintext check.

    python examples/heavy_hitters_demo.py --tcp
        Same sweep with the Helper behind a real framed TCP socket
        (`FramedTcpServer` on a loopback port in the same process).

    python examples/heavy_hitters_demo.py --smoke
        Tiny fixture (8-bit domain, 2 levels) for CI presubmit: seconds
        on CPU, asserts the private answer equals the plaintext oracle.
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_reports(config, values, seed: int = 0):
    """Generate every client's key pair; returns (keys0, keys1)."""
    from distributed_point_functions_tpu import heavy_hitters as hh

    client = hh.HeavyHittersClient(config)
    keys0, keys1 = [], []
    for v in values:
        k0, k1 = client.generate_report(v)
        keys0.append(k0)
        keys1.append(k1)
    return keys0, keys1


def demo_values(num_clients: int, seed: int):
    """A skewed value population: a few popular strings plus noise."""
    rng = random.Random(seed)
    popular = [b"cats", b"dogs", b"tpus"]
    weights = [5, 4, 3]
    values = []
    for v, w in zip(popular, weights):
        values.extend([v] * w)
    while len(values) < num_clients:
        values.append(bytes(rng.choices(b"abcdefgh", k=4)))
    rng.shuffle(values)
    return values[:num_clients]


def run_sweep(
    config,
    values,
    transport_kind: str,
    verbose: bool = True,
    admin_port=None,
):
    from distributed_point_functions_tpu import heavy_hitters as hh
    from distributed_point_functions_tpu.serving.transport import (
        FramedTcpServer,
        InProcessTransport,
        TcpTransport,
    )

    t0 = time.perf_counter()
    keys0, keys1 = build_reports(config, values)
    keygen_s = time.perf_counter() - t0

    leader_server = hh.HeavyHittersServer(config, keys0)
    helper_server = hh.HeavyHittersServer(config, keys1)
    helper = hh.HeavyHittersHelper(helper_server)

    tcp_server = None
    if transport_kind == "tcp":
        tcp_server = FramedTcpServer(
            helper.handle_wire, port=0, name="hh-helper"
        ).start()
        transport = TcpTransport("localhost", tcp_server.port)
        if verbose:
            print(f"[helper] framed TCP on :{tcp_server.port}")
    else:
        transport = InProcessTransport(helper.handle_wire)

    leader = hh.HeavyHittersLeader(leader_server, transport)
    admin = None
    if admin_port is not None:
        from distributed_point_functions_tpu.observability import (
            AdminServer,
            tracing,
        )

        admin = AdminServer(
            registry=leader.metrics,
            recorder=tracing.default_recorder(),
            port=admin_port,
            name="hh-leader",
        ).start()
        print(
            f"[leader] admin endpoint on :{admin.port} "
            "(/metrics /varz /tracez /healthz /profilez)"
        )
    try:
        t0 = time.perf_counter()
        result = leader.run()
        sweep_s = time.perf_counter() - t0
    finally:
        transport.close()
        if tcp_server is not None:
            tcp_server.stop()
        if admin is not None:
            admin.stop()

    if verbose:
        for st in result.rounds:
            print(
                f"round {st.round_index} ({st.bit_width:>2} bits): "
                f"frontier={st.frontier_width:<5} "
                f"survivors={st.survivors:<4} "
                f"prune={st.prune_ratio:.2f} "
                f"{st.wall_ms:8.1f} ms  "
                f"{st.bytes_sent + st.bytes_received} B on the wire"
            )
        print(
            f"{len(values)} clients: keygen {keygen_s:.2f}s, "
            f"sweep {sweep_s:.2f}s over {len(result.rounds)} rounds "
            f"({transport_kind} transport)"
        )
    return result


def check_result(result, values, config) -> None:
    from distributed_point_functions_tpu import heavy_hitters as hh

    want = hh.plaintext_heavy_hitters(values, config)
    got = result.as_dict()
    byte_aligned = config.domain_bits % 8 == 0
    for alpha in sorted(got):
        shown = (
            hh.decode_value(alpha, config.domain_bits)
            if byte_aligned
            else alpha
        )
        print(f"  {shown!r}: {got[alpha]}")
    if got != want:
        raise SystemExit(
            f"FAILED: private answer {got} != plaintext {want}"
        )
    print(
        f"OK: {len(got)} heavy hitters at threshold "
        f"{config.threshold} match the plaintext oracle exactly"
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--demo", action="store_true",
                    help="full sweep over the in-process transport")
    ap.add_argument("--tcp", action="store_true",
                    help="full sweep with the Helper on a TCP socket")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny 2-level fixture for CI presubmit")
    ap.add_argument("--clients", type=int, default=30)
    ap.add_argument("--threshold", type=int, default=3)
    ap.add_argument("--domain-bits", type=int, default=32,
                    help="value width in bits (32 = 4-byte strings)")
    ap.add_argument("--level-bits", type=int, default=8,
                    help="bits revealed per sweep round")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--admin-port", type=int, default=None,
                    help="serve the operator telemetry endpoint "
                    "(/metrics /varz /tracez /healthz /profilez) on "
                    "this port during the sweep (0 = auto-pick)")
    ap.add_argument("--platform", default="cpu",
                    help="JAX platform (default cpu)")
    args = ap.parse_args()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from distributed_point_functions_tpu import heavy_hitters as hh

    if args.smoke:
        config = hh.HeavyHittersConfig(
            domain_bits=8, level_bits=4, threshold=2
        )
        values = [3, 3, 3, 77, 77, 200, 9, 9, 14]
        result = run_sweep(config, values, "in-process")
        check_result(result, values, config)
        return

    if not (args.demo or args.tcp):
        raise SystemExit("pass --demo, --tcp, or --smoke")

    config = hh.HeavyHittersConfig(
        domain_bits=args.domain_bits,
        level_bits=args.level_bits,
        threshold=args.threshold,
    )
    values = demo_values(args.clients, args.seed)
    kind = "tcp" if args.tcp else "in-process"
    result = run_sweep(config, values, kind, admin_port=args.admin_port)
    check_result(result, values, config)


if __name__ == "__main__":
    main()
