"""Two-server dense PIR over real TCP sockets — the deployment model.

Thin CLI over the `serving/` runtime. The protocol, framing, batching,
deadline/retry policy, and metrics all live in
`distributed_point_functions_tpu/serving/` (`transport.py` frames proto
messages over TCP, `service.py` wraps the Leader/Helper roles from
`pir/server.py`); this script only parses flags, builds the shared demo
database, and wires the roles together:

    client ──LeaderRequest──> leader ──EncryptedHelperRequest──> helper
           <─masked response─        <──masked helper response──

The helper leg is encrypted end-to-end (client -> helper) with the
framework's X25519 + HKDF + AES-GCM hybrid scheme; the leader only ever
sees ciphertext. Responses are one-time-pad masked with the client's
AES-CTR seed, so the leader cannot read the helper's share either
(`pir/dpf_pir_server.cc:147-193` semantics).

Run it in one command (spawns helper + leader subprocesses, queries them,
checks the answers):

    python examples/leader_helper_demo.py --demo

or run the roles by hand in three terminals:

    python examples/leader_helper_demo.py --role helper --port 9001
    python examples/leader_helper_demo.py --role leader --port 9000 \
        --helper 127.0.0.1:9001
    python examples/leader_helper_demo.py --role client \
        --leader 127.0.0.1:9000 --indices 3,42,99
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

NUM_RECORDS = 512
RECORD_BYTES = 32


# ---------------------------------------------------------------------------
# Shared fixture: every role derives the same database deterministically
# (a real deployment would load it from storage).
# ---------------------------------------------------------------------------


def build_database():
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )

    records = [
        (b"record-%04d:" % i).ljust(RECORD_BYTES, b".")
        for i in range(NUM_RECORDS)
    ]
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build(), records


def _serving_config():
    """Demo-friendly knobs: no deadlines (the first request compiles jit
    programs, legitimately slow on CPU), generous helper leg."""
    from distributed_point_functions_tpu.serving import ServingConfig

    return ServingConfig(
        max_batch_size=64,
        max_wait_ms=2.0,
        request_timeout_ms=None,
        helper_timeout_ms=600_000.0,
        helper_retries=2,
    )


# ---------------------------------------------------------------------------
# Roles
# ---------------------------------------------------------------------------


def _maybe_admin(admin_port, registry, name: str, slo_config=None,
                 prober=None, bundles=None):
    """Start the operator telemetry endpoint when --admin-port is given
    (0 = auto-pick). Serves /metrics, /varz, /statusz, /tracez,
    /healthz, /eventz, and /profilez off the role's live registry,
    flight recorder, device telemetry, and event journal. `--slo-config
    <json>` attaches a declarative SLO tracker: hard breaches degrade
    /healthz to 503 and /statusz shows the burn table. With `--probe`
    (leader role) the blackbox prober and its debug bundles surface at
    /probez and /debugz, and /healthz degrades when a bit-identity
    probe goes stale."""
    if admin_port is None:
        return None
    from distributed_point_functions_tpu.observability import (
        AdminServer,
        tracing,
    )

    slo = None
    if slo_config is not None:
        from distributed_point_functions_tpu.observability.slo import (
            SloTracker,
        )

        slo = SloTracker.from_config(slo_config, registry)
    admin = AdminServer(
        registry=registry,
        recorder=tracing.default_recorder(),
        port=admin_port,
        name=name,
        slo=slo,
        prober=prober,
        bundles=bundles,
    )
    admin.start()
    extras = "".join(
        [" /probez /debugz" if prober is not None else "",
         "; SLOs: " + ",".join(o.name for o in slo.objectives)
         if slo else ""]
    )
    print(
        f"[{name}] admin endpoint on :{admin.port} "
        "(/metrics /varz /statusz /tracez /eventz /healthz /profilez"
        f"{extras})",
        flush=True,
    )
    return admin


def run_helper(port: int, admin_port=None, slo_config=None) -> None:
    from distributed_point_functions_tpu.serving import (
        FramedTcpServer,
        HelperSession,
    )
    from distributed_point_functions_tpu.testing import encrypt_decrypt

    db, _ = build_database()
    session = HelperSession(db, encrypt_decrypt.decrypt, _serving_config())
    _maybe_admin(admin_port, session.metrics, "helper", slo_config)
    server = FramedTcpServer(session.handle_wire, port=port, name="helper")
    print(f"[helper] listening on :{server.port}", flush=True)
    server.serve_forever()


def run_leader(
    port: int, helper_addr: str, admin_port=None, slo_config=None,
    probe: bool = False,
) -> None:
    from distributed_point_functions_tpu.serving import (
        FramedTcpServer,
        LeaderSession,
        TcpTransport,
        parse_hostport,
    )

    db, records = build_database()
    helper_host, helper_port = parse_hostport(helper_addr)
    session = LeaderSession(
        db, TcpTransport(helper_host, helper_port), _serving_config()
    )
    prober = bundles = None
    if probe:
        from distributed_point_functions_tpu.observability import (
            BundleManager,
        )
        from distributed_point_functions_tpu.serving.prober import Prober
        from distributed_point_functions_tpu.testing import encrypt_decrypt

        # Golden queries through the real serving path: the plain-pair
        # probes cover every planner tier locally, the e2e probe rides
        # the encrypted helper leg over the real TCP transport. A
        # bit-identity failure captures a debug bundle.
        bundles = BundleManager(name="leader")
        prober = Prober(
            session, records, encrypter=encrypt_decrypt.encrypt,
            period_s=10.0,
        )
        prober.add_failure_listener(bundles.on_probe_failure)
        prober.start()
        print(
            f"[leader] blackbox prober on ({', '.join(prober.kinds())}); "
            f"bundles -> {bundles.directory}",
            flush=True,
        )
    _maybe_admin(admin_port, session.metrics, "leader", slo_config,
                 prober=prober, bundles=bundles)
    server = FramedTcpServer(session.handle_wire, port=port, name="leader")
    print(f"[leader] listening on :{server.port}", flush=True)
    server.serve_forever()


def run_client(
    leader_addr: str, indices: list[int], max_attempts: int = 8
) -> list[bytes]:
    from distributed_point_functions_tpu import serialization
    from distributed_point_functions_tpu.observability import (
        propagation,
        tracing,
    )
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.protos import (
        private_information_retrieval_pb2 as pir_pb2,
    )
    from distributed_point_functions_tpu.serving import (
        TcpTransport,
        parse_hostport,
    )
    from distributed_point_functions_tpu.testing import encrypt_decrypt

    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    request, state = client.create_request(indices)
    inner = serialization.pir_request_to_proto(
        client.dpf, request
    ).SerializeToString()

    host, port = parse_hostport(leader_addr)
    with TcpTransport(host, port) as transport:
        # Enveloped request: carries a trace id out, and lets an
        # overloaded leader answer with a typed kind-3 refusal (the
        # RetryAfter hint) instead of a broken pipe.
        for attempt in range(max_attempts):
            data = transport.roundtrip(
                propagation.encode_request(tracing.new_trace_id(), inner)
            )
            try:
                _, payload = propagation.try_decode_response(data)
                break
            except propagation.WireErrorResponse as e:
                # Typed shed (admission quota, cost budget, brownout):
                # honor the server's backoff hint instead of hammering.
                if attempt + 1 >= max_attempts:
                    raise SystemExit(
                        f"leader still overloaded after "
                        f"{max_attempts} attempts: {e}"
                    )
                backoff = max(e.retry_after_s, 0.05)
                print(
                    f"[client] {e.error_type}: {e} — retrying in "
                    f"{backoff:.2f}s "
                    f"(attempt {attempt + 2}/{max_attempts})",
                    flush=True,
                )
                time.sleep(backoff)
    response = serialization.pir_response_from_proto(
        pir_pb2.PirResponse.FromString(payload)
    )
    return client.handle_response(response, state)


# ---------------------------------------------------------------------------
# One-command demo
# ---------------------------------------------------------------------------


def wait_listening(port: int, proc: subprocess.Popen, timeout: float = 300):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"subprocess exited early with rc={proc.returncode}"
            )
        try:
            with socket.create_connection(("localhost", port), timeout=1):
                return
        except OSError:
            time.sleep(0.5)
    raise TimeoutError(f"port {port} never came up")


def run_demo(base_port: int, platform: str) -> None:
    helper_port, leader_port = base_port + 1, base_port
    env = dict(os.environ)
    me = os.path.abspath(__file__)
    procs = [
        subprocess.Popen(
            [sys.executable, me, "--role", "helper",
             "--port", str(helper_port), "--platform", platform],
            env=env,
        ),
        subprocess.Popen(
            [sys.executable, me, "--role", "leader",
             "--port", str(leader_port),
             "--helper", f"localhost:{helper_port}",
             "--platform", platform],
            env=env,
        ),
    ]
    try:
        wait_listening(helper_port, procs[0])
        wait_listening(leader_port, procs[1])
        indices = [3, 42, NUM_RECORDS - 1]
        t0 = time.perf_counter()
        got = run_client(f"localhost:{leader_port}", indices)
        dt = time.perf_counter() - t0
        _, records = build_database()
        for idx, rec in zip(indices, got):
            status = "OK" if rec == records[idx] else "MISMATCH"
            print(f"index {idx}: {rec!r}  [{status}]")
        if [records[i] for i in indices] != got:
            raise SystemExit("demo FAILED: responses do not match records")
        print(f"demo OK: {len(indices)} private queries in {dt:.2f}s "
              "across three processes")
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", choices=["helper", "leader", "client"])
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--helper", default="localhost:9001",
                    help="helper host:port (leader role)")
    ap.add_argument("--leader", default="localhost:9000",
                    help="leader host:port (client role)")
    ap.add_argument("--indices", default="3,42,99")
    ap.add_argument("--admin-port", type=int, default=None,
                    help="serve the operator telemetry endpoint "
                    "(/metrics /varz /statusz /tracez /healthz /profilez) "
                    "on this port (0 = auto-pick; helper and leader roles)")
    ap.add_argument("--slo-config", default=None,
                    help="JSON file of declarative SLO objectives (see "
                    "docs/DESIGN.md §11); with --admin-port, hard "
                    "breaches degrade /healthz to 503 and /statusz "
                    "shows the burn table")
    ap.add_argument("--probe", action="store_true",
                    help="leader role: run the blackbox verification "
                    "prober (docs/DESIGN.md §15) — golden queries "
                    "through every planner tier plus the encrypted "
                    "helper leg, bit-identity asserted every cycle; "
                    "with --admin-port, history at /probez, incident "
                    "bundles at /debugz, probe staleness on /healthz")
    ap.add_argument("--demo", action="store_true",
                    help="spawn helper+leader and run a client against them")
    ap.add_argument("--platform", default="",
                    help="force a JAX platform (e.g. cpu); the demo "
                    "defaults to cpu — the environment's sitecustomize "
                    "would otherwise dial the TPU tunnel in every role "
                    "process")
    args = ap.parse_args()

    platform = args.platform or ("cpu" if args.demo else "")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    if args.demo:
        run_demo(args.port, platform)
    elif args.role == "helper":
        run_helper(args.port, admin_port=args.admin_port,
                   slo_config=args.slo_config)
    elif args.role == "leader":
        run_leader(args.port, args.helper, admin_port=args.admin_port,
                   slo_config=args.slo_config, probe=args.probe)
    elif args.role == "client":
        indices = [int(x) for x in args.indices.split(",")]
        for i, rec in enumerate(
            run_client(args.leader, indices)
        ):
            print(f"index {indices[i]}: {rec!r}")
    else:
        raise SystemExit("pass --demo or --role")


if __name__ == "__main__":
    main()
