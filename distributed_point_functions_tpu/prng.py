"""Seeded AES-128-CTR PRNG for the PIR one-time-pad masking.

Equivalent of the reference's `Aes128CtrSeededPrng`
(`pir/prng/aes_128_ctr_seeded_prng.h:33-85`, `.cc:42-104`): a deterministic
byte stream from a 16-byte seed (used as the AES key) and an optional 16-byte
nonce (used as the initial counter). Matches OpenSSL `AES_ctr128_encrypt`
semantics: the counter is the full 16-byte IV interpreted big-endian and
incremented once per block, and the stream position is preserved across
`get_random_bytes` calls of arbitrary lengths.

Runs host-side on the numpy AES oracle — OTP masking touches response bytes
on the host path anyway; the device path keeps responses masked.
"""

from __future__ import annotations

import secrets

import numpy as np

from .ops import aes

SEED_SIZE = 16


def generate_seed() -> bytes:
    """Cryptographically random 16-byte seed."""
    return secrets.token_bytes(SEED_SIZE)


class Aes128CtrSeededPrng:
    """Deterministic AES-128-CTR byte stream from (seed, nonce)."""

    def __init__(self, seed: bytes, nonce: bytes = b"\x00" * SEED_SIZE):
        if len(seed) != SEED_SIZE:
            raise ValueError(f"seed must be {SEED_SIZE} bytes")
        if len(nonce) != SEED_SIZE:
            raise ValueError(f"nonce must be {SEED_SIZE} bytes")
        self._round_keys = aes.key_expansion(seed)
        self._counter = int.from_bytes(nonce, "big")
        self._partial = b""  # unconsumed tail of the last keystream block

    def get_random_bytes(self, length: int) -> bytes:
        """Next `length` pseudorandom bytes of the stream."""
        if length < 0:
            raise ValueError("length must be non-negative")
        out = bytearray()
        if self._partial:
            take = min(length, len(self._partial))
            out += self._partial[:take]
            self._partial = self._partial[take:]
        remaining = length - len(out)
        if remaining > 0:
            num_blocks = (remaining + 15) // 16
            ctrs = np.zeros((num_blocks, 16), dtype=np.uint8)
            for i in range(num_blocks):
                c = (self._counter + i) % (1 << 128)
                ctrs[i] = np.frombuffer(c.to_bytes(16, "big"), dtype=np.uint8)
            self._counter = (self._counter + num_blocks) % (1 << 128)
            stream = aes.aes_encrypt_np(self._round_keys, ctrs).tobytes()
            out += stream[:remaining]
            self._partial = stream[remaining:]
        return bytes(out)


def xor_bytes(a: bytes, b: bytes) -> bytes:
    """Elementwise XOR of two equal-length byte strings."""
    if len(a) != len(b):
        raise ValueError("length mismatch")
    return (
        np.frombuffer(a, dtype=np.uint8) ^ np.frombuffer(b, dtype=np.uint8)
    ).tobytes()
