"""Converters between framework objects and the wire protos.

The proto schema is wire-compatible with the reference
(`dpf/distributed_point_function.proto`,
`pir/private_information_retrieval.proto`): keys, evaluation contexts, and
PIR requests/responses produced here parse in the reference implementation
and vice versa.

Value encoding follows the reference's `value_type_helpers` conventions:
integers of <= 64 bits go into `Value.Integer.value_uint64`, 128-bit values
into a `Block{high, low}`; IntModN values are represented by their base
integer; tuples recurse (`value_type_helpers.h:182-461`).
"""

from __future__ import annotations

from typing import List, Tuple

from . import value_types as vt_mod
from .dpf import (
    CorrectionWord,
    DistributedPointFunction,
    DpfKey,
    DpfParameters,
    EvaluationContext,
)
from .pir import messages
from .protos import dpf_pb2, pir_pb2

# ---------------------------------------------------------------------------
# Blocks and integers
# ---------------------------------------------------------------------------


def block_to_proto(x: int, out=None):
    out = out if out is not None else dpf_pb2.Block()
    out.high = (x >> 64) & 0xFFFFFFFFFFFFFFFF
    out.low = x & 0xFFFFFFFFFFFFFFFF
    return out


def block_from_proto(b) -> int:
    return (b.high << 64) | b.low


def _integer_to_proto(value: int, bits: int, out):
    if bits <= 64:
        out.value_uint64 = value
    else:
        block_to_proto(value, out.value_uint128)
    return out


def _integer_from_proto(p) -> int:
    if p.WhichOneof("value") == "value_uint128":
        return block_from_proto(p.value_uint128)
    return p.value_uint64


# ---------------------------------------------------------------------------
# ValueType
# ---------------------------------------------------------------------------


def value_type_to_proto(vt, out=None):
    out = out if out is not None else dpf_pb2.ValueType()
    if isinstance(vt, vt_mod.IntType):
        out.integer.bitsize = vt.bits
    elif isinstance(vt, vt_mod.XorType):
        out.xor_wrapper.bitsize = vt.bits
    elif isinstance(vt, vt_mod.IntModNType):
        out.int_mod_n.base_integer.bitsize = vt.base_bits
        _integer_to_proto(vt.modulus, vt.base_bits, out.int_mod_n.modulus)
    elif isinstance(vt, vt_mod.TupleType):
        for e in vt.elements:
            value_type_to_proto(e, out.tuple.elements.add())
    else:
        raise ValueError(f"unsupported value type {vt!r}")
    return out


def value_type_from_proto(p):
    kind = p.WhichOneof("type")
    if kind == "integer":
        return vt_mod.IntType(p.integer.bitsize)
    if kind == "xor_wrapper":
        return vt_mod.XorType(p.xor_wrapper.bitsize)
    if kind == "int_mod_n":
        return vt_mod.IntModNType(
            base_bits=p.int_mod_n.base_integer.bitsize,
            modulus=_integer_from_proto(p.int_mod_n.modulus),
        )
    if kind == "tuple":
        return vt_mod.TupleType(
            [value_type_from_proto(e) for e in p.tuple.elements]
        )
    raise ValueError("ValueType proto has no type set")


# ---------------------------------------------------------------------------
# Values
# ---------------------------------------------------------------------------


def value_to_proto(vt, value, out=None):
    out = out if out is not None else dpf_pb2.Value()
    if isinstance(vt, vt_mod.IntType):
        _integer_to_proto(value, vt.bits, out.integer)
    elif isinstance(vt, vt_mod.XorType):
        _integer_to_proto(value, vt.bits, out.xor_wrapper)
    elif isinstance(vt, vt_mod.IntModNType):
        _integer_to_proto(value, vt.base_bits, out.int_mod_n)
    elif isinstance(vt, vt_mod.TupleType):
        for e, v in zip(vt.elements, value):
            value_to_proto(e, v, out.tuple.elements.add())
    else:
        raise ValueError(f"unsupported value type {vt!r}")
    return out


def value_from_proto(vt, p):
    if isinstance(vt, vt_mod.IntType):
        return _integer_from_proto(p.integer)
    if isinstance(vt, vt_mod.XorType):
        return _integer_from_proto(p.xor_wrapper)
    if isinstance(vt, vt_mod.IntModNType):
        return _integer_from_proto(p.int_mod_n)
    if isinstance(vt, vt_mod.TupleType):
        return tuple(
            value_from_proto(e, x)
            for e, x in zip(vt.elements, p.tuple.elements)
        )
    raise ValueError(f"unsupported value type {vt!r}")


# ---------------------------------------------------------------------------
# Parameters / keys / evaluation contexts
# ---------------------------------------------------------------------------


def parameters_to_proto(p: DpfParameters, out=None):
    out = out if out is not None else dpf_pb2.DpfParameters()
    out.log_domain_size = p.log_domain_size
    value_type_to_proto(p.value_type, out.value_type)
    out.security_parameter = p.security_parameter
    return out


def parameters_from_proto(p) -> DpfParameters:
    return DpfParameters(
        log_domain_size=p.log_domain_size,
        value_type=value_type_from_proto(p.value_type),
        security_parameter=p.security_parameter,
    )


def key_to_proto(dpf: DistributedPointFunction, key: DpfKey, out=None):
    out = out if out is not None else dpf_pb2.DpfKey()
    block_to_proto(key.seed, out.seed)
    out.party = key.party
    last_vt = dpf.parameters[-1].value_type
    for i, cw in enumerate(key.correction_words):
        cw_proto = out.correction_words.add()
        block_to_proto(cw.seed, cw_proto.seed)
        cw_proto.control_left = cw.control_left
        cw_proto.control_right = cw.control_right
        if cw.value_correction is not None:
            hl = dpf._tree_to_hierarchy[i]
            vt = dpf.parameters[hl].value_type
            for v in cw.value_correction:
                value_to_proto(vt, v, cw_proto.value_correction.add())
    for v in key.last_level_value_correction:
        value_to_proto(last_vt, v, out.last_level_value_correction.add())
    return out


def key_from_proto(dpf: DistributedPointFunction, p) -> DpfKey:
    cws: List[CorrectionWord] = []
    for i, cw_proto in enumerate(p.correction_words):
        vc = None
        if len(cw_proto.value_correction) > 0:
            hl = dpf._tree_to_hierarchy.get(i)
            if hl is None:
                raise ValueError(
                    f"value correction present at tree level {i} which is "
                    "not an output level"
                )
            vt = dpf.parameters[hl].value_type
            vc = [value_from_proto(vt, v) for v in cw_proto.value_correction]
        cws.append(
            CorrectionWord(
                seed=block_from_proto(cw_proto.seed),
                control_left=cw_proto.control_left,
                control_right=cw_proto.control_right,
                value_correction=vc,
            )
        )
    last_vt = dpf.parameters[-1].value_type
    return DpfKey(
        seed=block_from_proto(p.seed),
        party=p.party,
        correction_words=cws,
        last_level_value_correction=[
            value_from_proto(last_vt, v)
            for v in p.last_level_value_correction
        ],
    )


def evaluation_context_to_proto(
    dpf: DistributedPointFunction, ctx: EvaluationContext, out=None
):
    out = out if out is not None else dpf_pb2.EvaluationContext()
    for p in dpf.parameters:
        parameters_to_proto(p, out.parameters.add())
    key_to_proto(dpf, ctx.key, out.key)
    out.previous_hierarchy_level = ctx.previous_hierarchy_level
    out.partial_evaluations_level = ctx.partial_evaluations_level
    for prefix, (seed, control) in sorted(ctx.partial_evaluations.items()):
        pe = out.partial_evaluations.add()
        block_to_proto(prefix, pe.prefix)
        block_to_proto(seed, pe.seed)
        pe.control_bit = bool(control)
    return out


def evaluation_context_from_proto(p) -> Tuple[DistributedPointFunction, EvaluationContext]:
    """Rebuilds the DPF from the embedded parameters plus the context."""
    dpf = DistributedPointFunction.create_incremental(
        [parameters_from_proto(q) for q in p.parameters]
    )
    ctx = EvaluationContext(
        key=key_from_proto(dpf, p.key),
        previous_hierarchy_level=p.previous_hierarchy_level,
        partial_evaluations={
            block_from_proto(pe.prefix): (
                block_from_proto(pe.seed),
                int(pe.control_bit),
            )
            for pe in p.partial_evaluations
        },
        partial_evaluations_level=p.partial_evaluations_level,
    )
    return dpf, ctx


# ---------------------------------------------------------------------------
# PIR messages
# ---------------------------------------------------------------------------


def pir_request_to_proto(
    dpf: DistributedPointFunction, request: "messages.PirRequest", out=None
):
    out = out if out is not None else pir_pb2.PirRequest()
    inner = out.dpf_pir_request
    if request.plain_request is not None:
        for k in request.plain_request.dpf_keys:
            key_to_proto(dpf, k, inner.plain_request.dpf_key.add())
    elif request.leader_request is not None:
        lr = request.leader_request
        for k in lr.plain_request.dpf_keys:
            key_to_proto(dpf, k, inner.leader_request.plain_request.dpf_key.add())
        inner.leader_request.encrypted_helper_request.encrypted_request = (
            lr.encrypted_helper_request.encrypted_request
        )
    elif request.encrypted_helper_request is not None:
        inner.encrypted_helper_request.encrypted_request = (
            request.encrypted_helper_request.encrypted_request
        )
    else:
        raise ValueError("PirRequest has no request set")
    return out


def pir_request_from_proto(dpf: DistributedPointFunction, p) -> "messages.PirRequest":
    inner = p.dpf_pir_request
    kind = inner.WhichOneof("wrapped_request")
    if kind == "plain_request":
        return messages.PirRequest(
            plain_request=messages.PlainRequest(
                dpf_keys=[key_from_proto(dpf, k) for k in inner.plain_request.dpf_key]
            )
        )
    if kind == "leader_request":
        lr = inner.leader_request
        return messages.PirRequest(
            leader_request=messages.LeaderRequest(
                plain_request=messages.PlainRequest(
                    dpf_keys=[
                        key_from_proto(dpf, k)
                        for k in lr.plain_request.dpf_key
                    ]
                ),
                encrypted_helper_request=messages.EncryptedHelperRequest(
                    encrypted_request=lr.encrypted_helper_request.encrypted_request
                ),
            )
        )
    if kind == "encrypted_helper_request":
        return messages.PirRequest(
            encrypted_helper_request=messages.EncryptedHelperRequest(
                encrypted_request=inner.encrypted_helper_request.encrypted_request
            )
        )
    raise ValueError("DpfPirRequest has no request set")


def helper_request_to_proto(
    dpf: DistributedPointFunction, hr: "messages.HelperRequest", out=None
):
    out = out if out is not None else pir_pb2.DpfPirRequest.HelperRequest()
    for k in hr.plain_request.dpf_keys:
        key_to_proto(dpf, k, out.plain_request.dpf_key.add())
    out.one_time_pad_seed = hr.one_time_pad_seed
    return out


def helper_request_from_proto(dpf: DistributedPointFunction, p) -> "messages.HelperRequest":
    return messages.HelperRequest(
        plain_request=messages.PlainRequest(
            dpf_keys=[key_from_proto(dpf, k) for k in p.plain_request.dpf_key]
        ),
        one_time_pad_seed=p.one_time_pad_seed,
    )


def pir_response_to_proto(response: "messages.PirResponse", out=None):
    out = out if out is not None else pir_pb2.PirResponse()
    for r in response.dpf_pir_response.masked_response:
        out.dpf_pir_response.masked_response.append(r)
    return out


def pir_response_from_proto(p) -> "messages.PirResponse":
    return messages.PirResponse(
        dpf_pir_response=messages.DpfPirResponse(
            masked_response=list(p.dpf_pir_response.masked_response)
        )
    )


def public_params_to_proto(params=None, out=None):
    """CuckooHashingParams (or None for the dense server) ->
    `PirServerPublicParams` (`private_information_retrieval.proto:55-60`).
    The dense server has no parameters; like the reference it returns the
    empty message (`dense_dpf_pir_server.cc:87-89`)."""
    out = out if out is not None else pir_pb2.PirServerPublicParams()
    if params is not None:
        dst = out.cuckoo_hashing_sparse_dpf_pir_server_params
        dst.num_buckets = params.num_buckets
        dst.num_hash_functions = params.num_hash_functions
        dst.hash_family_config.hash_family = (
            params.hash_family_config.hash_family
        )
        dst.hash_family_config.seed = params.hash_family_config.seed
    return out


def public_params_from_proto(p):
    """Returns CuckooHashingParams, or None for dense-server params."""
    from .hashing.hash_family_config import HashFamilyConfig
    from .pir.cuckoo_database import CuckooHashingParams

    which = p.WhichOneof("wrapped_pir_server_public_params")
    if which is None:
        return None
    src = p.cuckoo_hashing_sparse_dpf_pir_server_params
    return CuckooHashingParams(
        num_buckets=src.num_buckets,
        num_hash_functions=src.num_hash_functions,
        hash_family_config=HashFamilyConfig(
            hash_family=src.hash_family_config.hash_family,
            seed=src.hash_family_config.seed,
        ),
    )
