"""Blackbox verification prober: golden queries through the real
serving path, checked bit-for-bit against a plaintext oracle.

Two-server PIR fails *silently*: a single flipped bit in either
party's share XORs straight into the reconstructed record, every
transport frame still parses, every status code is 200, and every
latency SLO stays green. The only way to know a deployment is serving
the *right bits* is to continuously ask it questions whose answers are
known in advance — through the same batcher, planner, transport, and
device paths production queries take — and assert bit-identity on
what comes back.

The `Prober` owns a small set of golden indices into the served
database (the operator hands it the plaintext records, which the
serving side of a deployment has by construction) and per cycle runs
one probe of each enabled kind:

    pir_materialized   batched plain pair, tier floor cleared (a tiny
                       probe batch plans materialized naturally — the
                       floor can only demote, so "forcing" the top
                       tier means removing the constraint)
    pir_streaming      batched plain pair with the process tier floor
                       forced to streaming for the probe's duration
    pir_chunked        same, forced to chunked
    pir_unbatched      the same pair straight through
                       `server.handle_plain_request`, bypassing the
                       batcher (separates batcher bugs from eval bugs)
    leader_e2e         a full encrypted LeaderRequest through
                       `session.handle_request` — helper leg,
                       one-time-pad unmask and all (only when an
                       `encrypter` is provided); a session answering
                       in degraded (leader-share-only) mode is flagged
                       `degraded`, not failed — the answer is *known*
                       to be unreconstructable then; each result also
                       carries the request's merged critical-path
                       summary (`critical_path` key: the skew-corrected
                       helper_net / helper_queue / helper_compute
                       split) so /probez shows where probe latency went
    hh_sweep           a miniature heavy-hitters sweep over two
                       in-memory servers built from golden reports,
                       checked against `plaintext_heavy_hitters`
    sparse_kv          (sparse sessions) golden key→value pairs through
                       the batched cuckoo bucket-space path; each key's
                       reconstructed candidate set must resolve to its
                       oracle value
    sparse_absent      (sparse sessions) a golden key guaranteed absent
                       from the table; it must keep resolving to
                       not-found — a well-formed wrong value for a
                       missing key is the silent failure mode unique to
                       key-value PIR

For the dense probes the two plain responses are XORed together and
compared byte-for-byte against the oracle records (`xor(share0,
share1) == record` is the CGKS reconstruction identity — any
corruption anywhere in either evaluation breaks it).

Every probe lands in per-kind bounded history (`/probez`), counters
and a latency histogram in the session's metrics registry
(`prober.*`), and the event journal on state changes
(`prober.mismatch` / `prober.error` / `prober.recovered`). Failure
listeners (`add_failure_listener`) fire on mismatch/error — wiring
`BundleManager.on_probe_failure` there makes a wrong-bits incident
self-documenting. `freshness()` reports the age of each kind's last
pass; `AdminServer` turns a stale bit-identity probe into a 503 on
`/healthz` so the load balancer drains a process that cannot prove it
serves correct bits. `rate_floor_objective()` hands back a `rate_min`
SLO objective over `prober.probes` so a silently *stopped* prober is
itself a burn signal.

The background loop (`start()`) jitters its period (so a fleet's
probers do not synchronize) and bounds its duty cycle: after a cycle
that took `d` seconds it sleeps at least `d * (1/max_duty_cycle - 1)`,
so probing can never eat more than `max_duty_cycle` of the process
even when probes get slow — the prober must observe overload, not
contribute to it.

Layering: this module sits *above* `serving/` and `heavy_hitters/`
(`tools/check_layers.py` gives it its own top layer) and is
deliberately NOT exported from `serving/__init__.py` — import it as
`distributed_point_functions_tpu.serving.prober`.
"""

from __future__ import annotations

import collections
import contextlib
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..heavy_hitters.client import HeavyHittersClient
from ..heavy_hitters.protocol import (
    HeavyHittersConfig,
    HeavyHittersServer,
    plaintext_heavy_hitters,
    run_protocol,
)
from ..observability import critical_path
from ..observability import events as events_mod
from ..observability.slo import SloObjective
from ..pir.client import DenseDpfPirClient
from ..pir.server import set_tier_floor, tier_floor
from ..pir.sparse_client import (
    CuckooHashingSparseDpfPirClient,
    _is_prefix_padded_with_zeros,
)
from ..prng import xor_bytes

__all__ = ["CrossReplicaProbe", "Prober", "PROBE_STATUSES"]

PROBE_STATUSES = ("pass", "mismatch", "error", "degraded")

# Probe kinds whose pass proves bit-identity of the dense serving path;
# a stale last-pass on any of these degrades /healthz.
_IDENTITY_KINDS = (
    "pir_materialized",
    "pir_streaming",
    "pir_chunked",
    "pir_unbatched",
)

# Sparse identity kinds: golden key→value pairs reconstructing through
# the batched cuckoo path (`sparse_kv`), and a golden *absent* key that
# must keep resolving to not-found (`sparse_absent` — a server that
# starts answering wrong-but-well-formed values for absent keys is the
# silent failure mode unique to key-value PIR). Stale ⇒ /healthz 503,
# same as the dense identity kinds.
_SPARSE_IDENTITY_KINDS = ("sparse_kv", "sparse_absent")


class Prober:
    """Continuous blackbox canary over one serving session.

    `session` is a `PlainSession`/`LeaderSession` (anything with
    `handle_request` and a `server`); `records` the full plaintext
    database (the oracle). `indices` picks the golden queries (default:
    first, middle, last — distinct). `encrypter` enables the
    `leader_e2e` probe; `hh_values` (+ optional `hh_config`) enables
    the `hh_sweep` probe. `clock` must be monotonic.

    For a sparse (cuckoo key-value) session pass `sparse_records` — the
    full key→value plaintext mapping — instead of (or alongside)
    `records`: the dense probe kinds only run when `records` is given
    (a sparse session answers bucket-space queries, so dense golden
    *indices* are meaningless there), and `sparse_records` enables the
    `sparse_kv` + `sparse_absent` kinds. `sparse_absent_key` overrides
    the derived guaranteed-absent golden key.
    """

    def __init__(
        self,
        session,
        records: Optional[Sequence[bytes]] = None,
        *,
        indices: Optional[Sequence[int]] = None,
        encrypter=None,
        hh_values: Optional[Sequence] = None,
        hh_config: Optional[HeavyHittersConfig] = None,
        sparse_records: Optional[Dict[bytes, bytes]] = None,
        sparse_absent_key: Optional[bytes] = None,
        period_s: float = 5.0,
        jitter: float = 0.2,
        max_duty_cycle: float = 0.05,
        history: int = 32,
        freshness_window_s: Optional[float] = None,
        name: str = "prober",
        metrics=None,
        journal=None,
        clock=time.monotonic,
        rng_seed: int = 0,
    ):
        if not records and not sparse_records:
            raise ValueError("records must not be empty")
        if not 0.0 < max_duty_cycle <= 1.0:
            raise ValueError("max_duty_cycle must be in (0, 1]")
        self._session = session
        self._name = name
        self._period_s = float(period_s)
        self._jitter = float(jitter)
        self._max_duty_cycle = float(max_duty_cycle)
        self._freshness_window_s = (
            float(freshness_window_s)
            if freshness_window_s is not None
            else 3.0 * self._period_s
        )
        self._metrics = (
            metrics
            if metrics is not None
            else getattr(session, "metrics", None)
        )
        self._journal = journal
        self._clock = clock
        self._rng = random.Random(rng_seed)
        self._lock = threading.Lock()
        self._started_mono = clock()
        self._seq = 0
        self._cycles = 0
        self._failure_listeners: List[Callable[[dict], None]] = []
        self._history: Dict[str, collections.deque] = {}
        self._history_cap = max(1, int(history))
        # kind -> monotonic time of last pass / last status string
        self._last_pass: Dict[str, float] = {}
        self._last_status: Dict[str, str] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # The last leader_e2e probe's merged critical-path summary
        # (None until one runs against a critical-path-aware session).
        self._last_critical: Optional[dict] = None

        self._dense = bool(records)
        self._e2e = None
        if records:
            n = len(records)
            if indices is None:
                indices = sorted({0, n // 2, n - 1})
            indices = [int(i) for i in indices]
            for i in indices:
                if not 0 <= i < n:
                    raise ValueError(
                        f"golden index {i} out of bounds for {n}"
                    )
            self._indices = indices
            self._expected = [bytes(records[i]) for i in indices]

            # Golden requests are precomputed once: DPF keys are
            # stateless and reusable, so steady-state probing does no
            # key generation. `create_plain_requests` never calls the
            # encrypter, so a dummy suffices when no real one is
            # configured.
            client = DenseDpfPirClient(
                n,
                encrypter
                if encrypter is not None
                else (lambda pt, info: pt),
            )
            self._client = client
            self._db_size = n
            self._plain_pair = client.create_plain_requests(indices)
            if encrypter is not None:
                request, state = client.create_request(indices)
                self._e2e = (request, state, client)
        else:
            # Sparse-only prober: the dense kinds are disabled (a
            # cuckoo session answers bucket-space queries; dense golden
            # *indices* have no oracle meaning there).
            self._indices = []
            self._expected = []
            self._client = None
            self._db_size = 0
            self._plain_pair = None
        # Snapshot rotation: the database generation the golden pairs
        # are keyed to, plus the SnapshotManagers to pin during each
        # probe so a probe's two shares never straddle a flip (see
        # `bind_snapshots` / `rotate_goldens`).
        self._generation = getattr(
            getattr(session, "server", None), "database", None
        )
        self._generation = getattr(self._generation, "generation", 0)
        self._snapshot_pins: List = []

        # Sparse goldens: a handful of known key→value pairs plus one
        # key guaranteed absent, probed through the batched cuckoo path
        # (`_probe_sparse`). The plain request pair covers all of them
        # at once and is precomputed like the dense pair.
        self._sparse_pair = None
        self._sparse_keys: List[bytes] = []
        self._sparse_expected: List[bytes] = []
        self._sparse_absent: Optional[bytes] = None
        self._sparse_client = None
        self._sparse_num_hashes = 0
        if sparse_records:
            self._sparse_client = CuckooHashingSparseDpfPirClient.create(
                session.server.public_params,
                encrypter
                if encrypter is not None
                else (lambda pt, info: pt),
            )
            self._sparse_num_hashes = (
                session.server.public_params.num_hash_functions
            )
            self._set_sparse_goldens(sparse_records, sparse_absent_key)

        self._hh = None
        if hh_values:
            cfg = (
                hh_config
                if hh_config is not None
                else HeavyHittersConfig(
                    domain_bits=8, level_bits=4, threshold=2
                )
            )
            hh_client = HeavyHittersClient(cfg)
            keys0, keys1 = [], []
            for value in hh_values:
                k0, k1 = hh_client.generate_report(value)
                keys0.append(k0)
                keys1.append(k1)
            self._hh = (
                HeavyHittersServer(cfg, keys0),
                HeavyHittersServer(cfg, keys1),
                plaintext_heavy_hitters(list(hh_values), cfg),
            )

    # -- wiring -------------------------------------------------------------

    @property
    def name(self) -> str:
        return self._name

    def set_journal(self, journal):
        """Point probe events at a (replica-scoped) journal; None
        restores the process journal."""
        self._journal = journal
        return journal

    def kinds(self) -> List[str]:
        """The probe kinds this prober runs each cycle."""
        out = list(_IDENTITY_KINDS) if self._dense else []
        if self._sparse_pair is not None:
            out.extend(_SPARSE_IDENTITY_KINDS)
        if self._e2e is not None:
            out.append("leader_e2e")
        if self._hh is not None:
            out.append("hh_sweep")
        return out

    def add_failure_listener(self, listener: Callable[[dict], None]) -> None:
        """Register `listener(result)` for every mismatch/error probe
        (degraded-mode flags do not fire it — a degraded session is a
        *known* state, not a new incident). Exceptions are swallowed."""
        with self._lock:
            self._failure_listeners.append(listener)

    def rotate_goldens(
        self,
        records: Sequence[bytes],
        *,
        indices: Optional[Sequence[int]] = None,
        generation: Optional[int] = None,
    ) -> None:
        """Re-key the golden (index, plaintext) pairs to a rotated
        database generation. DPF keys select by *index*, so unchanged
        golden indices keep their precomputed requests — only the
        oracle plaintexts swap; passing new `indices` regenerates the
        requests too. Rotation preserves the database size
        (`swap_database` enforces it), so `records` must match."""
        if len(records) != self._db_size:
            raise ValueError(
                f"rotated records count {len(records)} != database size "
                f"{self._db_size} (rotation preserves geometry)"
            )
        with self._lock:
            if indices is not None:
                indices = [int(i) for i in indices]
                for i in indices:
                    if not 0 <= i < self._db_size:
                        raise ValueError(
                            f"golden index {i} out of bounds for "
                            f"{self._db_size}"
                        )
                if indices != self._indices:
                    self._indices = indices
                    self._plain_pair = (
                        self._client.create_plain_requests(indices)
                    )
                    if self._e2e is not None:
                        request, state = self._client.create_request(
                            indices
                        )
                        self._e2e = (request, state, self._client)
            self._expected = [
                bytes(records[i]) for i in self._indices
            ]
            if generation is not None:
                self._generation = int(generation)
            generation_now = self._generation
        journal = (
            self._journal
            if self._journal is not None
            else events_mod.default_journal()
        )
        journal.emit(
            "prober.goldens_rotated",
            f"golden pairs re-keyed to generation {generation_now}",
            severity="info",
            generation=generation_now,
        )

    def _set_sparse_goldens(self, sparse_records, absent_key) -> None:
        """(Re)build the sparse golden set from a key→value mapping:
        up to three present keys (sorted, for determinism), one
        guaranteed-absent key, and the precomputed batched plain
        request pair covering all of them. Caller holds `_lock` (or is
        `__init__`)."""
        norm = {}
        for k, v in sparse_records.items():
            kb = k.encode() if isinstance(k, str) else bytes(k)
            norm[kb] = v.encode() if isinstance(v, str) else bytes(v)
        keys = sorted(norm)[:3]
        if absent_key is None:
            # Keep the current absent golden while it stays absent; a
            # write batch that introduces it forces a re-derivation.
            absent_key = self._sparse_absent or b"prober-absent"
            while absent_key in norm:
                absent_key += b"!"
        else:
            absent_key = (
                absent_key.encode()
                if isinstance(absent_key, str)
                else bytes(absent_key)
            )
            if absent_key in norm:
                raise ValueError(
                    "sparse_absent_key is present in sparse_records"
                )
        regenerate = (
            keys != self._sparse_keys
            or absent_key != self._sparse_absent
            or self._sparse_pair is None
        )
        self._sparse_keys = keys
        self._sparse_expected = [norm[k] for k in keys]
        self._sparse_absent = absent_key
        if regenerate:
            self._sparse_pair = self._sparse_client.create_plain_requests(
                keys + [absent_key]
            )

    def rotate_sparse_goldens(
        self,
        records: Dict[bytes, bytes],
        *,
        absent_key: Optional[bytes] = None,
        generation: Optional[int] = None,
    ) -> None:
        """Re-key the sparse golden key→value pairs to a rotated
        database generation. Unlike dense rotation the key set may
        change (upserts add keys), so golden keys are re-picked from
        the new mapping and the request pair regenerated when they
        differ; the absent golden is kept while it stays absent."""
        if not records:
            raise ValueError("rotated sparse records must not be empty")
        if self._sparse_client is None:
            raise ValueError("prober has no sparse goldens to rotate")
        with self._lock:
            self._set_sparse_goldens(records, absent_key)
            if generation is not None:
                self._generation = int(generation)
            generation_now = self._generation
        journal = (
            self._journal
            if self._journal is not None
            else events_mod.default_journal()
        )
        journal.emit(
            "prober.goldens_rotated",
            f"sparse golden pairs re-keyed to generation {generation_now}",
            severity="info",
            generation=generation_now,
        )

    def bind_snapshots(self, manager, records_provider=None):
        """Track a `SnapshotManager` through rotations: every probe
        pins it (a probe's two shares must evaluate against ONE
        generation — the pin holds a pending flip off until the probe
        lands), and, when `records_provider(to_generation)` is given,
        every applied flip rotates the goldens to the new generation's
        plaintexts within the same flip callback — i.e. before the
        next probe cycle can run against stale oracles. Bind BOTH
        parties' managers in a two-party deployment so neither side
        flips mid-probe. Returns `manager` for chaining."""
        with self._lock:
            if manager not in self._snapshot_pins:
                self._snapshot_pins.append(manager)
        if records_provider is not None:
            def on_flip(record):
                records = records_provider(record["to_generation"])
                if not records:
                    return
                if isinstance(records, dict):
                    self.rotate_sparse_goldens(
                        records, generation=record["to_generation"]
                    )
                else:
                    self.rotate_goldens(
                        records, generation=record["to_generation"]
                    )

            manager.add_flip_listener(on_flip)
        return manager

    def _pinned_managers(self) -> List:
        with self._lock:
            managers = list(self._snapshot_pins)
        session_manager = getattr(self._session, "snapshots", None)
        if session_manager is not None and session_manager not in managers:
            managers.append(session_manager)
        return managers

    def rate_floor_objective(
        self, threshold: Optional[float] = None
    ) -> SloObjective:
        """A `rate_min` SLO objective over `prober.probes`: the probe
        rate falling below `threshold`/s means the prober died or
        stalled — silence must burn, not reassure. The default floor is
        a quarter of the configured steady-state rate (generous slack
        for jitter and duty-cycle stretching)."""
        if threshold is None:
            threshold = 0.25 * len(self.kinds()) / self._period_s
        return SloObjective(
            name=f"{self._name}_rate_floor",
            kind="rate_min",
            metric="prober.probes",
            threshold=threshold,
            severity="soft",
        )

    # -- probes -------------------------------------------------------------

    def _reconstruct(self, resp0, resp1) -> List[bytes]:
        masked0 = resp0.dpf_pir_response.masked_response
        masked1 = resp1.dpf_pir_response.masked_response
        if len(masked0) != len(masked1):
            raise ValueError(
                f"share count mismatch: {len(masked0)} vs {len(masked1)}"
            )
        return [xor_bytes(a, b) for a, b in zip(masked0, masked1)]

    def _check_records(self, got: List[bytes]) -> Optional[str]:
        """None iff bit-identical to the oracle; else a detail string."""
        if len(got) != len(self._expected):
            return (
                f"answer count {len(got)} != {len(self._expected)} golden"
            )
        for idx, want, have in zip(self._indices, self._expected, got):
            if want != have:
                return (
                    f"index {idx}: expected {want.hex()[:32]}.. "
                    f"got {have.hex()[:32]}.."
                )
        return None

    def _issue_batched(self, request):
        """One plain request through the session's batched path. A
        plain-role session takes it through `handle_request` (deadline,
        metrics, trace — the full front door); a Leader/Helper session
        role-dispatches plain requests away, so there the probe enters
        at the batcher hook (`_dispatch_plain`), which is the same
        shared-batch device path production shares ride."""
        server = self._session.server
        if getattr(server, "role", "plain") == "plain":
            return self._session.handle_request(request)
        return server._dispatch_plain(request)

    def _probe_tier(self, tier: Optional[str]) -> Optional[str]:
        """Run the batched plain pair at a forced planner tier (None =
        cleared floor, which a tiny batch plans materialized)."""
        prev = tier_floor()
        set_tier_floor(tier)
        try:
            req0, req1 = self._plain_pair
            resp0 = self._issue_batched(req0)
            resp1 = self._issue_batched(req1)
        finally:
            set_tier_floor(prev)
        return self._check_records(self._reconstruct(resp0, resp1))

    def _probe_unbatched(self) -> Optional[str]:
        req0, req1 = self._plain_pair
        server = self._session.server
        resp0 = server.handle_plain_request(req0)
        resp1 = server.handle_plain_request(req1)
        return self._check_records(self._reconstruct(resp0, resp1))

    def _probe_leader_e2e(self) -> Optional[str]:
        request, state, client = self._e2e
        response = self._session.handle_request(request)
        # The probe just rode the real two-party path, so the analyzer's
        # freshest Leader summary IS this request's critical path; stash
        # it for `_run_one` to attach to the /probez result.
        self._last_critical = critical_path.default_analyzer().last(
            "leader"
        )
        got = client.handle_response(response, state)
        return self._check_records(got)

    def _probe_sparse(self, absent: bool) -> Optional[str]:
        """Run the sparse golden pair through the batched path and
        resolve candidates client-side (the same zero-padded prefix
        match `CuckooHashingSparseDpfPirClient` applies). With
        `absent=False` every golden key must resolve to its oracle
        value; with `absent=True` the absent golden must resolve to
        nothing — a well-formed wrong value for a missing key is the
        silent failure mode unique to key-value PIR."""
        with self._lock:
            pair = self._sparse_pair
            keys = list(self._sparse_keys)
            expected = list(self._sparse_expected)
            absent_key = self._sparse_absent
            num_hashes = self._sparse_num_hashes
        req0, req1 = pair
        resp0 = self._issue_batched(req0)
        resp1 = self._issue_batched(req1)
        raw = self._reconstruct(resp0, resp1)
        queries = keys + [absent_key]
        if len(raw) != 2 * num_hashes * len(queries):
            return (
                f"candidate count {len(raw)} != "
                f"2 x {num_hashes} hashes x {len(queries)} queries"
            )

        def resolve(i: int) -> Optional[bytes]:
            for j in range(num_hashes):
                k = 2 * (num_hashes * i + j)
                if _is_prefix_padded_with_zeros(raw[k], queries[i]):
                    return raw[k + 1]
            return None

        if absent:
            got = resolve(len(queries) - 1)
            if got is not None:
                return (
                    f"absent key {absent_key!r} resolved to "
                    f"{got.hex()[:32]}.. (want not-found)"
                )
            return None
        for i, (key, want) in enumerate(zip(keys, expected)):
            got = resolve(i)
            if got is None:
                return f"golden key {key!r}: not found (want present)"
            if not _is_prefix_padded_with_zeros(got, want):
                return (
                    f"golden key {key!r}: expected {want.hex()[:32]}.. "
                    f"got {got.hex()[:32]}.."
                )
        return None

    def _probe_hh_sweep(self) -> Optional[str]:
        server0, server1, expected = self._hh
        server0.reset()
        server1.reset()
        result = run_protocol(server0, server1).as_dict()
        if result != expected:
            return f"heavy hitters {result} != oracle {expected}"
        return None

    def _run_one(self, kind: str) -> dict:
        t0 = time.perf_counter()
        status = "pass"
        detail = None
        try:
            # Pin every bound SnapshotManager for the probe's duration:
            # the two shares of a golden pair (and the oracle they are
            # checked against) must all belong to ONE generation, so a
            # pending rotation flip waits out the probe instead of
            # landing between its submissions.
            with contextlib.ExitStack() as stack:
                for manager in self._pinned_managers():
                    stack.enter_context(manager.pin())
                if kind == "pir_materialized":
                    detail = self._probe_tier(None)
                elif kind == "pir_streaming":
                    detail = self._probe_tier("streaming")
                elif kind == "pir_chunked":
                    detail = self._probe_tier("chunked")
                elif kind == "pir_unbatched":
                    detail = self._probe_unbatched()
                elif kind == "leader_e2e":
                    detail = self._probe_leader_e2e()
                elif kind == "sparse_kv":
                    detail = self._probe_sparse(absent=False)
                elif kind == "sparse_absent":
                    detail = self._probe_sparse(absent=True)
                elif kind == "hh_sweep":
                    detail = self._probe_hh_sweep()
                else:  # pragma: no cover - kinds() is the source of truth
                    raise ValueError(f"unknown probe kind {kind}")
            if detail is not None:
                status = "mismatch"
        except Exception as e:  # noqa: BLE001 - a probe must not kill the loop
            status = "error"
            detail = f"{type(e).__name__}: {e}"[:300]
        if status != "pass" and getattr(self._session, "degraded", False):
            # A Leader in leader-share-only mode *cannot* reconstruct —
            # flag it distinctly: the bits are not wrong, they are
            # declared absent.
            status = "degraded"
        ms = round((time.perf_counter() - t0) * 1e3, 3)
        with self._lock:
            self._seq += 1
            seq = self._seq
        result = {
            "kind": kind,
            "status": status,
            "ms": ms,
            "detail": detail,
            "seq": seq,
            "t_wall": round(time.time(), 3),
            "t_mono": round(self._clock(), 3),
        }
        if kind == "leader_e2e" and self._last_critical is not None:
            # Where the probe's own latency went: the skew-corrected
            # helper-leg decomposition for this request (/probez).
            result["critical_path"] = self._last_critical
        return result

    def _record(self, result: dict) -> None:
        kind, status = result["kind"], result["status"]
        now = result["t_mono"]
        with self._lock:
            history = self._history.setdefault(
                kind, collections.deque(maxlen=self._history_cap)
            )
            history.append(result)
            prev_status = self._last_status.get(kind)
            self._last_status[kind] = status
            if status == "pass":
                self._last_pass[kind] = now
            listeners = list(self._failure_listeners)
        status_metric = {
            "pass": "prober.passes",
            "mismatch": "prober.mismatches",
            "error": "prober.errors",
            "degraded": "prober.degraded",
        }[status]
        if self._metrics is not None:
            self._metrics.counter("prober.probes").inc()
            self._metrics.counter(status_metric, {"kind": kind}).inc()
            self._metrics.histogram(
                "prober.probe_ms", labels={"kind": kind}
            ).observe(result["ms"])
        journal = (
            self._journal
            if self._journal is not None
            else events_mod.default_journal()
        )
        if status == "mismatch":
            journal.emit(
                "prober.mismatch",
                f"{kind}: {result['detail']}",
                severity="error",
                probe_kind=kind,
                probe_seq=result["seq"],
            )
        elif status == "error":
            journal.emit(
                "prober.error",
                f"{kind}: {result['detail']}",
                severity="warning",
                coalesce_key=f"prober.error:{kind}",
                coalesce_s=self._period_s * 4,
                probe_kind=kind,
                probe_seq=result["seq"],
            )
        elif status == "pass" and prev_status in ("mismatch", "error"):
            journal.emit(
                "prober.recovered",
                f"{kind} passing again",
                severity="info",
                probe_kind=kind,
            )
        if status in ("mismatch", "error"):
            for listener in listeners:
                try:
                    listener(result)
                except Exception:  # noqa: BLE001 - canary must keep flying
                    pass

    def run_cycle(self) -> List[dict]:
        """Run one probe of every enabled kind; returns the results
        (tests and the CI smoke drive this directly — no thread)."""
        results = []
        for kind in self.kinds():
            result = self._run_one(kind)
            self._record(result)
            results.append(result)
        with self._lock:
            self._cycles += 1
        return results

    # -- reading ------------------------------------------------------------

    def freshness(self) -> Dict[str, dict]:
        """Per-kind probe freshness. A kind is `fresh` while its last
        pass (or, before any pass, the prober's start) is within the
        freshness window; identity kinds going stale should 503
        /healthz (see `AdminServer._healthz`)."""
        now = self._clock()
        out = {}
        with self._lock:
            for kind in self.kinds():
                last_pass = self._last_pass.get(kind)
                age = now - (
                    last_pass if last_pass is not None else self._started_mono
                )
                history = self._history.get(kind)
                last = history[-1] if history else None
                out[kind] = {
                    "last_status": self._last_status.get(kind),
                    "last_ms": last["ms"] if last else None,
                    "last_pass_age_s": (
                        round(now - last_pass, 3)
                        if last_pass is not None
                        else None
                    ),
                    "fresh": age <= self._freshness_window_s,
                    "identity": (
                        kind in _IDENTITY_KINDS
                        or kind in _SPARSE_IDENTITY_KINDS
                    ),
                    "detail": last["detail"] if last else None,
                }
        return out

    def export(self) -> dict:
        with self._lock:
            histories = {
                kind: [dict(r) for r in history]
                for kind, history in self._history.items()
            }
            cycles = self._cycles
        counts = {"pass": 0, "mismatch": 0, "error": 0, "degraded": 0}
        probes = 0
        for history in histories.values():
            for r in history:
                probes += 1
                counts[r["status"]] = counts.get(r["status"], 0) + 1
        with self._lock:
            generation = self._generation
        return {
            "name": self._name,
            "generation": generation,
            "period_s": self._period_s,
            "max_duty_cycle": self._max_duty_cycle,
            "freshness_window_s": self._freshness_window_s,
            "kinds": self.kinds(),
            "cycles": cycles,
            # Windowed over retained history (the ring is the report;
            # lifetime totals live in the metrics registry).
            "probes": probes,
            "passes": counts["pass"],
            "mismatches": counts["mismatch"],
            "errors": counts["error"],
            "degraded": counts["degraded"],
            "freshness": self.freshness(),
            "history": histories,
        }

    # -- background loop ----------------------------------------------------

    def start(self) -> "Prober":
        """Run cycles on a jittered daemon thread until `stop()`."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                t0 = self._clock()
                try:
                    self.run_cycle()
                except Exception:  # noqa: BLE001 - the loop outlives probes
                    pass
                took = max(0.0, self._clock() - t0)
                jittered = self._period_s * (
                    1.0 + self._jitter * self._rng.uniform(-1.0, 1.0)
                )
                # Duty-cycle floor: a cycle that took d seconds forces
                # >= d*(1/duty - 1) of sleep, bounding prober overhead
                # at max_duty_cycle of wall time no matter how slow
                # probes get.
                floor = took * (1.0 / self._max_duty_cycle - 1.0)
                if self._stop.wait(max(jittered, floor)):
                    return

        self._thread = threading.Thread(
            target=run, daemon=True, name=f"{self._name}-loop"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10)
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class CrossReplicaProbe:
    """Cross-replica consistency canary: the SAME golden pair issued to
    EVERY replica must reconstruct bit-identically at the same
    generation.

    A fleet replicates the whole two-server deployment N times, which
    adds a failure mode no single-pair prober can see: replica A and
    replica B each pass their own bit-identity probes yet serve
    *different* databases — a botched rotation, a partial upsert, a
    replica restored from the wrong snapshot. This probe runs one
    golden plain pair through every replica's real batched path, pins
    each replica's SnapshotManagers for its attempt (a replica
    mid-flip must answer from one generation), reconstructs per
    replica, then groups answers by the generation each replica was
    serving: **within a generation group every replica's bytes must be
    identical**, and when an oracle is known for that generation they
    must also match it. Replicas on different generations are NOT
    compared against each other — during a rotation that split is
    legitimate (and the router already refuses to mix them for one
    tenant); it is reported, not failed.

    Divergence emits a `fleet.divergence` event (severity error) and
    fires the failure listeners with a prober-shaped result dict, so
    wiring `BundleManager.on_probe_failure` here snapshots the debug
    bundle the moment two replicas disagree.

    `replicas` is a sequence — or a zero-arg callable returning one,
    e.g. ``replica_set.healthy`` — of duck-typed entries carrying
    `replica_id`, `leader` (a session), optional `snapshots` /
    `helper_snapshots`, and `serving_generation()`; `fleet.Replica`
    satisfies it, but this module never imports `fleet/` (the layering
    keeps fleet -> serving one-way).
    """

    def __init__(
        self,
        replicas,
        records: Sequence[bytes],
        *,
        indices: Optional[Sequence[int]] = None,
        records_provider: Optional[Callable[[int], Sequence[bytes]]] = None,
        generation: Optional[int] = None,
        history: int = 32,
        journal=None,
        metrics=None,
        clock=time.monotonic,
        name: str = "cross_replica",
    ):
        if not records:
            raise ValueError("records must not be empty")
        self._replicas = replicas
        self._records_provider = records_provider
        self._journal = journal
        self._metrics = metrics
        self._clock = clock
        self._name = name
        self._lock = threading.Lock()
        self._history: collections.deque = collections.deque(
            maxlen=max(1, int(history))
        )
        self._seq = 0
        self._cycles = 0
        self._divergences = 0
        self._errors = 0
        self._last_pass_mono: Optional[float] = None
        self._failure_listeners: List[Callable[[dict], None]] = []

        n = len(records)
        if indices is None:
            indices = sorted({0, n // 2, n - 1})
        self._indices = [int(i) for i in indices]
        for i in self._indices:
            if not 0 <= i < n:
                raise ValueError(f"golden index {i} out of bounds for {n}")
        self._db_size = n
        self._base_generation = int(generation) if generation else 0
        self._base_expected = [bytes(records[i]) for i in self._indices]
        # One golden pair for the whole fleet: issuing the SAME DPF
        # keys everywhere is the point — any byte difference between
        # replicas' reconstructions is divergence by construction.
        client = DenseDpfPirClient(n, lambda pt, info: pt)
        self._plain_pair = client.create_plain_requests(self._indices)

    def set_journal(self, journal):
        """Point divergence events at a specific journal; None restores
        the process journal."""
        self._journal = journal
        return journal

    def add_failure_listener(self, listener: Callable[[dict], None]) -> None:
        """`listener(result)` on every divergence/error cycle (wire
        `BundleManager.on_probe_failure` here); exceptions swallowed."""
        with self._lock:
            self._failure_listeners.append(listener)

    def _replica_list(self) -> List:
        replicas = self._replicas
        return list(replicas() if callable(replicas) else replicas)

    def _oracle_for(self, generation: int) -> Optional[List[bytes]]:
        """The expected golden plaintexts at `generation`, when known:
        the constructor records at the base generation, the provider's
        everywhere else (None when it cannot say)."""
        if self._records_provider is not None:
            records = self._records_provider(generation)
            if records:
                return [bytes(records[i]) for i in self._indices]
        if generation == self._base_generation:
            return list(self._base_expected)
        return None

    @staticmethod
    def _issue(leader, request):
        """Same entry rule as `Prober._issue_batched`, per replica."""
        server = leader.server
        if getattr(server, "role", "plain") == "plain":
            return leader.handle_request(request)
        return server._dispatch_plain(request)

    def run_cycle(self) -> dict:
        """Probe every replica once; returns the cycle result dict
        (status `pass` / `mismatch` / `error`)."""
        t0 = time.perf_counter()
        req0, req1 = self._plain_pair
        answers: Dict[str, dict] = {}
        errors: Dict[str, str] = {}
        for replica in self._replica_list():
            rid = replica.replica_id
            try:
                managers = [
                    m
                    for m in (
                        getattr(replica, "snapshots", None),
                        getattr(replica, "helper_snapshots", None),
                    )
                    if m is not None
                ]
                # Pin the replica's managers: its two shares (and the
                # generation label below) must belong to ONE generation
                # even while a fleet rotation is in flight.
                with contextlib.ExitStack() as stack:
                    for manager in managers:
                        stack.enter_context(manager.pin())
                    generation = replica.serving_generation()
                    resp0 = self._issue(replica.leader, req0)
                    resp1 = self._issue(replica.leader, req1)
                    masked0 = resp0.dpf_pir_response.masked_response
                    masked1 = resp1.dpf_pir_response.masked_response
                    got = [
                        xor_bytes(a, b) for a, b in zip(masked0, masked1)
                    ]
                answers[rid] = {"generation": generation, "records": got}
            except Exception as e:  # noqa: BLE001 - per-replica fault domain
                errors[rid] = f"{type(e).__name__}: {e}"[:300]

        # Group by serving generation; bit-identity is asserted within
        # each group (cross-generation disagreement during a rotation
        # is legitimate and merely reported).
        groups: Dict[int, Dict[str, List[bytes]]] = {}
        for rid, answer in answers.items():
            groups.setdefault(answer["generation"], {})[rid] = answer[
                "records"
            ]
        divergences: List[dict] = []
        for generation, members in sorted(groups.items()):
            rids = sorted(members)
            reference_rid = rids[0]
            reference = members[reference_rid]
            oracle = self._oracle_for(generation)
            for rid in rids:
                got = members[rid]
                baseline = oracle if oracle is not None else reference
                baseline_name = (
                    "oracle" if oracle is not None else reference_rid
                )
                for idx, want, have in zip(
                    self._indices, baseline, got
                ):
                    if want != have:
                        divergences.append(
                            {
                                "replica": rid,
                                "generation": generation,
                                "index": idx,
                                "against": baseline_name,
                                "expected": want.hex()[:32],
                                "got": have.hex()[:32],
                            }
                        )
                        break

        status = "pass"
        detail = None
        if divergences:
            status = "mismatch"
            first = divergences[0]
            detail = (
                f"replica {first['replica']} diverges from "
                f"{first['against']} at generation "
                f"{first['generation']}, index {first['index']}"
            )
        elif errors and not answers:
            status = "error"
            detail = f"every replica errored: {sorted(errors)}"
        ms = round((time.perf_counter() - t0) * 1e3, 3)
        with self._lock:
            self._seq += 1
            self._cycles += 1
            seq = self._seq
            if status == "mismatch":
                self._divergences += 1
            if errors:
                self._errors += len(errors)
            if status == "pass":
                self._last_pass_mono = self._clock()
            listeners = list(self._failure_listeners)
        result = {
            "kind": self._name,
            "status": status,
            "detail": detail,
            "ms": ms,
            "seq": seq,
            "t_wall": round(time.time(), 3),
            "t_mono": round(self._clock(), 3),
            "replicas": sorted(answers),
            "generations": {
                str(g): sorted(m) for g, m in sorted(groups.items())
            },
            "divergences": divergences,
            "errors": errors,
        }
        with self._lock:
            self._history.append(result)
        if self._metrics is not None:
            self._metrics.counter("fleet.probe_cycles").inc()
            if divergences:
                self._metrics.counter("fleet.divergences").inc(
                    len(divergences)
                )
        if status != "pass":
            journal = (
                self._journal
                if self._journal is not None
                else events_mod.default_journal()
            )
            journal.emit(
                "fleet.divergence"
                if status == "mismatch"
                else "fleet.probe_error",
                f"{self._name}: {detail}",
                severity="error",
                probe_kind=self._name,
                probe_seq=seq,
                divergences=len(divergences),
                replicas=sorted(answers),
            )
            for listener in listeners:
                try:
                    listener(result)
                except Exception:  # noqa: BLE001 - canary must keep flying
                    pass
        return result

    def last_pass_age_s(self) -> Optional[float]:
        """Seconds since the last fully passing cycle — the fleet SLO
        "divergence-probe freshness" reads this. None until the probe
        has passed once (graded as no_data, not a breach: a fleet that
        has not been probed yet is not failing its SLO)."""
        with self._lock:
            if self._last_pass_mono is None:
                return None
            return max(0.0, self._clock() - self._last_pass_mono)

    def export(self) -> dict:
        age = self.last_pass_age_s()
        with self._lock:
            return {
                "name": self._name,
                "indices": list(self._indices),
                "db_size": self._db_size,
                "cycles": self._cycles,
                "divergences": self._divergences,
                "errors": self._errors,
                "last_pass_age_s": (
                    round(age, 3) if age is not None else None
                ),
                "history": [dict(r) for r in self._history],
            }
