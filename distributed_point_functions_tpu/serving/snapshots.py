"""Versioned database snapshots: crash-safe rotation under live traffic.

Every session used to stage one immutable `DenseDpfPirDatabase` at
construction and serve it forever. The ROADMAP north-star is a
directory that changes continuously — and in the CGKS two-server model
the dangerous failure is *silent*: if the Leader's share evaluates
against generation N while the Helper answers from N+1, both shares
are perfectly well-formed and their XOR is garbage. No latency metric
flags it; only the PR 9 prober's bit-identity check would, after the
fact. Rotation therefore has to preserve one invariant end to end:

    a response is either computed entirely against one generation,
    or it is a typed refusal — never a cross-generation XOR.

`SnapshotManager` owns the generation lifecycle on one party:

* **stage(db)** — generation N+1 (built host-side, usually via
  `DenseDpfPirDatabase.Builder.build_from(prev)`) is staged into HBM
  double-buffered via `db.prestage()` while N keeps serving; the
  database's own `_stage_lock` and the `TransferLedger` already
  bracket the transfer. Failpoint site: `snapshot.stage`.
* **flip()** — arms a pending flip and applies it at a *batch
  boundary*: the `DynamicBatcher` worker calls `begin_batch()` before
  every evaluation (applying the pending flip first, when nothing is
  pinned) and `end_batch(gen)` after its fan-out, so a batch never
  evaluates half-and-half and in-flight buckets drain against the
  generation they bound. Unbatched readers bracket with `pin()`,
  which also holds a pending flip off. Failpoint site:
  `snapshot.flip`.
* **drain-then-free** — the old generation's HBM stagings are
  dropped (`release_stagings()`, journaled as `snapshot.drained`)
  only after its last in-flight batch retires, so a response being
  computed against N never loses its buffers mid-evaluation.

`RotationCoordinator` drives the two-party handshake: stage on both
parties, then flip the **Helper first and the Leader last**
(failpoint site `snapshot.helper_ack` between). During the bounded
window in between, the Leader's generation check (`serving/
service.py`) refuses the Helper's v3 echo with a typed
`SnapshotMismatch` and retries — the retry lands after the Leader's
own flip and converges. The window is measured: `staleness_ms` on the
flip-history record is the Helper->Leader flip gap. Any staging or
flip fault aborts both parties (`snapshot.abort`), leaving generation
N serving untouched — rotation is crash-safe because the flip is the
single commit point and everything before it is droppable staging.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Callable, List, Optional

from ..observability import events as events_mod
from ..robustness import failpoints

__all__ = [
    "SnapshotMismatch",
    "SnapshotManager",
    "RotationCoordinator",
]


class SnapshotMismatch(RuntimeError):
    """The two parties answered one query from different database
    generations. The shares must not be combined (their XOR is
    well-formed garbage); the Leader retries the whole request
    instead — see `ServingConfig.snapshot_retries`."""

    def __init__(
        self,
        leader_generation: Optional[int],
        helper_generation: Optional[int],
        message: str = "",
    ):
        super().__init__(
            message
            or (
                "snapshot generation mismatch: leader evaluated against "
                f"generation {leader_generation}, helper answered from "
                f"{helper_generation}"
            )
        )
        self.leader_generation = leader_generation
        self.helper_generation = helper_generation


class SnapshotManager:
    """One party's generation lifecycle (see module docstring).

    `session` is a serving `_Session` (duck-typed: `.server`,
    `.batcher`, `.metrics`, `.attach_snapshots`); construction wires
    the manager in as the batcher's generation source, so flips land
    only at batch boundaries from then on. `journal`/`bundles` default
    to the process journal and no bundle capture; `clock` is
    injectable for deterministic staleness tests.
    """

    def __init__(
        self,
        session,
        *,
        journal=None,
        bundles=None,
        clock=time.monotonic,
        name: str = "snapshots",
        history: int = 32,
    ):
        self._session = session
        self._server = session.server
        self._journal = journal
        self._bundles = bundles
        self._clock = clock
        self._name = name
        m = session.metrics
        self._c_flips = m.counter(f"{name}.flips")
        self._c_aborts = m.counter(f"{name}.aborts")
        self._c_mismatches = m.counter(f"{name}.mismatches")
        self._c_drained = m.counter(f"{name}.generations_drained")
        self._g_serving = m.gauge(f"{name}.serving_generation")
        self._g_staging = m.gauge(f"{name}.staging_generation")
        self._cond = threading.Condition()
        self._staging = None
        # Most recent prestage accounting (database.last_prestage_stats
        # from the last stage() call): mode full/delta, bytes staged vs
        # the full image, bytes saved.
        self._last_stage: Optional[dict] = None
        self._pending_flip = False
        # generation -> in-flight batch count (bound at begin_batch).
        self._inflight: dict = {}
        # Retired generations still owed a drain: generation -> db.
        self._retired: dict = {}
        self._pins = 0
        self._history: collections.deque = collections.deque(
            maxlen=max(1, history)
        )
        self._flip_listeners: List[Callable] = []
        self._g_serving.set(float(self.serving_generation()))
        self._g_staging.set(-1.0)
        session.attach_snapshots(self)

    # -- reading ------------------------------------------------------------

    def serving_generation(self) -> int:
        return self._server.database.generation

    def staging_generation(self) -> Optional[int]:
        with self._cond:
            return (
                self._staging.generation
                if self._staging is not None else None
            )

    def set_journal(self, journal):
        """Point rotation events at a (replica-scoped) journal; None
        restores the process journal. The fleet telemetry plane calls
        this so flips/aborts/drains carry replica identity."""
        self._journal = journal
        return journal

    def _emit(self, kind, message, severity="info", **fields):
        journal = (
            self._journal
            if self._journal is not None
            else events_mod.default_journal()
        )
        try:
            journal.emit(kind, message, severity=severity, **fields)
        except Exception:  # noqa: BLE001 - journaling never breaks rotation
            pass

    def add_flip_listener(self, listener: Callable[[dict], None]) -> None:
        """Register `listener(flip_record)`, called after every applied
        flip *outside* the manager lock (the prober re-keys its golden
        pairs here). Exceptions are swallowed."""
        with self._cond:
            self._flip_listeners.append(listener)

    # -- staging ------------------------------------------------------------

    def stage(self, database) -> int:
        """Stage generation N+1 into HBM double-buffered while N keeps
        serving; returns the bytes transferred (0 when the buffer was
        already resident). Geometry must match the serving database —
        a mismatch fails here, before any flip is armed. Replacing an
        already-staged (never-flipped) candidate drops its buffers."""
        cur = self._server.database
        validate = getattr(self._server, "validate_snapshot", None)
        if callable(validate):
            # Geometry-aware servers (the sparse cuckoo server) own
            # their swap precondition: cuckoo bucket count, hash
            # params/seed, dense row shapes. A mis-rotated snapshot
            # surfaces as the typed rotation fault, not a ValueError
            # that callers would read as a coding bug — and never
            # serves garbage.
            try:
                validate(database)
            except ValueError as e:
                self._c_mismatches.inc()
                raise SnapshotMismatch(
                    cur.generation,
                    database.generation,
                    message=f"staged generation rejected: {e}",
                ) from e
        else:
            if database.size != cur.size:
                raise ValueError(
                    f"staged generation size {database.size} != serving "
                    f"{cur.size}"
                )
            if database.max_value_size != cur.max_value_size:
                raise ValueError(
                    "staged generation max_value_size "
                    f"{database.max_value_size} != serving "
                    f"{cur.max_value_size}"
                )
        failpoints.fire("snapshot.stage")
        # Stage in the layout the server actually serves (a mesh server
        # shards generation N+1 over its shard axis here, so the flip
        # swaps one fully-assembled staging — all shards at once, never
        # a partial flip); plain `prestage()` otherwise.
        prestage = getattr(self._server, "prestage_database", None)
        if callable(prestage):
            staged_bytes = prestage(database)
        else:
            staged_bytes = database.prestage()
        # Delta prestage visibility: the database reports what it
        # actually uploaded vs the full image (serving/snapshots
        # rotation cost = `bytes_staged`; `bytes_saved` is the delta
        # win, 0 for a full staging).
        stage_stats = getattr(database, "last_prestage_stats", None)
        replaced = None
        with self._cond:
            if self._staging is not None and self._staging is not database:
                replaced = self._staging
            self._staging = database
            self._g_staging.set(float(database.generation))
            if stage_stats is not None:
                self._last_stage = dict(stage_stats)
        if replaced is not None:
            replaced.release_stagings()
        return staged_bytes

    # -- the batch-boundary contract (DynamicBatcher generation source) -----

    def begin_batch(self) -> int:
        """Called by the batcher worker before every evaluation: apply
        a pending flip first (unless pinned readers hold it off), then
        bind the batch to the now-serving generation."""
        fired = None
        with self._cond:
            if self._pending_flip and self._pins == 0:
                fired = self._apply_flip_locked()
            gen = self._server.database.generation
            self._inflight[gen] = self._inflight.get(gen, 0) + 1
        if fired is not None:
            self._after_flip(fired)
        return gen

    def end_batch(self, generation: int) -> None:
        """The batch bound at `begin_batch` has fully retired: its
        generation's drain counter steps down, and a retired (flipped-
        away) generation whose count reaches zero frees its stagings."""
        to_free = None
        with self._cond:
            n = self._inflight.get(generation, 0) - 1
            if n > 0:
                self._inflight[generation] = n
            else:
                self._inflight.pop(generation, None)
                to_free = self._retired.pop(generation, None)
            self._cond.notify_all()
        if to_free is not None:
            self._free_retired(to_free)

    def _free_retired(self, database) -> None:
        dropped = database.release_stagings()
        self._c_drained.inc()
        self._emit(
            "snapshot.drained",
            f"generation {database.generation} drained; "
            f"{dropped} staged buffer(s) freed",
            generation=database.generation,
            buffers_freed=dropped,
        )

    @contextlib.contextmanager
    def pin(self):
        """Bracket an unbatched multi-step read (e.g. one prober probe
        pair): a pending flip neither applies nor is newly applied
        while any pin is held, so everything inside sees one
        generation. Yields that generation."""
        with self._cond:
            self._pins += 1
            gen = self._server.database.generation
        try:
            yield gen
        finally:
            with self._cond:
                self._pins -= 1
                self._cond.notify_all()

    # -- flipping -----------------------------------------------------------

    def flip(self, timeout: float = 10.0) -> dict:
        """Commit the staged generation: applied immediately when the
        party is idle, otherwise armed and applied by the batcher
        worker at the next batch boundary (this call waits for it).
        Returns the flip-history record. Raises `TimeoutError` (after
        disarming) if in-flight work or pins never drain — the staged
        generation stays staged and N keeps serving."""
        failpoints.fire("snapshot.flip")
        fired = None
        waited_s = 0.0
        with self._cond:
            if self._staging is None:
                raise RuntimeError("no staged generation to flip to")
            target = self._staging.generation
            self._pending_flip = True
            deadline = time.monotonic() + max(0.0, timeout)
            while self._server.database.generation != target:
                if self._pending_flip and self._pins == 0 and not any(
                    self._inflight.values()
                ):
                    fired = self._apply_flip_locked()
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._pending_flip = False
                    raise TimeoutError(
                        f"flip to generation {target} timed out after "
                        f"{timeout:.1f}s (pins={self._pins}, inflight="
                        f"{sum(self._inflight.values())})"
                    )
                t_wait = time.monotonic()
                self._cond.wait(remaining)
                waited_s += time.monotonic() - t_wait
            record = self._history[-1]
        if waited_s > 0.0:
            # The flip's drain wait (in-flight batches / pins) is a
            # typed utilization bubble: the rotation held work back.
            try:
                from ..observability.utilization import (
                    default_utilization_tracker,
                )

                default_utilization_tracker().record_idle(
                    "snapshot_flip", waited_s, thread="rotation"
                )
            except Exception:  # noqa: BLE001 - accounting never breaks flips
                pass
        if fired is not None:
            self._after_flip(fired)
        return dict(record)

    def _apply_flip_locked(self) -> dict:
        """Swap the staged generation in at a proven batch boundary
        (caller holds the lock and has checked pins). The old
        generation retires: freed now if nothing is in flight against
        it, else parked until `end_batch` drains it."""
        new = self._staging
        old = self._server.swap_database(new)
        self._staging = None
        self._pending_flip = False
        record = {
            "from_generation": old.generation,
            "to_generation": new.generation,
            "t_mono": round(self._clock(), 6),
            "staleness_ms": None,
            "inflight_old": self._inflight.get(old.generation, 0),
        }
        self._retired[old.generation] = old
        record["old_freed"] = (
            "deferred"
            if self._inflight.get(old.generation, 0) > 0
            else "immediate"
        )
        self._history.append(record)
        self._c_flips.inc()
        self._g_serving.set(float(new.generation))
        self._g_staging.set(-1.0)
        self._cond.notify_all()
        return record

    def _after_flip(self, record: dict) -> None:
        """Post-commit work that must not run under the manager lock
        (a listener may submit to the batcher, whose worker needs
        `begin_batch`)."""
        if record.get("old_freed") == "immediate":
            with self._cond:
                db = self._retired.pop(record["from_generation"], None)
            if db is not None:
                self._free_retired(db)
        self._emit(
            "snapshot.flip",
            f"generation {record['from_generation']} -> "
            f"{record['to_generation']} "
            f"(old stagings {record['old_freed']})",
            from_generation=record["from_generation"],
            to_generation=record["to_generation"],
        )
        with self._cond:
            listeners = list(self._flip_listeners)
        for listener in listeners:
            try:
                listener(dict(record))
            except Exception:  # noqa: BLE001 - listeners must not break flips
                pass

    def note_staleness(self, staleness_ms: float) -> None:
        """Stamp the Helper->Leader flip gap (measured by the
        coordinator) onto the most recent flip record."""
        with self._cond:
            if self._history:
                self._history[-1]["staleness_ms"] = round(
                    float(staleness_ms), 3
                )

    # -- failure paths ------------------------------------------------------

    def abort(self, reason: str) -> None:
        """Drop the staged (never-flipped) candidate and disarm any
        pending flip; generation N keeps serving untouched. Idempotent
        — aborting with nothing staged only journals."""
        with self._cond:
            db = self._staging
            self._staging = None
            self._pending_flip = False
            self._g_staging.set(-1.0)
            self._cond.notify_all()
        if db is not None:
            try:
                db.release_stagings()
            except Exception:  # noqa: BLE001 - abort must not raise
                pass
        self._c_aborts.inc()
        self._emit(
            "snapshot.abort",
            f"rotation aborted: {reason}",
            severity="warning",
            reason=str(reason)[:256],
        )

    def record_mismatch(
        self,
        leader_generation: Optional[int],
        helper_generation: Optional[int],
        trace_id: Optional[str] = None,
    ) -> None:
        """A cross-generation answer was refused: count it, journal it,
        and capture a debug bundle (the mismatch window is exactly the
        state an operator needs frozen)."""
        self._c_mismatches.inc()
        self._emit(
            "snapshot.mismatch",
            f"refused cross-generation answer: leader={leader_generation} "
            f"helper={helper_generation}",
            severity="error",
            leader_generation=leader_generation,
            helper_generation=helper_generation,
            coalesce_key=(
                f"snapshot.mismatch:{leader_generation}:{helper_generation}"
            ),
            coalesce_s=1.0,
        )
        if self._bundles is not None:
            try:
                self._bundles.trigger(
                    "snapshot_mismatch",
                    {
                        "leader_generation": leader_generation,
                        "helper_generation": helper_generation,
                        "trace_id": trace_id,
                    },
                )
            except Exception:  # noqa: BLE001 - capture must not break serving
                pass

    def note_unchecked(self, peer_version: int) -> None:
        """A pre-v3 peer answered with no generation echo while
        rotation machinery is live: checking is disabled for that
        peer, journaled (coalesced) so the gap is visible, and the
        answer is still only combined when this party's own
        generation is current — never silently cross-generation."""
        self._emit(
            "snapshot.check_disabled",
            f"peer speaks wire v{peer_version}: generation checking "
            "disabled for this peer",
            severity="warning",
            peer_version=int(peer_version),
            coalesce_key=f"snapshot.check_disabled:{peer_version}",
            coalesce_s=5.0,
        )

    # -- export -------------------------------------------------------------

    def export(self) -> dict:
        with self._cond:
            return {
                "serving_generation": self._server.database.generation,
                "staging_generation": (
                    self._staging.generation
                    if self._staging is not None else None
                ),
                "pending_flip": self._pending_flip,
                "pins": self._pins,
                "inflight": {
                    str(g): n for g, n in sorted(self._inflight.items())
                },
                "retired_awaiting_drain": sorted(self._retired),
                "flips": self._c_flips.value,
                "aborts": self._c_aborts.value,
                "mismatches": self._c_mismatches.value,
                "last_stage": (
                    dict(self._last_stage)
                    if self._last_stage is not None else None
                ),
                "history": [dict(r) for r in self._history],
            }


class RotationCoordinator:
    """Two-party prepare->flip handshake (see module docstring).

    `leader` and `helper` are `SnapshotManager`s (helper None for a
    single-party/plain deployment). The flip order is deliberate —
    **Helper first, Leader last** — so the only cross-generation
    window is one the Leader's generation check turns into typed
    retries: a Leader answering from N while the Helper is already on
    N+1 refuses the echo and retries; the reverse order would need the
    Helper to police the Leader, which the wire does not support.
    """

    def __init__(self, leader: SnapshotManager, helper=None, clock=time.monotonic):
        self._leader = leader
        self._helper = helper
        self._clock = clock
        self._window_source = None

    def set_window_source(self, source) -> None:
        """Attach a forecast trough finder: a `window_s -> dict`
        callable (duck-typed — in practice
        `observability.forecast.Forecaster.window_source(series)`)
        whose dict carries at least `start_offset_s`. None detaches."""
        self._window_source = source

    def suggest_window(self, window_s: float = 30.0) -> dict:
        """When should the next rotation prestage start? With a window
        source attached, the forecast's lowest-load window inside its
        horizon; without one (or on any source error), now. Advisory
        only — `rotate()` never blocks on it."""
        suggestion = {
            "window_s": float(window_s),
            "start_offset_s": 0.0,
            "source": "none",
        }
        if self._window_source is None:
            return suggestion
        try:
            trough = self._window_source(window_s) or {}
        except Exception:  # noqa: BLE001 - advisory must not break rotation
            suggestion["source"] = "error"
            return suggestion
        suggestion.update(trough)
        suggestion["window_s"] = float(window_s)
        suggestion["source"] = "forecast"
        return suggestion

    def rotate(
        self,
        leader_db,
        helper_db=None,
        timeout: float = 10.0,
    ) -> dict:
        """Stage both parties, then flip Helper-first/Leader-last.
        Returns a report with the measured `staleness_ms` window. Any
        fault aborts both parties and re-raises: generation N keeps
        serving and the staged buffers are dropped."""
        if self._helper is not None and helper_db is None:
            raise ValueError(
                "helper_db is required when a helper manager is attached "
                "(the parties stage distinct database objects)"
            )
        report = {
            "to_generation": leader_db.generation,
            "staleness_ms": 0.0,
        }
        try:
            report["leader_staged_bytes"] = self._leader.stage(leader_db)
            if self._helper is not None:
                report["helper_staged_bytes"] = self._helper.stage(
                    helper_db
                )
            # Chaos site: the prepare->flip ack between staging both
            # parties and committing either — a fault here must leave
            # generation N serving on both.
            failpoints.fire("snapshot.helper_ack")
            t_helper = None
            if self._helper is not None:
                self._helper.flip(timeout=timeout)
                t_helper = self._clock()
            self._leader.flip(timeout=timeout)
            if t_helper is not None:
                staleness_ms = max(0.0, (self._clock() - t_helper) * 1e3)
                report["staleness_ms"] = round(staleness_ms, 3)
                self._leader.note_staleness(staleness_ms)
        except Exception as e:
            self._leader.abort(f"rotation to {leader_db.generation}: {e}")
            if self._helper is not None:
                self._helper.abort(
                    f"rotation to {leader_db.generation}: {e}"
                )
            raise
        return report
