"""Leader/Helper/Plain serving sessions: the production runtime roles.

`pir/server.py` implements the protocol roles with injected transport
and crypto callbacks; these sessions wrap them into deployable objects:

* every plain evaluation (a plain-role request, the Leader's own share,
  the Helper's decrypted request) routes through one `DynamicBatcher`
  per session via the server's `set_plain_handler` hook, so concurrent
  requests share device steps and jit cache entries;
* requests carry per-request **deadlines** (default
  `ServingConfig.request_timeout_ms`) enforced both in the batcher
  queue and on the submitting thread;
* the Leader's Helper leg gets a per-attempt **timeout** and bounded
  **exponential-backoff retry**; exhausted retries raise
  `HelperUnavailable`, or — when the operator opts in with
  `allow_degraded` — degrade to the Leader's own
  `handle_plain_request` share so the session keeps answering (the
  response is flagged in metrics; a client that sees degraded service
  must fall back to plain single-server queries to read real records);
* a **circuit breaker** (`robustness/breaker.py`) fronts the helper
  leg: after `breaker_failure_threshold` consecutive leg failures it
  opens and requests fast-fail to `HelperUnavailable` in well under a
  millisecond instead of paying the timeout+backoff ladder each; after
  `breaker_reset_ms` one half-open probe request runs the real leg,
  and its success closes the breaker AND exits degraded mode — the
  next responses are full two-share answers again. Breaker state is a
  gauge (`leader.breaker_state`: 0 closed / 1 half-open / 2 open —
  point an SLO `gauge_max` objective at it for a burn signal), an
  export on the session (`breaker_export()`, the /statusz row), and
  counters (`leader.breaker_opens`, `leader.breaker_fast_fails`,
  `leader.degraded_exits`);
* a `MetricsRegistry` per session (injectable, so co-located sessions
  can share one) records queue/batch/retry/latency counters, exported
  with `session.metrics.export()`;
* every request roots an observability **trace** (`observability/
  tracing.py`): wire decode/encode, queue wait, batch assembly, and
  device compute land as spans, the finished trace lands in the flight
  recorder (`/tracez` on an `AdminServer`). On the Leader, the trace id
  rides to the Helper inside a versioned envelope
  (`observability/propagation.py`) and the Helper's server-side spans
  come back in the reply, so helper-leg RTT decomposes into network
  vs. Helper-reported compute. Old-version peers interop: a Helper fed
  a bare proto answers a bare proto, and a Leader whose enveloped
  request faults a v0 Helper downgrades that transport to bare proto
  (counted in `leader.wire_downgrades`) and retries within its
  existing retry budget.

Sessions speak either library `messages.PirRequest` objects
(`handle_request`) or the framed proto wire format (`handle_wire`,
pluggable straight into `transport.FramedTcpServer`).
"""

from __future__ import annotations

import contextvars
import dataclasses
import os
import time
from typing import Optional

import threading

from .. import serialization
from ..capacity.admission import AdmissionController, TenantPolicy
from ..capacity.brownout import BrownoutController
from ..capacity.recalibrate import CapacityAccuracy, default_recalibrator
from ..observability import costmodel as costmodel_mod
from ..observability import events as events_mod
from ..observability import critical_path, propagation, tracing
from ..observability import phases as phases_mod
from ..observability.utilization import default_utilization_tracker
from ..observability.device import (
    default_telemetry,
    install_jax_monitoring_listener,
)
from ..pir import messages
from ..pir.database import DenseDpfPirDatabase
from ..pir.server import DenseDpfPirServer, clear_tier_floor, set_tier_floor
from ..robustness import failpoints
from ..robustness.breaker import CircuitBreaker
from .batcher import DeadlineExceeded, DynamicBatcher, Overloaded
from .metrics import MetricsRegistry
from .snapshots import SnapshotMismatch
from .transport import Transport, TransportError, TransportTimeout

__all__ = [
    "ServingConfig",
    "HelperUnavailable",
    "SnapshotMismatch",
    "PlainSession",
    "LeaderSession",
    "HelperSession",
    "DeadlineExceeded",
    "Overloaded",
    "TenantPolicy",
]


class HelperUnavailable(RuntimeError):
    """The Helper leg failed every attempt (timeouts and/or refusals)."""


@dataclasses.dataclass
class ServingConfig:
    """Operator knobs for a serving session.

    `request_timeout_ms=None` disables deadlines (a cold first request
    compiles jit programs and may legitimately take minutes on CPU).
    `helper_retries` counts retries *after* the first attempt; backoff
    doubles from `helper_backoff_ms` up to `helper_backoff_max_ms`.
    `allow_degraded=True` opts into Leader-share-only responses when the
    Helper is permanently down (see module docstring for the privacy
    and correctness contract).

    The breaker fields shape the Leader's helper-leg circuit breaker:
    it opens after `breaker_failure_threshold` consecutive failed legs
    (each exhausted retry ladder counts its attempts individually) and
    admits one half-open probe per `breaker_reset_ms` window.
    `breaker_enabled=False` restores the PR 2 behavior (every request
    pays the full ladder).

    `admission_enabled=True` replaces the batcher's request-count
    bound with cost-aware admission (`capacity/admission.py`): doomed
    and over-quota requests shed at submit with a `retry_after_s`
    hint, per-tenant quotas/weights via `session.set_tenant()`, and
    weighted-fair dequeue. `admission_queue_budget_ms` is the queued
    estimated-device-ms the controller will hold before shedding.

    The helper retry *budget* bounds the retry:success ratio so
    retries cannot amplify an overload: each successful leg earns
    `helper_retry_budget_ratio` retry tokens (capped at
    `helper_retry_budget_min`, which is also the starting balance) and
    each retry spends one; an empty budget skips the remaining ladder
    and raises `HelperUnavailable` immediately (counted in
    `leader.retries_budget_exhausted`). The PR 7 breaker handles a
    *dead* Helper; the budget handles a *slow* one.
    """

    max_batch_size: int = 64
    max_wait_ms: float = 2.0
    max_queue: int = 256
    request_timeout_ms: Optional[float] = None
    helper_timeout_ms: Optional[float] = 30_000.0
    helper_retries: int = 2
    helper_backoff_ms: float = 10.0
    helper_backoff_max_ms: float = 250.0
    allow_degraded: bool = False
    batching: bool = True
    breaker_enabled: bool = True
    breaker_failure_threshold: int = 5
    breaker_reset_ms: float = 1000.0
    admission_enabled: bool = False
    admission_queue_budget_ms: float = 250.0
    helper_retry_budget_ratio: float = 0.1
    helper_retry_budget_min: float = 10.0
    # False pins the Leader's envelope probe at v1: no Helper phase
    # digest, no skew estimate, no critical-path decomposition — the
    # knob the digest-piggyback overhead benchmark flips.
    helper_digest: bool = True
    # How many times the Leader re-runs a request whose Helper answer
    # came from a different database generation (typed
    # SnapshotMismatch, never a cross-generation XOR). Retries
    # converge because the Leader's own pending flip applies at the
    # next batch boundary; the window is the coordinator's bounded
    # Helper-first/Leader-last flip gap.
    snapshot_retries: int = 3
    # Batcher pipeline depth: 2 (default) lets the worker dispatch
    # bucket N while a completion thread fans out bucket N-1 (see
    # serving/batcher.py); 1 restores strictly serial
    # dispatch-then-complete batches.
    pipeline_depth: int = 2
    # Device-utilization accounting (observability/utilization.py):
    # the batcher worker/completion threads and the Leader's helper
    # leg report busy/idle intervals with typed bubble causes into the
    # process-wide tracker (read on /utilz). False detaches it — the
    # knob the utilization_overhead benchmark flips.
    utilization: bool = True


# The deadline travels from handle_request into the server's plain
# handler (called synchronously, possibly from inside the Leader's
# while_waiting callback on the same thread) without threading it
# through the reference-mirroring server signatures.
_DEADLINE: contextvars.ContextVar = contextvars.ContextVar(
    "serving_deadline", default=None
)
# The requesting tenant rides the same way: set at handle_request,
# read where the plain handler submits to the batcher.
_TENANT: contextvars.ContextVar = contextvars.ContextVar(
    "serving_tenant", default="default"
)
# The snapshot generation the most recent batched evaluation on this
# context bound to (set by _batched_plain_handler from the batcher's
# batch-boundary stamp). The Helper's handle_wire echoes it in the v3
# reply; the Leader's _send_to_helper compares it against the Helper's
# echo. Reset to None at each entry point so a stale value from a
# previous request on the same thread can never bleed into the check.
_EVAL_GENERATION: contextvars.ContextVar = contextvars.ContextVar(
    "serving_eval_generation", default=None
)


# ---------------------------------------------------------------------------
# Persistent JAX compilation cache (opt-in, process-wide)
# ---------------------------------------------------------------------------

_COMPILE_CACHE_ENV = "DPF_TPU_COMPILE_CACHE_DIR"
_compile_cache_state: Optional[dict] = None
_compile_cache_lock = threading.Lock()


def _cache_entries(path: str) -> int:
    try:
        return sum(1 for n in os.listdir(path) if not n.startswith("."))
    except OSError:
        return 0


def install_compile_cache() -> Optional[dict]:
    """Opt-in persistent JAX compilation cache: when
    `DPF_TPU_COMPILE_CACHE_DIR` is set, point
    `jax_compilation_cache_dir` at it so a restarted process deserializes
    yesterday's XLA programs instead of recompiling them on the first
    request. Idempotent and process-wide (the cache is a JAX global);
    returns the state dict (None when the env is unset). The state —
    cache dir, entries present at startup (warm), and entries persisted
    by this process (cold compiles now cached for the next restart) —
    is pushed into the device telemetry so `/statusz`'s compile table
    shows it next to the per-site compile counts."""
    global _compile_cache_state
    with _compile_cache_lock:
        if _compile_cache_state is not None:
            return _compile_cache_state
        path = os.environ.get(_COMPILE_CACHE_ENV, "").strip()
        if not path:
            return None
        try:
            os.makedirs(path, exist_ok=True)
            import jax

            jax.config.update("jax_compilation_cache_dir", path)
            # Persist every program: serving's jit shapes are few and
            # bucketed, and the cold first request is exactly what the
            # cache exists to kill. Older jaxlibs lack the thresholds.
            for knob, value in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1),
            ):
                try:
                    jax.config.update(knob, value)
                except Exception:  # noqa: BLE001 - knob absent is fine
                    pass
            state = {
                "dir": path,
                "warm_entries_at_start": _cache_entries(path),
            }
        except Exception as e:  # noqa: BLE001 - cache is an optimization
            state = {"dir": path, "error": f"{type(e).__name__}: {e}"}
        _compile_cache_state = state

    def _info() -> dict:
        out = dict(state)
        if "error" not in out:
            current = _cache_entries(path)
            out["entries"] = current
            out["persisted_this_process"] = max(
                0, current - state["warm_entries_at_start"]
            )
        return out

    default_telemetry().set_compile_cache_info(_info)
    return state


class _Session:
    """Shared session mechanics: batcher wiring, deadlines, wire codec."""

    def __init__(
        self,
        server: DenseDpfPirServer,
        config: Optional[ServingConfig],
        metrics: Optional[MetricsRegistry],
        name: str,
    ):
        self._server = server
        self._config = config if config is not None else ServingConfig()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._name = name
        # Opt-in persistent compilation cache first: it must be wired
        # before the session's first jit dispatch to serve warm
        # programs (no-op without DPF_TPU_COMPILE_CACHE_DIR).
        install_compile_cache()
        # Device telemetry rides the session's registry: compile events
        # and HBM watermarks from the dispatch sites below show up on
        # this session's /metrics and /statusz. The jax.monitoring
        # bridge is one process-wide listener (idempotent install).
        default_telemetry().bind_registry(self.metrics)
        install_jax_monitoring_listener(default_telemetry().compile_tracker)
        phases_mod.default_phase_recorder().bind_registry(self.metrics)
        # Cost-model accuracy: the process-wide ledger mirrors residual
        # histograms + the drift gauge into this session's registry,
        # and the shared recalibrator closes the loop on the default
        # capacity model's prices. `capacity_accuracy` is the read
        # model /capacityz and the /statusz section render.
        ledger = costmodel_mod.default_cost_ledger()
        ledger.bind_registry(self.metrics)
        self.capacity_accuracy = CapacityAccuracy(
            ledger=ledger, recalibrator=default_recalibrator()
        )
        # Snapshot rotation (serving/snapshots.py): a SnapshotManager
        # wires itself in via attach_snapshots at construction.
        self.snapshots = None
        self.admission: Optional[AdmissionController] = None
        if self._config.admission_enabled:
            self.admission = AdmissionController(
                queue_budget_ms=self._config.admission_queue_budget_ms,
                metrics=self.metrics,
                name=f"{name}.admission",
            )
        self._batcher: Optional[DynamicBatcher] = None
        if self._config.batching:
            self._batcher = DynamicBatcher(
                self._evaluate_keys,
                max_batch_size=self._config.max_batch_size,
                max_wait_ms=self._config.max_wait_ms,
                max_queue=self._config.max_queue,
                metrics=self.metrics,
                name=f"{name}.batcher",
                admission=self.admission,
                pipeline_depth=self._config.pipeline_depth,
            )
            server.set_plain_handler(self._batched_plain_handler)
        # Device-utilization accounting: the batcher threads (and the
        # helper leg below) report busy/idle intervals into the
        # process-wide tracker; gauges/bubble histograms mirror into
        # this session's registry. config.utilization=False detaches.
        # Replica-scoped event routing: None means the process-global
        # journal (single-replica deployments, unchanged); the fleet
        # telemetry plane points this at a scoped journal so breaker
        # transitions, degraded-mode flips, and generation-skew lines
        # carry replica identity when N replicas share one process.
        self._session_journal = None
        self._util = None
        if self._config.utilization:
            self._util = default_utilization_tracker()
            self._util.bind_registry(self.metrics)
            if self._batcher is not None:
                self._batcher.set_utilization(self._util)
        # Mesh wiring: a 2-D-mesh server tells the batcher its key-axis
        # granularity (buckets pad to it, so batches land
        # pre-partitioned) and the capacity model its shape (admission
        # and brownout then price per-shard bytes and per-mesh q/s
        # without any changes of their own).
        is_2d = getattr(server, "_mesh_is_2d", None)
        if callable(is_2d) and is_2d():
            multiple = int(server.batch_key_multiple())
            if multiple > 1 and self._batcher is not None:
                self._batcher.set_key_multiple(multiple)
            mesh = server._mesh
            axes = tuple(mesh.axis_names)
            from ..capacity.model import default_capacity_model

            default_capacity_model().configure_mesh(
                int(mesh.shape[axes[0]]), int(mesh.shape[axes[1]])
            )

    @property
    def server(self) -> DenseDpfPirServer:
        return self._server

    @property
    def config(self) -> ServingConfig:
        return self._config

    @property
    def batcher(self) -> Optional[DynamicBatcher]:
        return self._batcher

    def attach_snapshots(self, manager):
        """Wire a `SnapshotManager` into the session: generation flips
        then land only at this session's batch boundaries, and the
        wire entry points carry/check the generation field."""
        self.snapshots = manager
        if self._batcher is not None:
            self._batcher.set_generation_source(manager)
        return manager

    def set_journal(self, journal):
        """Route this session's own events through `journal` (a
        replica-scoped `EventJournal`); None restores the process
        journal."""
        self._session_journal = journal
        return journal

    def _emit(self, kind, message, severity="info", **fields):
        journal = (
            self._session_journal
            if getattr(self, "_session_journal", None) is not None
            else events_mod.default_journal()
        )
        try:
            journal.emit(kind, message, severity=severity, **fields)
        except Exception:  # noqa: BLE001 - journaling never breaks serving
            pass

    def attach_workload(self, observatory, key_fn=None):
        """Wire a `WorkloadObservatory` onto this session's hot path:
        every `handle_request` observes batch size, tenant, and
        deadline headroom. Key indices stay out by default — DPF keys
        hide them from this server (the protocol's point) — unless the
        caller supplies `key_fn(request) -> indices`, legitimate only
        where indices are public (plain/trusted deployments, load
        generators). Returns `observatory` for chaining."""
        self._workload = observatory
        self._workload_key_fn = key_fn
        return observatory

    def _observe_workload(self, request, deadline, tenant, now) -> None:
        observatory = getattr(self, "_workload", None)
        if observatory is None:
            return
        try:
            plain = getattr(request, "plain_request", None)
            num_keys = len(plain.dpf_keys) if plain is not None else 1
            key_fn = getattr(self, "_workload_key_fn", None)
            indices = key_fn(request) if key_fn is not None else None
            observatory.observe(
                num_keys=num_keys,
                tenant=tenant,
                key_indices=indices,
                deadline_s=(
                    max(0.0, deadline - now)
                    if deadline is not None
                    else None
                ),
                now=now,
            )
        except Exception:  # noqa: BLE001 - observation never breaks serving
            pass

    def set_utilization(self, tracker):
        """Swap this session's utilization tracker — the fleet telemetry
        plane rebinds each replica's sessions to a replica-scoped
        tracker so N replicas in one process stop reporting busy/idle
        into the shared process-global one. Mirrors the construction
        wiring: gauges bind into this session's registry and the batcher
        threads report into the new tracker from the next interval on."""
        self._util = tracker
        if tracker is not None:
            tracker.bind_registry(self.metrics)
            if self._batcher is not None:
                self._batcher.set_utilization(tracker)
        return tracker

    # -- QoS / brownout -----------------------------------------------------

    def set_tenant(self, tenant: str, policy: TenantPolicy) -> None:
        """Register a tenant's QoS contract (requires
        `admission_enabled`)."""
        if self.admission is None:
            raise RuntimeError(
                "set_tenant requires ServingConfig.admission_enabled"
            )
        self.admission.set_tenant(tenant, policy)

    def attach_brownout(
        self,
        brownout: BrownoutController,
        batch_cap: int = 8,
        cheap_tier: str = "streaming",
    ) -> BrownoutController:
        """Wire the ladder's steps to this session's knobs: admission
        priority floors (steps 1 and 4, when admission is enabled),
        the batcher's batch cap (step 2), and the process-wide PIR
        tier floor (step 3). Returns `brownout` for chaining."""
        if self.admission is not None:
            adm = self.admission
            brownout.add_step_action(
                "shed_low_priority",
                lambda: adm.set_min_priority(1),
                lambda: adm.set_min_priority(0),
            )
            # Reverts land in reverse engage order, so critical_only
            # reverting returns the floor to 1 (shed_low_priority is
            # still engaged at that point).
            brownout.add_step_action(
                "critical_only",
                lambda: adm.set_min_priority(2),
                lambda: adm.set_min_priority(1),
            )
        if self._batcher is not None:
            batcher = self._batcher
            brownout.add_step_action(
                "cap_batches",
                lambda: batcher.set_batch_cap(batch_cap),
                lambda: batcher.set_batch_cap(None),
            )
        brownout.add_step_action(
            "force_cheap_tier",
            lambda: set_tier_floor(cheap_tier),
            clear_tier_floor,
        )
        return brownout

    # -- batching -----------------------------------------------------------

    def _evaluate_keys(self, keys):
        """The batcher's evaluation function: one real device step for
        the whole coalesced key batch."""
        response = self._server.handle_plain_request(
            messages.PirRequest(
                plain_request=messages.PlainRequest(dpf_keys=keys)
            )
        )
        return response.dpf_pir_response.masked_response

    def _batched_plain_handler(self, request):
        out, generation = self._batcher.submit_ex(
            request.plain_request.dpf_keys,
            deadline=_DEADLINE.get(),
            tenant=_TENANT.get(),
        )
        if generation is not None:
            # Published for the enclosing entry point (the Helper's
            # echo / the Leader's own-share generation): deliberately
            # un-scoped — the reader is up-stack on this same context
            # and each entry point resets it to None first.
            _EVAL_GENERATION.set(generation)
        return messages.PirResponse(
            dpf_pir_response=messages.DpfPirResponse(
                masked_response=list(out)
            )
        )

    # -- request entry points -----------------------------------------------

    def _default_deadline(self) -> Optional[float]:
        if self._config.request_timeout_ms is None:
            return None
        return time.monotonic() + self._config.request_timeout_ms / 1e3

    def handle_request(
        self,
        request: "messages.PirRequest",
        deadline: Optional[float] = None,
        tenant: str = "default",
    ) -> "messages.PirResponse":
        """Serve one request; `deadline` is absolute `time.monotonic()`
        seconds (defaults from `request_timeout_ms`); `tenant` keys the
        admission QoS policy when enabled."""
        if deadline is None:
            deadline = self._default_deadline()
        self._observe_workload(request, deadline, tenant, time.monotonic())
        token = _DEADLINE.set(deadline)
        tenant_token = _TENANT.set(tenant)
        try:
            with tracing.trace_request(
                f"{self._name}.request", role=self._name
            ):
                with phases_mod.default_phase_recorder().request(
                    self._name
                ):
                    with self.metrics.timed(f"{self._name}.request_ms"):
                        return self._server.handle_request(request)
        finally:
            _TENANT.reset(tenant_token)
            _DEADLINE.reset(token)

    def handle_wire(self, data: bytes) -> bytes:
        """Framed proto entry point (plugs into `FramedTcpServer`).

        An incoming trace-context envelope (a new-version Leader's
        helper leg) is unwrapped here: the inner proto serves under the
        propagated trace id and the reply wraps back with this side's
        stage spans. A bare proto (old-version peer, or a client) is
        served and answered bare, unchanged.

        A shed request (`Overloaded`) from an *enveloped* peer answers
        with a typed kind-3 error envelope carrying the `retry_after_s`
        hint; a bare-proto peer sees the exception propagate to the
        transport exactly as before (old peers could not parse the
        envelope anyway).

        The reply always uses the *request's* envelope version, so a v1
        Leader never sees v2 fields. A v2 request gets the critical-path
        digest piggybacked on the reply: this side's phase waterfall
        plus the perf_counter-domain receive/send timestamps the Leader
        needs for NTP-style skew estimation. A v3 request gets the
        snapshot generation this side's share was evaluated against
        echoed in the reply meta — the Leader's cross-generation check
        depends on that echo being the *evaluated* generation, not
        whatever is serving by reply time.
        """
        from ..protos import private_information_retrieval_pb2 as pir_pb2

        recv_ms = time.perf_counter() * 1e3
        trace_id, inner, req_version, req_generation = (
            propagation.try_decode_request_ext(data)
        )
        resp_version = min(req_version, propagation.PROPAGATION_VERSION)
        # Fresh per request: a stale generation from a previous request
        # on this thread must never be echoed as this one's.
        _EVAL_GENERATION.set(None)
        t0 = time.perf_counter()
        with tracing.trace_request(
            f"{self._name}.request",
            trace_id=trace_id,
            fresh=trace_id is not None,
            role=self._name,
        ) as trace:
            # fresh at the RPC boundary for the same reason as the
            # trace: an in-process transport runs this on the Leader's
            # thread, and the Helper's phases must not merge into the
            # Leader's record.
            with phases_mod.default_phase_recorder().request(
                self._name, fresh=trace_id is not None
            ):
                with tracing.span("decode"), phases_mod.phase("respond"):
                    proto = pir_pb2.PirRequest.FromString(inner)
                    request = serialization.pir_request_from_proto(
                        self._server.dpf, proto
                    )
                try:
                    response = self.handle_request(request)
                except Overloaded as e:
                    if trace_id is None:
                        raise
                    self.metrics.counter(
                        f"{self._name}.wire_overloads"
                    ).inc()
                    return propagation.encode_error(
                        "overloaded",
                        message=str(e),
                        retry_after_s=getattr(e, "retry_after_s", 0.0),
                        trace_id=trace.trace_id,
                    )
                with tracing.span("encode"), phases_mod.phase("respond"):
                    out = serialization.pir_response_to_proto(
                        response
                    ).SerializeToString()
            if trace_id is None:
                return out
            # The generation this request's share actually evaluated
            # against (stamped at the batch boundary); falls back to
            # the serving generation for unbatched sessions.
            served_generation = _EVAL_GENERATION.get()
            if served_generation is None and self.snapshots is not None:
                served_generation = self.snapshots.serving_generation()
            if (
                req_generation is not None
                and served_generation is not None
                and req_generation != served_generation
            ):
                # The peer believed a different generation was current
                # when it sent. Harmless here — the Leader's echo check
                # is the enforcement point — but worth a (coalesced)
                # line on the timeline while the rotation window is
                # open.
                self._emit(
                    "snapshot.mismatch",
                    f"request bound generation {req_generation}, "
                    f"evaluated against {served_generation}",
                    severity="warning",
                    party=self._name,
                    request_generation=req_generation,
                    served_generation=served_generation,
                    coalesce_key=(
                        f"snapshot.skew:{self._name}:"
                        f"{req_generation}:{served_generation}"
                    ),
                    coalesce_s=1.0,
                )
            # The phases context has closed: trace.attrs["phases"] is
            # this request's final waterfall (the v2 digest).
            return propagation.encode_response(
                out,
                trace.trace_id,
                server_ms=(time.perf_counter() - t0) * 1e3,
                spans=trace.span_list(),
                version=resp_version,
                phases=trace.attrs.get("phases"),
                recv_ms=recv_ms,
                send_ms=time.perf_counter() * 1e3,
                generation=served_generation,
            )

    def close(self) -> None:
        if self._batcher is not None:
            self._server.set_plain_handler(None)
            self._batcher.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class PlainSession(_Session):
    """Single-server (trusted) serving: plain requests, batched.

    `server=` swaps in a pre-built plain-role server (the sparse
    sessions in `serving/sparse.py` reuse every session mechanic this
    way); the default builds a dense server from `database`."""

    def __init__(
        self,
        database: Optional[DenseDpfPirDatabase] = None,
        config: Optional[ServingConfig] = None,
        metrics: Optional[MetricsRegistry] = None,
        mesh=None,
        server=None,
    ):
        if server is None:
            server = DenseDpfPirServer.create_plain(database, mesh=mesh)
        super().__init__(server, config, metrics, "plain")


class HelperSession(_Session):
    """The Helper role: decrypts its leg, evaluates (batched), masks."""

    def __init__(
        self,
        database: Optional[DenseDpfPirDatabase] = None,
        decrypter=None,
        config: Optional[ServingConfig] = None,
        metrics: Optional[MetricsRegistry] = None,
        mesh=None,
        server=None,
    ):
        if server is None:
            server = DenseDpfPirServer.create_helper(
                database, decrypter, mesh=mesh
            )
        super().__init__(server, config, metrics, "helper")


class LeaderSession(_Session):
    """The Leader role: forwards the encrypted Helper leg over an
    injected `Transport` with timeout/retry/backoff, computes its own
    share while waiting, and XOR-combines the masked responses.

    `server=` swaps in a pre-built leader-role server; build it around
    this session's `self._send_to_helper` bound method (subclasses set
    `self._transport` first, then construct the server — see
    `serving/sparse.py:SparseLeaderSession`)."""

    def __init__(
        self,
        database: Optional[DenseDpfPirDatabase] = None,
        helper_transport: Optional[Transport] = None,
        config: Optional[ServingConfig] = None,
        metrics: Optional[MetricsRegistry] = None,
        mesh=None,
        server=None,
    ):
        self._transport = helper_transport
        if server is None:
            server = DenseDpfPirServer.create_leader(
                database, self._send_to_helper, mesh=mesh
            )
        super().__init__(server, config, metrics, "leader")
        m = self.metrics
        self._c_retries = m.counter("leader.helper_retries")
        self._c_timeouts = m.counter("leader.helper_timeouts")
        self._c_failures = m.counter("leader.helper_failures")
        self._c_degraded = m.counter("leader.degraded_responses")
        self._c_downgrades = m.counter("leader.wire_downgrades")
        self._c_mismatches = m.counter("leader.snapshot_mismatches")
        self._c_snapshot_retries = m.counter("leader.snapshot_retries")
        # None = envelope support unknown (probe with an envelope);
        # False = peer rejected it once (bare proto from then on);
        # True = peer answered an envelope.
        self._peer_envelope: Optional[bool] = None
        # Envelope version ladder: probe at v3 (generation handshake +
        # critical-path digest), step one version down per non-timeout
        # fault — v3 -> v2 (losing only the generation echo; checking
        # goes disabled-but-journaled) -> v1 (losing the digest) ->
        # bare proto. Each step is sticky, retry-neutral, and counted
        # once in leader.wire_downgrades, so an old Helper costs
        # exactly (3 - its version) probes.
        self._peer_wire_version = (
            propagation.PROPAGATION_VERSION
            if self._config.helper_digest else 1
        )
        # Critical-path analysis rides the phase recorder's close hook;
        # install is idempotent and binds critical.* to this registry.
        critical_path.install(registry=m)
        # Degraded mode is now *state*, not just a per-response counter:
        # entered when a request falls back to its Leader-only share,
        # exited the moment the breaker's half-open probe closes it.
        self._degraded = False
        self._g_degraded = m.gauge("leader.degraded_mode")
        self._c_degraded_exits = m.counter("leader.degraded_exits")
        self._g_breaker = m.gauge("leader.breaker_state")
        self._c_breaker_opens = m.counter("leader.breaker_opens")
        self._c_fast_fails = m.counter("leader.breaker_fast_fails")
        self._c_helper_overloaded = m.counter("leader.helper_overloaded")
        # Retry budget: bounds the retry:success ratio so an overloaded
        # (slow-but-alive) Helper is not hammered with amplified load.
        self._c_budget_exhausted = m.counter(
            "leader.retries_budget_exhausted"
        )
        self._retry_lock = threading.Lock()
        self._retry_tokens = float(self._config.helper_retry_budget_min)
        self._g_retry_tokens = m.gauge("leader.retry_budget_tokens")
        self._g_retry_tokens.set(self._retry_tokens)
        self._breaker: Optional[CircuitBreaker] = None
        if self._config.breaker_enabled:
            self._breaker = CircuitBreaker(
                failure_threshold=self._config.breaker_failure_threshold,
                reset_timeout_ms=self._config.breaker_reset_ms,
                name="leader.helper",
            )
            self._breaker.on_transition(self._on_breaker_transition)

    @property
    def degraded(self) -> bool:
        """Whether the session is currently answering Leader-share-only
        responses (recoverable: a successful half-open probe exits)."""
        return self._degraded

    @property
    def breaker(self) -> Optional[CircuitBreaker]:
        return self._breaker

    def breaker_export(self) -> Optional[dict]:
        """The /statusz row for this session's helper-leg breaker."""
        if self._breaker is None:
            return None
        out = self._breaker.export()
        out["degraded_mode"] = self._degraded
        return out

    def _on_breaker_transition(self, old: str, new: str) -> None:
        self._g_breaker.set(float(self._breaker.state_code()))
        self._emit(
            "breaker.transition",
            f"helper-leg breaker {old} -> {new}",
            severity="error" if new == "open" else "info",
            old=old,
            new=new,
        )
        if new == "open":
            self._c_breaker_opens.inc()
        if new == "closed" and self._degraded:
            # The half-open probe proved the Helper healthy again:
            # degraded mode ends here, not at process restart.
            self._degraded = False
            self._g_degraded.set(0.0)
            self._c_degraded_exits.inc()

    # -- helper leg ---------------------------------------------------------

    def _retry_budget_take(self) -> bool:
        """Spend one retry token; False means the budget is exhausted
        and the ladder must stop retrying."""
        with self._retry_lock:
            if self._retry_tokens >= 1.0:
                self._retry_tokens -= 1.0
                self._g_retry_tokens.set(round(self._retry_tokens, 3))
                return True
            return False

    def _retry_budget_earn(self) -> None:
        """A successful leg earns back `helper_retry_budget_ratio`
        tokens, capped at the starting balance — the cap is what bounds
        the long-run retry:success ratio."""
        cfg = self._config
        with self._retry_lock:
            self._retry_tokens = min(
                float(cfg.helper_retry_budget_min),
                self._retry_tokens + cfg.helper_retry_budget_ratio,
            )
            self._g_retry_tokens.set(round(self._retry_tokens, 3))

    def _send_to_helper(self, helper_request, while_waiting):
        """`ForwardHelperRequestFn` with retry: serialize, round-trip
        with a per-attempt timeout, back off and retry on transport
        faults. `while_waiting` (the Leader's own share) runs exactly
        once, overlapped with the first successful send.

        The request goes out wrapped in a trace-context envelope until
        the peer proves it is old-version: a non-timeout fault on an
        envelope probe (an old Helper fails parsing the envelope and
        drops the connection) steps the version ladder — v2 to v1
        (losing only the critical-path digest), then v1 to bare proto —
        before the normal retry policy resumes. Timeouts do NOT
        downgrade — a slow Helper is not an old one.
        """
        # Fresh per leg: the own-share evaluation below stamps the
        # generation it bound to; a stale stamp from a previous request
        # on this thread must never satisfy the echo check.
        _EVAL_GENERATION.set(None)
        breaker = self._breaker
        if breaker is not None and not breaker.allow():
            # Open breaker: fail in microseconds — no serialization, no
            # connect, no backoff. The caller's degraded path (or the
            # client's retry policy) takes over.
            self._c_fast_fails.inc()
            raise HelperUnavailable(
                "helper circuit breaker is open (fast-fail)"
            )
        wire = serialization.pir_request_to_proto(
            self._server.dpf, helper_request
        ).SerializeToString()
        cfg = self._config
        called = [False]
        # The own-share window (perf_counter ms): the skew estimator
        # subtracts whatever part of it ran serially inside the
        # round-trip bracket, so own-share compute is never booked as
        # wire time (the in-process transport runs it inline).
        share_window = [None]
        # The generation the own share bound to, captured the moment
        # the share returns. It must NOT be read from _EVAL_GENERATION
        # after the round-trip: an in-process Helper runs handle_wire
        # on this same thread/context and would overwrite it with the
        # HELPER's generation — turning the mismatch check into
        # helper-vs-helper, which can never fire.
        own_gen_box = [None]

        def leader_share_once():
            if not called[0]:
                called[0] = True
                s0 = time.perf_counter()
                try:
                    with tracing.span("leader_own_share"):
                        while_waiting()
                finally:
                    share_window[0] = (s0 * 1e3,
                                       time.perf_counter() * 1e3)
                    own_gen_box[0] = _EVAL_GENERATION.get()

        timeout = (
            None if cfg.helper_timeout_ms is None
            else cfg.helper_timeout_ms / 1e3
        )
        backoff = cfg.helper_backoff_ms / 1e3
        trace = tracing.current_trace()
        last: Optional[Exception] = None
        attempt = 0
        while attempt <= cfg.helper_retries:
            enveloped = self._peer_envelope is not False
            payload = (
                propagation.encode_request(
                    trace.trace_id if trace is not None
                    else tracing.new_trace_id(),
                    wire,
                    version=self._peer_wire_version,
                    # Advisory: the serving generation at send time
                    # (the own share has not evaluated yet — it runs
                    # overlapped with this round-trip). The Helper
                    # journals skew against it; the authoritative
                    # check below compares echo vs. own-share binding.
                    generation=(
                        self.snapshots.serving_generation()
                        if self.snapshots is not None else None
                    ),
                )
                if enveloped
                else wire
            )
            try:
                # Chaos site: an injected fault here exercises the
                # retry ladder and the breaker exactly like a helper
                # timeout would.
                failpoints.fire("service.helper_leg", error=TransportTimeout)
                t0 = time.perf_counter()
                with self.metrics.timed("leader.helper_leg_ms"):
                    data = self._transport.roundtrip(
                        payload, timeout=timeout,
                        on_sent=leader_share_once,
                    )
                rtt_ms = (time.perf_counter() - t0) * 1e3
                if breaker is not None:
                    breaker.record_success()
                self._retry_budget_earn()
                break
            except Exception as e:  # noqa: BLE001 - triaged below
                is_transport = isinstance(e, TransportError)
                if (
                    enveloped
                    and self._peer_envelope is None
                    and not isinstance(e, TransportTimeout)
                ):
                    # Probe fault: plausibly an old peer choking on the
                    # envelope. Step ONE version down the ladder — v3
                    # to v2 (losing the generation echo; checking goes
                    # disabled-but-journaled), v2 to v1 (losing the
                    # digest), then v1 to bare proto — and re-send
                    # immediately. No step consumes a retry attempt
                    # (each is sticky, so the ladder runs at most
                    # PROPAGATION_VERSION times per transport) or feeds
                    # the breaker: a version mismatch is not a dead
                    # Helper.
                    if self._peer_wire_version > 1:
                        self._peer_wire_version -= 1
                    else:
                        self._peer_envelope = False
                    self._c_downgrades.inc()
                    last = e
                    continue
                if not is_transport:
                    raise
                if breaker is not None:
                    breaker.record_failure()
                last = e
                if isinstance(e, TransportTimeout):
                    self._c_timeouts.inc()
                if attempt >= cfg.helper_retries:
                    self._c_failures.inc()
                    raise HelperUnavailable(
                        f"helper leg failed after {attempt + 1} "
                        f"attempt(s): {e}"
                    ) from e
                if not self._retry_budget_take():
                    # The fleet-level retry:success ratio is spent:
                    # retrying now would amplify the very overload
                    # that is making the Helper slow. Fail fast and
                    # let the client's backoff spread the load.
                    self._c_budget_exhausted.inc()
                    self._c_failures.inc()
                    raise HelperUnavailable(
                        f"helper retry budget exhausted after "
                        f"{attempt + 1} attempt(s): {e}"
                    ) from e
                self._c_retries.inc()
                time.sleep(min(backoff, cfg.helper_backoff_max_ms / 1e3))
                backoff *= 2
                attempt += 1
        else:
            self._c_failures.inc()
            raise HelperUnavailable(
                f"helper leg failed after {attempt} attempt(s): {last}"
            ) from last
        # A misbehaving-but-fast helper could answer before the share ran.
        leader_share_once()
        # Out-of-band attribution: the helper leg's RTT overlaps the
        # Leader's own-share compute (by design), so the waterfall's
        # helper_rtt phase can exceed end-to-end minus device_compute.
        phases_mod.record("helper_rtt", rtt_ms)
        # Utilization: only the RTT tail NOT hidden behind the
        # own-share compute is a real barrier — the Leader sat idle
        # from the share's end to the round-trip's return.
        if getattr(self, "_util", None) is not None:
            exposed_ms = (
                rtt_ms if share_window[0] is None
                else max(0.0, (t0 * 1e3 + rtt_ms) - share_window[0][1])
            )
            try:
                self._util.record_idle(
                    "helper_rtt", exposed_ms / 1e3, thread="leader"
                )
            except Exception:  # noqa: BLE001 - accounting never breaks serving
                pass
        try:
            meta, inner = (
                propagation.try_decode_response(data)
                if enveloped
                else (None, data)
            )
        except propagation.WireErrorResponse as e:
            # A typed refusal is a live, envelope-speaking peer (the
            # breaker already recorded the round-trip as a success) —
            # surface it as Overloaded with the peer's backoff hint
            # rather than burning retries against a shedding Helper.
            self._peer_envelope = True
            if e.error_type == "overloaded":
                self._c_helper_overloaded.inc()
                raise Overloaded(
                    f"helper shed the request: {e}",
                    retry_after_s=e.retry_after_s,
                    reason="helper_overloaded",
                ) from e
            raise
        if enveloped:
            self._peer_envelope = meta is not None
        if self.snapshots is not None:
            # The generation handshake. own_generation is what this
            # Leader's share actually evaluated against (stamped at
            # the batch boundary by _batched_plain_handler, captured
            # at share return — see own_gen_box above);
            # helper_generation is the Helper's echo of the same for
            # its share. Disagreement means the XOR would be
            # well-formed garbage — refuse typed, never combine.
            own_generation = own_gen_box[0]
            helper_generation = (
                meta.get("generation") if meta is not None else None
            )
            if helper_generation is None:
                if own_generation is not None:
                    # Pre-v3 peer (or a Helper without rotation
                    # machinery): checking is disabled for this peer,
                    # journaled so the gap is visible.
                    self.snapshots.note_unchecked(
                        self._peer_wire_version
                        if self._peer_envelope else 0
                    )
            elif (
                own_generation is not None
                and own_generation != helper_generation
            ):
                self._c_mismatches.inc()
                self.snapshots.record_mismatch(
                    own_generation,
                    helper_generation,
                    trace_id=(
                        trace.trace_id if trace is not None else None
                    ),
                )
                raise SnapshotMismatch(own_generation, helper_generation)
        if meta is not None:
            # Decompose the helper leg: the Helper reports its own
            # server time, the rest of the RTT is the network (plus
            # framing) — and the Helper's stage spans graft on under a
            # `helper.` prefix.
            remote_ms = float(meta.get("server_ms", 0.0))
            network_ms = max(0.0, rtt_ms - remote_ms)
            self.metrics.histogram("leader.helper_remote_ms").observe(
                remote_ms
            )
            self.metrics.histogram("leader.helper_network_ms").observe(
                network_ms
            )
            # v2 digest: NTP-style skew estimate from this exchange's
            # four timestamps, then the helper_net / helper_queue /
            # helper_compute split. The own-share window is subtracted
            # from the exchange rtt where it overlapped the bracket.
            skew = None
            decomp = None
            t0_ms, t3_ms = t0 * 1e3, t0 * 1e3 + rtt_ms
            if meta.get("recv_ms") is not None and (
                meta.get("send_ms") is not None
            ):
                win = share_window[0]
                overlap_ms = (
                    max(0.0, min(win[1], t3_ms) - max(win[0], t0_ms))
                    if win is not None else 0.0
                )
                skew = critical_path.estimate_skew(
                    t0_ms, t3_ms,
                    float(meta["recv_ms"]), float(meta["send_ms"]),
                    overlap_ms=overlap_ms,
                )
                decomp = critical_path.decompose_helper_leg(
                    skew, meta.get("phases")
                )
                if decomp is not None:
                    phases_mod.record(
                        "helper_net", decomp["helper_net_ms"]
                    )
                    phases_mod.record(
                        "helper_queue", decomp["helper_queue_ms"]
                    )
                    phases_mod.record(
                        "helper_compute", decomp["helper_compute_ms"]
                    )
                req = phases_mod.current_request()
                if req is not None:
                    req.set_meta("helper_leg", {
                        "rtt_ms": rtt_ms,
                        "own_ms": (
                            win[1] - win[0] if win is not None else 0.0
                        ),
                        "skew": skew.as_dict(),
                        "decomp": decomp,
                        "helper_phases": meta.get("phases") or {},
                    })
            if trace is not None:
                extra = {}
                if skew is not None:
                    extra["offset_ms_est"] = round(skew.offset_ms, 3)
                    extra["offset_uncertainty_ms"] = round(
                        skew.uncertainty_ms, 3
                    )
                trace.add_span(
                    "helper_leg", rtt_ms, remote_ms=round(remote_ms, 3),
                    network_ms=round(network_ms, 3), **extra,
                )
                # With a skew estimate, remote spans land at their
                # corrected position on THIS trace's timeline: the
                # Helper's recv_ms maps into the Leader clock via the
                # offset, then rebases against the trace start.
                base_offset_ms = None
                if skew is not None:
                    trace_start_ms = (
                        time.perf_counter() * 1e3 - trace.elapsed_ms()
                    )
                    base_offset_ms = (
                        float(meta["recv_ms"]) - skew.offset_ms
                        - trace_start_ms
                    )
                trace.add_remote_spans(
                    meta.get("spans", []), prefix="helper.",
                    base_offset_ms=base_offset_ms,
                )
                trace.add_span("helper_network", network_ms)
        elif trace is not None:
            trace.add_span("helper_leg", rtt_ms)
        from ..protos import private_information_retrieval_pb2 as pir_pb2

        with tracing.span("decode"):
            return serialization.pir_response_from_proto(
                pir_pb2.PirResponse.FromString(inner)
            )

    # -- degradation --------------------------------------------------------

    def handle_request(self, request, deadline=None):
        if deadline is None:
            deadline = self._default_deadline()
        retries = max(0, self._config.snapshot_retries)
        attempt = 0
        while True:
            try:
                return self._handle_request_once(request, deadline)
            except SnapshotMismatch:
                # Typed cross-generation refusal from the handshake:
                # retry the WHOLE request — the own share re-evaluates
                # (binding to the post-flip generation once the
                # pending flip lands at a batch boundary) and the
                # Helper leg re-runs. Bounded: the coordinator flips
                # Helper-first/Leader-last, so the window closes as
                # soon as this party's flip applies.
                if attempt >= retries:
                    raise
                attempt += 1
                self._c_snapshot_retries.inc()
                # A breath per attempt: the flip this retry is waiting
                # on applies on the batcher worker, not this thread.
                time.sleep(0.002 * attempt)

    def _handle_request_once(self, request, deadline):
        try:
            return super().handle_request(request, deadline)
        except HelperUnavailable:
            if not (
                self._config.allow_degraded
                and request.leader_request is not None
            ):
                raise
            # Operator-sanctioned degraded mode: answer with the
            # Leader's own share only. The client cannot unmask a real
            # record from this (the Helper's share is missing) — it is a
            # liveness signal telling clients to fall back to plain
            # queries — but the session stays up and keeps its batcher,
            # metrics, and deadlines exercised. The mode is recoverable:
            # the breaker's half-open probe closing it flips
            # `self._degraded` back off (see _on_breaker_transition).
            self._c_degraded.inc()
            if not self._degraded:
                self._degraded = True
                self._g_degraded.set(1.0)
                self._emit(
                    "service.degraded",
                    "helper unavailable; serving leader-share-only",
                    severity="error",
                )
            token = _DEADLINE.set(deadline)
            try:
                return self._server._dispatch_plain(
                    messages.PirRequest(
                        plain_request=request.leader_request.plain_request
                    )
                )
            finally:
                _DEADLINE.reset(token)

    def close(self):
        super().close()
        self._transport.close()
