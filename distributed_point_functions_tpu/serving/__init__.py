"""Production Leader/Helper serving runtime.

Promotes the two-server deployment model from a demo script into a
subsystem: dynamic shape-bucketed batching (`batcher`), session objects
with deadlines, Helper retry, and degradation (`service`), reusable
framed transports (`transport`), and a dependency-free metrics registry
(`metrics`). Cost-aware admission, per-tenant QoS, and the brownout
ladder plug in from `capacity/` (enable with
`ServingConfig.admission_enabled`; see `_Session.attach_brownout`).
Layering: serving -> pir -> capacity -> ops -> observability, never
the reverse (enforced by `tools/check_layers.py` in presubmit).

Observability rides along everywhere: sessions root a trace per
request, the batcher and the role handlers mark stage spans, and the
`observability.AdminServer` serves the registry + flight recorder over
HTTP (`/metrics`, `/varz`, `/tracez`, `/healthz`, `/profilez`).
"""

from .batcher import (
    DeadlineExceeded,
    DynamicBatcher,
    Overloaded,
    bucket_size,
)
from .metrics import Counter, Gauge, Histogram, MetricsRegistry, labeled_name
from .service import (
    HelperSession,
    HelperUnavailable,
    LeaderSession,
    PlainSession,
    ServingConfig,
    TenantPolicy,
)
from .snapshots import (
    RotationCoordinator,
    SnapshotManager,
    SnapshotMismatch,
)
from .sparse import (
    SparseHelperSession,
    SparseLeaderSession,
    SparsePlainSession,
    make_sparse_client,
    sparse_lookup,
    sparse_lookup_plain,
)
from .transport import (
    FramedTcpServer,
    InProcessTransport,
    TcpTransport,
    Transport,
    TransportError,
    TransportTimeout,
    parse_hostport,
    recv_msg,
    send_msg,
)

__all__ = [
    "Counter",
    "DeadlineExceeded",
    "DynamicBatcher",
    "FramedTcpServer",
    "Gauge",
    "HelperSession",
    "HelperUnavailable",
    "Histogram",
    "InProcessTransport",
    "LeaderSession",
    "MetricsRegistry",
    "Overloaded",
    "PlainSession",
    "RotationCoordinator",
    "ServingConfig",
    "SnapshotManager",
    "SnapshotMismatch",
    "SparseHelperSession",
    "SparseLeaderSession",
    "SparsePlainSession",
    "TcpTransport",
    "TenantPolicy",
    "Transport",
    "TransportError",
    "TransportTimeout",
    "bucket_size",
    "labeled_name",
    "make_sparse_client",
    "parse_hostport",
    "recv_msg",
    "send_msg",
    "sparse_lookup",
    "sparse_lookup_plain",
]
