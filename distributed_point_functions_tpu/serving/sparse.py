"""Sparse key-value PIR serving: cuckoo-hashed retrieval at
production parity.

String-keyed lookups ride the exact dense serving stack — the
DynamicBatcher coalesces concurrent queries into padded power-of-two
key buckets, the Leader/Helper sessions keep their wire envelopes,
retry ladder, breaker, and generation handshake — because a sparse
query *is* a dense request over the cuckoo bucket space
(`pir/sparse_server.py`): each DPF key selects one bucket of the
`1.5×n`-bucket table and the server answers with **two** masked
responses per key, the bucket's key and its value, from the two
parallel dense stores.

The only seam is the per-key result shape. The batcher's contract is
one result per submitted key; the dense sessions return one masked
response per key, the sparse server returns two. The
`_SparseEvaluationMixin` below adapts at exactly that seam: the
evaluation function groups the interleaved (key, value) responses into
one tuple per DPF key (so batcher coalescing, padding, pipelining, and
generation binding all apply unchanged), and the plain handler
re-flattens them to the reference's interleaved wire order. Everything
else — deadlines, tenants, admission, brownout, snapshots, the wire-v3
generation echo — is inherited, not reimplemented.

Resolution is client-side (`pir/sparse_client.py`): each queried
string hashes to `num_hash_functions` candidate buckets; the value
whose returned key plaintext equals the query (zero-padded prefix
check) wins, and a query matching no candidate resolves to the typed
`KeyNotFound` — never a wrong value.

Writes are snapshot rotations: build generation N+1 with
`CuckooHashedDpfPirDatabase.Builder.build_from(prev)` (upsert; touched
buckets only), `SnapshotManager.stage()` prestages just those bucket
rows against the resident stagings (`bytes_saved > 0`), and the
batch-boundary flip applies unchanged.

Capacity treats sparse traffic as its own workload: admission prices
`price_sparse_pir_keys` (two dense inner products per key) and the
cost-accuracy ledger joins terminal batches under "sparse", so dense
recalibration never skews sparse admission (or vice versa).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..capacity.model import default_capacity_model
from ..pir import messages
from ..pir.cuckoo_database import (
    CuckooHashedDpfPirDatabase,
    CuckooHashingParams,
)
from ..pir.sparse_client import (
    CuckooHashingSparseDpfPirClient,
    KeyNotFound,
    _is_prefix_padded_with_zeros,
)
from ..pir.sparse_server import CuckooHashingSparseDpfPirServer
from ..prng import xor_bytes
from .metrics import MetricsRegistry
from .service import (
    _DEADLINE,
    _EVAL_GENERATION,
    _TENANT,
    HelperSession,
    LeaderSession,
    PlainSession,
    ServingConfig,
)
from .transport import Transport


class _SparseEvaluationMixin:
    """Adapts the dense session mechanics to the sparse server's
    two-responses-per-key shape (see module docstring)."""

    def _sparse_init(self) -> None:
        """Post-`_Session.__init__` wiring: price sparse work as its
        own workload, for both the admission controller (charge two
        inner products per key before enqueueing) and the terminal
        batch cost join (ledger cells under "sparse")."""
        model = default_capacity_model()
        num_blocks = self._server.database.num_selection_blocks

        def pricer(num_keys):
            return model.price_sparse_pir_keys(num_keys, num_blocks)

        if self._batcher is not None:
            self._batcher.set_cost_model("sparse", pricer)
        if self.admission is not None:
            self.admission.set_pricer(pricer)

    def _evaluate_keys(self, keys):
        """One real device step for the coalesced bucket-space key
        batch; returns one `(key_bytes, value_bytes)` tuple per DPF key
        — the batcher's one-result-per-key contract (padding duplicates
        a real key, so its pair is well-formed and discarded)."""
        response = self._server.handle_plain_request(
            messages.PirRequest(
                plain_request=messages.PlainRequest(dpf_keys=list(keys))
            )
        )
        masked = response.dpf_pir_response.masked_response
        if len(masked) != 2 * len(keys):
            raise RuntimeError(
                f"sparse evaluation returned {len(masked)} masked "
                f"responses for {len(keys)} keys (want 2 per key)"
            )
        return [
            (masked[2 * i], masked[2 * i + 1]) for i in range(len(keys))
        ]

    def _batched_plain_handler(self, request):
        out, generation = self._batcher.submit_ex(
            request.plain_request.dpf_keys,
            deadline=_DEADLINE.get(),
            tenant=_TENANT.get(),
        )
        if generation is not None:
            # Same deliberately-unscoped publication as the dense
            # handler: the enclosing entry point (Helper echo / Leader
            # own-share binding) reads it up-stack on this context.
            _EVAL_GENERATION.set(generation)
        masked = []
        for key_bytes, value_bytes in out:
            masked.append(key_bytes)
            masked.append(value_bytes)
        return messages.PirResponse(
            dpf_pir_response=messages.DpfPirResponse(
                masked_response=masked
            )
        )


class SparsePlainSession(_SparseEvaluationMixin, PlainSession):
    """Single-server (trusted) sparse serving: bucket-space plain
    requests, batched. The private two-server deployment is
    `SparseLeaderSession` + `SparseHelperSession`."""

    def __init__(
        self,
        params: CuckooHashingParams,
        database: CuckooHashedDpfPirDatabase,
        config: Optional[ServingConfig] = None,
        metrics: Optional[MetricsRegistry] = None,
        mesh=None,
    ):
        server = CuckooHashingSparseDpfPirServer.create_plain(
            params, database, mesh=mesh
        )
        super().__init__(config=config, metrics=metrics, server=server)
        self._sparse_init()


class SparseHelperSession(_SparseEvaluationMixin, HelperSession):
    """The Helper role over a sparse database: decrypts its leg,
    evaluates the bucket-space batch, masks both response streams."""

    def __init__(
        self,
        params: CuckooHashingParams,
        database: CuckooHashedDpfPirDatabase,
        decrypter,
        config: Optional[ServingConfig] = None,
        metrics: Optional[MetricsRegistry] = None,
        mesh=None,
    ):
        server = CuckooHashingSparseDpfPirServer.create_helper(
            params, database, decrypter, mesh=mesh
        )
        super().__init__(config=config, metrics=metrics, server=server)
        self._sparse_init()


class SparseLeaderSession(_SparseEvaluationMixin, LeaderSession):
    """The Leader role over a sparse database: forwards the encrypted
    Helper leg (retry ladder, breaker, wire-v3 generation handshake —
    all inherited), computes its own two-per-key share while waiting,
    XOR-combines."""

    def __init__(
        self,
        params: CuckooHashingParams,
        database: CuckooHashedDpfPirDatabase,
        helper_transport: Transport,
        config: Optional[ServingConfig] = None,
        metrics: Optional[MetricsRegistry] = None,
        mesh=None,
    ):
        # The server needs the bound sender before LeaderSession's
        # __init__ runs (same trick LeaderSession itself uses); the
        # sender only fires at request time, after init completes.
        self._transport = helper_transport
        server = CuckooHashingSparseDpfPirServer.create_leader(
            params, database, self._send_to_helper, mesh=mesh
        )
        super().__init__(
            helper_transport=helper_transport,
            config=config,
            metrics=metrics,
            server=server,
        )
        self._sparse_init()


# -- client-side lookup helpers ---------------------------------------------


def make_sparse_client(
    session, encrypter=None
) -> CuckooHashingSparseDpfPirClient:
    """A lookup client bound to `session`'s cuckoo geometry. With no
    `encrypter` the helper leg is left plaintext — fine for
    `SparsePlainSession` and in-process tests; pass the deployment's
    HPKE encrypter for a real Leader."""
    if encrypter is None:
        encrypter = lambda pt, info: pt  # noqa: E731 - identity leg
    return CuckooHashingSparseDpfPirClient.create(
        session.server.public_params, encrypter
    )


def sparse_lookup(session, client, query: Sequence[bytes]) -> List:
    """One end-to-end key-value lookup through a combining role session
    (`SparseLeaderSession`): per queried string, the value bytes when
    present, else `KeyNotFound(key)`."""
    request, state = client.create_request(list(query))
    response = session.handle_request(request)
    return client.resolve(response, state)


def sparse_lookup_plain(session, client, query: Sequence[bytes]) -> List:
    """Two-share lookup against ONE `SparsePlainSession`: both plain
    DPF shares go through the same session over the same database, so
    the XOR of the two masked streams is the plaintext (key, value)
    candidates — the protocol identity the prober also leans on. Per
    queried string: value bytes, else `KeyNotFound(key)`."""
    qbytes = [
        q.encode() if isinstance(q, str) else bytes(q) for q in query
    ]
    r0, r1 = client.create_plain_requests(qbytes)
    a = session.handle_request(r0).dpf_pir_response.masked_response
    b = session.handle_request(r1).dpf_pir_response.masked_response
    raw = [xor_bytes(x, y) for x, y in zip(a, b)]
    num_hashes = session.server.public_params.num_hash_functions
    results: List = []
    for i, q in enumerate(qbytes):
        found = None
        for j in range(num_hashes):
            k = 2 * (num_hashes * i + j)
            if found is None and _is_prefix_padded_with_zeros(raw[k], q):
                found = raw[k + 1]
        results.append(found if found is not None else KeyNotFound(q))
    return results
