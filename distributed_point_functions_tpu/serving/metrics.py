"""Dependency-free serving metrics: counters, gauges, histograms.

The serving runtime needs observability (queue depth, batch-size
distribution, padding waste, jit-cache hits vs. recompiles, Helper
retry/timeout counts, latency percentiles) without pulling a metrics
client into the image. This registry is the stdlib answer: thread-safe
instruments keyed by name, exported as one plain dict so any caller —
the closed-loop bench, a debug endpoint, a log line — can serialize it.

Timed regions double as profiler annotations: `registry.timed(name)`
wraps the block in `utils.profiling.annotate(name)` (a named TraceAnnotation
inside an active xprof trace) *and* records the wall-clock milliseconds
into the `name` histogram, so the same instrumentation feeds both the
metrics dict and a device trace.

**Labels.** The registry is a flat namespace; instruments that vary by
role, level, or tier use the labeling convention `base{k=v,k2=v2}` —
built with `labeled_name()` or by passing `labels=` to
`counter`/`gauge`/`histogram`/`timed`. Keys are sorted, so the same
label set always maps to the same instrument. The Prometheus renderer
(`observability/exposition.py`) splits the suffix back into label
pairs, turning e.g. `request_ms{role=leader}` and
`request_ms{role=helper}` into one labeled metric family instead of
two colliding flat names.

**Exemplars.** When a histogram observation happens inside an active
request trace (`observability.tracing.current_trace()`), the bucket it
lands in remembers that observation's value and trace id (most recent
wins). The Prometheus renderer exposes them OpenMetrics-style
(`_bucket{le="50"} 12 # {trace_id="deadbeef..."} 48.2 <ts>`), so an
operator staring at a slow bucket can jump straight to the matching
flight-recorder trace on `/tracez` instead of guessing which request
put it there.
"""

from __future__ import annotations

import bisect
import collections
import contextlib
import threading
import time
from typing import Dict, Optional, Sequence

from ..observability import tracing
from ..utils.profiling import annotate

# Default latency bucket upper bounds, in milliseconds.
DEFAULT_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 10000.0,
)

# Bounded reservoir per histogram: enough samples for stable p99 at
# serving rates without unbounded growth on long-lived processes.
_RESERVOIR = 8192


def labeled_name(base: str, labels: Optional[Dict[str, object]] = None) -> str:
    """Canonical `base{k=v,k2=v2}` instrument name (keys sorted). Label
    values must not contain `,` `=` `{` `}` — they would corrupt the
    parse on exposition."""
    if not labels:
        return base
    for k, v in labels.items():
        if any(c in f"{k}{v}" for c in ",={}"):
            raise ValueError(
                f"label {k}={v!r} contains a reserved character"
            )
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{base}{{{inner}}}"


def split_labeled_name(name: str):
    """Inverse of `labeled_name`: `base{k=v,k2=v2}` -> (base, {k: v}).
    Values come back as strings (labels are stringified on the way in)."""
    if name.endswith("}") and "{" in name:
        base, _, inner = name.partition("{")
        pairs = [p.split("=", 1) for p in inner[:-1].split(",") if p]
        return base, {k: v for k, v in pairs}
    return name, {}


class Counter:
    """Monotonic counter."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Point-in-time value (queue depth, in-flight requests)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram:
    """Cumulative bucket counts plus a bounded sample reservoir.

    Buckets give the exported dict a stable, mergeable shape; the
    reservoir (most recent `_RESERVOIR` observations) gives exact
    percentiles at serving horizons.
    """

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS_MS):
        self._lock = threading.Lock()
        self._bounds = tuple(sorted(buckets))
        self._counts = [0] * (len(self._bounds) + 1)  # +inf overflow
        self._samples = collections.deque(maxlen=_RESERVOIR)
        self._count = 0
        self._sum = 0.0
        # bucket index -> (value, trace_id, unix_ts); most recent
        # traced observation per bucket (see module docstring).
        self._exemplars: Dict[int, tuple] = {}

    def observe(self, v: float) -> None:
        trace = tracing.current_trace()
        with self._lock:
            idx = bisect.bisect_left(self._bounds, v)
            self._counts[idx] += 1
            self._samples.append(v)
            self._count += 1
            self._sum += v
            if trace is not None:
                self._exemplars[idx] = (v, trace.trace_id, time.time())

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @staticmethod
    def _rank(ordered, p: float) -> Optional[float]:
        """Percentile `p` from an already-sorted sample list — the one
        shared implementation, so callers that need several percentiles
        (export) sort the reservoir exactly once."""
        if not ordered:
            return None
        i = min(len(ordered) - 1, max(0, round(p / 100 * (len(ordered) - 1))))
        return ordered[i]

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self._bounds) + 1)
            self._samples.clear()
            self._count = 0
            self._sum = 0.0
            self._exemplars.clear()

    def percentile(self, p: float) -> Optional[float]:
        """Exact percentile over the reservoir; None with no samples."""
        with self._lock:
            ordered = sorted(self._samples)
        return self._rank(ordered, p)

    def export(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            count, total = self._count, self._sum
            ordered = sorted(self._samples)
            exemplars = dict(self._exemplars)

        def pct(p):
            v = self._rank(ordered, p)
            return None if v is None else round(v, 4)

        keys = [str(b) for b in self._bounds] + ["+inf"]
        out = {
            "count": count,
            "sum": round(total, 4),
            "mean": round(total / count, 4) if count else None,
            "p50": pct(50),
            "p95": pct(95),
            "p99": pct(99),
            "max": round(ordered[-1], 4) if ordered else None,
            "buckets": {
                **{str(b): c for b, c in zip(self._bounds, counts)},
                "+inf": counts[-1],
            },
        }
        if exemplars:
            out["exemplars"] = {
                keys[idx]: {
                    "value": round(value, 4),
                    "trace_id": trace_id,
                    "ts": round(ts, 3),
                }
                for idx, (value, trace_id, ts) in sorted(exemplars.items())
            }
        return out


class MetricsRegistry:
    """Named instruments, created on first use, exported as one dict."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str, labels: Optional[Dict] = None) -> Counter:
        name = labeled_name(name, labels)
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str, labels: Optional[Dict] = None) -> Gauge:
        name = labeled_name(name, labels)
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def histogram(
        self,
        name: str,
        buckets: Sequence[float] = DEFAULT_BUCKETS_MS,
        labels: Optional[Dict] = None,
    ) -> Histogram:
        name = labeled_name(name, labels)
        with self._lock:
            return self._histograms.setdefault(name, Histogram(buckets))

    @contextlib.contextmanager
    def timed(self, name: str, labels: Optional[Dict] = None):
        """Time the block into histogram `name` (ms) inside a profiler
        annotation of the same name."""
        hist = self.histogram(name, labels=labels)
        t0 = time.perf_counter()
        with annotate(name):
            try:
                yield
            finally:
                hist.observe((time.perf_counter() - t0) * 1e3)

    def export(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {k: g.value for k, g in sorted(gauges.items())},
            "histograms": {
                k: h.export() for k, h in sorted(histograms.items())
            },
        }

    def snapshot(self) -> dict:
        """`export()` under a name that pairs with `reset()`: tests and
        benchmarks take a snapshot of exactly the activity since the
        last reset, instead of a since-process-start aggregate."""
        return self.export()

    def histogram_counts(self, suffix: str) -> Dict[str, int]:
        """Observation counts for histograms whose base name (labels
        stripped) ends with `suffix`. O(matching histograms) with no
        reservoir sort — the fleet QPS derivation polls this on every
        telemetry sample, where a full `export()` would sort every
        reservoir just to read one integer."""
        with self._lock:
            items = list(self._histograms.items())
        return {
            name: h.count
            for name, h in items
            if name.split("{", 1)[0].endswith(suffix)
        }

    def scoped(self, labels: Dict[str, object]) -> "ScopedRegistry":
        """Cheap child registry: every instrument created through it
        carries `labels` (e.g. `{"replica": "r0"}`) merged into the
        call-site labels, and its `export`/`snapshot`/`reset` see only
        its own slice of the parent namespace. The parent keeps the
        single flat store, so `default_telemetry().bind_registry(parent)`
        mirrors and `parent.reset()` keep their existing semantics."""
        return ScopedRegistry(self, labels)

    def reset(self) -> None:
        """Zero every instrument IN PLACE so metric state cannot leak
        across test cases or bench repetitions sharing one registry.
        Instruments stay registered and long-lived holders (the
        batcher's counters, the sampler) keep writing to the same live
        objects — no orphans, no re-fetch after a reset. Counters drop
        to 0, gauges to 0.0, histograms to empty (bucket counts,
        reservoir, exemplars)."""
        with self._lock:
            instruments = (
                list(self._counters.values())
                + list(self._gauges.values())
                + list(self._histograms.values())
            )
        for instrument in instruments:
            instrument._reset()


class ScopedRegistry:
    """Label-scoped view over a parent `MetricsRegistry`.

    Construction is O(len(labels)) and allocates no instrument storage:
    the parent owns every Counter/Gauge/Histogram, this view only merges
    its scope labels into each lookup. That makes one process hosting N
    replicas cheap — N views over one registry — while `reset()` and
    `snapshot()` on a view touch only instruments whose name carries
    all of the view's labels, so one replica's bench reset cannot zero
    its neighbors (the `reset()`/`snapshot()` interplay that a shared
    flat registry used to get wrong). Nested `scoped()` composes by
    merging label dicts (child wins on key conflicts).
    """

    def __init__(self, parent: MetricsRegistry, labels: Dict[str, object]):
        if not labels:
            raise ValueError("a scoped registry needs at least one label")
        labeled_name("scope", labels)  # validate reserved characters now
        self._parent = parent
        self._labels = {str(k): str(v) for k, v in labels.items()}

    @property
    def parent(self) -> MetricsRegistry:
        return self._parent

    @property
    def labels(self) -> Dict[str, str]:
        return dict(self._labels)

    def _merged(self, labels: Optional[Dict]) -> Dict:
        if not labels:
            return dict(self._labels)
        merged = dict(self._labels)
        merged.update(labels)
        return merged

    def counter(self, name: str, labels: Optional[Dict] = None) -> Counter:
        return self._parent.counter(name, self._merged(labels))

    def gauge(self, name: str, labels: Optional[Dict] = None) -> Gauge:
        return self._parent.gauge(name, self._merged(labels))

    def histogram(
        self,
        name: str,
        buckets: Sequence[float] = DEFAULT_BUCKETS_MS,
        labels: Optional[Dict] = None,
    ) -> Histogram:
        return self._parent.histogram(name, buckets, self._merged(labels))

    def timed(self, name: str, labels: Optional[Dict] = None):
        return self._parent.timed(name, self._merged(labels))

    def scoped(self, labels: Dict[str, object]) -> "ScopedRegistry":
        return ScopedRegistry(self._parent, self._merged(labels))

    def owns(self, name: str) -> bool:
        """True when instrument `name` carries every scope label."""
        _, labels = split_labeled_name(name)
        return all(labels.get(k) == v for k, v in self._labels.items())

    def export(self) -> dict:
        full = self._parent.export()
        return {
            kind: {k: v for k, v in section.items() if self.owns(k)}
            for kind, section in full.items()
        }

    def snapshot(self) -> dict:
        return self.export()

    def reset(self) -> None:
        """Zero only this view's slice of the parent (in place, same
        live-object guarantee as `MetricsRegistry.reset`)."""
        with self._parent._lock:
            instruments = [
                obj
                for section in (
                    self._parent._counters,
                    self._parent._gauges,
                    self._parent._histograms,
                )
                for name, obj in section.items()
                if self.owns(name)
            ]
        for instrument in instruments:
            instrument._reset()
