"""Reusable framed transports for the Leader/Helper deployment.

The reference keeps the Leader->Helper leg behind an injected callback
(`pir/dpf_pir_server.h:92-109`: transport-agnostic, no RPC stack
in-repo) and its tests play the network with in-process lambdas. The
demo script grew a real TCP framing on top of that seam; this module is
that framing extracted into library classes:

* `send_msg` / `recv_msg` — 4-byte big-endian length-prefixed messages,
  the demo's wire format unchanged (any proto message rides inside).
* `InProcessTransport` — the reference's "lambda as the network",
  conforming to the same `Transport` surface so protocol tests and the
  serving sessions are transport-blind.
* `TcpTransport` — a client connection with reuse across round trips,
  per-call timeouts, and one transparent reconnect when a pooled
  connection has gone stale (helper restarted between requests). The
  whole call — both legs AND the reconnect+resend — runs against one
  absolute deadline derived from `timeout`, so a caller's budget is
  never overshot by a retry.
* `FramedTcpServer` — the serving side: a threading TCP server that
  feeds each framed request to a `handler(bytes) -> bytes` and writes
  the framed response back on the same connection.

Errors normalize to `TransportError` (connectivity) and its subclass
`TransportTimeout` (deadline on one leg) so retry policy in
`serving/service.py` can tell a slow Helper from a dead one.

Fault-injection sites (`robustness/failpoints.py`; inert unless armed):
`transport.tcp.connect`, `transport.tcp.send`, `transport.tcp.recv`,
`transport.inproc.roundtrip` raise transport faults; the frame-level
`transport.request` / `transport.response` mutate sites corrupt or
truncate payloads on BOTH transports — the chaos harness uses them to
prove a flipped byte surfaces as a protocol error, never a wrong
decoded share.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import struct
import threading
import time
from typing import Callable, Optional

from ..robustness import failpoints

logger = logging.getLogger(__name__)

# Hard cap on a framed message; matches the demo's sanity bound.
MAX_MESSAGE_BYTES = 1 << 30


class TransportError(ConnectionError):
    """The peer is unreachable or the connection broke mid-message."""


class TransportTimeout(TransportError):
    """One send/receive leg exceeded its deadline (peer may be alive)."""


# ---------------------------------------------------------------------------
# Framing: 4-byte big-endian length prefix per message.
# ---------------------------------------------------------------------------


def send_msg(sock: socket.socket, data: bytes) -> None:
    if len(data) > MAX_MESSAGE_BYTES:
        raise ValueError(f"message too large ({len(data)} bytes)")
    sock.sendall(struct.pack(">I", len(data)) + data)


def recv_msg(sock: socket.socket) -> bytes:
    header = _recv_exact(sock, 4)
    (length,) = struct.unpack(">I", header)
    if length > MAX_MESSAGE_BYTES:
        raise TransportError(f"unreasonable message length {length}")
    return _recv_exact(sock, length)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("peer closed the connection")
        buf += chunk
    return buf


def parse_hostport(s: str, default_host: str = "localhost") -> tuple:
    host, _, port = s.rpartition(":")
    return host or default_host, int(port)


# ---------------------------------------------------------------------------
# Client-side transports
# ---------------------------------------------------------------------------


class Transport:
    """One request/response exchange with a peer.

    `on_sent`, when given, fires after the request has been handed to the
    peer and before the response is awaited — the hook the Leader role
    uses to compute its own share while the Helper works
    (`dpf_pir_server.cc:108-110`). It may fire more than once if a send
    is transparently retried, so callbacks must be idempotent.
    """

    def roundtrip(
        self,
        payload: bytes,
        timeout: Optional[float] = None,
        on_sent: Optional[Callable[[], None]] = None,
    ) -> bytes:
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - trivial default
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class InProcessTransport(Transport):
    """The reference's in-process lambda network as a `Transport`."""

    def __init__(self, handler: Callable[[bytes], bytes]):
        if handler is None:
            raise ValueError("handler must not be None")
        self._handler = handler

    def roundtrip(self, payload, timeout=None, on_sent=None):
        failpoints.fire("transport.inproc.roundtrip", error=TransportError)
        payload = failpoints.mutate("transport.request", payload)
        if on_sent is not None:
            on_sent()
        return failpoints.mutate("transport.response", self._handler(payload))


class TcpTransport(Transport):
    """Framed TCP client with connection reuse and reconnect.

    The socket persists across `roundtrip` calls (the demo paid a fresh
    TCP handshake per helper leg). A timeout on either leg surfaces as
    `TransportTimeout` and drops the connection — the response to a
    timed-out request must never be read as the answer to a later one.
    A stale pooled connection (peer restarted) gets one transparent
    reconnect+resend; a fresh connection failing is the peer's problem
    and raises immediately. The reconnect+resend runs inside the SAME
    per-call deadline as the original attempt (an absolute deadline is
    taken at entry and every leg — including the reconnect's TCP
    handshake — gets only the remaining budget), so a caller asking
    for `timeout` seconds never waits longer than that.

    `metrics`, when given (duck-typed: anything with `counter(name)`),
    counts transparent reconnects in `transport.reconnects` alongside
    the instance's `reconnects` attribute.
    """

    def __init__(
        self,
        host: str,
        port: int,
        connect_timeout: float = 5.0,
        metrics=None,
    ):
        self._host = host
        self._port = port
        self._connect_timeout = connect_timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self.reconnects = 0
        self._c_reconnects = (
            metrics.counter("transport.reconnects")
            if metrics is not None
            else None
        )

    def _connect(self, budget: Optional[float] = None) -> socket.socket:
        timeout = self._connect_timeout
        if budget is not None:
            timeout = min(timeout, max(budget, 1e-3))
        failpoints.fire("transport.tcp.connect", error=TransportError)
        try:
            return socket.create_connection(
                (self._host, self._port), timeout=timeout
            )
        except OSError as e:
            raise TransportError(
                f"cannot connect to {self._host}:{self._port}: {e}"
            ) from e

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:  # pragma: no cover - close best-effort
                pass
            self._sock = None

    def _count_reconnect(self) -> None:
        self.reconnects += 1
        if self._c_reconnects is not None:
            self._c_reconnects.inc()

    def _exchange(self, sock, payload, timeout, on_sent) -> bytes:
        if timeout is not None and timeout <= 0:
            raise socket.timeout("per-call deadline exhausted")
        sock.settimeout(timeout)
        failpoints.fire("transport.tcp.send", error=TransportError)
        payload = failpoints.mutate("transport.request", payload)
        send_msg(sock, payload)
        if on_sent is not None:
            on_sent()
        failpoints.fire("transport.tcp.recv", error=TransportError)
        return failpoints.mutate("transport.response", recv_msg(sock))

    def roundtrip(self, payload, timeout=None, on_sent=None):
        deadline = None if timeout is None else time.monotonic() + timeout

        def remaining() -> Optional[float]:
            return (
                None if deadline is None else deadline - time.monotonic()
            )

        with self._lock:
            reused = self._sock is not None
            if not reused:
                self._sock = self._connect(remaining())
            try:
                return self._exchange(
                    self._sock, payload, remaining(), on_sent
                )
            except (socket.timeout, TimeoutError) as e:
                self._drop()
                raise TransportTimeout(
                    f"no response from {self._host}:{self._port} "
                    f"within {timeout}s"
                ) from e
            except (TransportError, OSError) as e:
                self._drop()
                if not reused:
                    raise TransportError(str(e)) from e
                # Pooled connection went stale (peer restarted between
                # round trips): reconnect once and resend — but only
                # within what is left of THIS call's deadline.
                budget = remaining()
                if budget is not None and budget <= 0:
                    raise TransportTimeout(
                        f"connection to {self._host}:{self._port} went "
                        f"stale and no budget remains of {timeout}s to "
                        f"reconnect"
                    ) from e
                self._count_reconnect()
                self._sock = self._connect(budget)
                try:
                    return self._exchange(
                        self._sock, payload, remaining(), on_sent
                    )
                except (socket.timeout, TimeoutError) as e2:
                    self._drop()
                    raise TransportTimeout(
                        f"no response from {self._host}:{self._port} "
                        f"within {timeout}s"
                    ) from e2
                except (TransportError, OSError) as e2:
                    self._drop()
                    raise TransportError(str(e2)) from e2

    def close(self):
        with self._lock:
            self._drop()


# ---------------------------------------------------------------------------
# Server side
# ---------------------------------------------------------------------------


class FramedTcpServer:
    """Threaded length-prefixed request->response server.

    Each connection loops framed-request -> `handler` -> framed-response
    until the peer disconnects (connection reuse on the serving side).
    A handler exception closes that connection — the client observes a
    `TransportError` and applies its own retry policy — and is logged
    rather than silently swallowed.
    """

    def __init__(
        self,
        handler: Callable[[bytes], bytes],
        host: str = "",
        port: int = 0,
        name: str = "serving",
    ):
        self._handler = handler
        self._name = name
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        data = recv_msg(self.request)
                    except (TransportError, OSError, struct.error):
                        return
                    try:
                        reply = outer._handler(data)
                    except Exception:
                        logger.exception(
                            "[%s] handler failed; closing connection",
                            outer._name,
                        )
                        return
                    try:
                        send_msg(self.request, reply)
                    except OSError:
                        return

        class _Server(socketserver.ThreadingTCPServer):
            # Live connections are tracked so `stop()` can really stop:
            # ThreadingTCPServer.shutdown() only ends the accept loop,
            # leaving per-connection daemon threads serving old sockets.
            allow_reuse_address = True
            daemon_threads = True

            def __init__(self, *args, **kwargs):
                super().__init__(*args, **kwargs)
                self._conns = set()
                self._conns_lock = threading.Lock()

            def process_request(self, request, client_address):
                with self._conns_lock:
                    self._conns.add(request)
                super().process_request(request, client_address)

            def shutdown_request(self, request):
                with self._conns_lock:
                    self._conns.discard(request)
                super().shutdown_request(request)

            def close_connections(self):
                with self._conns_lock:
                    conns = list(self._conns)
                for c in conns:
                    try:
                        c.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "FramedTcpServer":
        """Serve on a background thread (tests, embedded use)."""
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name=f"{self._name}-tcp",
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Serve on the calling thread (the CLI roles)."""
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.close_connections()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
