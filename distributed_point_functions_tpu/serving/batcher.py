"""Thread-safe dynamic batcher: coalesce concurrent PIR requests.

The TPU serving cost model (PR 1's planner/streaming pipeline, and
BBCGGI arXiv:2012.14884 before it) is dominated by batched DPF
evaluation: throughput scales with the number of keys a single device
step evaluates, while a one-key step pays the whole dispatch cost.
Nothing in the library formed those batches — every caller of
`handle_plain_request` paid its own device step. This batcher is the
missing piece:

* Concurrent `submit(keys)` calls coalesce into one evaluation of their
  concatenated keys. A batch closes when it holds `max_batch_size` keys
  or `max_wait_ms` after its first request arrived, whichever is first.
* The batch's key count pads up to a **power-of-two bucket** (duplicate
  of the first key; its result is discarded). Every jitted program in
  the serving path specializes on `num_keys`, so bucketing bounds the
  number of distinct compilations at `log2(max_batch_size)+1` instead
  of one per observed arrival pattern — each bucket hits an existing
  jit/planner cache entry.
* Admission is a **bounded queue**: when `max_queue` requests are
  already waiting, `submit` sheds load with `Overloaded` instead of
  growing an unbounded backlog. With an `AdmissionController`
  (`capacity/admission.py`) attached, the count bound becomes a
  backstop behind cost-aware admission: each request is priced in
  estimated device-ms, doomed work (drain estimate past the deadline)
  and over-quota tenants are shed *at admission* with a
  `retry_after_s` hint on the `Overloaded`, and the queue dequeues
  across tenants in weighted-fair order instead of global FIFO.
* Requests carry an optional absolute **deadline** (`time.monotonic()`
  seconds). The worker drops requests that expired while the batch was
  forming *before* any device work is dispatched — they fail with
  `DeadlineExceeded` without evaluating, and a bucket whose every
  request died skips the dispatch entirely — and the submitting thread
  enforces the same deadline on its wait.
* With `pipeline_depth >= 2` the worker runs a bounded two-stage
  pipeline: it dispatches bucket N (admission gate, padding,
  `begin_batch` generation binding, the evaluation itself) while a
  completion thread finishes bucket N-1 (result fan-out, phase
  attribution, `end_batch`, cost-ledger feed). The handoff queue holds
  at most `pipeline_depth - 1` evaluated buckets, so the worker never
  runs further ahead than the pipeline depth. Semantics are preserved
  exactly: the generation is still bound at dispatch by the worker
  (serial, so flips still land only at batch boundaries), a bucket's
  `end_batch` still fires only after its last response fans out (so a
  rotation can never free buffers or idle-flip between the dispatch
  and completion halves), and `close()` drains the in-flight stage
  before returning. Depth 1 completes inline — the pre-pipeline
  behavior, bit for bit.

The batcher is generic over the evaluation function
(`evaluate(keys) -> list of per-key results`), so it serves any of the
server roles (and unit tests run it on stubs with no JAX at all).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence

from ..capacity.admission import AdmissionController, WeightedFairQueue
from ..capacity.model import default_capacity_model
from ..observability import costmodel as costmodel_mod
from ..observability import tracing
from ..observability import phases as phases_mod
from ..observability.device import default_telemetry, shape_key
from ..robustness import failpoints
from .metrics import MetricsRegistry


class Overloaded(RuntimeError):
    """The request was shed at admission, not enqueued. `retry_after_s`
    is the server's drain-based backoff hint (0 = none given) and
    `reason` the admission `ShedReason` value string, when cost-aware
    admission made the call."""

    def __init__(
        self,
        message: str = "",
        retry_after_s: float = 0.0,
        reason: Optional[str] = None,
    ):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.reason = reason


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before its batch was evaluated."""


def bucket_size(num_keys: int) -> int:
    """Smallest power of two >= num_keys (the jit-shape bucket)."""
    if num_keys <= 0:
        raise ValueError("num_keys must be positive")
    return 1 << (num_keys - 1).bit_length()


def _h2d_bytes(telemetry) -> int:
    """Cumulative host->device bytes from the transfer ledger (0 when
    unavailable); deltas across a batch attribute its staging traffic
    to the cost ledger."""
    try:
        return int(telemetry.transfers.export()["totals"]["h2d_bytes"])
    except Exception:  # noqa: BLE001 - accounting never breaks serving
        return 0


def _sync_wait_ms(telemetry) -> float:
    """Cumulative exposed transfer-sync wall time from the ledger (0.0
    when unavailable); deltas across an evaluation split the wall time
    into device-feeding work vs `staging_sync` bubbles."""
    try:
        return float(telemetry.transfers.sync_wait_ms())
    except Exception:  # noqa: BLE001 - accounting never breaks serving
        return 0.0


class _Pending:
    __slots__ = (
        "keys", "deadline", "event", "result", "error", "t0", "abandoned",
        "trace", "phases", "tenant", "cost", "generation",
    )

    def __init__(self, keys, deadline, tenant="default", cost=None):
        self.keys = keys
        self.deadline = deadline
        self.tenant = tenant
        self.cost = cost  # admission WorkCost, released on completion
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t0 = time.monotonic()
        self.abandoned = False
        # Snapshot generation the batch actually evaluated against
        # (None without a generation source); stamped by the worker at
        # the batch boundary, read back through `submit_ex`.
        self.generation: Optional[int] = None
        # The submitting request's trace: the worker thread appends the
        # queue-wait / device-compute spans onto it by reference. Same
        # deal for the phase record — the worker attributes
        # queue/batch/compile/device phases onto it.
        self.trace = tracing.current_trace()
        self.phases = phases_mod.current_request()


class _BatchResult:
    """One evaluated bucket between the worker's dispatch half and the
    completion half. Everything the fan-out needs is captured at
    dispatch time (on the worker), so completion never reads worker
    state that a later bucket may have advanced."""

    __slots__ = (
        "live", "results", "error", "collected", "eval_ms", "assembly_s",
        "bucket", "flat_len", "pad_waste", "generation", "batch_phases",
        "transfer_bytes", "gate_t",
    )

    def __init__(self):
        self.results = None
        self.error = None
        self.collected = {}
        self.eval_ms = 0.0
        self.batch_phases = None
        self.transfer_bytes = 0


class DynamicBatcher:
    """See module docstring. One background worker forms and evaluates
    batches (plus, at `pipeline_depth >= 2`, a completion thread that
    fans out bucket N-1 while the worker dispatches bucket N); `submit`
    blocks the calling thread until its slice of the batch result is
    ready (or raises `Overloaded` / `DeadlineExceeded` / the
    evaluation error)."""

    def __init__(
        self,
        evaluate: Callable[[List], List],
        *,
        max_batch_size: int = 64,
        max_wait_ms: float = 2.0,
        max_queue: int = 256,
        metrics: Optional[MetricsRegistry] = None,
        name: str = "batcher",
        admission: Optional[AdmissionController] = None,
        pipeline_depth: int = 1,
    ):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        self._evaluate = evaluate
        self._max_batch_size = max_batch_size
        self._batch_cap: Optional[int] = None  # brownout step 2
        self._max_wait_s = max(0.0, max_wait_ms) / 1e3
        self._max_queue = max_queue
        self._admission = admission
        self._name = name
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        m, n = self.metrics, name
        self._c_submitted = m.counter(f"{n}.requests_submitted")
        self._c_shed = m.counter(f"{n}.requests_shed")
        self._c_deadline = m.counter(f"{n}.requests_deadline_exceeded")
        self._c_batches = m.counter(f"{n}.batches")
        self._c_pad = m.counter(f"{n}.padded_keys")
        self._c_compiles = m.counter(f"{n}.jit_bucket_compiles")
        self._c_hits = m.counter(f"{n}.jit_bucket_hits")
        self._g_depth = m.gauge(f"{n}.queue_depth")
        self._h_batch = m.histogram(
            f"{n}.batch_keys", buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256)
        )
        self._h_latency = m.histogram(f"{n}.request_latency_ms")
        self._h_queue_wait = m.histogram(f"{n}.queue_wait_ms")
        self._h_pad_waste = m.histogram(
            f"{n}.pad_waste_ratio",
            buckets=(0.0, 0.05, 0.1, 0.25, 0.5, 0.75, 0.875, 1.0),
        )
        self._c_expired_in_batch = m.counter(f"{n}.expired_in_batch")
        self._c_batches_skipped = m.counter(f"{n}.batches_skipped_dead")
        # Phase-attribution residual that would have gone negative:
        # collected phase brackets exceeded the measured wall time
        # (clock skew, nested brackets). `dispatch` clamps at zero and
        # the excess lands here instead of corrupting the residual.
        self._c_slop = m.counter(f"{n}.attribution_slop_ms")
        m.gauge(f"{n}.pipeline_depth").set(float(pipeline_depth))
        self._cond = threading.Condition()
        # Weighted-fair across tenants under cost-aware admission;
        # plain FIFO otherwise (and WFQ degenerates to FIFO for a
        # single tenant, so either way one-tenant order is arrival
        # order).
        self._queue = WeightedFairQueue() if admission is not None else deque()
        # Snapshot rotation hook (`serving/snapshots.py`): the worker
        # calls begin_batch()/end_batch() around every evaluation so
        # flips land only at batch boundaries and in-flight batches
        # pin their generation's stagings.
        self._generation_source = None
        # Utilization hook (`observability/utilization.py`): the worker
        # and completion threads report busy/idle intervals with typed
        # bubble causes (see `set_utilization`). None = no accounting.
        self._util = None
        # Key-bucket granularity: mesh serving pads buckets to a
        # multiple of the key-axis size so batches land pre-partitioned
        # over the key axis (see `set_key_multiple`). 1 = plain
        # power-of-two buckets.
        self._key_multiple = 1
        # Cost-ledger identity: which workload the terminal batches are
        # joined under and how a bucket is priced (see
        # `set_cost_model`). Defaults to dense pir pricing.
        self._cost_workload = "pir"
        self._cost_pricer = None
        self._seen_buckets: set = set()
        self._closed = False
        # Depth-2 pipeline handoff: the worker appends evaluated
        # buckets, the completion thread pops them. Bounded at
        # pipeline_depth - 1 so the worker can run at most one bucket
        # ahead of the completion half (depth 1 => no thread, inline
        # completion = pre-pipeline behavior).
        self._pipeline_depth = int(pipeline_depth)
        self._complete_q: deque = deque()
        self._complete_cond = threading.Condition()
        self._worker_done = False
        self._completer: Optional[threading.Thread] = None
        if self._pipeline_depth > 1:
            self._completer = threading.Thread(
                target=self._complete_loop, daemon=True,
                name=f"{name}-completer",
            )
            self._completer.start()
        self._worker = threading.Thread(
            target=self._run, daemon=True, name=f"{name}-worker"
        )
        self._worker.start()

    # -- client side --------------------------------------------------------

    def submit(
        self,
        keys: Sequence,
        deadline: Optional[float] = None,
        tenant: str = "default",
    ) -> List:
        """Evaluate `keys` as part of a coalesced batch; returns one
        result per key, in order. `deadline` is absolute
        `time.monotonic()` seconds; `tenant` keys the QoS policy when
        cost-aware admission is attached (ignored otherwise)."""
        results, _ = self.submit_ex(keys, deadline, tenant)
        return results

    def submit_ex(
        self,
        keys: Sequence,
        deadline: Optional[float] = None,
        tenant: str = "default",
    ):
        """`submit`, but returns `(results, generation)` where
        `generation` is the snapshot generation the batch evaluated
        against (None without an attached generation source) — the
        Leader binds its own share to it and refuses a Helper echo
        from any other generation."""
        keys = list(keys)
        if not keys:
            raise ValueError("keys must not be empty")
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._queue) >= self._max_queue:
                # Count bound: the whole admission story without a
                # controller, a backstop behind it (in case the cost
                # model underprices a pathological workload).
                self._c_shed.inc()
                raise Overloaded(
                    f"{self._name}: admission queue full "
                    f"({self._max_queue} requests waiting)"
                )
            cost = None
            if self._admission is not None:
                decision = self._admission.admit(
                    len(keys), tenant=tenant, deadline=deadline
                )
                if not decision.admitted:
                    self._c_shed.inc()
                    raise Overloaded(
                        f"{self._name}: shed at admission "
                        f"({decision.reason.value}); retry after "
                        f"{decision.retry_after_s:.3f}s",
                        retry_after_s=decision.retry_after_s,
                        reason=decision.reason.value,
                    )
                cost = decision.cost
            pending = _Pending(keys, deadline, tenant=tenant, cost=cost)
            if self._admission is not None:
                policy = self._admission.policy(tenant)
                self._queue.push(
                    pending,
                    tenant=tenant,
                    weight=policy.weight,
                    cost=float(len(keys)),
                )
            else:
                self._queue.append(pending)
            self._g_depth.set(len(self._queue))
            self._c_submitted.inc()
            self._cond.notify()
        timeout = (
            None if deadline is None
            else max(0.0, deadline - time.monotonic())
        )
        if not pending.event.wait(timeout):
            with self._cond:
                pending.abandoned = True
            # The worker may complete it concurrently; deadline still wins.
            if not pending.event.is_set() or pending.error is not None:
                self._c_deadline.inc()
                raise DeadlineExceeded(
                    f"{self._name}: deadline passed after "
                    f"{(time.monotonic() - pending.t0) * 1e3:.1f} ms in queue"
                )
        if pending.error is not None:
            raise pending.error
        return pending.result, pending.generation

    # -- snapshot rotation hook ---------------------------------------------

    def set_generation_source(self, source) -> None:
        """Attach a `SnapshotManager` (duck-typed: `begin_batch()`
        returning the bound generation, `end_batch(generation)` when
        the batch retires). Flips then land only between batches, so a
        batch never evaluates half against generation N and half
        against N+1."""
        with self._cond:
            self._generation_source = source

    def _end_batch(self, generation) -> None:
        if generation is None:
            return
        source = self._generation_source
        if source is not None:
            try:
                source.end_batch(generation)
            except Exception:  # noqa: BLE001 - bookkeeping never kills the worker
                pass

    # -- utilization hook ---------------------------------------------------

    def set_utilization(self, tracker) -> None:
        """Attach a `UtilizationTracker` (duck-typed:
        `record_busy(seconds, thread=)` and `record_idle(cause,
        seconds, thread=)`). The worker thread then attributes every
        second it spends to device-feeding work or a typed bubble —
        empty_queue / admission_shed / batch_wait / pipeline_full /
        staging_sync — and the completion thread reports fan-out time;
        None detaches."""
        with self._cond:
            self._util = tracker

    def _util_busy(self, seconds: float, thread: str = "worker") -> None:
        util = self._util
        if util is None or seconds <= 0.0:
            return
        try:
            util.record_busy(seconds, thread=thread)
        except Exception:  # noqa: BLE001 - accounting never breaks serving
            pass

    def _util_idle(
        self, cause: str, seconds: float, thread: str = "worker"
    ) -> None:
        util = self._util
        if util is None or seconds <= 0.0:
            return
        try:
            util.record_idle(cause, seconds, thread=thread)
        except Exception:  # noqa: BLE001 - accounting never breaks serving
            pass

    # -- cost-model hook ----------------------------------------------------

    def set_cost_model(self, workload: str, pricer=None) -> None:
        """Re-key the terminal-batch cost join: `workload` names the
        ledger cell family (dense sessions use "pir", sparse sessions
        "sparse") and `pricer`, when given, maps an executed padded
        bucket size to a `WorkCost` estimate (defaults to the capacity
        model's dense `price_pir_keys`). Sparse serving attaches
        `price_sparse_pir_keys` here so the accuracy ledger and the
        recalibration loop see sparse traffic as its own workload."""
        if not workload:
            raise ValueError("workload must be non-empty")
        with self._cond:
            self._cost_workload = str(workload)
            self._cost_pricer = pricer

    # -- brownout hook ------------------------------------------------------

    def set_batch_cap(self, cap: Optional[int]) -> None:
        """Cap the effective batch size below `max_batch_size` (the
        brownout ladder's `cap_batches` step trades peak throughput
        for shorter queue drains); None clears."""
        if cap is not None and cap < 1:
            raise ValueError("batch cap must be >= 1 (or None)")
        with self._cond:
            self._batch_cap = cap

    # -- mesh hook ----------------------------------------------------------

    def set_key_multiple(self, multiple: int) -> None:
        """Pad every key bucket up to a multiple of `multiple` (the
        serving mesh's key-axis size) so batches flow into the sharded
        step pre-partitioned, with no gather and no fresh jit shape per
        request count. Power-of-two buckets already satisfy any
        power-of-two multiple <= the bucket; the rounding only moves
        buckets smaller than the multiple."""
        if multiple < 1:
            raise ValueError("key multiple must be >= 1")
        with self._cond:
            self._key_multiple = int(multiple)

    # -- worker -------------------------------------------------------------

    def _pop_next(self):
        # Caller holds self._cond.
        return (
            self._queue.pop()
            if self._admission is not None
            else self._queue.popleft()
        )

    def _peek_next(self):
        # Caller holds self._cond.
        if not self._queue:
            return None
        return (
            self._queue.peek()
            if self._admission is not None
            else self._queue[0]
        )

    def _release(self, pending: _Pending) -> None:
        """An admitted request reached a terminal state: give its
        estimated cost back to the admission drain model."""
        if self._admission is not None:
            self._admission.release(pending.cost)

    def _collect(self):
        """Block for the first request, then fill the batch until
        `max_batch_size` keys or `max_wait_ms` elapse. Returns
        (batch, assembly_seconds) — assembly measured from the first
        pop, i.e. the window spent waiting for co-batchable arrivals —
        or None only at shutdown with an empty queue."""
        util = self._util
        empty_s = 0.0
        form_s = 0.0
        shed_before = self._c_shed.value if util is not None else 0
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                t_wait = time.monotonic()
                self._cond.wait()
                empty_s += time.monotonic() - t_wait
            t_first = time.monotonic()
            batch = [self._pop_next()]
            num_keys = len(batch[0].keys)
            max_batch = self._max_batch_size
            if self._batch_cap is not None:
                max_batch = min(max_batch, self._batch_cap)
            close_at = time.monotonic() + self._max_wait_s
            while num_keys < max_batch:
                if self._queue:
                    nxt = self._peek_next()
                    if num_keys + len(nxt.keys) > max_batch:
                        break
                    self._pop_next()
                    batch.append(nxt)
                    num_keys += len(nxt.keys)
                    continue
                remaining = close_at - time.monotonic()
                if remaining <= 0 or self._closed:
                    break
                t_wait = time.monotonic()
                self._cond.wait(remaining)
                form_s += time.monotonic() - t_wait
            self._g_depth.set(len(self._queue))
        # Bubble attribution, outside the lock. An empty-queue wait
        # during which admission shed requests is idle the policy
        # manufactured, not absent demand — attribute it there.
        if util is not None:
            if empty_s > 0.0:
                cause = (
                    "admission_shed"
                    if self._c_shed.value - shed_before > 0
                    else "empty_queue"
                )
                self._util_idle(cause, empty_s)
            if form_s > 0.0:
                self._util_idle("batch_wait", form_s)
        return batch, time.monotonic() - t_first

    def _run(self) -> None:
        try:
            self._run_loop()
        finally:
            # Unblock the completion thread (and let it exit once the
            # handoff queue drains) no matter how the worker stopped.
            with self._complete_cond:
                self._worker_done = True
                self._complete_cond.notify_all()

    def _run_loop(self) -> None:
        while True:
            collected = self._collect()
            if collected is None:
                return
            batch, assembly_s = collected
            # Pre-dispatch deadline gate: requests that expired while
            # the batch was forming are dropped HERE, before any device
            # work, so an expired request never costs an evaluation.
            now = time.monotonic()
            live = []
            for p in batch:
                if p.abandoned or (
                    p.deadline is not None and now > p.deadline
                ):
                    # Dropped unevaluated; the submitter raises
                    # DeadlineExceeded (and counts it) on its side.
                    self._c_expired_in_batch.inc()
                    self._release(p)
                    p.error = DeadlineExceeded("expired in queue")
                    p.event.set()
                    continue
                live.append(p)
            if not live:
                # Every request in the bucket died while batching:
                # skip padding, bucketing, and the device dispatch
                # entirely.
                self._c_batches_skipped.inc()
                continue
            flat = [k for p in live for k in p.keys]
            bucket = bucket_size(len(flat))
            multiple = self._key_multiple
            if bucket % multiple:
                bucket = -(-bucket // multiple) * multiple
            padded = flat + [flat[0]] * (bucket - len(flat))
            pad_waste = (bucket - len(flat)) / bucket
            if bucket in self._seen_buckets:
                self._c_hits.inc()
            else:
                self._seen_buckets.add(bucket)
                self._c_compiles.inc()
            self._c_batches.inc()
            self._c_pad.inc(bucket - len(flat))
            self._h_batch.observe(len(flat))
            self._h_pad_waste.observe(pad_waste)
            # Batch boundary: a pending snapshot flip applies HERE (or
            # not at all until the next batch), then the whole bucket
            # evaluates and binds against one generation.
            generation = None
            source = self._generation_source
            if source is not None:
                try:
                    generation = source.begin_batch()
                except Exception:  # noqa: BLE001 - rotation never kills serving
                    generation = None
            for p in live:
                p.generation = generation
            record = _BatchResult()
            record.live = live
            record.assembly_s = assembly_s
            record.bucket = bucket
            record.flat_len = len(flat)
            record.pad_waste = pad_waste
            record.generation = generation
            record.gate_t = now
            try:
                # Chaos site: a worker-side fault here must fan out to
                # every live request and leave the worker serving.
                failpoints.fire("batcher.evaluate")
                telemetry = default_telemetry()
                h2d_before = _h2d_bytes(telemetry)
                sync_before = _sync_wait_ms(telemetry)
                t_eval = time.perf_counter()
                tracker = telemetry.compile_tracker
                recorder = phases_mod.default_phase_recorder()
                with self.metrics.timed(f"{self._name}.evaluate_ms"), \
                        tracker.dispatch(
                            f"{self._name}.evaluate",
                            shape_key(("k", bucket)),
                        ), \
                        recorder.collect() as batch_phases:
                    # The batch-scoped record soaks up phase() brackets
                    # inside the evaluation path (h2d staging,
                    # compile-vs-compute in pir/server); the completion
                    # half re-attributes them to every live request.
                    results = list(self._evaluate(padded))
                record.eval_ms = (time.perf_counter() - t_eval) * 1e3
                # Utilization split: the evaluation wall is busy time
                # minus whatever it spent blocked in exposed transfer
                # syncs — those are `staging_sync` bubbles, so the
                # causes still sum to measured idle.
                if self._util is not None:
                    eval_s = record.eval_ms / 1e3
                    stall_s = min(eval_s, max(
                        0.0, _sync_wait_ms(telemetry) - sync_before
                    ) / 1e3)
                    self._util_busy(eval_s - stall_s)
                    self._util_idle("staging_sync", stall_s)
                record.results = results
                record.collected = (
                    batch_phases.snapshot()
                    if batch_phases is not None else {}
                )
                record.batch_phases = batch_phases
                # Measured worker-side, right after the evaluation
                # returns, so bucket N's staging traffic can never
                # bleed into bucket N-1's cost record on the
                # completion thread.
                record.transfer_bytes = max(
                    0, _h2d_bytes(telemetry) - h2d_before
                )
                if len(results) < len(flat):
                    raise RuntimeError(
                        f"evaluate returned {len(results)} results for "
                        f"{len(flat)} keys"
                    )
            except Exception as e:  # noqa: BLE001 - fan the error out
                record.error = e
            self._dispatch_complete(record)

    # -- completion half ----------------------------------------------------

    def _dispatch_complete(self, record: _BatchResult) -> None:
        """Hand an evaluated bucket to the completion half. Depth 1
        completes inline on the worker (pre-pipeline behavior);
        otherwise the handoff queue is bounded at depth-1 evaluated
        buckets, so the worker blocks rather than running unboundedly
        ahead of fan-out."""
        if self._completer is None:
            self._finish(record)
            return
        waited_s = 0.0
        with self._complete_cond:
            while len(self._complete_q) >= self._pipeline_depth - 1:
                t_wait = time.monotonic()
                self._complete_cond.wait()
                waited_s += time.monotonic() - t_wait
            self._complete_q.append(record)
            self._complete_cond.notify_all()
        # Worker blocked on the bounded handoff queue: the completion
        # half is the bottleneck, not the device feed.
        self._util_idle("pipeline_full", waited_s)

    def _complete_loop(self) -> None:
        while True:
            with self._complete_cond:
                while not self._complete_q and not self._worker_done:
                    self._complete_cond.wait()
                if not self._complete_q:
                    return
                record = self._complete_q.popleft()
                self._complete_cond.notify_all()
            t_finish = time.monotonic()
            try:
                self._finish(record)
                self._util_busy(
                    time.monotonic() - t_finish, thread="completer"
                )
            except Exception as e:  # noqa: BLE001 - never kill the completer
                for p in record.live:
                    if not p.event.is_set():
                        p.error = e
                        p.event.set()

    def _finish(self, rec: _BatchResult) -> None:
        """Complete one evaluated bucket: error/result fan-out, phase
        attribution, `end_batch`, cost-ledger feed. Runs inline on the
        worker at depth 1 and on the completion thread otherwise; reads
        only the `_BatchResult` snapshot, never live worker state."""
        if rec.error is not None:
            for p in rec.live:
                self._release(p)
                p.error = rec.error
                p.event.set()
            self._end_batch(rec.generation)
            return
        collected = rec.collected
        # Whatever the evaluation spent outside any phase bracket is
        # batcher/handler overhead: dispatch. Clamped at zero — when
        # the brackets over-cover the wall time the excess is recorded
        # as attribution slop instead of a negative residual.
        collected_ms = sum(collected.values())
        dispatch_ms = max(0.0, rec.eval_ms - collected_ms)
        slop_ms = max(0.0, collected_ms - rec.eval_ms)
        if slop_ms > 0.0:
            self._c_slop.inc(slop_ms)
        # Batch-level stage aggregates (once per batch) ...
        tracing.add_span(
            "batch_assembly", rec.assembly_s * 1e3,
            bucket=rec.bucket, batch_keys=rec.flat_len,
        )
        tracing.add_span(
            "device_compute", rec.eval_ms,
            pad_waste_ratio=round(rec.pad_waste, 4),
        )
        offset = 0
        done = time.monotonic()
        for p in rec.live:
            p.result = rec.results[offset:offset + len(p.keys)]
            offset += len(p.keys)
            queue_wait_ms = (rec.gate_t - p.t0) * 1e3
            self._h_queue_wait.observe(queue_wait_ms)
            self._h_latency.observe((done - p.t0) * 1e3)
            # ... and per-request spans grafted onto the submitting
            # thread's trace so /tracez decomposes each request.
            if p.trace is not None:
                p.trace.add_span("queue_wait", queue_wait_ms)
                p.trace.add_span(
                    "batch_assembly", rec.assembly_s * 1e3,
                    bucket=rec.bucket, batch_keys=rec.flat_len,
                )
                p.trace.add_span(
                    "device_compute", rec.eval_ms,
                    pad_waste_ratio=round(rec.pad_waste, 4),
                )
            if p.phases is not None:
                p.phases.add("queue", queue_wait_ms)
                p.phases.add("batch", rec.assembly_s * 1e3)
                p.phases.add_many(collected)
                p.phases.add("dispatch", dispatch_ms)
            self._release(p)
            p.event.set()
        # The batch has fully retired against its generation: let a
        # waiting flip proceed (and the old generation's stagings
        # drop once its last batch lands here).
        self._end_batch(rec.generation)
        # Terminal batch outcome: join the capacity-model estimate
        # for the executed bucket with the measured device truth
        # (after every waiter is released, so accounting adds no
        # request latency).
        self._observe_cost(rec)

    def _observe_cost(self, rec: _BatchResult) -> None:
        """Feed the cost ledger one (estimate, truth) pair for this
        batch. The estimate is what the capacity model would charge for
        the executed padded bucket (corrections included, so the
        recalibration loop is closed); the truth is the exclusive
        `device_compute` phase from the batch-scoped record, falling
        back to wall time minus compile when the evaluation path has no
        phase brackets (stub evaluators in tests). Never raises."""
        try:
            plan_meta = (
                rec.batch_phases.get_meta("serving_plan")
                if rec.batch_phases is not None else None
            ) or {}
            tier = str(plan_meta.get("mode", "unplanned"))
            actual_ms = rec.collected.get("device_compute", 0.0)
            if actual_ms <= 0.0:
                actual_ms = max(
                    0.0, rec.eval_ms - rec.collected.get("compile", 0.0)
                )
            pricer = self._cost_pricer
            if pricer is not None:
                predicted = pricer(rec.bucket)
            else:
                predicted = default_capacity_model().price_pir_keys(
                    rec.bucket
                )
            trace = next(
                (p.trace for p in rec.live if p.trace is not None), None
            )
            costmodel_mod.default_cost_ledger().observe(
                self._cost_workload, tier, str(rec.bucket),
                predicted_device_ms=predicted.device_ms,
                actual_device_ms=actual_ms,
                transfer_bytes=rec.transfer_bytes,
                trace=trace,
            )
        except Exception:  # noqa: BLE001 - accounting never breaks serving
            pass

    # -- lifecycle ----------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Drain the queue, then stop the worker — and, when pipelined,
        the completion thread, so every in-flight bucket fans out
        before close() returns. Subsequent submits raise."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout=timeout)
        if self._completer is not None:
            with self._complete_cond:
                self._worker_done = True
                self._complete_cond.notify_all()
            self._completer.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
