"""Fleet telemetry plane: per-replica scopes + the fleet aggregator.

`observability/federation.py` knows how to *merge* telemetry exports;
this module knows where they come from. Two pieces:

`ReplicaTelemetry` is one replica's scope: a replica-tagged
`EventJournal` (every event gains `replica=<id>`, coalesce keys are
prefixed so two replicas' storms cannot merge), a per-replica
`UtilizationTracker`, a per-replica `TimeSeriesStore` fed by one
`MetricsSampler` per session registry, and the wiring (`adopt`) that
threads the scope into the replica's existing components — sessions
(`set_journal`/`set_utilization`), `SnapshotManager`s, and the pair's
prober. Before this, N replicas in one process silently shared the
process-global journal/tracker/TSDB and an incident timeline could not
say *which* replica's breaker opened.

`FleetTelemetry` is the aggregator: it scrapes every scope into one
merged metrics view (`replica`-labeled rows + sum/mean/bucket-merge
aggregates), one causally ordered cross-replica timeline
(monotonic-rebased — see federation.py), fleet-level TSDB series
(`fleet.qps`, `fleet.duty_cycle_pct`, `fleet.routable_replicas`,
per-replica generation lag), and a fleet `SloTracker` over its own
derived gauges:

    fleet_routable_floor      gauge_min  fleet.routable_replicas
    fleet_rotation_staleness  gauge_max  fleet.rotation_staleness_ms
    fleet_probe_freshness     gauge_max  fleet.divergence_probe_age_s
    fleet_spillover_rate      gauge_max  fleet.spillover_rate_pct

Hard breaches degrade the fleet `/healthz` verdict, and `wire_bundles`
turns the first hard burn (or a probe divergence) into ONE fleet-wide
debug bundle: every replica's scrape as its own section plus the merged
timeline, in one directory.

Scraping is transport-agnostic by construction: `FleetTelemetry`
consumes `ReplicaTelemetry.scrape()` dicts and duck-typed exports, so
the multi-host mesh of ROADMAP item 2 can implement the same scrape
over RPC without this module changing. Layering: fleet -> serving ->
observability, one-way, per `tools/check_layers.py`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..observability import events as events_mod
from ..observability import federation
from ..observability.events import EventJournal
from ..observability.slo import SloObjective, SloTracker
from ..observability.timeseries import MetricsSampler, TimeSeriesStore
from ..observability.utilization import UtilizationTracker
from ..serving.metrics import MetricsRegistry, split_labeled_name

__all__ = [
    "ReplicaTelemetry",
    "FleetTelemetry",
    "default_fleet_objectives",
]


def default_fleet_objectives(
    min_routable: int = 2,
    rotation_staleness_ceiling_ms: float = 30_000.0,
    probe_age_ceiling_s: float = 120.0,
    spillover_ceiling_pct: float = 35.0,
) -> List[SloObjective]:
    """The fleet SLO catalog (DESIGN.md §24). Routable floor and probe
    freshness are hard — a fleet below quorum or unable to prove
    bit-identity must stop taking traffic; staleness and spillover rate
    are soft — they page, they do not drain."""
    return [
        SloObjective(
            name="fleet_routable_floor",
            kind="gauge_min",
            metric="fleet.routable_replicas",
            threshold=float(min_routable),
            severity="hard",
        ),
        SloObjective(
            name="fleet_rotation_staleness",
            kind="gauge_max",
            metric="fleet.rotation_staleness_ms",
            threshold=float(rotation_staleness_ceiling_ms),
            severity="soft",
        ),
        SloObjective(
            name="fleet_probe_freshness",
            kind="gauge_max",
            metric="fleet.divergence_probe_age_s",
            threshold=float(probe_age_ceiling_s),
            severity="hard",
        ),
        SloObjective(
            name="fleet_spillover_rate",
            kind="gauge_max",
            metric="fleet.spillover_rate_pct",
            threshold=float(spillover_ceiling_pct),
            severity="soft",
        ),
    ]


class ReplicaTelemetry:
    """One replica's telemetry scope (see module docstring)."""

    def __init__(
        self,
        replica_id: str,
        *,
        journal: Optional[EventJournal] = None,
        utilization: Optional[UtilizationTracker] = None,
        store: Optional[TimeSeriesStore] = None,
        journal_capacity: int = 256,
        max_series: int = 64,
        workload=None,
        clock=time.monotonic,
    ):
        self.replica_id = str(replica_id)
        # workload is a replica-scoped WorkloadObservatory (opt-in via
        # the constructor or set_workload); its export rides the scrape
        # so the aggregator can federate per-replica traffic shapes.
        self.workload = workload
        self.journal = (
            journal
            if journal is not None
            else EventJournal(
                capacity=journal_capacity, clock=clock, scope=self.replica_id
            )
        )
        self.utilization = (
            utilization
            if utilization is not None
            else UtilizationTracker(clock=clock, journal=self.journal)
        )
        self.store = (
            store
            if store is not None
            else TimeSeriesStore(max_series=max_series, clock=clock)
        )
        self._clock = clock
        self._registries: List = []
        self._samplers: List[MetricsSampler] = []
        self._replica = None

    def adopt(self, replica) -> "ReplicaTelemetry":
        """Thread this scope into `replica`'s components: sessions get
        the scoped journal + utilization tracker, snapshot managers and
        the pair's prober get the journal, and one sampler per session
        registry feeds the scoped TSDB. Components lacking the setters
        (stub sessions in tests) are skipped — adoption is best-effort
        by design, the scrape below works either way."""
        self._replica = replica
        for session in (replica.leader, getattr(replica, "helper", None)):
            if session is None:
                continue
            if callable(getattr(session, "set_journal", None)):
                session.set_journal(self.journal)
            # Only rebind utilization where the session had tracking on
            # (config.utilization=False stays off).
            if getattr(session, "_util", None) is not None and callable(
                getattr(session, "set_utilization", None)
            ):
                session.set_utilization(self.utilization)
            registry = getattr(session, "metrics", None)
            if registry is not None and all(
                registry is not r for r in self._registries
            ):
                self._registries.append(registry)
        managers = (
            replica.managers() if callable(getattr(replica, "managers", None))
            else []
        )
        for manager in managers:
            if callable(getattr(manager, "set_journal", None)):
                manager.set_journal(self.journal)
        prober = getattr(replica, "prober", None)
        if prober is not None and callable(
            getattr(prober, "set_journal", None)
        ):
            prober.set_journal(self.journal)
        self._samplers = [
            MetricsSampler(
                store=self.store,
                registry=registry,
                utilization=self.utilization if i == 0 else None,
                journal=self.journal,
                clock=self._clock,
            )
            for i, registry in enumerate(self._registries)
        ]
        return self

    def sample_once(self, now: Optional[float] = None) -> int:
        """One deterministic sampling pass over every session registry
        (and the utilization tracker) into the scoped TSDB."""
        return sum(s.sample_once(now) for s in self._samplers)

    def metrics_export(self) -> dict:
        """The replica's registries merged to one flat registry-shaped
        export (leader + helper summed where names collide, histogram
        buckets merged)."""
        if not self._registries:
            return {"counters": {}, "gauges": {}, "histograms": {}}
        if len(self._registries) == 1:
            return self._registries[0].export()
        return federation.merged_flat(
            {
                f"party{i}": registry.export()
                for i, registry in enumerate(self._registries)
            }
        )

    def request_count(self) -> int:
        """Requests served, read from the `*.request_ms` histogram
        counts every session exports — the fleet QPS series derives
        from deltas of this. Uses the registry's cheap count accessor
        when present (a full export sorts every reservoir; this runs on
        every fleet sample)."""
        total = 0
        for registry in self._registries:
            if callable(getattr(registry, "histogram_counts", None)):
                total += sum(
                    registry.histogram_counts(".request_ms").values()
                )
                continue
            for name, hist in registry.export().get(
                "histograms", {}
            ).items():
                base = name.split("{", 1)[0]
                if base.endswith(".request_ms"):
                    total += int(hist.get("count", 0))
        return total

    def set_workload(self, observatory) -> "ReplicaTelemetry":
        """Attach (or replace) this replica's workload observatory and
        wire its gauge source into the scoped samplers so its headline
        numbers become TSDB series on every sampling pass."""
        self.workload = observatory
        if observatory is not None:
            for sampler in self._samplers:
                sampler.add_extra_source(observatory.gauge_source)
        return self

    def scrape(self) -> dict:
        """Everything the aggregator (or a future RPC scraper) needs,
        as one plain dict."""
        out = {
            "replica_id": self.replica_id,
            "metrics": self.metrics_export(),
            "journal": self.journal.export(),
            "utilization": self.utilization.export(),
            "timeseries": {
                "series_count": self.store.export()["series_count"],
                "dropped_series": self.store.export().get(
                    "dropped_series", 0
                ),
            },
        }
        if self.workload is not None:
            out["workload"] = self.workload.export()
        return out


class FleetTelemetry:
    """Aggregate N `ReplicaTelemetry` scopes into one fleet view."""

    def __init__(
        self,
        replica_set,
        *,
        router=None,
        rotation=None,
        probe=None,
        journal: Optional[EventJournal] = None,
        objectives: Optional[List[SloObjective]] = None,
        store: Optional[TimeSeriesStore] = None,
        registry: Optional[MetricsRegistry] = None,
        clock=time.monotonic,
        name: str = "fleet",
    ):
        self._set = replica_set
        self._router = router
        self._rotation = rotation
        self._probe = probe
        self._name = name
        self._clock = clock
        # Fleet-level events (fleet.replica_state, fleet.rotation,
        # fleet.spillover_storm ...) live on whatever journal the fleet
        # components were built with; default: the process journal.
        self.journal = (
            journal if journal is not None else events_mod.default_journal()
        )
        self.registry = registry if registry is not None else MetricsRegistry()
        self.store = (
            store
            if store is not None
            else TimeSeriesStore(max_series=64, clock=clock)
        )
        self.slo = SloTracker(
            objectives
            if objectives is not None
            else default_fleet_objectives(),
            self.registry,
            clock=clock,
        )
        self._lock = threading.Lock()
        self._scopes: Dict[str, ReplicaTelemetry] = {}
        self._bundles = None
        self._samples = 0
        self._last_sample_mono: Optional[float] = None
        # replica_id -> (request_count, t_mono) marks for QPS deltas
        self._qps_marks: Dict[str, tuple] = {}
        self._qps: Dict[str, float] = {}

    # -- scoping -------------------------------------------------------------

    def scope(self, replica, **kwargs) -> ReplicaTelemetry:
        """Create a `ReplicaTelemetry` for `replica`, adopt it into the
        replica's components, and register it with the aggregator."""
        telemetry = ReplicaTelemetry(
            replica.replica_id, clock=self._clock, **kwargs
        ).adopt(replica)
        return self.attach(telemetry)

    def attach(self, telemetry: ReplicaTelemetry) -> ReplicaTelemetry:
        with self._lock:
            self._scopes[telemetry.replica_id] = telemetry
            bundles = self._bundles
        if bundles is not None:
            self._add_replica_source(bundles, telemetry)
        return telemetry

    def scopes(self) -> Dict[str, ReplicaTelemetry]:
        with self._lock:
            return dict(self._scopes)

    def set_router(self, router):
        self._router = router
        return router

    def set_rotation(self, rotation):
        self._rotation = rotation
        return rotation

    def set_probe(self, probe):
        self._probe = probe
        return probe

    # -- sampling ------------------------------------------------------------

    def _routable(self) -> int:
        try:
            return len(self._set.healthy())
        except Exception:  # noqa: BLE001 - sampling never raises
            return 0

    def sample(self, now: Optional[float] = None) -> dict:
        """One aggregation pass: refresh every fleet gauge from the live
        fleet objects, drive each scope's sampler, append the fleet TSDB
        series, and grade the fleet SLOs (burn listeners — the bundle
        trigger — fire from here). Returns the derived values."""
        if now is None:
            now = self._clock()
        scopes = self.scopes()
        for telemetry in scopes.values():
            telemetry.sample_once(now)

        gauges = self.registry
        routable = self._routable()
        gauges.gauge("fleet.routable_replicas").set(float(routable))

        # Per-replica QPS from request-count deltas; fleet QPS is the sum.
        fleet_qps = 0.0
        for rid, telemetry in scopes.items():
            count = telemetry.request_count()
            mark = self._qps_marks.get(rid)
            self._qps_marks[rid] = (count, now)
            if mark is not None and now > mark[1]:
                rate = max(0.0, (count - mark[0]) / (now - mark[1]))
                self._qps[rid] = round(rate, 3)
                gauges.gauge(
                    "fleet.replica_qps", labels={"replica": rid}
                ).set(self._qps[rid])
        fleet_qps = round(sum(self._qps.values()), 3)
        gauges.gauge("fleet.qps").set(fleet_qps)

        # Fleet duty cycle: mean of the replicas' trackers (a percent
        # sums wrong — federation's mean rule, applied at the source).
        duties = [
            telemetry.utilization.last_duty_cycle_pct()
            for telemetry in scopes.values()
        ]
        duties = [d for d in duties if d is not None]
        duty = round(sum(duties) / len(duties), 3) if duties else None
        if duty is not None:
            gauges.gauge("fleet.duty_cycle_pct").set(duty)

        # Per-replica generation lag behind the fleet max.
        lags: Dict[str, int] = {}
        try:
            generations = self._set.generations()
        except Exception:  # noqa: BLE001
            generations = {}
        if generations:
            newest = max(generations.values())
            for rid, generation in generations.items():
                lags[rid] = int(newest - generation)
                gauges.gauge(
                    "fleet.generation_lag", labels={"replica": rid}
                ).set(float(lags[rid]))

        if self._router is not None and callable(
            getattr(self._router, "spillover_rate_pct", None)
        ):
            gauges.gauge("fleet.spillover_rate_pct").set(
                self._router.spillover_rate_pct()
            )
        if self._rotation is not None:
            report = (self._rotation.export() or {}).get("last_report")
            if report and report.get("staleness_ms") is not None:
                gauges.gauge("fleet.rotation_staleness_ms").set(
                    float(report["staleness_ms"])
                )
        if self._probe is not None and callable(
            getattr(self._probe, "last_pass_age_s", None)
        ):
            age = self._probe.last_pass_age_s()
            if age is not None:
                gauges.gauge("fleet.divergence_probe_age_s").set(
                    round(age, 3)
                )

        # Fleet TSDB series (the `extra_sources` shape, recorded here
        # directly so a deployment without a sampler thread still gets
        # series on every sample()).
        series = self.fleet_series()
        for series_name, value in series.items():
            self.store.record(series_name, value, t=now)

        self.slo.evaluate()
        with self._lock:
            self._samples += 1
            self._last_sample_mono = now
        return {
            "routable": routable,
            "qps": fleet_qps,
            "duty_cycle_pct": duty,
            "generation_lag": lags,
            "series": series,
        }

    def fleet_series(self) -> Dict[str, float]:
        """Current fleet gauge values as `{series_name: value}` — also
        usable verbatim as a `MetricsSampler` extra source."""
        out: Dict[str, float] = {}
        for name, value in self.registry.export().get("gauges", {}).items():
            base, labels = split_labeled_name(name)
            rid = labels.get("replica")
            # Labeled per-replica gauges become dotted series names
            # (`fleet.generation_lag.r1`) — TSDB series are flat.
            out[f"{base}.{rid}" if rid is not None else base] = value
        return out

    # -- merged views --------------------------------------------------------

    def metrics(self) -> dict:
        """The merged fleet metrics view: per-replica rows + aggregates
        (federation merge rules), plus the fleet's own derived
        instruments under `fleet`."""
        scopes = self.scopes()
        merged = federation.merge_metrics(
            {
                rid: telemetry.metrics_export()
                for rid, telemetry in scopes.items()
            }
        )
        merged["fleet"] = self.registry.export()
        return merged

    def timeline(
        self,
        n: Optional[int] = None,
        kind: Optional[str] = None,
        min_severity: Optional[str] = None,
    ) -> dict:
        """The fleet timeline: every replica's scoped journal plus the
        fleet-level journal, interleaved on the rebased clock."""
        scopes = self.scopes()
        journals: Dict[str, object] = {
            rid: telemetry.journal.export()
            for rid, telemetry in scopes.items()
        }
        journals[self._name] = self.journal.export()
        return federation.merge_timelines(
            journals, n=n, kind=kind, min_severity=min_severity
        )

    def healthz(self) -> dict:
        """The fleet-level health verdict: hard fleet-SLO breaches (on
        freshly sampled gauges) degrade it, exactly like a session's
        `/healthz`."""
        self.sample()
        breaches = self.slo.breaches(evaluate=True)
        try:
            states = {
                r.replica_id: self._set.state(r.replica_id)
                for r in self._set.replicas()
            }
        except Exception:  # noqa: BLE001
            states = {}
        healthy = not breaches
        return {
            "fleet": self._name,
            "status": "ok" if healthy else "degraded",
            "healthy": healthy,
            "routable": self._routable(),
            "replicas": states,
            "breaches": breaches,
        }

    def export(self) -> dict:
        """The `/fleet-statusz` state: per-replica scrapes, the merged
        metrics view, fleet SLOs, router/rotation/probe summaries."""
        scopes = self.scopes()
        per_replica = {}
        for rid, telemetry in scopes.items():
            scrape = telemetry.scrape()
            scrape["qps"] = self._qps.get(rid)
            try:
                scrape["state"] = self._set.state(rid)
            except Exception:  # noqa: BLE001
                scrape["state"] = None
            per_replica[rid] = scrape
        with self._lock:
            samples = self._samples
            last = self._last_sample_mono
        out = {
            "name": self._name,
            "replicas": per_replica,
            "merged": self.metrics(),
            "slo": self.slo.export(),
            "samples": samples,
            "last_sample_age_s": (
                round(self._clock() - last, 3) if last is not None else None
            ),
            "timeseries": {
                "series_count": self.store.export()["series_count"],
            },
        }
        workloads = {
            rid: scrape["workload"]
            for rid, scrape in per_replica.items()
            if scrape.get("workload") is not None
        }
        if workloads:
            out["workload"] = federation.merge_workloads(workloads)
        if self._router is not None:
            out["router"] = self._router.export()
        if self._rotation is not None:
            out["rotation"] = self._rotation.export()
        if self._probe is not None:
            probe_export = dict(self._probe.export())
            probe_export.pop("history", None)
            out["probe"] = probe_export
        return out

    # -- bundles -------------------------------------------------------------

    def _add_replica_source(self, bundles, telemetry: ReplicaTelemetry):
        bundles.add_source(
            f"replica_{telemetry.replica_id}", telemetry.scrape
        )

    def wire_bundles(self, bundles):
        """Register fleet-wide sources on `bundles` and arm the
        triggers: one hard fleet-SLO burn or one probe divergence
        captures ONE bundle holding every replica's section plus the
        merged timeline (the manager's cooldown keeps a storm from
        multiplying it)."""
        with self._lock:
            self._bundles = bundles
            scopes = list(self._scopes.values())
        for telemetry in scopes:
            self._add_replica_source(bundles, telemetry)
        bundles.add_source("fleet_timeline", self.timeline)
        bundles.add_source("fleet_status", self.export)
        self.slo.add_burn_listener(bundles.on_burn)
        if self._probe is not None and callable(
            getattr(self._probe, "add_failure_listener", None)
        ):
            self._probe.add_failure_listener(bundles.on_probe_failure)
        return bundles
