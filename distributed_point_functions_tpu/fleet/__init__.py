"""Replica fleet serving: registry, price-aware routing, quorum rotation.

The top layer of the serving stack: N Leader/Helper pairs composed
into one operable fleet. `registry` tracks replica health states fed
by breaker transitions and probe freshness, `router` is the sticky
price-aware front door with same-generation spillover, and `rotation`
extends the per-pair snapshot handshake to a quorum-gated fleet-wide
flip. Cross-replica bit-identity is proven by
`serving.prober.CrossReplicaProbe` (which stays in serving/ so the
layering keeps fleet -> serving one-way). `telemetry` is the fleet
telemetry plane: per-replica scopes (`ReplicaTelemetry`) and the
`FleetTelemetry` aggregator behind `/fleet-statusz` and
`/fleet-timelinez` (merge rules live in `observability/federation.py`).
"""

from .registry import REPLICA_STATES, Replica, ReplicaSet
from .rotation import FleetRotationCoordinator, QuorumFailed
from .router import FleetRouter
from .telemetry import (
    FleetTelemetry,
    ReplicaTelemetry,
    default_fleet_objectives,
)

__all__ = [
    "REPLICA_STATES",
    "Replica",
    "ReplicaSet",
    "FleetRouter",
    "FleetRotationCoordinator",
    "FleetTelemetry",
    "ReplicaTelemetry",
    "QuorumFailed",
    "default_fleet_objectives",
]
