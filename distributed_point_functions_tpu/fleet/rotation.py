"""Fleet-wide quorum rotation: stage everywhere, flip on quorum ack.

PR 12's `RotationCoordinator` rotates ONE pair atomically (stage both
parties, flip Helper-first/Leader-last). A fleet multiplies the
failure modes: a replica can die mid-stage, flip late, or come back
on the wrong generation — and a client whose two shares come from
different generations reconstructs well-formed garbage. The fleet
coordinator keeps the per-pair handshake exactly as PR 12 built it
and adds a two-phase commit across replicas:

  Phase 1 — stage generation N+1 on every non-dead replica (each pair
  stages Leader then Helper; the per-replica chaos site
  ``fleet.stage.<replica_id>`` fires between marking the replica
  `staging` and staging its managers, mirroring ``snapshot.stage``).
  A replica that faults here has its staged buffers aborted and
  becomes a laggard candidate.

  Quorum gate — if fewer than `quorum` replicas staged cleanly, the
  rotation aborts EVERYWHERE: every staged buffer is dropped, every
  state restored, and `QuorumFailed` raised. Generation N keeps
  serving on the whole fleet; nothing flipped.

  Phase 2 — flip every acked replica (Helper first, Leader last,
  per-pair staleness noted into its manager). A flip fault aborts
  that pair and demotes it to laggard; the quorum already committed,
  so the fleet moves to N+1 regardless.

  Phase 3 — each laggard is SHED from the router's candidate set
  (`draining`: no new tenants can land on a mixed-generation pair),
  then re-staged and flipped party by party — skipping any party
  already at the target generation, so a replica that flipped its
  Helper but faulted on its Leader converges instead of double-
  flipping — and readmitted on success, or marked `dead` on failure.

Mixed generations never reach one tenant: the router only spills
within the primary's generation, per-session generation pinning rides
the existing wire-v3 handshake, and laggards are out of the candidate
set until they converge.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..observability import events as events_mod
from ..robustness import failpoints
from .registry import Replica, ReplicaSet

__all__ = ["QuorumFailed", "FleetRotationCoordinator"]


class QuorumFailed(RuntimeError):
    """Raised when fewer replicas staged the new generation than the
    configured quorum; the rotation was aborted fleet-wide and the old
    generation keeps serving everywhere."""

    def __init__(self, to_generation, acked, failed, quorum):
        self.to_generation = to_generation
        self.acked = list(acked)
        self.failed = dict(failed)
        self.quorum = quorum
        super().__init__(
            f"quorum failed for generation {to_generation}: "
            f"{len(self.acked)}/{quorum} staged "
            f"(failed: {sorted(self.failed)})"
        )


class FleetRotationCoordinator:
    """Quorum-gated fleet rotation over a `ReplicaSet` (module
    docstring has the phase machine)."""

    def __init__(
        self,
        replica_set: ReplicaSet,
        *,
        quorum: Optional[int] = None,
        clock=time.monotonic,
        journal=None,
    ):
        self._set = replica_set
        self._quorum = quorum
        self._clock = clock
        self._journal = journal
        self._telemetry = None
        self._rotations = 0
        self._quorum_failures = 0
        self._last_report: Optional[dict] = None

    def set_telemetry(self, telemetry):
        """Attach a `FleetTelemetry` (duck-typed: `.sample()`) to be
        resampled right after each rotation, so
        `fleet.rotation_staleness_ms` reflects the flip immediately
        instead of at the next sampler tick."""
        self._telemetry = telemetry
        return telemetry

    # -- helpers -------------------------------------------------------------

    def _resolve_dbs(self, databases, replica: Replica) -> Tuple:
        """`databases` is either a mapping `replica_id -> (leader_db,
        helper_db)` or a callable `replica -> (leader_db, helper_db)`
        (helper_db None for a plain replica)."""
        if callable(databases):
            pair = databases(replica)
        else:
            pair = databases[replica.replica_id]
        leader_db, helper_db = pair
        if replica.helper_snapshots is not None and helper_db is None:
            raise ValueError(
                f"replica {replica.replica_id!r} has a helper manager "
                "but no helper database (the parties stage distinct "
                "database objects)"
            )
        return leader_db, helper_db

    @staticmethod
    def _abort_pair(replica: Replica, reason: str) -> None:
        for manager in replica.managers():
            manager.abort(reason)

    def _stage_pair(self, replica: Replica, leader_db, helper_db) -> dict:
        staged = {"leader_staged_bytes": replica.snapshots.stage(leader_db)}
        if replica.helper_snapshots is not None:
            staged["helper_staged_bytes"] = replica.helper_snapshots.stage(
                helper_db
            )
        return staged

    def _flip_pair(
        self, replica: Replica, timeout: float
    ) -> float:
        """Helper-first/Leader-last flip (PR 12's ordering) returning
        the pair's measured staleness window in ms."""
        t_helper = None
        if replica.helper_snapshots is not None:
            replica.helper_snapshots.flip(timeout=timeout)
            t_helper = self._clock()
        replica.snapshots.flip(timeout=timeout)
        if t_helper is None:
            return 0.0
        staleness_ms = max(0.0, (self._clock() - t_helper) * 1e3)
        replica.snapshots.note_staleness(staleness_ms)
        return round(staleness_ms, 3)

    def _converge_laggard(
        self, replica: Replica, leader_db, helper_db,
        to_generation: int, timeout: float,
    ) -> None:
        """Bring one shed laggard to the target generation, party by
        party. A party already AT the target (e.g. the Helper flipped
        before the Leader faulted) is skipped — `SnapshotManager.flip`
        at the current generation would return a stale record and
        leave a staged candidate armed."""
        pairs = [(replica.snapshots, leader_db)]
        if replica.helper_snapshots is not None:
            pairs.append((replica.helper_snapshots, helper_db))
        # Helper converges first, same ordering rationale as the flip.
        for manager, db in reversed(pairs):
            if manager.serving_generation() == to_generation:
                continue
            manager.abort(f"laggard re-stage to {to_generation}")
            manager.stage(db)
            manager.flip(timeout=timeout)

    def _emit(self, kind, message, severity="info", **fields):
        journal = (
            self._journal
            if self._journal is not None
            else events_mod.default_journal()
        )
        try:
            journal.emit(kind, message, severity=severity, **fields)
        except Exception:  # noqa: BLE001 - journaling never breaks rotation
            pass

    # -- the rotation --------------------------------------------------------

    def rotate(self, databases, timeout: float = 10.0) -> dict:
        """Run one fleet rotation (module docstring has the phases).
        Returns the report dict; raises `QuorumFailed` when staging
        fell short of quorum (in which case nothing flipped anywhere).
        """
        participants = [
            r for r in self._set.alive() if r.snapshots is not None
        ]
        if not participants:
            raise ValueError("no rotatable replicas (none have snapshots)")
        quorum = (
            self._quorum
            if self._quorum is not None
            else len(participants) // 2 + 1
        )
        if not 1 <= quorum <= len(participants):
            raise ValueError(
                f"quorum {quorum} out of range for "
                f"{len(participants)} participants"
            )
        dbs: Dict[str, Tuple] = {
            r.replica_id: self._resolve_dbs(databases, r)
            for r in participants
        }
        to_generation = dbs[participants[0].replica_id][0].generation
        per_replica: Dict[str, dict] = {}

        # Phase 1: stage everywhere.
        acked: List[Replica] = []
        failed: Dict[str, str] = {}
        for replica in participants:
            rid = replica.replica_id
            prev_state = self._set.state(rid)
            self._set.mark(rid, "staging", reason=f"stage {to_generation}")
            try:
                failpoints.fire(f"fleet.stage.{rid}")
                per_replica[rid] = self._stage_pair(replica, *dbs[rid])
                acked.append(replica)
            except Exception as e:  # noqa: BLE001 - per-replica fault domain
                self._abort_pair(replica, f"stage {to_generation}: {e}")
                failed[rid] = str(e)
                per_replica[rid] = {"stage_error": str(e)}
                self._set.mark(rid, prev_state, reason=f"stage failed: {e}")

        # Quorum gate: short of quorum, nothing flips anywhere.
        if len(acked) < quorum:
            for replica in acked:
                self._abort_pair(
                    replica,
                    f"quorum failed for generation {to_generation}",
                )
                self._set.mark(
                    replica.replica_id, "serving",
                    reason="rotation aborted (quorum failed)",
                )
            self._quorum_failures += 1
            self._emit(
                "fleet.quorum_failed",
                f"rotation to {to_generation} aborted: "
                f"{len(acked)}/{quorum} replicas staged",
                severity="error",
                to_generation=to_generation,
                acked=[r.replica_id for r in acked],
                failed=sorted(failed),
                quorum=quorum,
            )
            raise QuorumFailed(to_generation, (
                r.replica_id for r in acked), failed, quorum)

        # Phase 2: flip the acked set; flip faults demote to laggard.
        flipped: List[str] = []
        laggards: Dict[str, str] = dict(failed)
        worst_staleness = 0.0
        for replica in acked:
            rid = replica.replica_id
            try:
                staleness_ms = self._flip_pair(replica, timeout)
                per_replica[rid]["staleness_ms"] = staleness_ms
                worst_staleness = max(worst_staleness, staleness_ms)
                flipped.append(rid)
                self._set.mark(
                    rid, "serving", reason=f"serving {to_generation}"
                )
            except Exception as e:  # noqa: BLE001 - per-replica fault domain
                self._abort_pair(replica, f"flip {to_generation}: {e}")
                per_replica[rid]["flip_error"] = str(e)
                laggards[rid] = str(e)

        # Phase 3: shed each laggard, converge it, readmit or bury it.
        laggard_outcomes: Dict[str, str] = {}
        for rid, why in laggards.items():
            replica = self._set.get(rid)
            self._set.shed(
                rid, reason=f"rotation laggard at {to_generation}: {why}"
            )
            try:
                self._converge_laggard(
                    replica, *dbs[rid], to_generation, timeout
                )
                self._set.readmit(
                    rid, reason=f"laggard converged to {to_generation}"
                )
                laggard_outcomes[rid] = "recovered"
            except Exception as e:  # noqa: BLE001 - per-replica fault domain
                self._abort_pair(replica, f"laggard converge: {e}")
                self._set.kill(
                    rid, reason=f"laggard unrecoverable: {e}"
                )
                laggard_outcomes[rid] = "dead"

        self._rotations += 1
        report = {
            "to_generation": to_generation,
            "quorum": quorum,
            "participants": [r.replica_id for r in participants],
            "acked": [r.replica_id for r in acked],
            "flipped": flipped,
            "laggards": laggard_outcomes,
            "staleness_ms": round(worst_staleness, 3),
            "per_replica": per_replica,
        }
        self._last_report = report
        self._emit(
            "fleet.rotation",
            f"fleet rotated to generation {to_generation}: "
            f"{len(flipped)}/{len(participants)} flipped in phase 2, "
            f"laggards {laggard_outcomes or '{}'}",
            severity="warning" if laggard_outcomes else "info",
            **{k: v for k, v in report.items() if k != "per_replica"},
        )
        if self._telemetry is not None:
            try:
                self._telemetry.sample()
            except Exception:  # noqa: BLE001 - telemetry never breaks rotation
                pass
        return report

    def export(self) -> dict:
        return {
            "rotations": self._rotations,
            "quorum_failures": self._quorum_failures,
            "last_report": self._last_report,
        }
