"""Price-aware front door: spread tenants across healthy replicas.

The router is the fleet's single admission point. Placement is
price-driven, not round-robin: each healthy replica advertises its
live `CapacityModel` price card (`price_export` — stamped with the
replica id by the registry) and its admission-queue depth, and the
router scores a replica as

    modeled device-ms per probe batch * (1 + queue_depth)

— the cheapest *idle* replica wins, and a cheap-but-backlogged
replica loses to a slightly pricier idle one. Tenants are sticky: the
first pick pins `tenant -> replica` so a tenant's session state
(wire-v3 handshake, generation pin, batcher fairness bucket) stays on
one pair, and the pin survives as long as the replica stays serving.

When the affine replica sheds (`Overloaded` from its admission
queue), the router spills to the other healthy replicas — but ONLY
those currently serving the same generation as the tenant's primary:
a spillover XOR of shares from two generations is well-formed garbage
(the CGKS'95 failure mode PR 12 exists to prevent), so a replica
mid-flip is skipped and counted rather than risked. Every attempt
runs with that replica's SnapshotManagers pinned so a fleet rotation
cannot flip a generation out from under the in-flight request.

If the whole candidate set sheds, the router raises one typed fleet
`Overloaded` aggregating the per-replica hints (smallest positive
`retry_after_s`, `reason="fleet"`), so clients see the same
backpressure contract as a single pair.

**Trace stitching.** The router roots one trace per request
(`fleet.request`) before its first attempt; the chosen session's own
`trace_request` then *joins* that trace instead of opening a new one
(nested non-fresh traces reuse the active root — `observability/
tracing.py`), so a primary-shed -> spillover-served request shows up on
`/tracez` as ONE trace carrying a `hops` list of
`(replica, attempt, reason, outcome)` records plus one `fleet.attempt`
span per replica tried. The phase recorder stamps `attrs["phases"]`
onto the same trace, so hops and phase timings ride one record.

**Spillover observability.** With a `metrics=` registry the router
counts `fleet.spillover{from=...,to=...,reason=...}` per spillover
edge, and it watches the spillover rate over a sliding window of
requests: crossing `storm_band` emits one coalesced
`fleet.spillover_storm` journal event (the predictive-capacity loop of
ROADMAP item 5 consumes the series, the operator the event).
"""

from __future__ import annotations

import collections
import contextlib
import threading
from typing import Dict, List, Optional

from ..observability import events as events_mod
from ..observability import tracing
from ..serving.batcher import Overloaded
from .registry import Replica, ReplicaSet

__all__ = ["FleetRouter"]


class FleetRouter:
    """Replica-aware request front door over a `ReplicaSet`."""

    def __init__(
        self,
        replica_set: ReplicaSet,
        *,
        price_keys: int = 8,
        journal=None,
        metrics=None,
        storm_band: float = 0.2,
        storm_window: int = 50,
        storm_coalesce_s: float = 5.0,
    ):
        self._set = replica_set
        self._price_keys = int(price_keys)
        self._journal = journal
        self._metrics = metrics
        self._storm_band = float(storm_band)
        self._storm_coalesce_s = float(storm_coalesce_s)
        self._lock = threading.Lock()
        self._affinity: Dict[str, str] = {}
        self._routed: Dict[str, int] = {}
        self._spillovers = 0
        self._generation_skips = 0
        self._fleet_sheds = 0
        self._moves = 0
        self._storms = 0
        # 1 per request that needed at least one spillover, else 0;
        # mean over the window is the live spillover rate.
        self._spill_window: collections.deque = collections.deque(
            maxlen=max(4, int(storm_window))
        )

    # -- placement -----------------------------------------------------------

    def _score(self, replica: Replica) -> float:
        """Price x backlog: modeled device-ms for a probe batch scaled
        by the live admission-queue depth."""
        price = replica.price(self._price_keys)
        return float(price["device_ms"]) * (1.0 + replica.queue_depth())

    def pick(self, tenant: str = "default") -> Replica:
        """The tenant's replica: sticky while the pinned replica stays
        serving, otherwise the cheapest-scored healthy replica (and
        the pin moves there)."""
        healthy = self._set.healthy()
        if not healthy:
            raise Overloaded(
                "no serving replicas in the fleet", reason="fleet"
            )
        by_id = {r.replica_id: r for r in healthy}
        with self._lock:
            pinned = self._affinity.get(tenant)
        if pinned in by_id:
            return by_id[pinned]
        choice = min(healthy, key=self._score)
        with self._lock:
            if pinned is not None:
                self._moves += 1
            self._affinity[tenant] = choice.replica_id
        if pinned is not None:
            self._emit(
                "fleet.affinity_moved",
                f"tenant {tenant!r}: {pinned} -> {choice.replica_id}",
                tenant=tenant,
                old=pinned,
                new=choice.replica_id,
            )
        return choice

    def _candidates(self, tenant: str) -> List[Replica]:
        """Primary first, then same-generation spillover targets.

        Cross-generation spillover is forbidden: shares XORed across
        generations reconstruct garbage, so replicas serving a
        different generation than the tenant's primary are skipped
        (and counted) rather than tried.
        """
        primary = self.pick(tenant)
        generation = primary.serving_generation()
        candidates = [primary]
        for replica in self._set.healthy():
            if replica.replica_id == primary.replica_id:
                continue
            if replica.serving_generation() != generation:
                with self._lock:
                    self._generation_skips += 1
                continue
            candidates.append(replica)
        return candidates

    # -- serving -------------------------------------------------------------

    def handle_request(
        self, request, tenant: str = "default", deadline=None
    ):
        """Serve one request on the tenant's replica, spilling over on
        admission shed; raises a fleet-typed `Overloaded` only when
        every same-generation candidate shed. The whole routing episode
        runs under one trace (see module docstring) whose `hops` attr
        records every replica tried."""
        candidates = self._candidates(tenant)
        sheds: List[Overloaded] = []
        with tracing.trace_request("fleet.request", tenant=tenant) as trace:
            hops = trace.attrs.setdefault("hops", [])
            primary_id = candidates[0].replica_id
            for i, replica in enumerate(candidates):
                rid = replica.replica_id
                reason = (
                    "primary"
                    if i == 0
                    else f"spillover:{sheds[-1].reason or 'shed'}"
                )
                if i > 0:
                    with self._lock:
                        self._spillovers += 1
                    if self._metrics is not None:
                        self._metrics.counter(
                            "fleet.spillover",
                            labels={
                                "from": primary_id,
                                "to": rid,
                                "reason": sheds[-1].reason or "shed",
                            },
                        ).inc()
                hop = {
                    "replica": rid,
                    "attempt": i,
                    "reason": reason,
                    "outcome": "shed",
                }
                hops.append(hop)
                try:
                    # Pin both parties' generations for the attempt: a
                    # fleet rotation must not flip a replica out from
                    # under an admitted request.
                    with tracing.span(
                        "fleet.attempt", replica=rid, attempt=i
                    ), contextlib.ExitStack() as stack:
                        for manager in replica.managers():
                            stack.enter_context(manager.pin())
                        response = replica.leader.handle_request(
                            request, deadline=deadline, tenant=tenant
                        )
                    hop["outcome"] = "served"
                    with self._lock:
                        self._routed[rid] = self._routed.get(rid, 0) + 1
                    self._note_spill_outcome(i > 0)
                    return response
                except Overloaded as exc:
                    sheds.append(exc)
                    continue
            self._note_spill_outcome(True)
            with self._lock:
                self._fleet_sheds += 1
            retry_hints = [
                s.retry_after_s for s in sheds if s.retry_after_s > 0
            ]
            exc = Overloaded(
                f"all {len(candidates)} candidate replicas shed "
                f"(tenant {tenant!r})",
                retry_after_s=min(retry_hints) if retry_hints else 0.0,
                reason="fleet",
            )
            self._emit(
                "fleet.shed",
                f"fleet-wide shed for tenant {tenant!r} "
                f"({len(candidates)} candidates)",
                severity="warning",
                tenant=tenant,
                candidates=len(candidates),
                retry_after_s=exc.retry_after_s,
            )
            raise exc

    def _note_spill_outcome(self, spilled: bool) -> None:
        """Feed the sliding spillover-rate window and emit the coalesced
        storm event when the rate crosses the band (only once the window
        has enough requests to mean anything)."""
        with self._lock:
            self._spill_window.append(1 if spilled else 0)
            window = len(self._spill_window)
            if window < self._spill_window.maxlen // 2:
                return
            rate = sum(self._spill_window) / window
            if rate <= self._storm_band:
                return
            self._storms += 1
        self._emit(
            "fleet.spillover_storm",
            f"spillover rate {rate * 100:.1f}% over last {window} "
            f"requests (band {self._storm_band * 100:.0f}%)",
            severity="warning",
            coalesce_key="fleet.spillover_storm",
            coalesce_s=self._storm_coalesce_s,
            rate_pct=round(rate * 100, 2),
            window=window,
        )

    def spillover_rate_pct(self) -> float:
        """Live spillover rate (percent of recent requests that needed
        at least one spillover) — the fleet SLO ceiling reads this."""
        with self._lock:
            if not self._spill_window:
                return 0.0
            return round(
                100.0 * sum(self._spill_window) / len(self._spill_window), 3
            )

    # -- reading -------------------------------------------------------------

    def affinity(self, tenant: str) -> Optional[str]:
        with self._lock:
            return self._affinity.get(tenant)

    def forget(self, tenant: str) -> None:
        with self._lock:
            self._affinity.pop(tenant, None)

    def _emit(self, kind, message, severity="info", **fields):
        journal = (
            self._journal
            if self._journal is not None
            else events_mod.default_journal()
        )
        try:
            journal.emit(kind, message, severity=severity, **fields)
        except Exception:  # noqa: BLE001 - journaling never breaks routing
            pass

    def export(self) -> dict:
        rate = self.spillover_rate_pct()
        with self._lock:
            return {
                "tenants": len(self._affinity),
                "affinity": dict(self._affinity),
                "routed": dict(self._routed),
                "spillovers": self._spillovers,
                "generation_skips": self._generation_skips,
                "fleet_sheds": self._fleet_sheds,
                "affinity_moves": self._moves,
                "spillover_rate_pct": rate,
                "spillover_storms": self._storms,
                "storm_band_pct": round(self._storm_band * 100, 1),
            }
