"""Replica fleet registry: N Leader/Helper pairs as one operable set.

PR 13 proved one logical pair over a device mesh; "millions of users"
means N non-colluding Leader/Helper pairs behind one front door — the
CGKS'95 two-server model *replicated*. Everything below this module
already exists per pair (sessions, `SnapshotManager`, breaker,
prober, `CapacityModel`); the registry is the composition layer that
tracks which pairs may take traffic.

Each `Replica` bundles one pair's handles; the `ReplicaSet` assigns
each a health state:

    serving    healthy, in the router's candidate set
    staging    mid-rotation (generation N+1 staged, not yet flipped);
               still serving generation N
    draining   shed — existing work finishes, the router skips it
               (laggard rotation, open helper-leg breaker, stale
               probes, operator shed)
    dead       removed from rotation until an operator readmits it

State is *fed*, not polled: adding a replica subscribes to its
Leader's helper-leg breaker (`open` drains the replica — a pair whose
Helper is unreachable answers degraded shares no client can unmask —
and `closed` restores it), and `refresh()` applies the same probe
staleness rule `AdminServer._healthz` serves 503s with, so the
in-process view and the per-replica `/healthz` agree. Explicit
`shed`/`readmit`/`kill` cover the rotation coordinator and operators.

Every transition is journaled (`fleet.replica_state`) and kept in a
bounded history; `export()` is the `/fleetz` admin page and the fleet
debug-bundle source.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional

from ..capacity.model import default_capacity_model
from ..observability import events as events_mod

__all__ = ["REPLICA_STATES", "Replica", "ReplicaSet"]

REPLICA_STATES = ("serving", "staging", "draining", "dead")


class Replica:
    """One Leader/Helper pair's handles, addressable by a stable id.

    `leader` is the pair's front session (`LeaderSession` or
    `PlainSession` — anything with `handle_request`/`server`/
    `metrics`); `helper` its Helper-side session when the pair is
    two-party. `leader_snapshots`/`helper_snapshots` are the parties'
    `SnapshotManager`s (rotation and per-request generation pinning
    need both); `prober` the pair's blackbox canary; `capacity` the
    pair's price model (defaults to the process model, which a
    single-process fleet shares). Construction stamps the replica id
    onto the capacity model so its price exports are attributable.
    """

    def __init__(
        self,
        replica_id: str,
        leader,
        helper=None,
        *,
        leader_snapshots=None,
        helper_snapshots=None,
        prober=None,
        capacity=None,
    ):
        self.replica_id = str(replica_id)
        self.leader = leader
        self.helper = helper
        self.snapshots = leader_snapshots
        self.helper_snapshots = helper_snapshots
        self.prober = prober
        self.capacity = (
            capacity if capacity is not None else default_capacity_model()
        )
        self.capacity.set_replica(self.replica_id)

    # -- live readings -------------------------------------------------------

    def serving_generation(self) -> int:
        if self.snapshots is not None:
            return self.snapshots.serving_generation()
        db = getattr(getattr(self.leader, "server", None), "database", None)
        return int(getattr(db, "generation", 0))

    def staging_generation(self) -> Optional[int]:
        if self.snapshots is not None:
            return self.snapshots.staging_generation()
        return None

    def managers(self) -> List:
        """The pair's SnapshotManagers (leader first), for pinning."""
        return [
            m
            for m in (self.snapshots, self.helper_snapshots)
            if m is not None
        ]

    def queue_depth(self) -> float:
        """Live admission-queue depth summed over the pair's batchers
        (the `*.batcher.queue_depth` gauges both sessions already
        export)."""
        depth = 0.0
        for session in (self.leader, self.helper):
            metrics = getattr(session, "metrics", None)
            if metrics is None:
                continue
            gauges = metrics.export().get("gauges", {})
            depth += sum(
                v
                for k, v in gauges.items()
                if k.split("{", 1)[0].endswith(".queue_depth")
            )
        return depth

    def price(self, num_keys: int = 8) -> dict:
        """This replica's price card (see `CapacityModel.price_export`)."""
        num_blocks = getattr(
            getattr(self.leader, "server", None), "_num_blocks", None
        )
        return self.capacity.price_export(num_keys, num_blocks)

    def degraded(self) -> bool:
        return bool(getattr(self.leader, "degraded", False))

    def probe_fresh(self) -> Optional[bool]:
        """Whether every identity probe kind is fresh (None without a
        prober — freshness then cannot gate health)."""
        if self.prober is None:
            return None
        freshness = self.prober.freshness()
        return all(
            v.get("fresh", True)
            for v in freshness.values()
            if v.get("identity")
        )

    def export(self) -> dict:
        breaker = None
        breaker_export = getattr(self.leader, "breaker_export", None)
        if callable(breaker_export):
            breaker = breaker_export()
        return {
            "replica_id": self.replica_id,
            "role": "pair" if self.helper is not None else "plain",
            "serving_generation": self.serving_generation(),
            "staging_generation": self.staging_generation(),
            "degraded": self.degraded(),
            "queue_depth": self.queue_depth(),
            "price": self.price(),
            "breaker": breaker,
            "probe_fresh": self.probe_fresh(),
        }


class ReplicaSet:
    """Health-stated registry of the fleet's replicas (module docstring
    has the state meanings). Thread-safe; transitions are journaled
    and counted, `export()` backs `/fleetz`."""

    def __init__(
        self,
        *,
        journal=None,
        clock=time.monotonic,
        history: int = 64,
    ):
        self._journal = journal
        self._clock = clock
        self._lock = threading.RLock()
        self._replicas: "collections.OrderedDict[str, Replica]" = (
            collections.OrderedDict()
        )
        self._states: Dict[str, str] = {}
        self._reasons: Dict[str, str] = {}
        self._since: Dict[str, float] = {}
        self._history: collections.deque = collections.deque(
            maxlen=max(1, history)
        )
        self._sheds = 0
        self._readmissions = 0
        self._deaths = 0
        self._listeners: List[Callable[[str, str, str, str], None]] = []

    # -- membership ----------------------------------------------------------

    def add(self, replica: Replica, state: str = "serving") -> Replica:
        """Register a replica and subscribe to its Leader's helper-leg
        breaker: `open` drains it (a Helperless pair serves shares no
        client can unmask), `closed` restores it."""
        if state not in REPLICA_STATES:
            raise ValueError(f"unknown replica state {state!r}")
        rid = replica.replica_id
        with self._lock:
            if rid in self._replicas:
                raise ValueError(f"replica {rid!r} already registered")
            self._replicas[rid] = replica
            self._states[rid] = state
            self._reasons[rid] = "registered"
            self._since[rid] = self._clock()
        breaker = getattr(replica.leader, "breaker", None)
        if breaker is not None:
            breaker.on_transition(
                lambda old, new, rid=rid: self._on_breaker(rid, old, new)
            )
        self._emit(
            "fleet.replica_added",
            f"replica {rid} registered ({state})",
            replica=rid,
            state=state,
        )
        return replica

    def _on_breaker(self, rid: str, old: str, new: str) -> None:
        if new == "open":
            self.mark(rid, "draining", reason="helper-leg breaker open")
        elif new == "closed":
            with self._lock:
                breaker_drained = (
                    self._states.get(rid) == "draining"
                    and "breaker" in self._reasons.get(rid, "")
                )
            if breaker_drained:
                self.mark(
                    rid, "serving", reason="helper-leg breaker closed"
                )

    # -- transitions ---------------------------------------------------------

    def add_listener(
        self, listener: Callable[[str, str, str, str], None]
    ) -> None:
        """`listener(replica_id, old_state, new_state, reason)` after
        every applied transition; exceptions are swallowed."""
        with self._lock:
            self._listeners.append(listener)

    def mark(self, rid: str, state: str, reason: str = "") -> str:
        """Transition `rid` to `state`; returns the previous state.
        Idempotent transitions (same state) only refresh the reason."""
        if state not in REPLICA_STATES:
            raise ValueError(f"unknown replica state {state!r}")
        with self._lock:
            if rid not in self._replicas:
                raise KeyError(f"unknown replica {rid!r}")
            old = self._states[rid]
            self._states[rid] = state
            self._reasons[rid] = reason
            if old != state:
                self._since[rid] = self._clock()
                self._history.append(
                    {
                        "replica": rid,
                        "from": old,
                        "to": state,
                        "reason": reason,
                        "t_mono": round(self._clock(), 3),
                    }
                )
                if state == "dead":
                    self._deaths += 1
            listeners = list(self._listeners)
        if old != state:
            self._emit(
                "fleet.replica_state",
                f"replica {rid}: {old} -> {state}"
                + (f" ({reason})" if reason else ""),
                severity="warning" if state in ("draining", "dead")
                else "info",
                replica=rid,
                old=old,
                new=state,
                reason=reason,
            )
            for listener in listeners:
                try:
                    listener(rid, old, state, reason)
                except Exception:  # noqa: BLE001 - registry must keep state
                    pass
        return old

    def shed(self, rid: str, reason: str = "shed") -> None:
        """Drain a replica out of the router's candidate set (existing
        work finishes; no new tenants land on it)."""
        with self._lock:
            self._sheds += 1
        self.mark(rid, "draining", reason=reason)

    def readmit(self, rid: str, reason: str = "readmitted") -> None:
        with self._lock:
            self._readmissions += 1
        self.mark(rid, "serving", reason=reason)

    def kill(self, rid: str, reason: str = "killed") -> None:
        self.mark(rid, "dead", reason=reason)

    # -- reading -------------------------------------------------------------

    def get(self, rid: str) -> Replica:
        with self._lock:
            return self._replicas[rid]

    def state(self, rid: str) -> str:
        with self._lock:
            return self._states[rid]

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def alive(self) -> List[Replica]:
        """Every non-dead replica (rotation's participant set)."""
        with self._lock:
            return [
                r
                for rid, r in self._replicas.items()
                if self._states[rid] != "dead"
            ]

    def healthy(self) -> List[Replica]:
        """The router's candidate set: serving or staging state
        (a staging replica still answers from its current generation —
        prestaging N+1 must not read as a fleet-wide outage), and not
        failing its own probe freshness (a pair that cannot prove
        bit-identity must not take new tenants, same rule as its
        /healthz)."""
        with self._lock:
            candidates = [
                r
                for rid, r in self._replicas.items()
                if self._states[rid] in ("serving", "staging")
            ]
        return [r for r in candidates if r.probe_fresh() is not False]

    def generations(self) -> Dict[str, int]:
        return {
            r.replica_id: r.serving_generation() for r in self.replicas()
        }

    def refresh(self) -> Dict[str, str]:
        """Apply probe freshness to health: a serving replica whose
        identity probes went stale drains (same signal its /healthz
        503s on); a drained-for-staleness replica whose probes pass
        again is restored. Returns the post-refresh state map."""
        for replica in self.replicas():
            fresh = replica.probe_fresh()
            if fresh is None:
                continue
            rid = replica.replica_id
            with self._lock:
                state = self._states[rid]
                reason = self._reasons.get(rid, "")
            if state == "serving" and not fresh:
                self.mark(rid, "draining", reason="identity probes stale")
            elif state == "draining" and fresh and "stale" in reason:
                self.mark(rid, "serving", reason="identity probes fresh")
        with self._lock:
            return dict(self._states)

    # -- export --------------------------------------------------------------

    def _emit(self, kind, message, severity="info", **fields):
        journal = (
            self._journal
            if self._journal is not None
            else events_mod.default_journal()
        )
        try:
            journal.emit(kind, message, severity=severity, **fields)
        except Exception:  # noqa: BLE001 - journaling never breaks the fleet
            pass

    def export(self) -> dict:
        """The /fleetz view: per-replica state + live readings, state
        counts, transition history."""
        now = self._clock()
        with self._lock:
            rows = {}
            for rid, replica in self._replicas.items():
                row = replica.export()
                row["state"] = self._states[rid]
                row["reason"] = self._reasons.get(rid, "")
                row["since_s"] = round(now - self._since[rid], 3)
                rows[rid] = row
            counts: Dict[str, int] = {s: 0 for s in REPLICA_STATES}
            for state in self._states.values():
                counts[state] += 1
            return {
                "replicas": rows,
                "counts": counts,
                "sheds": self._sheds,
                "readmissions": self._readmissions,
                "deaths": self._deaths,
                "history": [dict(r) for r in self._history],
            }
