"""PIR wire messages and their binary codec.

Mirrors the message structure of the reference's
`pir/private_information_retrieval.proto`:

* `PirRequest` wraps one of `PlainRequest` (a batch of DPF keys,
  `:105-107`), `LeaderRequest` (plain request + encrypted helper request,
  `:110-116`), or `EncryptedHelperRequest` (opaque ciphertext, `:119-123`).
* `HelperRequest` = plain request + one-time-pad seed (`:126-133`) — this is
  the message that travels encrypted from client to helper.
* `PirResponse` carries one masked response byte-string per query
  (`:69-74`).

The codec is a compact deterministic binary format (length-prefixed,
little-endian); the proto-compatible serialization lives in
`distributed_point_functions_tpu.protos`. The helper request must be *bytes*
on the wire because the encryption seam (`EncryptHelperRequestFn`,
`dpf_pir_client.h:43-45`) operates on serialized messages.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List, Optional, Sequence

from ..dpf import CorrectionWord, DistributedPointFunction, DpfKey

# ---------------------------------------------------------------------------
# Message dataclasses
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PlainRequest:
    dpf_keys: List[DpfKey]


@dataclasses.dataclass
class EncryptedHelperRequest:
    encrypted_request: bytes


@dataclasses.dataclass
class LeaderRequest:
    plain_request: PlainRequest
    encrypted_helper_request: EncryptedHelperRequest


@dataclasses.dataclass
class HelperRequest:
    plain_request: PlainRequest
    one_time_pad_seed: bytes


@dataclasses.dataclass
class PirRequest:
    """Exactly one of the fields is set (the proto oneof)."""

    plain_request: Optional[PlainRequest] = None
    leader_request: Optional[LeaderRequest] = None
    encrypted_helper_request: Optional[EncryptedHelperRequest] = None


@dataclasses.dataclass
class DpfPirResponse:
    masked_response: List[bytes]


@dataclasses.dataclass
class PirResponse:
    dpf_pir_response: DpfPirResponse


@dataclasses.dataclass
class DenseDpfPirRequestClientState:
    one_time_pad_seed: bytes


# ---------------------------------------------------------------------------
# Binary codec
# ---------------------------------------------------------------------------


def _pack_bytes(b: bytes) -> bytes:
    return struct.pack("<I", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("truncated message")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def bytes_field(self) -> bytes:
        return self.take(self.u32())


def _serialize_values(dpf: DistributedPointFunction, hierarchy_level: int,
                      values: Sequence) -> bytes:
    vt = dpf.parameters[hierarchy_level].value_type
    out = struct.pack("<I", len(values))
    for v in values:
        out += vt.value_to_bytes(v)
    return out


def _parse_values(dpf: DistributedPointFunction, hierarchy_level: int,
                  r: _Reader) -> list:
    vt = dpf.parameters[hierarchy_level].value_type
    n = r.u32()
    return [vt.value_from_bytes(r.take(vt.value_byte_size())) for _ in range(n)]


def serialize_dpf_key(dpf: DistributedPointFunction, key: DpfKey) -> bytes:
    """Encode a DpfKey for the given DPF's parameters."""
    out = [key.seed.to_bytes(16, "little"), bytes([key.party])]
    out.append(struct.pack("<I", len(key.correction_words)))
    for i, cw in enumerate(key.correction_words):
        out.append(cw.seed.to_bytes(16, "little"))
        out.append(bytes([cw.control_left | (cw.control_right << 1)]))
        if cw.value_correction is None:
            out.append(struct.pack("<I", 0xFFFFFFFF))
        else:
            hl = dpf._tree_to_hierarchy[i]
            out.append(_serialize_values(dpf, hl, cw.value_correction))
    out.append(
        _serialize_values(
            dpf, len(dpf.parameters) - 1, key.last_level_value_correction
        )
    )
    return b"".join(out)


def parse_dpf_key(dpf: DistributedPointFunction, r: _Reader) -> DpfKey:
    seed = int.from_bytes(r.take(16), "little")
    party = r.take(1)[0]
    ncw = r.u32()
    cws = []
    for i in range(ncw):
        cw_seed = int.from_bytes(r.take(16), "little")
        ctl = r.take(1)[0]
        marker = struct.unpack("<I", r.data[r.pos : r.pos + 4])[0]
        if marker == 0xFFFFFFFF:
            r.take(4)
            vc = None
        else:
            hl = dpf._tree_to_hierarchy.get(i)
            if hl is None:
                raise ValueError(
                    f"value correction present at tree level {i} which is "
                    "not an output level"
                )
            vc = _parse_values(dpf, hl, r)
        cws.append(
            CorrectionWord(
                seed=cw_seed,
                control_left=bool(ctl & 1),
                control_right=bool(ctl & 2),
                value_correction=vc,
            )
        )
    last_vc = _parse_values(dpf, len(dpf.parameters) - 1, r)
    return DpfKey(
        seed=seed,
        party=party,
        correction_words=cws,
        last_level_value_correction=last_vc,
    )


def serialize_helper_request(
    dpf: DistributedPointFunction, request: HelperRequest
) -> bytes:
    out = [struct.pack("<I", len(request.plain_request.dpf_keys))]
    for key in request.plain_request.dpf_keys:
        out.append(_pack_bytes(serialize_dpf_key(dpf, key)))
    out.append(_pack_bytes(request.one_time_pad_seed))
    return b"".join(out)


def parse_helper_request(
    dpf: DistributedPointFunction, data: bytes
) -> HelperRequest:
    r = _Reader(data)
    nkeys = r.u32()
    keys = []
    for _ in range(nkeys):
        keys.append(parse_dpf_key(dpf, _Reader(r.bytes_field())))
    seed = r.bytes_field()
    return HelperRequest(
        plain_request=PlainRequest(dpf_keys=keys), one_time_pad_seed=seed
    )
