"""PIR wire messages and their binary codec.

Mirrors the message structure of the reference's
`pir/private_information_retrieval.proto`:

* `PirRequest` wraps one of `PlainRequest` (a batch of DPF keys,
  `:105-107`), `LeaderRequest` (plain request + encrypted helper request,
  `:110-116`), or `EncryptedHelperRequest` (opaque ciphertext, `:119-123`).
* `HelperRequest` = plain request + one-time-pad seed (`:126-133`) — this is
  the message that travels encrypted from client to helper.
* `PirResponse` carries one masked response byte-string per query
  (`:69-74`).

The wire codec is the proto schema itself (wire-compatible with the
reference; see `../serialization.py` and `../protos/`). The helper request
must be *bytes* on the wire because the encryption seam
(`EncryptHelperRequestFn`, `dpf_pir_client.h:43-45`) operates on serialized
messages.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..dpf import DistributedPointFunction, DpfKey

# ---------------------------------------------------------------------------
# Message dataclasses
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PlainRequest:
    dpf_keys: List[DpfKey]


@dataclasses.dataclass
class EncryptedHelperRequest:
    encrypted_request: bytes


@dataclasses.dataclass
class LeaderRequest:
    plain_request: PlainRequest
    encrypted_helper_request: EncryptedHelperRequest


@dataclasses.dataclass
class HelperRequest:
    plain_request: PlainRequest
    one_time_pad_seed: bytes


@dataclasses.dataclass
class PirRequest:
    """Exactly one of the fields is set (the proto oneof)."""

    plain_request: Optional[PlainRequest] = None
    leader_request: Optional[LeaderRequest] = None
    encrypted_helper_request: Optional[EncryptedHelperRequest] = None


@dataclasses.dataclass
class DpfPirResponse:
    masked_response: List[bytes]


@dataclasses.dataclass
class PirResponse:
    dpf_pir_response: DpfPirResponse


@dataclasses.dataclass
class DenseDpfPirRequestClientState:
    one_time_pad_seed: bytes


# ---------------------------------------------------------------------------
# Wire codec (proto-based; see ../serialization.py)
# ---------------------------------------------------------------------------


def serialize_helper_request(
    dpf: DistributedPointFunction, request: HelperRequest
) -> bytes:
    """Proto wire format (`DpfPirRequest.HelperRequest`) — what travels
    encrypted from the client to the helper, byte-compatible with the
    reference (`dense_dpf_pir_client.cc:109-113`)."""
    from .. import serialization

    return serialization.helper_request_to_proto(
        dpf, request
    ).SerializeToString()


def parse_helper_request(
    dpf: DistributedPointFunction, data: bytes
) -> HelperRequest:
    from .. import serialization
    from ..protos import pir_pb2

    proto = pir_pb2.DpfPirRequest.HelperRequest()
    if not proto.ParseFromString(data):
        # ParseFromString returns bytes consumed; zero-length data is valid
        # proto3 (all defaults) but an empty helper request is not useful.
        raise ValueError("request does not encrypt a valid HelperRequest")
    return serialization.helper_request_from_proto(dpf, proto)
