"""Dense PIR client (`pir/dense_dpf_pir_client.h`, `.cc:41-163`).

Per queried index the client generates a two-party DPF key pair with
`alpha = index // 128` and `beta = 1 << (index % 128)` (one selection bit
inside a 128-bit block, `dense_dpf_pir_client.cc:92-103`), assembles a
`LeaderRequest` carrying its own share plus the helper's share encrypted via
the injected `encrypter` callback, and later unmasks the response with the
AES-CTR one-time pad it seeded.
"""

from __future__ import annotations

import math
from typing import Callable, List, Sequence, Tuple

from ..dpf import DistributedPointFunction, DpfParameters
from ..prng import Aes128CtrSeededPrng, generate_seed, xor_bytes
from ..value_types import XorType
from . import messages

# encrypter(plaintext: bytes, context_info: bytes) -> bytes
EncryptHelperRequestFn = Callable[[bytes, bytes], bytes]

ENCRYPTION_CONTEXT_INFO = b"DpfPirServer"
BITS_PER_BLOCK = 128


class DenseDpfPirClient:
    """Client for `DenseDpfPirServer`."""

    def __init__(
        self,
        database_size: int,
        encrypter: EncryptHelperRequestFn,
        encryption_context_info: bytes = ENCRYPTION_CONTEXT_INFO,
    ):
        if database_size <= 0:
            raise ValueError("database_size must be positive")
        if encrypter is None:
            raise ValueError("encrypter must not be None")
        self._database_size = database_size
        self._encrypter = encrypter
        self._encryption_context_info = encryption_context_info
        log_domain_size = max(0, math.ceil(math.log2(database_size)))
        self._dpf = DistributedPointFunction.create(
            DpfParameters(
                log_domain_size=log_domain_size, value_type=XorType(128)
            )
        )

    @classmethod
    def create(
        cls,
        database_size: int,
        encrypter: EncryptHelperRequestFn,
        encryption_context_info: bytes = ENCRYPTION_CONTEXT_INFO,
    ) -> "DenseDpfPirClient":
        return cls(database_size, encrypter, encryption_context_info)

    @property
    def dpf(self) -> DistributedPointFunction:
        return self._dpf

    def _generate_key_pairs(self, query_indices: Sequence[int]):
        alphas, betas = [], []
        for query in query_indices:
            if query < 0:
                raise ValueError("all query_indices must be non-negative")
            if query >= self._database_size:
                raise ValueError("all query_indices must be in bounds")
            alphas.append(query // BITS_PER_BLOCK)
            betas.append(1 << (query % BITS_PER_BLOCK))
        # Batched: all keys' tree levels in lockstep (one AES batch per
        # level instead of a per-key Python recurrence).
        return self._dpf.generate_keys_batch(alphas, betas)

    def create_request(
        self, query_indices: Sequence[int]
    ) -> Tuple["messages.PirRequest", "messages.DenseDpfPirRequestClientState"]:
        """Build a LeaderRequest plus the client state needed to unmask."""
        leader_keys, helper_keys = self._generate_key_pairs(query_indices)
        otp_seed = generate_seed()
        helper_request = messages.HelperRequest(
            plain_request=messages.PlainRequest(dpf_keys=helper_keys),
            one_time_pad_seed=otp_seed,
        )
        ciphertext = self._encrypter(
            messages.serialize_helper_request(self._dpf, helper_request),
            self._encryption_context_info,
        )
        request = messages.PirRequest(
            leader_request=messages.LeaderRequest(
                plain_request=messages.PlainRequest(dpf_keys=leader_keys),
                encrypted_helper_request=messages.EncryptedHelperRequest(
                    encrypted_request=ciphertext
                ),
            )
        )
        return request, messages.DenseDpfPirRequestClientState(
            one_time_pad_seed=otp_seed
        )

    def create_plain_requests(
        self, query_indices: Sequence[int]
    ) -> Tuple["messages.PirRequest", "messages.PirRequest"]:
        """Two plain requests (one per party) — the test/request-generator
        path (`pir/testing/request_generator.h:34-62`)."""
        leader_keys, helper_keys = self._generate_key_pairs(query_indices)
        return (
            messages.PirRequest(
                plain_request=messages.PlainRequest(dpf_keys=leader_keys)
            ),
            messages.PirRequest(
                plain_request=messages.PlainRequest(dpf_keys=helper_keys)
            ),
        )

    def handle_response(
        self,
        response: "messages.PirResponse",
        client_state: "messages.DenseDpfPirRequestClientState",
    ) -> List[bytes]:
        """Unmask the combined Leader response with the one-time pad."""
        masked = response.dpf_pir_response.masked_response
        if not masked:
            raise ValueError("masked_response must not be empty")
        if not client_state.one_time_pad_seed:
            raise ValueError("one_time_pad_seed must not be empty")
        prng = Aes128CtrSeededPrng(client_state.one_time_pad_seed)
        return [
            xor_bytes(r, prng.get_random_bytes(len(r))) for r in masked
        ]
