"""Dense in-memory PIR database living in device HBM.

The reference packs all records into one 128-bit-aligned host buffer and
XORs with Highway SIMD (`pir/dense_dpf_pir_database.h:101-111`,
`.cc:112-161`). The TPU redesign packs records into a single
`uint32[num_records_padded, record_words]` array resident in HBM: every
record is zero-padded to the maximum record size, and the record count is
padded to a multiple of 128 so whole selection blocks line up with rows.
`inner_product_with` runs the jitted XOR-reduction kernel
(`ops/inner_product.py`) over the entire query batch in one database pass.
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from ..ops.inner_product import xor_inner_product


class DenseDpfPirDatabase:
    """Immutable dense database; construct via `DenseDpfPirDatabase.Builder`."""

    class Builder:
        def __init__(self):
            self._records: List[bytes] = []

        def insert(self, value: bytes) -> "DenseDpfPirDatabase.Builder":
            if isinstance(value, str):
                value = value.encode()
            self._records.append(bytes(value))
            return self

        def clone(self) -> "DenseDpfPirDatabase.Builder":
            b = DenseDpfPirDatabase.Builder()
            b._records = list(self._records)
            return b

        def build(self) -> "DenseDpfPirDatabase":
            return DenseDpfPirDatabase(self._records)

    def __init__(self, records: Sequence[bytes]):
        self._records = [bytes(r) for r in records]
        self._max_value_size = max((len(r) for r in self._records), default=0)
        num_records = len(self._records)
        self._num_padded = max(128, ((num_records + 127) // 128) * 128)
        record_bytes = max(4, ((self._max_value_size + 3) // 4) * 4)
        buf = np.zeros((self._num_padded, record_bytes), dtype=np.uint8)
        for i, r in enumerate(self._records):
            buf[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
        self._db_words = jnp.asarray(
            np.ascontiguousarray(buf).view("<u4").astype(np.uint32)
        )

    @property
    def size(self) -> int:
        """Number of records."""
        return len(self._records)

    @property
    def max_value_size(self) -> int:
        return self._max_value_size

    @property
    def num_selection_bits(self) -> int:
        """Selection bits a query must provide (padded record count)."""
        return self._num_padded

    @property
    def num_selection_blocks(self) -> int:
        return self._num_padded // 128

    @property
    def db_words(self) -> jnp.ndarray:
        """uint32[num_records_padded, record_words] HBM-resident buffer."""
        return self._db_words

    def record(self, i: int) -> bytes:
        return self._records[i]

    def inner_product_with(self, selections: jnp.ndarray) -> List[bytes]:
        """XOR of all records whose selection bit is 1, per query.

        `selections`: uint32[num_queries, B, 4] packed blocks with
        B * 128 >= num_selection_bits. Returns one byte-string of
        `max_value_size` per query (the reference's result convention,
        `inner_product_hwy.cc:271-272`).
        """
        if selections.ndim != 3 or selections.shape[-1] != 4:
            raise ValueError("selections must be uint32[nq, B, 4]")
        if selections.shape[1] * 128 < self.size:
            raise ValueError(
                f"selections contain {selections.shape[1] * 128} bits, "
                f"expected at least {self.size}"
            )
        needed = self.num_selection_blocks
        if selections.shape[1] > needed:
            selections = selections[:, :needed]
        elif selections.shape[1] < needed:
            pad = needed - selections.shape[1]
            selections = jnp.pad(selections, ((0, 0), (0, pad), (0, 0)))
        out = np.asarray(xor_inner_product(self._db_words, selections))
        raw = np.ascontiguousarray(out.astype("<u4")).view(np.uint8)
        return [
            raw[q, : self._max_value_size].tobytes()
            for q in range(out.shape[0])
        ]
