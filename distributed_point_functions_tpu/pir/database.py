"""Dense in-memory PIR database living in device HBM.

The reference packs all records into one 128-bit-aligned host buffer and
XORs with Highway SIMD (`pir/dense_dpf_pir_database.h:101-111`,
`.cc:112-161`). The TPU redesign packs records into a single
`uint32[num_records_padded, record_words]` array resident in HBM: every
record is zero-padded to the maximum record size, and the record count is
padded to a multiple of 128 so whole selection blocks line up with rows.

`inner_product_with` serves the whole query batch in one database pass
through a tier chain: on TPU the v2 Pallas MXU kernel first
(`ops/inner_product_pallas.py:xor_inner_product_pallas2_staged`, one
large int8 dot per tile, bit-major layout staged once on first use);
then the v1 Pallas kernel and the pure-jnp MXU bit-plane path (same
math, no Mosaic dependency; both f32-exact only to 2^24 records); and
finally — elsewhere (CPU tests) or on any failure — the jitted jnp
XOR-reduction. Set
``DPF_TPU_INNER_PRODUCT=pallas2|pallas|bitplane|jnp`` to force a tier
(forced tiers propagate their errors instead of falling through).
"""

from __future__ import annotations

import os
import threading
import time
import warnings
import weakref
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.device import default_telemetry

from ..ops.inner_product import (
    xor_inner_product,
    xor_inner_product_bitplane,
)
from ..ops.inner_product_pallas import (
    MAX_RECORDS_EXACT,
    permute_db_bitmajor,
    xor_inner_product_pallas2_staged,
    xor_inner_product_pallas_staged,
)


def _v2_tile_knobs() -> dict:
    """Serving-time tile overrides for the v2 MXU kernel
    (DPF_TPU_IP_TQ / DPF_TPU_IP_TG / DPF_TPU_IP_JC), so a capture window
    can A/B the serving path's own tiles without code edits. Unset,
    malformed, or invalid values keep the kernel defaults — a bad knob
    must not knock the pallas2 tier out of serving for the process."""
    knobs = {}
    for env, key, valid in (
        ("DPF_TPU_IP_TQ", "tile_queries", lambda v: v > 0),
        ("DPF_TPU_IP_TG", "tile_groups", lambda v: v > 0),
        ("DPF_TPU_IP_JC", "j_chunk", lambda v: v > 0 and 32 % v == 0),
    ):
        raw = os.environ.get(env, "")
        if not raw:
            continue
        try:
            val = int(raw)
        except ValueError:
            val = None
        if val is None or not valid(val):
            warnings.warn(
                f"{env}={raw!r} is not a valid {key}; keeping the "
                "kernel default"
            )
            continue
        knobs[key] = val
    return knobs


_PIPELINE_ENV = "DPF_TPU_PIPELINED_STAGING"


def pipelined_staging_enabled() -> bool:
    """Whether chunked device stagings upload piece-by-piece on JAX's
    async dispatch stream (one final sync) instead of one synchronous
    full-image `device_put`. On by default; set
    DPF_TPU_PIPELINED_STAGING=0 to restore the upfront path (the
    bench's A/B baseline). Read per staging call so tests and capture
    windows can flip it without rebuilding databases."""
    return os.environ.get(_PIPELINE_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


def _stage_pieces_pipelined(ledger, pieces, assemble,
                            phase: str = "db_staging"):
    """Pipelined H2D staging: each host piece is its own counted async
    `device_put` — JAX's dispatch queue bounds the copy stream (the
    host runs at most one piece ahead of the DMA engine, the
    double-buffer depth) with no per-piece sync — then `assemble`
    combines the parts device-side and ONE final counted sync drains
    everything. The wall time between the first put returning and that
    final sync is host work (issuing the remaining uploads,
    dispatching the assembly) performed while copies were already in
    flight; the ledger accumulates it as the phase's `overlapped_ms`,
    the hidden half of the phase's transfer time."""
    parts = []
    t_first = None
    for piece in pieces:
        parts.append(ledger.device_put(piece, phase=phase))
        if t_first is None:
            t_first = time.perf_counter()
    arr = assemble(parts)
    if t_first is not None:
        ledger.record_overlap((time.perf_counter() - t_first) * 1e3, phase)
    return ledger.block_until_ready(arr, phase=phase)


def words_to_record_bytes(
    out: np.ndarray, num_keys: int, size: int
) -> List[bytes]:
    """uint32[nq, W] inner products -> per-query record byte strings.

    Little-endian words, truncated to the database's record size (the
    reference's result convention, `inner_product_hwy.cc:271-272`). The
    single home of this codec — the servers' sharded/chunked paths and the
    database all share it.
    """
    raw = np.ascontiguousarray(out[:num_keys].astype("<u4")).view(np.uint8)
    return [raw[q, :size].tobytes() for q in range(num_keys)]


class DenseDpfPirDatabase:
    """Immutable dense database; construct via `DenseDpfPirDatabase.Builder`.

    Every database carries a **generation** tag (`generation`, default
    0): a monotonically increasing snapshot version the serving runtime
    (`serving/snapshots.py`) binds batches and wire envelopes to, so a
    rotated deployment can prove both parties answered one query from
    the same data. `Builder.build_from(prev)` derives generation N+1
    from N host-side — staged `update(i, value)` rows are repacked in
    place of a full re-insert when they fit the previous layout.
    """

    class Builder:
        def __init__(self):
            self._records: List[bytes] = []
            # index -> staged replacement, applied by build()/build_from()
            self._updates: dict = {}

        def insert(self, value: bytes) -> "DenseDpfPirDatabase.Builder":
            if isinstance(value, str):
                value = value.encode()
            self._records.append(bytes(value))
            return self

        def update(self, i: int, value: bytes) -> "DenseDpfPirDatabase.Builder":
            """Stage an in-place replacement of record `i`. Under
            `build()` the index refers to this builder's inserted
            records; under `build_from(prev)` it refers to `prev`'s
            records — the delta path that makes generation N+1 cheap."""
            if isinstance(value, str):
                value = value.encode()
            i = int(i)
            if i < 0:
                raise IndexError(f"update index {i} must be >= 0")
            self._updates[i] = bytes(value)
            return self

        def clone(self) -> "DenseDpfPirDatabase.Builder":
            b = DenseDpfPirDatabase.Builder()
            b._records = list(self._records)
            b._updates = dict(self._updates)
            return b

        def build(self) -> "DenseDpfPirDatabase":
            records = list(self._records)
            for i, value in self._updates.items():
                if i >= len(records):
                    raise IndexError(
                        f"update index {i} out of bounds for "
                        f"{len(records)} inserted records"
                    )
                records[i] = value
            return DenseDpfPirDatabase(records)

        def build_from(
            self, prev: "DenseDpfPirDatabase"
        ) -> "DenseDpfPirDatabase":
            """Derive generation N+1 from database `prev` host-side:
            `prev`'s records with this builder's staged `update`s
            applied (and any `insert`ed records appended), tagged
            `prev.generation + 1`. When nothing is appended and every
            updated value fits `prev`'s packed row layout, the packed
            host buffer is copied and only the updated rows repacked —
            no per-record re-insert at directory scale."""
            for i in self._updates:
                if i >= prev.size:
                    raise IndexError(
                        f"update index {i} out of bounds for previous "
                        f"generation of {prev.size} records"
                    )
            generation = prev.generation + 1
            fits_in_place = not self._records and all(
                len(v) <= prev._max_value_size
                for v in self._updates.values()
            )
            if fits_in_place:
                return DenseDpfPirDatabase._from_delta(
                    prev, self._updates, generation
                )
            records = list(prev._records)
            for i, value in self._updates.items():
                records[i] = value
            records.extend(self._records)
            return DenseDpfPirDatabase(records, generation=generation)

    def __init__(self, records: Sequence[bytes], generation: int = 0):
        self._records = [bytes(r) for r in records]
        self._generation = int(generation)
        self._max_value_size = max((len(r) for r in self._records), default=0)
        num_records = len(self._records)
        self._num_padded = max(128, ((num_records + 127) // 128) * 128)
        record_bytes = max(4, ((self._max_value_size + 3) // 4) * 4)
        buf = np.zeros((self._num_padded, record_bytes), dtype=np.uint8)
        # Vectorized variable-length packing (chunked): a per-record Python
        # loop is minutes of host time at the sparse-PIR benchmark scale
        # (1.5 * 2^24 buckets).
        chunk = 1 << 20
        for s in range(0, num_records, chunk):
            rs = self._records[s : s + chunk]
            data = np.frombuffer(b"".join(rs), dtype=np.uint8)
            if data.size == 0:
                continue
            lengths = np.fromiter(
                (len(r) for r in rs), dtype=np.int64, count=len(rs)
            )
            ends = np.cumsum(lengths)
            starts = ends - lengths
            rows = np.repeat(np.arange(s, s + len(rs)), lengths)
            cols = np.arange(data.size, dtype=np.int64) - np.repeat(
                starts, lengths
            )
            buf[rows, cols] = data
        # Host copy; device staging is lazy so the Pallas path only ever
        # holds the bit-major layout in HBM (not both layouts).
        self._host_words = np.ascontiguousarray(buf).view("<u4").astype(
            np.uint32
        )
        self._init_runtime()

    def _init_runtime(self) -> None:
        """Device-staging slots and tier-fallback memory (fresh per
        instance — a delta build shares host bytes, never stagings)."""
        self._db_words = None  # row-major device copy (jnp fallback path)
        self._db_perm = None  # bit-major layout, staged on first pallas use
        # Bitrev-block staging (the v2 gather-free serving exit): same
        # records with 128-record blocks bit-reversal-permuted, padded
        # to a power-of-two block count. Built lazily; a process serving
        # one expansion mode holds one staging.
        self._host_rev = None
        self._db_words_rev = None
        self._db_perm_rev = None
        # Streaming staging (blocked-bitrev chunk spans), one plan at a
        # time: ((cut_levels, bitmajor[, mesh fingerprint]),
        # uint32[nc, ...] device array — mesh-sharded when staged with
        # `mesh=`).
        self._streaming_stage = None
        # Per-shard detail of the last mesh staging (statusz/bundles).
        self._mesh_staging_info = None
        # All lazy stagings build under this lock: concurrent first
        # requests must not stage the database twice (each staging is a
        # full HBM copy). Reentrant because _staged_perm -> _row_words
        # -> _host_words_bitrev nest.
        self._stage_lock = threading.RLock()
        self._failed_tiers: set = set()
        self._failed_knobs: set = set()  # v2 knob combos that crashed
        # Delta-build lineage (`_from_delta` fills these): the previous
        # generation (weakly, so a rotation chain never retains every
        # ancestor's host image) and the sorted updated row indices.
        # When the base's staging is still resident, `prestage()` /
        # `db_words` / `streaming_chunks` scatter only these rows'
        # chunks into a NEW device buffer instead of re-uploading the
        # full image.
        self._delta_base = None
        self._delta_rows = None
        # {mode, bytes_staged, bytes_full_image, bytes_saved,
        #  generation} for the most recent prestage() call —
        # snapshots/statusz/bench read the delta ratio here.
        self.last_prestage_stats = None

    @classmethod
    def _from_delta(
        cls,
        prev: "DenseDpfPirDatabase",
        updates: dict,
        generation: int,
    ) -> "DenseDpfPirDatabase":
        """Generation N+1 from N without re-inserting: copy the packed
        host buffer and repack only the updated rows. Caller guarantees
        every update index is in range and every value fits `prev`'s
        record width (`build_from` checks and falls back otherwise)."""
        db = cls.__new__(cls)
        records = list(prev._records)
        for i, value in updates.items():
            records[i] = value
        db._records = records
        db._generation = int(generation)
        db._max_value_size = prev._max_value_size
        db._num_padded = prev._num_padded
        host = prev._host_words.copy()
        record_bytes = host.shape[1] * 4
        for i, value in updates.items():
            row = np.zeros(record_bytes, dtype=np.uint8)
            row[: len(value)] = np.frombuffer(value, dtype=np.uint8)
            host[i] = row.view("<u4").astype(np.uint32)
        db._host_words = host
        db._init_runtime()
        db._delta_base = weakref.ref(prev)
        db._delta_rows = sorted(int(i) for i in updates)
        return db

    @property
    def size(self) -> int:
        """Number of records."""
        return len(self._records)

    @property
    def generation(self) -> int:
        """Snapshot generation tag (0 = untagged / initial build)."""
        return self._generation

    @property
    def max_value_size(self) -> int:
        return self._max_value_size

    @property
    def num_selection_bits(self) -> int:
        """Selection bits a query must provide (padded record count)."""
        return self._num_padded

    @property
    def num_selection_blocks(self) -> int:
        return self._num_padded // 128

    @property
    def db_words(self) -> jnp.ndarray:
        """uint32[num_records_padded, record_words] device buffer."""
        with self._stage_lock:
            if self._db_words is None:
                telemetry = default_telemetry()
                ledger = telemetry.transfers
                with telemetry.hbm.phase("db_staging"):
                    taken = False
                    try:
                        taken = self._stage_rowmajor_delta(ledger)
                    except Exception as e:  # noqa: BLE001 - full restage
                        warnings.warn(
                            "delta row-major staging failed; staging in "
                            f"full ({str(e).splitlines()[0][:200]})"
                        )
                    if not taken:
                        host = self._host_words
                        slabs = min(8, host.shape[0] // 128)
                        if pipelined_staging_enabled() and slabs >= 2:
                            self._db_words = _stage_pieces_pipelined(
                                ledger,
                                np.array_split(host, slabs),
                                lambda parts: jnp.concatenate(
                                    parts, axis=0
                                ),
                            )
                        else:
                            self._db_words = ledger.block_until_ready(
                                ledger.device_put(
                                    host, phase="db_staging"
                                ),
                                phase="db_staging",
                            )
            return self._db_words

    def _delta_base_db(self):
        """The previous generation of a delta build, while something
        still holds it alive (serving or retired-awaiting-drain);
        None otherwise — the weakref keeps a rotation chain from
        retaining every ancestor's host image."""
        ref = self._delta_base
        return ref() if ref is not None else None

    def _stage_rowmajor_delta(self, ledger) -> bool:
        """Delta staging of the row-major buffer: scatter only this
        generation's updated rows into the base generation's resident
        device buffer. `.at[rows].set` builds a NEW buffer — the base,
        possibly still serving, is never mutated — while only the
        updated rows (plus a tiny index vector) cross the PCIe bus.
        Returns True when taken; callers fall back to full staging."""
        rows = self._delta_rows
        base = self._delta_base_db()
        if rows is None or base is None:
            return False
        base_words = base._db_words
        if base_words is None or tuple(base_words.shape) != tuple(
            self._host_words.shape
        ):
            return False
        if not rows:
            # Empty delta: generation N+1's bytes are N's exactly, and
            # jax arrays are immutable, so sharing the buffer is safe.
            self._db_words = base_words
            return True
        num_rows, width = self._host_words.shape
        if len(rows) * (width + 1) >= num_rows * width:
            # The delta touches (nearly) everything: the scattered rows
            # plus the index vector would cross the bus at full-image
            # cost or worse. Stage in full instead.
            return False
        idx = np.asarray(rows, dtype=np.int32)
        vals = np.ascontiguousarray(self._host_words[idx])
        self._db_words = ledger.block_until_ready(
            base_words.at[
                ledger.device_put(idx, phase="db_staging")
            ].set(ledger.device_put(vals, phase="db_staging")),
            phase="db_staging",
        )
        return True

    def record(self, i: int) -> bytes:
        return self._records[i]

    def prestage(
        self,
        mesh=None,
        *,
        cut_levels: int | None = None,
        bitmajor: bool = False,
        shard_axis: str = "shard",
    ) -> int:
        """Eagerly stage the serving device buffer (the double-buffer
        half of a snapshot rotation: generation N+1 moves into HBM while
        N keeps serving, so the flip itself transfers nothing).

        Without `mesh`: stages the row-major single-device buffer;
        layout variants (bit-major, bitrev, streaming) still stage
        lazily on first use — except for a **delta build**
        (`Builder.build_from`), where (a) any layout the base
        generation holds resident is rebuilt by scattering only the
        updated rows/chunks into it (a new buffer; the base is never
        mutated), and (b) the base's single-device streaming staging,
        when present, is pre-built in the same layout so the post-flip
        first request is a cache hit on the streaming tier too. Mesh
        stagings always restage in full (per-device scatter of a
        sharded buffer is not worth the choreography; documented
        limitation). Returns the bytes this call moved host->device
        (0 if everything was already resident); `last_prestage_stats`
        carries {mode, bytes_staged, bytes_full_image, bytes_saved,
        generation} for snapshots, /statusz, and the bench.
        """
        if mesh is not None:
            if cut_levels is None:
                raise ValueError("prestage(mesh=...) needs cut_levels")
            with self._stage_lock:
                key = self._streaming_key(
                    cut_levels, bitmajor, mesh, shard_axis
                )
                if (
                    self._streaming_stage is not None
                    and self._streaming_stage[0] == key
                ):
                    return 0
                self.streaming_chunks(
                    cut_levels=cut_levels,
                    bitmajor=bitmajor,
                    mesh=mesh,
                    shard_axis=shard_axis,
                )
                info = self._mesh_staging_info or {}
                staged = int(info.get("total_bytes", 0))
                self.last_prestage_stats = {
                    "mode": "full",
                    "bytes_staged": staged,
                    "bytes_full_image": staged,
                    "bytes_saved": 0,
                    "generation": int(self._generation),
                }
                return staged
        telemetry = default_telemetry()
        ledger = telemetry.transfers
        with self._stage_lock:
            bytes_before = ledger.bytes_h2d("db_staging")
            full_equiv = 0
            staged_new = False
            base = (
                self._delta_base_db()
                if self._delta_rows is not None else None
            )
            # Serve-layout double buffer for delta builds: when the
            # base generation holds a resident single-device streaming
            # staging, build ours in the same layout now (the delta
            # scatter path inside streaming_chunks) instead of leaving
            # it to the post-flip first request.
            if base is not None and self._streaming_stage is None:
                with base._stage_lock:
                    bstage = base._streaming_stage
                if bstage is not None and len(bstage[0]) == 2:
                    cut, bm = bstage[0]
                    self.streaming_chunks(cut_levels=cut, bitmajor=bm)
                    full_equiv += int(self._host_words_padded().nbytes)
                    staged_new = True
            if self._db_words is None:
                _ = self.db_words
                full_equiv += int(self._host_words.nbytes)
                staged_new = True
            if not staged_new:
                return 0
            if ledger.enabled:
                staged = max(
                    0, ledger.bytes_h2d("db_staging") - bytes_before
                )
            else:
                staged = full_equiv
            saved = max(0, full_equiv - staged)
            self.last_prestage_stats = {
                "mode": (
                    "delta"
                    if self._delta_rows is not None and saved > 0
                    else "full"
                ),
                "bytes_staged": int(staged),
                "bytes_full_image": int(full_equiv),
                "bytes_saved": int(saved),
                "generation": int(self._generation),
            }
            return int(staged)

    def release_stagings(self) -> int:
        """Drop every device staging (row-major, bit-major, bitrev,
        streaming) so a retired generation's HBM is reclaimable the
        moment its last in-flight batch drains. The host buffer stays —
        re-staging is possible but a retired snapshot normally never
        serves again. Returns the number of device buffers dropped."""
        with self._stage_lock:
            dropped = 0
            for attr in (
                "_db_words", "_db_perm", "_db_words_rev", "_db_perm_rev",
            ):
                if getattr(self, attr) is not None:
                    setattr(self, attr, None)
                    dropped += 1
            if self._streaming_stage is not None:
                self._streaming_stage = None
                dropped += 1
            self._mesh_staging_info = None
            self._host_rev = None
        # One HBM sample after the drop so the db_staging watermark and
        # live-bytes gauge reflect the reclaim without waiting for the
        # next staging to bracket a phase.
        try:
            default_telemetry().hbm.sample()
        except Exception:  # noqa: BLE001 - telemetry never raises
            pass
        return dropped

    def bitrev_block_count(self) -> int:
        """Block count of the bitrev staging: the padded power of two a
        full covering-subtree expansion emits."""
        nb = self.num_selection_blocks
        return 1 << max(0, (nb - 1).bit_length())

    def _host_words_padded(self) -> np.ndarray:
        """Host rows zero-padded to the bitrev staging's block count."""
        rows = self.bitrev_block_count() * 128
        hw = self._host_words
        if rows > hw.shape[0]:
            hw = np.concatenate(
                [hw, np.zeros((rows - hw.shape[0], hw.shape[1]),
                              np.uint32)]
            )
        return hw

    def _host_words_bitrev(self) -> np.ndarray:
        with self._stage_lock:
            if self._host_rev is None:
                from .dense_eval_planes_v2 import bitrev_block_permute_records

                self._host_rev = bitrev_block_permute_records(
                    self._host_words_padded()
                )
            return self._host_rev

    def _row_words(self, bitrev_blocks: bool = False) -> jnp.ndarray:
        """Row-major device layout (the jnp tier's input)."""
        if not bitrev_blocks:
            return self.db_words
        with self._stage_lock:
            if self._db_words_rev is None:
                telemetry = default_telemetry()
                with telemetry.hbm.phase("db_staging"):
                    self._db_words_rev = (
                        telemetry.transfers.block_until_ready(
                            telemetry.transfers.device_put(
                                self._host_words_bitrev(),
                                phase="db_staging",
                            ),
                            phase="db_staging",
                        )
                    )
                # The host-side permuted copy only exists to feed device
                # stagings; keeping it would hold a second full database
                # in host RSS for the process lifetime. (Rebuilt from
                # `_host_words` if another staging needs it.)
                self._host_rev = None
            return self._db_words_rev

    def _staged_perm(self, bitrev_blocks: bool = False) -> jnp.ndarray:
        """Bit-major layout (`permute_db_bitmajor`), staged once."""
        with self._stage_lock:
            ledger = default_telemetry().transfers
            if bitrev_blocks:
                if self._db_perm_rev is None:
                    with default_telemetry().hbm.phase("db_staging"):
                        self._db_perm_rev = ledger.block_until_ready(
                            permute_db_bitmajor(
                                ledger.device_put(
                                    self._host_words_bitrev(),
                                    phase="db_staging",
                                )
                            ),
                            phase="db_staging",
                        )
                    self._host_rev = None  # see _row_words
                return self._db_perm_rev
            if self._db_perm is None:
                with default_telemetry().hbm.phase("db_staging"):
                    self._db_perm = ledger.block_until_ready(
                        permute_db_bitmajor(
                            ledger.device_put(
                                self._host_words, phase="db_staging"
                            )
                        ),
                        phase="db_staging",
                    )
            return self._db_perm

    @staticmethod
    def _streaming_key(cut_levels, bitmajor, mesh, shard_axis):
        """Cache key for one streaming staging. Mesh stagings key on the
        device assignment + shard axis so a mesh change restages."""
        base = (int(cut_levels), bool(bitmajor))
        if mesh is None:
            return base
        fingerprint = (
            str(shard_axis),
            tuple(mesh.axis_names),
            tuple(int(s) for s in mesh.devices.shape),
            tuple(d.id for d in mesh.devices.flat),
        )
        return base + (fingerprint,)

    def mesh_staging_info(self) -> dict | None:
        """Per-shard detail of the live mesh staging (device id, chunk
        span, bytes, copies), or None when not mesh-staged."""
        with self._stage_lock:
            info = self._mesh_staging_info
            return dict(info) if info is not None else None

    def streaming_chunks(
        self,
        *,
        cut_levels: int,
        bitmajor: bool,
        mesh=None,
        shard_axis: str = "shard",
    ) -> jnp.ndarray:
        """Device staging for the streaming serving plan: records in
        streaming (blocked bit-reversed) block order, split into
        `2**cut_levels` chunk spans along the leading axis.

        Returns uint32[nc, chunk_records, W] row-major, or
        uint32[nc, 32, Gc, W] bit-major per chunk when `bitmajor` (the
        pallas2 scan tier). One staging is cached at a time, keyed by
        the plan split — a batch-size change that moves the planner's
        cut restages (the covering padded row count is plan-invariant,
        only the chunk boundaries move).

        With `mesh`, the chunk axis is sharded over `shard_axis`: each
        device's span of chunk spans is `jax.device_put` directly from
        the host slice to that device (no single-device detour, no
        cross-device reshard), assembled into one global array under a
        `NamedSharding`. Each per-device upload is counted in the
        TransferLedger under `db_staging`, and per-shard HBM watermarks
        land under `db_staging/dev<N>`.
        """
        from .dense_eval_planes_v2 import streaming_block_permute_records

        key = self._streaming_key(cut_levels, bitmajor, mesh, shard_axis)
        with self._stage_lock:
            if (
                self._streaming_stage is not None
                and self._streaming_stage[0] == key
            ):
                return self._streaming_stage[1]
            if mesh is not None:
                host = streaming_block_permute_records(
                    self._host_words_padded(), cut_levels
                )
                arr = self._stage_chunks_mesh(
                    host, 1 << cut_levels, mesh, shard_axis, bitmajor
                )
                self._streaming_stage = (key, arr)
                return arr
            self._mesh_staging_info = None
            ledger = default_telemetry().transfers
            with default_telemetry().hbm.phase("db_staging"):
                arr = None
                try:
                    arr = self._stage_streaming_delta(key, ledger)
                except Exception as e:  # noqa: BLE001 - full restage
                    warnings.warn(
                        "delta streaming staging failed; restaging in "
                        f"full ({str(e).splitlines()[0][:200]})"
                    )
                if arr is None:
                    arr = self._stage_streaming_full(
                        ledger, cut_levels, bitmajor
                    )
            self._streaming_stage = (key, arr)
            return arr

    def _stage_streaming_full(self, ledger, cut_levels: int,
                              bitmajor: bool):
        """Full-image staging of the single-device streaming layout —
        pipelined per-chunk when enabled (async puts, device-side
        assembly, one sync), the one-shot upfront put otherwise."""
        from .dense_eval_planes_v2 import streaming_block_permute_records

        host = streaming_block_permute_records(
            self._host_words_padded(), cut_levels
        )
        nc = 1 << cut_levels
        chunks = host.reshape(nc, -1, host.shape[1])
        if pipelined_staging_enabled() and nc >= 2:
            if bitmajor:
                from ..ops.inner_product_pallas import permute_db_bitmajor

                # stage_db_chunks_bitmajor == vmap(permute_db_bitmajor)
                # over equal chunk spans, so assembling the per-chunk
                # uploads and vmapping reproduces it bit for bit.
                return _stage_pieces_pipelined(
                    ledger, list(chunks),
                    lambda parts: jax.vmap(permute_db_bitmajor)(
                        jnp.stack(parts)
                    ),
                )
            return _stage_pieces_pipelined(ledger, list(chunks), jnp.stack)
        if bitmajor:
            from ..ops.inner_product_pallas import stage_db_chunks_bitmajor

            return ledger.block_until_ready(
                stage_db_chunks_bitmajor(
                    ledger.device_put(host, phase="db_staging"), nc
                ),
                phase="db_staging",
            )
        return ledger.block_until_ready(
            ledger.device_put(chunks, phase="db_staging"),
            phase="db_staging",
        )

    def _stage_streaming_delta(self, key, ledger):
        """Delta staging of the (non-mesh) streaming layout: upload
        only the chunks containing an updated record and scatter them
        into the base generation's resident staging (a new device
        array; the base keeps serving its own buffers). Returns the
        staged array, or None when the delta path does not apply (not
        a delta build, base released or never staged this layout, key
        mismatch)."""
        rows = self._delta_rows
        base = self._delta_base_db()
        if rows is None or base is None or len(key) != 2:
            return None
        with base._stage_lock:
            stage = base._streaming_stage
        if stage is None or stage[0] != key:
            return None
        cut_levels, bitmajor = key
        base_arr = stage[1]
        from .dense_eval_planes_v2 import streaming_block_order

        host = self._host_words_padded()
        width = host.shape[1]
        nb = host.shape[0] // 128
        levels = max(0, (nb - 1).bit_length())
        nc = 1 << cut_levels
        if nb != 1 << levels or nc > nb or int(base_arr.shape[0]) != nc:
            return None
        bpc = nb // nc
        order = streaming_block_order(levels, cut_levels)
        # Updated record i lives in natural block i // 128, which the
        # involution places at staged position order[i // 128]; staged
        # positions group into chunks of bpc consecutive blocks.
        touched = sorted({int(order[r // 128]) // bpc for r in rows})
        if not touched:
            return base_arr
        if len(touched) >= nc:
            # Every chunk holds an update: a scatter of all chunks is a
            # full-image upload plus overhead. Restage in full instead.
            return None
        blocks = host.reshape(nb, 128, width)
        pieces = np.stack([
            blocks[order[c * bpc:(c + 1) * bpc]].reshape(bpc * 128, width)
            for c in touched
        ])
        dvals = ledger.device_put(pieces, phase="db_staging")
        if bitmajor:
            from ..ops.inner_product_pallas import permute_db_bitmajor

            dvals = jax.vmap(permute_db_bitmajor)(dvals)
        didx = ledger.device_put(
            np.asarray(touched, dtype=np.int32), phase="db_staging"
        )
        return ledger.block_until_ready(
            base_arr.at[didx].set(dvals), phase="db_staging"
        )

    def _stage_chunks_mesh(self, host, nc, mesh, shard_axis, bitmajor):
        """Place chunk spans pre-partitioned over the mesh's shard axis.

        Row-major chunks [nc, chunk_records, W] go up directly. The
        bit-major layout needs the on-device permute
        (`stage_db_chunks_bitmajor`), so the row-major sharded upload is
        followed by a jitted shard-local transform constrained to the
        same shard-axis sharding — records still never cross devices.
        """
        from jax.sharding import NamedSharding, PartitionSpec

        if nc % int(mesh.shape[shard_axis]):
            raise ValueError(
                f"{nc} chunks not divisible by the {shard_axis} axis "
                f"({mesh.shape[shard_axis]} devices)"
            )
        telemetry = default_telemetry()
        ledger = telemetry.transfers
        chunks = host.reshape(nc, -1, host.shape[1])
        spec = PartitionSpec(shard_axis, None, None)
        sharding = NamedSharding(mesh, spec)
        idx_map = sharding.addressable_devices_indices_map(chunks.shape)
        pieces = []
        shards = []
        total = 0
        for dev, index in sorted(
            idx_map.items(), key=lambda kv: kv[0].id
        ):
            piece = np.ascontiguousarray(chunks[index])
            with telemetry.hbm.phase(f"db_staging/dev{dev.id}"):
                darr = jax.device_put(piece, dev)
                darr.block_until_ready()
            ledger.record_h2d(int(piece.nbytes), phase="db_staging")
            span = index[0]
            shards.append({
                "device": int(dev.id),
                "chunk_start": int(span.start or 0),
                "chunk_stop": int(
                    span.stop if span.stop is not None else nc
                ),
                "bytes": int(piece.nbytes),
                "copies": 1,
            })
            total += int(piece.nbytes)
            pieces.append(darr)
        arr = jax.make_array_from_single_device_arrays(
            chunks.shape, sharding, pieces
        )
        if bitmajor:
            from ..ops.inner_product_pallas import (
                stage_db_chunks_bitmajor,
            )

            rows = jax.jit(
                lambda x: jax.lax.with_sharding_constraint(
                    x.reshape(-1, x.shape[-1]),
                    NamedSharding(mesh, PartitionSpec(shard_axis, None)),
                )
            )(arr)
            arr = jax.jit(
                lambda x: jax.lax.with_sharding_constraint(
                    stage_db_chunks_bitmajor(x, nc),
                    NamedSharding(
                        mesh,
                        PartitionSpec(shard_axis, None, None, None),
                    ),
                )
            )(rows)
            arr = ledger.block_until_ready(arr, phase="db_staging")
        self._mesh_staging_info = {
            "shard_axis": str(shard_axis),
            "num_shards": int(mesh.shape[shard_axis]),
            "num_chunks": int(nc),
            "bitmajor": bool(bitmajor),
            "total_bytes": total,
            "copies": len(shards),
            "generation": int(self._generation),
            "shards": shards,
        }
        return arr

    def _tier_chain(self):
        """(tiers-to-try, forced): the inner-product fallback chain.

        Auto mode on TPU: the v2 Pallas kernel (one large int8 MXU dot
        per tile, exact int32 counts — no record cap below int32 range),
        then the v1 Pallas kernel and the pure-jnp bit-plane path (both
        f32-exact only to 2^24 records), then the jnp XOR reduction.
        A forced tier propagates its errors instead of falling through.
        """
        mode = os.environ.get("DPF_TPU_INNER_PRODUCT", "auto")
        if mode != "auto":
            return [mode], True
        chain = []
        if jax.default_backend() == "tpu":
            chain.append("pallas2")
            if self._num_padded <= MAX_RECORDS_EXACT:
                chain += ["pallas", "bitplane"]
        chain.append("jnp")
        return chain, False

    def _inner_product_device(
        self, selections: jnp.ndarray, bitrev_blocks: bool = False
    ) -> jnp.ndarray:
        chain, forced = self._tier_chain()
        for tier in chain:
            # Remembered failures: a failed trace/compile is not cached
            # by jit, so retrying would pay it on every batch.
            if tier in self._failed_tiers:
                continue
            try:
                if tier == "pallas2":
                    knobs = _v2_tile_knobs()
                    knob_key = tuple(sorted(knobs.items()))
                    if knob_key in self._failed_knobs:
                        knobs, knob_key = {}, ()
                    try:
                        return xor_inner_product_pallas2_staged(
                            self._staged_perm(bitrev_blocks), selections,
                            **knobs
                        )
                    except Exception as e:  # noqa: BLE001
                        # The positivity pre-check above cannot know the
                        # kernel's real tile floors/multiples; a
                        # positive-but-unsupported knob (e.g. TG below the
                        # 16-lane miscompile floor) must cost ONE retry
                        # with defaults — remembered, so later batches go
                        # straight to the defaults (a failed trace is not
                        # cached by jit) — not the pallas2 tier itself.
                        if not knobs:
                            raise
                        self._failed_knobs.add(knob_key)
                        warnings.warn(
                            "pallas2 failed with env tile knobs "
                            f"{knobs}; retrying with kernel defaults "
                            f"({str(e).splitlines()[0][:200]})"
                        )
                        return xor_inner_product_pallas2_staged(
                            self._staged_perm(bitrev_blocks), selections
                        )
                if tier == "pallas":
                    return xor_inner_product_pallas_staged(
                        self._staged_perm(bitrev_blocks), selections
                    )
                if tier == "bitplane":
                    return xor_inner_product_bitplane(
                        self._staged_perm(bitrev_blocks), selections
                    )
                if tier == "jnp":
                    return xor_inner_product(
                        self._row_words(bitrev_blocks), selections
                    )
                raise ValueError(
                    f"unknown DPF_TPU_INNER_PRODUCT tier {tier!r}"
                )
            except Exception as e:  # noqa: BLE001 - fall through the chain
                if forced or tier == "jnp":
                    raise
                self._failed_tiers.add(tier)
                if tier == chain[-2]:
                    # jnp path reads row-major only.
                    self._db_perm = None
                    self._db_perm_rev = None
                warnings.warn(
                    f"{tier} inner product failed; falling back "
                    f"({str(e).splitlines()[0][:200]})"
                )
        raise AssertionError("unreachable: jnp tier returns or raises")

    def inner_product_with(
        self, selections: jnp.ndarray, *, bitrev_blocks: bool = False
    ) -> List[bytes]:
        """XOR of all records whose selection bit is 1, per query.

        `selections`: uint32[num_queries, B, 4] packed blocks with
        B * 128 >= num_selection_bits. Returns one byte-string of
        `max_value_size` per query (the reference's result convention,
        `inner_product_hwy.cc:271-272`).

        With `bitrev_blocks=True` the selection blocks arrive in the
        doubling (bit-reversed) leaf order of a `bitrev_leaves=True`
        expansion, and the product runs against the bitrev-permuted
        staging — same responses, no exit gather on the expansion side.
        The block count must then equal `bitrev_block_count()` exactly.
        """
        if selections.ndim != 3 or selections.shape[-1] != 4:
            raise ValueError("selections must be uint32[nq, B, 4]")
        if bitrev_blocks:
            needed = self.bitrev_block_count()
            if selections.shape[1] != needed:
                raise ValueError(
                    f"bitrev selections must cover exactly {needed} "
                    f"blocks, got {selections.shape[1]}"
                )
        else:
            if selections.shape[1] * 128 < self.size:
                raise ValueError(
                    f"selections contain {selections.shape[1] * 128} "
                    f"bits, expected at least {self.size}"
                )
            needed = self.num_selection_blocks
            if selections.shape[1] > needed:
                selections = selections[:, :needed]
            elif selections.shape[1] < needed:
                pad = needed - selections.shape[1]
                selections = jnp.pad(
                    selections, ((0, 0), (0, pad), (0, 0))
                )
        out = default_telemetry().transfers.to_host(
            self._inner_product_device(selections, bitrev_blocks),
            phase="result_readback",
        )
        return words_to_record_bytes(out, out.shape[0], self._max_value_size)
