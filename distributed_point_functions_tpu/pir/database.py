"""Dense in-memory PIR database living in device HBM.

The reference packs all records into one 128-bit-aligned host buffer and
XORs with Highway SIMD (`pir/dense_dpf_pir_database.h:101-111`,
`.cc:112-161`). The TPU redesign packs records into a single
`uint32[num_records_padded, record_words]` array resident in HBM: every
record is zero-padded to the maximum record size, and the record count is
padded to a multiple of 128 so whole selection blocks line up with rows.

`inner_product_with` serves the whole query batch in one database pass
through a three-tier chain: on TPU the Pallas MXU kernel
(`ops/inner_product_pallas.py`, bit-major layout staged once on first
use); on its failure the pure-jnp MXU bit-plane path
(`ops/inner_product.py:xor_inner_product_bitplane`, same math, no Mosaic
dependency); and finally — elsewhere (CPU tests), beyond the 2^24-record
f32-exactness bound, or on any failure — the jitted jnp XOR-reduction.
Set ``DPF_TPU_INNER_PRODUCT=pallas|bitplane|jnp`` to force a tier
(forced tiers propagate their errors instead of falling through).
"""

from __future__ import annotations

import os
import warnings
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.inner_product import (
    xor_inner_product,
    xor_inner_product_bitplane,
)
from ..ops.inner_product_pallas import (
    MAX_RECORDS_EXACT,
    permute_db_bitmajor,
    xor_inner_product_pallas_staged,
)


def words_to_record_bytes(
    out: np.ndarray, num_keys: int, size: int
) -> List[bytes]:
    """uint32[nq, W] inner products -> per-query record byte strings.

    Little-endian words, truncated to the database's record size (the
    reference's result convention, `inner_product_hwy.cc:271-272`). The
    single home of this codec — the servers' sharded/chunked paths and the
    database all share it.
    """
    raw = np.ascontiguousarray(out[:num_keys].astype("<u4")).view(np.uint8)
    return [raw[q, :size].tobytes() for q in range(num_keys)]


class DenseDpfPirDatabase:
    """Immutable dense database; construct via `DenseDpfPirDatabase.Builder`."""

    class Builder:
        def __init__(self):
            self._records: List[bytes] = []

        def insert(self, value: bytes) -> "DenseDpfPirDatabase.Builder":
            if isinstance(value, str):
                value = value.encode()
            self._records.append(bytes(value))
            return self

        def clone(self) -> "DenseDpfPirDatabase.Builder":
            b = DenseDpfPirDatabase.Builder()
            b._records = list(self._records)
            return b

        def build(self) -> "DenseDpfPirDatabase":
            return DenseDpfPirDatabase(self._records)

    def __init__(self, records: Sequence[bytes]):
        self._records = [bytes(r) for r in records]
        self._max_value_size = max((len(r) for r in self._records), default=0)
        num_records = len(self._records)
        self._num_padded = max(128, ((num_records + 127) // 128) * 128)
        record_bytes = max(4, ((self._max_value_size + 3) // 4) * 4)
        buf = np.zeros((self._num_padded, record_bytes), dtype=np.uint8)
        # Vectorized variable-length packing (chunked): a per-record Python
        # loop is minutes of host time at the sparse-PIR benchmark scale
        # (1.5 * 2^24 buckets).
        chunk = 1 << 20
        for s in range(0, num_records, chunk):
            rs = self._records[s : s + chunk]
            data = np.frombuffer(b"".join(rs), dtype=np.uint8)
            if data.size == 0:
                continue
            lengths = np.fromiter(
                (len(r) for r in rs), dtype=np.int64, count=len(rs)
            )
            ends = np.cumsum(lengths)
            starts = ends - lengths
            rows = np.repeat(np.arange(s, s + len(rs)), lengths)
            cols = np.arange(data.size, dtype=np.int64) - np.repeat(
                starts, lengths
            )
            buf[rows, cols] = data
        # Host copy; device staging is lazy so the Pallas path only ever
        # holds the bit-major layout in HBM (not both layouts).
        self._host_words = np.ascontiguousarray(buf).view("<u4").astype(
            np.uint32
        )
        self._db_words = None  # row-major device copy (jnp fallback path)
        self._db_perm = None  # bit-major layout, staged on first pallas use
        self._pallas_failed = False

    @property
    def size(self) -> int:
        """Number of records."""
        return len(self._records)

    @property
    def max_value_size(self) -> int:
        return self._max_value_size

    @property
    def num_selection_bits(self) -> int:
        """Selection bits a query must provide (padded record count)."""
        return self._num_padded

    @property
    def num_selection_blocks(self) -> int:
        return self._num_padded // 128

    @property
    def db_words(self) -> jnp.ndarray:
        """uint32[num_records_padded, record_words] device buffer."""
        if self._db_words is None:
            self._db_words = jnp.asarray(self._host_words)
        return self._db_words

    def record(self, i: int) -> bytes:
        return self._records[i]

    def _use_pallas(self) -> bool:
        mode = os.environ.get("DPF_TPU_INNER_PRODUCT", "auto")
        if mode == "pallas":
            return True
        if mode in ("jnp", "bitplane"):
            return False
        return (
            not self._pallas_failed
            and jax.default_backend() == "tpu"
            and self._num_padded <= MAX_RECORDS_EXACT
        )

    def _inner_product_device(self, selections: jnp.ndarray) -> jnp.ndarray:
        mode = os.environ.get("DPF_TPU_INNER_PRODUCT", "auto")
        if self._use_pallas():
            try:
                if self._db_perm is None:
                    self._db_perm = jax.block_until_ready(
                        permute_db_bitmajor(jnp.asarray(self._host_words))
                    )
                return xor_inner_product_pallas_staged(
                    self._db_perm, selections
                )
            except Exception as e:
                if mode == "pallas":
                    raise
                # Remember the failure: a failed trace/compile is not
                # cached by jit, so retrying would pay it on every batch.
                self._pallas_failed = True
                warnings.warn(
                    "pallas inner-product kernel failed; serving via the "
                    f"bit-plane jnp path ({str(e).splitlines()[0][:200]})"
                )
        # Middle fallback: the same MXU bit-plane math in pure jnp — no
        # Mosaic dependency (`ops/inner_product.py`). Same staged layout
        # and record-count bound as the Pallas kernel. A forced
        # mode=bitplane propagates its errors (incl. the record-count
        # bound); auto mode falls through to the XOR path on any failure.
        if mode == "bitplane" or (
            mode == "auto"
            and jax.default_backend() == "tpu"
            and self._num_padded <= MAX_RECORDS_EXACT
        ):
            try:
                if self._db_perm is None:
                    self._db_perm = jax.block_until_ready(
                        permute_db_bitmajor(jnp.asarray(self._host_words))
                    )
                return xor_inner_product_bitplane(self._db_perm, selections)
            except Exception as e:  # noqa: BLE001
                if mode == "bitplane":
                    raise
                self._db_perm = None
                warnings.warn(
                    "bit-plane inner product failed; serving via the XOR "
                    f"path ({str(e).splitlines()[0][:200]})"
                )
        return xor_inner_product(self.db_words, selections)

    def inner_product_with(self, selections: jnp.ndarray) -> List[bytes]:
        """XOR of all records whose selection bit is 1, per query.

        `selections`: uint32[num_queries, B, 4] packed blocks with
        B * 128 >= num_selection_bits. Returns one byte-string of
        `max_value_size` per query (the reference's result convention,
        `inner_product_hwy.cc:271-272`).
        """
        if selections.ndim != 3 or selections.shape[-1] != 4:
            raise ValueError("selections must be uint32[nq, B, 4]")
        if selections.shape[1] * 128 < self.size:
            raise ValueError(
                f"selections contain {selections.shape[1] * 128} bits, "
                f"expected at least {self.size}"
            )
        needed = self.num_selection_blocks
        if selections.shape[1] > needed:
            selections = selections[:, :needed]
        elif selections.shape[1] < needed:
            pad = needed - selections.shape[1]
            selections = jnp.pad(selections, ((0, 0), (0, pad), (0, 0)))
        out = np.asarray(self._inner_product_device(selections))
        return words_to_record_bytes(out, out.shape[0], self._max_value_size)
