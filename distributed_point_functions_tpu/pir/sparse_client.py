"""Sparse PIR client (`pir/cuckoo_hashing_sparse_dpf_pir_client.{h,cc}`).

Each queried string is hashed with all of the server's hash functions; the
resulting bucket indices become one dense-PIR request over the bucket space
(`cuckoo_hashing_sparse_dpf_pir_client.cc:108-134`). Response handling gets
`(key, value)` pairs for every candidate bucket and selects the value whose
returned key matches the query (zero-padded prefix check,
`cuckoo_hashing_sparse_dpf_pir_client.cc:136-187`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import dataclasses

from ..hashing import create_hash_family_from_config
from ..hashing.hash_family import create_hash_functions
from . import messages
from .client import (
    DenseDpfPirClient,
    ENCRYPTION_CONTEXT_INFO,
    EncryptHelperRequestFn,
)
from .cuckoo_database import CuckooHashingParams


@dataclasses.dataclass
class CuckooHashingSparseDpfPirRequestClientState:
    one_time_pad_seed: bytes
    query_strings: List[bytes]


@dataclasses.dataclass(frozen=True)
class KeyNotFound:
    """Typed absent-key result: no candidate bucket's key plaintext
    matched the queried string. Honest semantics — a lookup never
    degrades to a wrong value; callers branch on this type instead of
    testing a value against None."""

    key: bytes

    def __bool__(self) -> bool:
        return False


def _is_prefix_padded_with_zeros(data: bytes, prefix: bytes) -> bool:
    if data[: len(prefix)] != prefix[: len(data)]:
        return False
    return all(b == 0 for b in data[len(prefix) :])


class CuckooHashingSparseDpfPirClient:
    """Client for `CuckooHashingSparseDpfPirServer`."""

    def __init__(
        self,
        params: CuckooHashingParams,
        encrypter: EncryptHelperRequestFn,
        encryption_context_info: bytes = ENCRYPTION_CONTEXT_INFO,
    ):
        if params.num_buckets <= 0:
            raise ValueError("num_buckets must be positive")
        if params.num_hash_functions <= 0:
            raise ValueError("num_hash_functions must be positive")
        family = create_hash_family_from_config(params.hash_family_config)
        self._hash_functions = create_hash_functions(
            family, params.num_hash_functions
        )
        self._num_buckets = params.num_buckets
        self._wrapped_client = DenseDpfPirClient.create(
            params.num_buckets, encrypter, encryption_context_info
        )

    @classmethod
    def create(cls, params, encrypter,
               encryption_context_info=ENCRYPTION_CONTEXT_INFO):
        return cls(params, encrypter, encryption_context_info)

    @classmethod
    def create_from_public_params(
        cls,
        public_params,
        encrypter,
        encryption_context_info=ENCRYPTION_CONTEXT_INFO,
    ):
        """Construct from the server's wire-format public params — a
        `PirServerPublicParams` proto or its serialized bytes
        (`cuckoo_hashing_sparse_dpf_pir_client_test.cc:170`)."""
        from .. import serialization
        from ..protos import pir_pb2

        if isinstance(public_params, (bytes, bytearray)):
            proto = pir_pb2.PirServerPublicParams()
            proto.ParseFromString(bytes(public_params))
            public_params = proto
        params = serialization.public_params_from_proto(public_params)
        if params is None:
            raise ValueError(
                "public params do not contain cuckoo hashing parameters"
            )
        return cls(params, encrypter, encryption_context_info)

    def _bucket_indices(self, query: Sequence[bytes]) -> List[int]:
        indices = []
        for q in query:
            q = q.encode() if isinstance(q, str) else bytes(q)
            for fn in self._hash_functions:
                indices.append(fn(q, self._num_buckets))
        return indices

    def create_request(
        self, query: Sequence[bytes]
    ) -> Tuple["messages.PirRequest", CuckooHashingSparseDpfPirRequestClientState]:
        qbytes = [
            q.encode() if isinstance(q, str) else bytes(q) for q in query
        ]
        request, dense_state = self._wrapped_client.create_request(
            self._bucket_indices(qbytes)
        )
        return request, CuckooHashingSparseDpfPirRequestClientState(
            one_time_pad_seed=dense_state.one_time_pad_seed,
            query_strings=qbytes,
        )

    def create_plain_requests(self, query: Sequence[bytes]):
        qbytes = [
            q.encode() if isinstance(q, str) else bytes(q) for q in query
        ]
        reqs = self._wrapped_client.create_plain_requests(
            self._bucket_indices(qbytes)
        )
        return reqs

    def handle_response(
        self,
        response: "messages.PirResponse",
        client_state: CuckooHashingSparseDpfPirRequestClientState,
    ) -> List[Optional[bytes]]:
        """Per query: the value if the key was present, else None."""
        num_hashes = len(self._hash_functions)
        masked = response.dpf_pir_response.masked_response
        nq = len(client_state.query_strings)
        if nq * num_hashes * 2 != len(masked):
            raise ValueError(
                "number of responses must be equal to the number of queries "
                "times the number of hash functions times 2"
            )
        raw = self._wrapped_client.handle_response(
            response,
            messages.DenseDpfPirRequestClientState(
                one_time_pad_seed=client_state.one_time_pad_seed
            ),
        )
        result: List[Optional[bytes]] = [None] * nq
        for i in range(nq):
            for j in range(num_hashes):
                raw_index = 2 * (num_hashes * i + j)
                if result[i] is None and _is_prefix_padded_with_zeros(
                    raw[raw_index], client_state.query_strings[i]
                ):
                    result[i] = raw[raw_index + 1]
        return result

    def resolve(
        self,
        response: "messages.PirResponse",
        client_state: CuckooHashingSparseDpfPirRequestClientState,
    ) -> List:
        """`handle_response` with typed absence: per query, the value
        bytes when the key was present, else `KeyNotFound(key)`."""
        values = self.handle_response(response, client_state)
        return [
            value if value is not None else KeyNotFound(key)
            for key, value in zip(client_state.query_strings, values)
        ]
