"""Cuckoo-hashed sparse PIR database
(`pir/cuckoo_hashed_dpf_pir_database.{h,cc}`).

The builder cuckoo-hashes all string keys into a `num_buckets`-slot table
(`cuckoo_hashed_dpf_pir_database.cc:97-146`), then stores keys and values in
**two parallel dense databases** — empty strings in vacant buckets — so one
set of selection blocks retrieves `(key, value)` record pairs with two XOR
inner products (`cuckoo_hashed_dpf_pir_database.cc:164-183`).
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from ..hashing import CuckooHashTable, create_hash_family_from_config
from ..hashing.hash_family import create_hash_functions
from ..hashing.hash_family_config import HASH_FAMILY_SHA256
from .database import DenseDpfPirDatabase


@dataclasses.dataclass(frozen=True)
class CuckooHashingParams:
    """Mirrors `CuckooHashingParams` (`private_information_retrieval.proto:93-100`)."""

    num_buckets: int
    num_hash_functions: int
    hash_family_config: "HashFamilyConfig"  # noqa: F821


class CuckooHashedDpfPirDatabase:
    """Sparse (string-keyed) database; build via `.Builder`."""

    class Builder:
        def __init__(self):
            self._records: Dict[bytes, bytes] = {}
            self._params: Optional[CuckooHashingParams] = None
            self._generation = 0

        def set_params(self, params: CuckooHashingParams):
            self._params = params
            return self

        def set_generation(self, generation: int):
            """Snapshot generation tag stamped on the built database and
            both parallel dense databases — sparse PIR adopts the
            serving-side rotation machinery (`serving/snapshots.py`)
            unchanged because the tag travels the same way."""
            self._generation = int(generation)
            return self

        def insert(self, key_value: Tuple[bytes, bytes]):
            key, value = key_value
            key = key.encode() if isinstance(key, str) else bytes(key)
            value = value.encode() if isinstance(value, str) else bytes(value)
            self._records[key] = value
            return self

        def clone(self):
            b = CuckooHashedDpfPirDatabase.Builder()
            b._records = dict(self._records)
            b._params = self._params
            b._generation = self._generation
            return b

        def build(self) -> "CuckooHashedDpfPirDatabase":
            if self._params is None:
                raise ValueError("params must be set before build")
            params = self._params
            if params.num_buckets <= 0:
                raise ValueError("num_buckets must be positive")
            if params.num_hash_functions <= 0:
                raise ValueError("num_hash_functions must be positive")
            for key in self._records:
                if not key:
                    raise ValueError("key cannot be empty")
            slots = self._build_slots(params)
            key_records: List[bytes] = []
            value_records: List[bytes] = []
            for slot in slots:
                if slot is not None:
                    key_records.append(slot)
                    value_records.append(self._records[slot])
                else:
                    key_records.append(b"")
                    value_records.append(b"")
            return CuckooHashedDpfPirDatabase(
                DenseDpfPirDatabase(
                    key_records, generation=self._generation
                ),
                DenseDpfPirDatabase(
                    value_records, generation=self._generation
                ),
                size=len(self._records),
                num_buckets=params.num_buckets,
                generation=self._generation,
                params=params,
                slots=slots,
            )

        def build_from(
            self, prev: "CuckooHashedDpfPirDatabase"
        ) -> "CuckooHashedDpfPirDatabase":
            """Derive sparse generation N+1 from `prev` by **upsert**:
            this builder's records overwrite or extend `prev`'s key→value
            mapping (no delete path — retire a key with a full rebuild).

            Keys already present keep their cuckoo bucket, so a write
            batch touches exactly the buckets it changes; new keys are
            inserted into a table preseeded with `prev`'s assignment
            (evictions may relocate old keys, still counted as touched
            buckets). Both parallel dense stores then go through
            `DenseDpfPirDatabase.Builder.build_from`, which scatters
            only the touched rows into the resident staging on
            `prestage()` — a key-value write batch becomes a cheap
            delta rotation. Oversized values fall back to a full dense
            rebuild inside the dense builder; still correct, still
            generation N+1.
            """
            if prev.params is None or prev.slots is None:
                raise ValueError(
                    "build_from needs a previous generation built by "
                    "CuckooHashedDpfPirDatabase.Builder (params and slot "
                    "assignment retained)"
                )
            params = prev.params
            if self._params is not None and self._params != params:
                raise ValueError(
                    "build_from cannot change cuckoo params; rebuild "
                    "from scratch to re-geometry"
                )
            for key in self._records:
                if not key:
                    raise ValueError("key cannot be empty")
            prev_slots = list(prev.slots)
            prev_keys = {
                key: bucket
                for bucket, key in enumerate(prev_slots)
                if key is not None
            }
            new_keys = [k for k in self._records if k not in prev_keys]
            if new_keys:
                slots = self._insert_into(params, prev_slots, new_keys)
            else:
                slots = prev_slots
            generation = prev.generation + 1
            key_builder = DenseDpfPirDatabase.Builder()
            value_builder = DenseDpfPirDatabase.Builder()

            def value_at(key):
                # Staged write wins; a relocated old key carries its
                # value over from its previous bucket's value row.
                if key is None:
                    return b""
                if key in self._records:
                    return self._records[key]
                return prev.value_database.record(prev_keys[key])

            for bucket, key in enumerate(slots):
                moved = key != prev_slots[bucket]
                rewritten = (
                    key is not None
                    and key in self._records
                    and self._records[key]
                    != prev.value_database.record(bucket)
                )
                if moved or rewritten:
                    key_builder.update(bucket, key or b"")
                    value_builder.update(bucket, value_at(key))
            return CuckooHashedDpfPirDatabase(
                key_builder.build_from(prev.key_database),
                value_builder.build_from(prev.value_database),
                size=len(prev_keys) + len(new_keys),
                num_buckets=params.num_buckets,
                generation=generation,
                params=params,
                slots=slots,
            )

        def _insert_into(self, params, prev_slots, new_keys):
            """Slot assignment extending `prev_slots` with `new_keys`:
            a Python cuckoo table preseeded with the previous layout
            (buckets lazily rehashed only if an old key gets evicted)."""
            family = create_hash_family_from_config(
                params.hash_family_config
            )
            hash_functions = create_hash_functions(
                family, params.num_hash_functions
            )
            table = CuckooHashTable(
                hash_functions,
                params.num_buckets,
                max_relocations=max(128, len(new_keys)),
                max_stash_size=0,
            )
            for bucket, key in enumerate(prev_slots):
                if key is not None:
                    table.preseed(bucket, key)
            for key in new_keys:
                table.insert(key)
            return table.get_table()

        def _build_slots(self, params):
            """bucket -> key (or None): the cuckoo assignment.

            The native builder (`native/cuckoo_build.cc`, same SHA256
            family semantics, ~50x faster at the 2^24-key scale) is
            tried first unless DPF_NATIVE_CUCKOO=0; any legal assignment
            serves the protocol, so its layout needn't match the Python
            builder's. Fallback is the Python `CuckooHashTable` loop.
            """
            import os as _os

            keys = list(self._records)
            if (
                _os.environ.get("DPF_NATIVE_CUCKOO", "1") != "0"
                and params.hash_family_config.hash_family
                == HASH_FAMILY_SHA256
            ):
                try:
                    from .. import native as _native

                    family_seed = params.hash_family_config.seed
                    family_seed = (
                        family_seed.encode()
                        if isinstance(family_seed, str)
                        else bytes(family_seed)
                    )
                    seeds = [
                        family_seed + str(i).encode()
                        for i in range(params.num_hash_functions)
                    ]
                    idx = _native.cuckoo_build(
                        keys,
                        seeds,
                        params.num_buckets,
                        max_relocations=max(128, len(keys)),
                    )
                    return [
                        keys[i] if i >= 0 else None for i in idx
                    ]
                except Exception as e:  # noqa: BLE001 - python fallback
                    warnings.warn(
                        "native cuckoo builder unavailable; using the "
                        f"Python insertion loop ({e})"
                    )
            family = create_hash_family_from_config(
                params.hash_family_config
            )
            hash_functions = create_hash_functions(
                family, params.num_hash_functions
            )
            table = CuckooHashTable(
                hash_functions,
                params.num_buckets,
                max_relocations=max(128, len(keys)),
                max_stash_size=0,
            )
            for key in keys:
                table.insert(key)
            return table.get_table()

    def __init__(
        self,
        key_database: DenseDpfPirDatabase,
        value_database: DenseDpfPirDatabase,
        size: int,
        num_buckets: int,
        generation: int = 0,
        params: Optional[CuckooHashingParams] = None,
        slots: Optional[List[Optional[bytes]]] = None,
    ):
        self._key_database = key_database
        self._value_database = value_database
        self._size = size
        self._num_buckets = num_buckets
        self._generation = int(generation)
        # Geometry + slot assignment, retained by Builder.build()/
        # build_from() so (a) `Builder.build_from` can derive the next
        # generation without re-hashing untouched keys and (b) the
        # serving runtime can validate a staged snapshot's cuckoo
        # geometry against the serving one. None when constructed
        # directly (legacy path) — such databases can serve but not
        # seed a delta build.
        self._params = params
        self._slots = list(slots) if slots is not None else None
        self.last_prestage_stats = None

    @property
    def size(self) -> int:
        """Number of real (non-dummy) records."""
        return self._size

    @property
    def generation(self) -> int:
        """Snapshot generation tag (0 = untagged), shared with both
        parallel dense databases."""
        return self._generation

    @property
    def num_buckets(self) -> int:
        return self._num_buckets

    @property
    def key_database(self) -> DenseDpfPirDatabase:
        """The parallel dense database of bucket keys (mesh serving and
        diagnostics; treat as read-only)."""
        return self._key_database

    @property
    def value_database(self) -> DenseDpfPirDatabase:
        """The parallel dense database of bucket values."""
        return self._value_database

    @property
    def params(self) -> Optional[CuckooHashingParams]:
        """Cuckoo geometry this database was built under (None when
        constructed without a Builder)."""
        return self._params

    @property
    def slots(self) -> Optional[List[Optional[bytes]]]:
        """bucket -> key (or None) assignment (None when constructed
        without a Builder)."""
        return self._slots

    @property
    def num_selection_blocks(self) -> int:
        return self._key_database.num_selection_blocks

    @property
    def max_value_size(self) -> int:
        """Largest packed row across both parallel dense stores."""
        return max(
            self._key_database.max_value_size,
            self._value_database.max_value_size,
        )

    def prestage(self, mesh=None, **kwargs) -> int:
        """Eagerly stage both parallel dense stores (the double-buffer
        half of a sparse snapshot rotation); returns the bytes moved
        host->device and merges both stores' `last_prestage_stats`.
        For a `Builder.build_from` generation whose base stagings are
        resident, each dense store scatters only its touched bucket
        rows — `bytes_saved > 0` is the delta-rotation win."""
        staged = self._key_database.prestage(mesh, **kwargs)
        staged += self._value_database.prestage(mesh, **kwargs)
        merged = {
            "mode": None,
            "bytes_staged": 0,
            "bytes_full_image": 0,
            "bytes_saved": 0,
            "generation": self._generation,
        }
        for store in (self._key_database, self._value_database):
            stats = store.last_prestage_stats
            if not stats or stats.get("generation") != self._generation:
                continue
            for field in ("bytes_staged", "bytes_full_image",
                          "bytes_saved"):
                merged[field] += int(stats.get(field, 0))
            if merged["mode"] != "delta":
                merged["mode"] = stats.get("mode")
        if merged["mode"] is not None:
            self.last_prestage_stats = merged
        return int(staged)

    def release_stagings(self) -> int:
        """Drop both stores' device stagings; returns buffers dropped."""
        return (
            self._key_database.release_stagings()
            + self._value_database.release_stagings()
        )

    def inner_product_with(
        self, selections: jnp.ndarray
    ) -> List[Tuple[bytes, bytes]]:
        keys = self._key_database.inner_product_with(selections)
        values = self._value_database.inner_product_with(selections)
        return list(zip(keys, values))
