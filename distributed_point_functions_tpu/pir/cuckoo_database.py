"""Cuckoo-hashed sparse PIR database
(`pir/cuckoo_hashed_dpf_pir_database.{h,cc}`).

The builder cuckoo-hashes all string keys into a `num_buckets`-slot table
(`cuckoo_hashed_dpf_pir_database.cc:97-146`), then stores keys and values in
**two parallel dense databases** — empty strings in vacant buckets — so one
set of selection blocks retrieves `(key, value)` record pairs with two XOR
inner products (`cuckoo_hashed_dpf_pir_database.cc:164-183`).
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from ..hashing import CuckooHashTable, create_hash_family_from_config
from ..hashing.hash_family import create_hash_functions
from ..hashing.hash_family_config import HASH_FAMILY_SHA256
from .database import DenseDpfPirDatabase


@dataclasses.dataclass(frozen=True)
class CuckooHashingParams:
    """Mirrors `CuckooHashingParams` (`private_information_retrieval.proto:93-100`)."""

    num_buckets: int
    num_hash_functions: int
    hash_family_config: "HashFamilyConfig"  # noqa: F821


class CuckooHashedDpfPirDatabase:
    """Sparse (string-keyed) database; build via `.Builder`."""

    class Builder:
        def __init__(self):
            self._records: Dict[bytes, bytes] = {}
            self._params: Optional[CuckooHashingParams] = None
            self._generation = 0

        def set_params(self, params: CuckooHashingParams):
            self._params = params
            return self

        def set_generation(self, generation: int):
            """Snapshot generation tag stamped on the built database and
            both parallel dense databases — sparse PIR adopts the
            serving-side rotation machinery (`serving/snapshots.py`)
            unchanged because the tag travels the same way."""
            self._generation = int(generation)
            return self

        def insert(self, key_value: Tuple[bytes, bytes]):
            key, value = key_value
            key = key.encode() if isinstance(key, str) else bytes(key)
            value = value.encode() if isinstance(value, str) else bytes(value)
            self._records[key] = value
            return self

        def clone(self):
            b = CuckooHashedDpfPirDatabase.Builder()
            b._records = dict(self._records)
            b._params = self._params
            b._generation = self._generation
            return b

        def build(self) -> "CuckooHashedDpfPirDatabase":
            if self._params is None:
                raise ValueError("params must be set before build")
            params = self._params
            if params.num_buckets <= 0:
                raise ValueError("num_buckets must be positive")
            if params.num_hash_functions <= 0:
                raise ValueError("num_hash_functions must be positive")
            for key in self._records:
                if not key:
                    raise ValueError("key cannot be empty")
            slots = self._build_slots(params)
            key_records: List[bytes] = []
            value_records: List[bytes] = []
            for slot in slots:
                if slot is not None:
                    key_records.append(slot)
                    value_records.append(self._records[slot])
                else:
                    key_records.append(b"")
                    value_records.append(b"")
            return CuckooHashedDpfPirDatabase(
                DenseDpfPirDatabase(
                    key_records, generation=self._generation
                ),
                DenseDpfPirDatabase(
                    value_records, generation=self._generation
                ),
                size=len(self._records),
                num_buckets=params.num_buckets,
                generation=self._generation,
            )

        def _build_slots(self, params):
            """bucket -> key (or None): the cuckoo assignment.

            The native builder (`native/cuckoo_build.cc`, same SHA256
            family semantics, ~50x faster at the 2^24-key scale) is
            tried first unless DPF_NATIVE_CUCKOO=0; any legal assignment
            serves the protocol, so its layout needn't match the Python
            builder's. Fallback is the Python `CuckooHashTable` loop.
            """
            import os as _os

            keys = list(self._records)
            if (
                _os.environ.get("DPF_NATIVE_CUCKOO", "1") != "0"
                and params.hash_family_config.hash_family
                == HASH_FAMILY_SHA256
            ):
                try:
                    from .. import native as _native

                    family_seed = params.hash_family_config.seed
                    family_seed = (
                        family_seed.encode()
                        if isinstance(family_seed, str)
                        else bytes(family_seed)
                    )
                    seeds = [
                        family_seed + str(i).encode()
                        for i in range(params.num_hash_functions)
                    ]
                    idx = _native.cuckoo_build(
                        keys,
                        seeds,
                        params.num_buckets,
                        max_relocations=max(128, len(keys)),
                    )
                    return [
                        keys[i] if i >= 0 else None for i in idx
                    ]
                except Exception as e:  # noqa: BLE001 - python fallback
                    warnings.warn(
                        "native cuckoo builder unavailable; using the "
                        f"Python insertion loop ({e})"
                    )
            family = create_hash_family_from_config(
                params.hash_family_config
            )
            hash_functions = create_hash_functions(
                family, params.num_hash_functions
            )
            table = CuckooHashTable(
                hash_functions,
                params.num_buckets,
                max_relocations=max(128, len(keys)),
                max_stash_size=0,
            )
            for key in keys:
                table.insert(key)
            return table.get_table()

    def __init__(
        self,
        key_database: DenseDpfPirDatabase,
        value_database: DenseDpfPirDatabase,
        size: int,
        num_buckets: int,
        generation: int = 0,
    ):
        self._key_database = key_database
        self._value_database = value_database
        self._size = size
        self._num_buckets = num_buckets
        self._generation = int(generation)

    @property
    def size(self) -> int:
        """Number of real (non-dummy) records."""
        return self._size

    @property
    def generation(self) -> int:
        """Snapshot generation tag (0 = untagged), shared with both
        parallel dense databases."""
        return self._generation

    @property
    def num_buckets(self) -> int:
        return self._num_buckets

    @property
    def key_database(self) -> DenseDpfPirDatabase:
        """The parallel dense database of bucket keys (mesh serving and
        diagnostics; treat as read-only)."""
        return self._key_database

    @property
    def value_database(self) -> DenseDpfPirDatabase:
        """The parallel dense database of bucket values."""
        return self._value_database

    @property
    def num_selection_blocks(self) -> int:
        return self._key_database.num_selection_blocks

    def inner_product_with(
        self, selections: jnp.ndarray
    ) -> List[Tuple[bytes, bytes]]:
        keys = self._key_database.inner_product_with(selections)
        values = self._value_database.inner_product_with(selections)
        return list(zip(keys, values))
