"""Sparse (string-keyed) PIR server over a cuckoo-hashed database
(`pir/cuckoo_hashing_sparse_dpf_pir_server.{h,cc}`).

`generate_params` draws 3 hash functions over `1.5 * num_elements` buckets
with a random 16-byte seed (`cuckoo_hashing_sparse_dpf_pir_server.cc:36-65`).
Requests are dense-PIR requests over the bucket space; each query returns
**two** masked responses — the bucket's key and its value
(`cuckoo_hashing_sparse_dpf_pir_server.cc:126-165`).
"""

from __future__ import annotations

import math
import secrets

from ..dpf import DistributedPointFunction, DpfParameters
from ..value_types import XorType
from . import messages
from .cuckoo_database import CuckooHashedDpfPirDatabase, CuckooHashingParams
from .database import words_to_record_bytes
from .dense_eval import selection_blocks_for_keys
from .server import (
    DecryptHelperRequestFn,
    DpfPirServer,
    ENCRYPTION_CONTEXT_INFO,
    ForwardHelperRequestFn,
)
from ..hashing.hash_family_config import (
    HASH_FAMILY_SHA256,
    HASH_FUNCTION_SEED_LENGTH_BYTES,
    HashFamilyConfig,
)

NUM_HASH_FUNCTIONS = 3
BUCKETS_PER_ELEMENT = 1.5


class CuckooHashingSparseDpfPirServer(DpfPirServer):
    """See module docstring."""

    def __init__(self, params: CuckooHashingParams,
                 database: CuckooHashedDpfPirDatabase, mesh=None):
        super().__init__()
        if params.num_buckets <= 0:
            raise ValueError("num_buckets must be positive")
        if params.num_hash_functions <= 0:
            raise ValueError("num_hash_functions must be positive")
        if database is None:
            raise ValueError("database cannot be None")
        if database.num_buckets != params.num_buckets:
            raise ValueError(
                "number of buckets in the database does not match "
                "params.num_buckets"
            )
        self._params = params
        self._database = database
        # Multi-chip serving: bucket rows of BOTH parallel dense databases
        # sharded over the mesh, one expansion per query batch
        # (`parallel/sharded.py:sharded_dense_pir_step_multi`).
        self._mesh = mesh
        self._sharded_step = None
        self._sharded_dbs = None
        log_domain_size = max(0, math.ceil(math.log2(params.num_buckets)))
        self._dpf = DistributedPointFunction.create(
            DpfParameters(
                log_domain_size=log_domain_size, value_type=XorType(128)
            )
        )
        self._num_blocks = database.num_selection_blocks

    @staticmethod
    def generate_params(
        num_elements: int,
        hash_family: int = HASH_FAMILY_SHA256,
        seed: bytes | None = None,
    ) -> CuckooHashingParams:
        if num_elements <= 0:
            raise ValueError("num_elements must be positive")
        if seed is None:
            seed = secrets.token_bytes(HASH_FUNCTION_SEED_LENGTH_BYTES)
        return CuckooHashingParams(
            num_buckets=int(BUCKETS_PER_ELEMENT * num_elements),
            num_hash_functions=NUM_HASH_FUNCTIONS,
            hash_family_config=HashFamilyConfig(
                hash_family=hash_family, seed=seed
            ),
        )

    # -- constructors -------------------------------------------------------

    @classmethod
    def create_plain(cls, params, database, mesh=None):
        return cls(params, database, mesh=mesh)

    @classmethod
    def create_leader(cls, params, database,
                      sender: ForwardHelperRequestFn, mesh=None):
        server = cls(params, database, mesh=mesh)
        server.make_leader(sender)
        return server

    @classmethod
    def create_helper(cls, params, database,
                      decrypter: DecryptHelperRequestFn, mesh=None):
        server = cls(params, database, mesh=mesh)
        server.make_helper(decrypter, ENCRYPTION_CONTEXT_INFO)
        return server

    # -- request handling ---------------------------------------------------

    @property
    def public_params(self) -> CuckooHashingParams:
        """The params a client needs (hash config + bucket count)."""
        return self._params

    @property
    def database(self) -> CuckooHashedDpfPirDatabase:
        """The currently-serving sparse database (the snapshot manager
        reads its generation tag, mirroring `DenseDpfPirServer`)."""
        return self._database

    def validate_snapshot(
        self, database: CuckooHashedDpfPirDatabase
    ) -> None:
        """Raise ValueError unless `database` is swappable in place of
        the serving one: same cuckoo geometry (bucket count, hash count,
        hash family + seed — a client hashing with the serving params
        must land on the staged layout's buckets) and the same dense
        row shapes (a staged selection batch must stay valid across the
        flip). The serving runtime (`serving/snapshots.py`) calls this
        polymorphically during `SnapshotManager.stage` and converts the
        ValueError into a typed `SnapshotMismatch`."""
        if database is None:
            raise ValueError("database cannot be None")
        if not hasattr(database, "num_buckets"):
            raise ValueError(
                "sparse server cannot serve a dense database snapshot"
            )
        if database.num_buckets != self._params.num_buckets:
            raise ValueError(
                f"snapshot has {database.num_buckets} buckets, serving "
                f"geometry has {self._params.num_buckets}"
            )
        staged_params = getattr(database, "params", None)
        if staged_params is not None and staged_params != self._params:
            raise ValueError(
                "snapshot cuckoo params (hash count/family/seed) do not "
                "match the serving geometry"
            )
        if database.num_selection_blocks != self._num_blocks:
            raise ValueError(
                f"snapshot spans {database.num_selection_blocks} "
                f"selection blocks, serving database spans "
                f"{self._num_blocks}"
            )
        for name, staged, cur in (
            ("key", database.key_database,
             self._database.key_database),
            ("value", database.value_database,
             self._database.value_database),
        ):
            if staged.max_value_size != cur.max_value_size:
                raise ValueError(
                    f"snapshot {name} rows pack "
                    f"{staged.max_value_size} bytes, serving database "
                    f"packs {cur.max_value_size}"
                )

    def swap_database(
        self, database: CuckooHashedDpfPirDatabase
    ) -> CuckooHashedDpfPirDatabase:
        """Atomically replace the serving sparse database (the snapshot
        flip). Geometry is validated first (`validate_snapshot`); the
        sharded step is retained — identical geometry compiles to the
        same shapes — but the per-device database shards restage from
        the new generation. Returns the previous database."""
        self.validate_snapshot(database)
        old, self._database = self._database, database
        if self._sharded_dbs is not None:
            from ..parallel.sharded import (
                pad_rows_to_mesh,
                shard_database,
            )

            ndev = self._mesh.devices.size
            self._sharded_dbs = tuple(
                shard_database(
                    self._mesh, pad_rows_to_mesh(dense.db_words, ndev)
                )
                for dense in (database.key_database,
                              database.value_database)
            )
        return old

    def get_public_params(self):
        """Wire-format params (`cuckoo_hashing_sparse_dpf_pir_server.h:99`):
        a `PirServerPublicParams` proto the client consumes remotely."""
        from .. import serialization

        return serialization.public_params_to_proto(self._params)

    @property
    def dpf(self) -> DistributedPointFunction:
        return self._dpf

    def _parse_helper_request(self, data: bytes) -> "messages.HelperRequest":
        return messages.parse_helper_request(self._dpf, data)

    def handle_plain_request(
        self, request: "messages.PirRequest"
    ) -> "messages.PirResponse":
        if request.plain_request is None:
            raise ValueError("request must contain a valid PlainRequest")
        keys = request.plain_request.dpf_keys
        if not keys:
            raise ValueError("dpf_keys must not be empty")
        expected_cw = self._dpf._tree_levels_needed - 1
        for key in keys:
            if key.party not in (0, 1):
                raise ValueError("key.party must be 0 or 1")
            if len(key.correction_words) != expected_cw:
                raise ValueError(
                    f"key has {len(key.correction_words)} correction words, "
                    f"expected {expected_cw}"
                )
        if self._mesh is not None:
            pairs = self._inner_products_sharded(keys)
        else:
            selections = selection_blocks_for_keys(
                self._dpf, keys, self._num_blocks
            )
            pairs = self._database.inner_product_with(selections)
        masked = []
        for key_bytes, value_bytes in pairs:
            masked.append(key_bytes)
            masked.append(value_bytes)
        return messages.PirResponse(
            dpf_pir_response=messages.DpfPirResponse(masked_response=masked)
        )

    # -- multi-chip serving -------------------------------------------------

    def _ensure_sharded(self):
        """Build the two-database sharded step once: bucket rows pad to
        128 * mesh size; the expansion covers the padded block count so
        every device's bit range is defined."""
        if self._sharded_step is not None:
            return
        from ..parallel.sharded import (
            pad_rows_to_mesh,
            shard_database,
            sharded_dense_pir_step_multi,
        )

        ndev = self._mesh.devices.size
        dbs = [
            pad_rows_to_mesh(dense.db_words, ndev)
            for dense in (self._database.key_database,
                          self._database.value_database)
        ]
        padded_blocks = dbs[0].shape[0] // 128
        total_levels = self._dpf._tree_levels_needed - 1
        expand_levels = min(
            max(0, (padded_blocks - 1).bit_length()), total_levels
        )
        self._sharded_step = sharded_dense_pir_step_multi(
            self._mesh,
            walk_levels=total_levels - expand_levels,
            expand_levels=expand_levels,
            num_blocks=padded_blocks,
            num_databases=2,
            real_num_blocks=self._num_blocks,
        )
        self._sharded_dbs = tuple(
            shard_database(self._mesh, db) for db in dbs
        )

    def _inner_products_sharded(self, keys):
        import numpy as np

        from ..parallel.sharded import pad_staged_queries
        from .dense_eval import stage_keys

        self._ensure_sharded()
        num_keys = len(keys)
        staged = pad_staged_queries(
            stage_keys(keys), self._mesh.devices.size
        )
        out_keys, out_values = self._sharded_step(
            *staged, *self._sharded_dbs
        )
        results = [
            words_to_record_bytes(
                np.asarray(out), num_keys, dense.max_value_size
            )
            for dense, out in (
                (self._database.key_database, out_keys),
                (self._database.value_database, out_values),
            )
        ]
        return list(zip(results[0], results[1]))
