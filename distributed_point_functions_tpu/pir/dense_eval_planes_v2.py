"""Key-major plane-resident dense-PIR expansion (v2): the layout-clean
XLA path.

The v1 plane expansion (`dense_eval_planes.py`) keeps the lane axis
node-major/key-minor (lane = node * key_groups + key_group). That forces
two per-level layout materializations the r04 xprof blames for ~70% of
the serving expansion step (copies 8.1 ms + reshapes 5.25 ms +
concatenates 2.3 ms of a 23.1 ms step):

* per-key correction operands must be broadcast *periodically* along the
  minor lane axis (`_tile_keys`: a `jnp.tile` = broadcast + reshape whose
  intermediate has the tiny key-group count in a tiled dimension — a
  pad-heavy relayout materializing a state-sized array every level), and
* the exit permutation back to natural block order is a state-sized
  gather every batch.

v2 removes both by construction:

* **Key-group axis leading.** State is `uint32[kg, 16, 8, W]` (kg =
  padded_keys/32, W = subtree width): the tiled physical dims are always
  (8, W), so no shape in the level loop carries a padded tile, and every
  per-key operand (`[kg, 16, 8, 1]` seed corrections, `[kg, 1]`
  direction words) broadcasts along the minor W axis **natively** — zero
  materialized operands. The plane ops (`sigma_planes`,
  `aes_rounds_planes`, `mmo_hash_planes`) are elementwise over the
  trailing lane axis, so `jax.vmap` over the leading kg axis reuses them
  unchanged.
* **No exit gather in serving mode.** Leaves exit in the doubling
  (bit-reversed) order; `bitrev_leaves=True` hands them to the inner
  product as-is, and the serving side bit-reversal-permutes the
  database's record *blocks* once at staging (`bitrev_permutation` is an
  involution), so per-batch cost is zero. `bitrev_leaves=False` applies
  the natural-order gather for bit-identity with
  `dense_eval.evaluate_selection_blocks` (differential tests).

Reference semantics: `ExpandSeeds` breadth-first buffer reuse
(`dpf/distributed_point_function.cc:289-372`) restricted to the covering
subtree; the [all-left; all-right] append per level is the same
recurrence per key pyramid, so the per-key leaf order is the classic
bit-reversal, exactly as v1's pure-XLA path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import keys as fixed_keys
from ..ops.aes_bitslice import (
    aes_rounds_planes,
    limbs_to_planes,
    mmo_hash_planes,
    planes_to_limbs,
    sigma_planes,
)
from ..ops.inner_product import xor_inner_product_accumulate
from ..ops.inner_product_pallas import xor_inner_product_pallas2_accumulate
from .dense_eval import _walk_zeros
from .dense_eval_planes import (
    bitrev_permutation,
    pack_key_bits,
    pack_key_planes,
)

U32 = jnp.uint32

_sigma_v = jax.vmap(sigma_planes)
_aes_v = jax.vmap(aes_rounds_planes, in_axes=(None, 0))
_mmo_v = jax.vmap(mmo_hash_planes, in_axes=(None, 0))


def pack_key_planes_kg(cw: jnp.ndarray) -> jnp.ndarray:
    """uint32[nkp, 4] per-key 128-bit words -> uint32[kg, 16, 8, 1]
    key-major plane masks (native broadcast operand along W)."""
    return jnp.moveaxis(pack_key_planes(cw), -1, 0)[..., None]


def expand_level_planes_v2(state, ctrl, cw_p, cwl_w, cwr_w):
    """One [all-left; all-right] doubling level in key-major layout.

    state: uint32[kg, 16, 8, W] planes; ctrl: uint32[kg, W] packed parent
    control bits (word [k, n] = keys 32k..32k+31 at node n); cw_p:
    uint32[kg, 16, 8, 1] seed-correction planes; cwl_w / cwr_w:
    uint32[kg, 1] packed direction-correction words. Returns
    (state [kg, 16, 8, 2W], ctrl [kg, 2W])."""
    sig = _sigma_v(state)
    left = _aes_v(fixed_keys.RK_LEFT, sig) ^ sig
    right = _aes_v(fixed_keys.RK_RIGHT, sig) ^ sig
    st = jnp.concatenate([left, right], axis=-1)
    ctrl2 = jnp.concatenate([ctrl, ctrl], axis=-1)
    st = st ^ (cw_p & ctrl2[:, None, None, :])
    t_new = st[:, 0, 0]  # LSB plane = control bits
    st = st.at[:, 0, 0].set(jnp.zeros_like(t_new))
    w = ctrl.shape[-1]
    kg = ctrl.shape[0]
    cw_dir = jnp.concatenate(
        [
            jnp.broadcast_to(cwl_w, (kg, w)),
            jnp.broadcast_to(cwr_w, (kg, w)),
        ],
        axis=-1,
    )
    return st, t_new ^ (ctrl2 & cw_dir)


def _pad_keys32(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc):
    """Pad the per-key operand set to a 32-multiple of keys (the plane
    packing granule). Padded keys expand to garbage-but-deterministic
    leaves; callers slice results back to the real key count."""
    nk = seeds0.shape[0]
    pad_keys = (-nk) % 32
    if pad_keys:
        seeds0 = jnp.pad(seeds0, ((0, pad_keys), (0, 0)))
        control0 = jnp.pad(control0, ((0, pad_keys),))
        cw_seeds = jnp.pad(cw_seeds, ((0, 0), (0, pad_keys), (0, 0)))
        cw_left = jnp.pad(cw_left, ((0, 0), (0, pad_keys)))
        cw_right = jnp.pad(cw_right, ((0, 0), (0, pad_keys)))
        last_vc = jnp.pad(last_vc, ((0, pad_keys), (0, 0)))
    return seeds0, control0, cw_seeds, cw_left, cw_right, last_vc


def _planes_leaves_to_blocks(values: jnp.ndarray) -> jnp.ndarray:
    """Leave plane space once: value planes [kg, 16, 8, w] ->
    packed selection blocks [kg*32, w, 4] (leaf axis order preserved)."""
    kg = values.shape[0]
    w = values.shape[-1]
    lim = jax.vmap(planes_to_limbs)(values)  # [kg, w*32, 4]
    lim = lim.reshape(kg, w, 32, 4)
    out = jnp.moveaxis(lim, 0, 1).reshape(w, kg * 32, 4)
    return jnp.moveaxis(out, 0, 1)  # [kg*32, w, 4]


def evaluate_selection_blocks_planes_v2(
    seeds0: jnp.ndarray,
    control0: jnp.ndarray,
    cw_seeds: jnp.ndarray,
    cw_left: jnp.ndarray,
    cw_right: jnp.ndarray,
    last_vc: jnp.ndarray,
    *,
    walk_levels: int,
    expand_levels: int,
    num_blocks: int,
    bitrev_leaves: bool = False,
) -> jnp.ndarray:
    """Drop-in for `dense_eval.evaluate_selection_blocks` (bit-identical
    output with `bitrev_leaves=False`), computed with the key-major
    plane expansion.

    With `bitrev_leaves=True` the leaf axis stays in doubling order
    (natural block g at position bitrev(g)) and is NOT truncated to
    `num_blocks` — for serving paths that bit-reverse the database's
    record blocks at staging instead.
    """
    nk = seeds0.shape[0]
    seeds0, control0, cw_seeds, cw_left, cw_right, last_vc = _pad_keys32(
        seeds0, control0, cw_seeds, cw_left, cw_right, last_vc
    )

    # Phase 1 (limb space, [nk, 4] only): walk the all-zeros prefix.
    seeds, control = _walk_zeros(
        seeds0, control0, cw_seeds[:walk_levels], cw_left[:walk_levels]
    )

    # Enter key-major plane space once: [kg, 16, 8, 1].
    state = jnp.moveaxis(limbs_to_planes(seeds), -1, 0)[..., None]
    ctrl = pack_key_bits(control.astype(U32))[:, None]  # [kg, 1]

    for i in range(expand_levels):
        lvl = walk_levels + i
        state, ctrl = expand_level_planes_v2(
            state,
            ctrl,
            pack_key_planes_kg(cw_seeds[lvl]),
            pack_key_bits(cw_left[lvl])[:, None],
            pack_key_bits(cw_right[lvl])[:, None],
        )

    # Leaf value blocks: output PRG + XOR value correction (party
    # negation is the identity for XOR shares).
    values = _mmo_v(fixed_keys.RK_VALUE, state)
    values = values ^ (pack_key_planes_kg(last_vc) & ctrl[:, None, None, :])

    out = _planes_leaves_to_blocks(values)  # [nkp, w, 4]
    if not bitrev_leaves:
        perm = jnp.asarray(bitrev_permutation(expand_levels))
        out = out[:, perm, :][:, :num_blocks, :]
        if out.shape[1] < num_blocks:
            # Blocks beyond the tree's capacity (mesh-padded databases)
            # can only select guaranteed-zero rows.
            out = jnp.pad(
                out, ((0, 0), (0, num_blocks - out.shape[1]), (0, 0))
            )
    return out[:nk]


def bitrev_block_permute_records(db_host: np.ndarray) -> np.ndarray:
    """Bit-reversal-permute a record-major database's 128-record blocks
    (host-side, once at staging) so a `bitrev_leaves=True` expansion's
    selection vector lines up with it. The permutation is an involution;
    responses are XOR-sums over (selection, record) pairs, so applying
    the same permutation to both sides leaves every response unchanged.
    """
    num_records = db_host.shape[0]
    if num_records % 128:
        raise ValueError("record count must be padded to a multiple of 128")
    num_blocks = num_records // 128
    levels = max(0, (num_blocks - 1).bit_length())
    if num_blocks != 1 << levels:
        raise ValueError("block count must be a power of two")
    perm = bitrev_permutation(levels)
    return (
        db_host.reshape(num_blocks, 128, -1)[perm]
        .reshape(num_records, -1)
    )


# ---------------------------------------------------------------------------
# Streaming fused expand -> inner-product serving pipeline.
#
# The covering subtree is expanded down to `cut_levels` once; the last
# `chunk_levels` doubling levels then run inside a jitted `lax.scan`, one
# tail subtree (= one cut-state lane) per step, and each step's selection
# blocks are XOR/MXU-accumulated against the matching database block span
# immediately.  The full `uint32[num_queries, num_blocks, 4]` selection
# matrix never exists in HBM, and XLA double-buffers the next database
# chunk read against the current tail expansion.
#
# Block order.  After `cut` doubling levels, cut-state lane c holds the
# node whose natural cut-bit prefix is bitrev_cut(c); expanding that lane
# alone `r` more levels emits sub-leaf position q holding natural
# sub-index bitrev_r(q).  Scan step c therefore covers natural blocks
#     (bitrev_cut(c) << r) | bitrev_r(q),  q = 0..2^r-1,
# which is NOT a contiguous span of the full-bitrev staging (a contiguous
# full-bitrev span is a set of leaves sharing a path *suffix*, scattered
# across all tail subtrees).  The database is instead staged once in this
# *blocked* bit-reversed block order (`streaming_block_order`, an
# involution that degenerates to the plain bit-reversal when cut == 0 or
# r == 0), so every scan step reads one contiguous chunk.
# ---------------------------------------------------------------------------


def streaming_block_order(expand_levels: int, cut_levels: int) -> np.ndarray:
    """Natural block index held at each staged position of the streaming
    database layout: position c * 2^r + q (scan step c, row-block q)
    holds natural block (bitrev_cut(c) << r) | bitrev_r(q), with
    r = expand_levels - cut_levels."""
    if not 0 <= cut_levels <= expand_levels:
        raise ValueError("cut_levels must be in [0, expand_levels]")
    r = expand_levels - cut_levels
    pre = np.asarray(bitrev_permutation(cut_levels), dtype=np.int64)
    sub = np.asarray(bitrev_permutation(r), dtype=np.int64)
    return ((pre[:, None] << r) | sub[None, :]).reshape(-1)


def streaming_block_permute_records(
    db_host: np.ndarray, cut_levels: int
) -> np.ndarray:
    """Permute a record-major database's 128-record blocks into streaming
    block order (host-side, once at staging). Row count must already be
    padded to a power-of-two block count covering the tree."""
    num_records = db_host.shape[0]
    if num_records % 128:
        raise ValueError("record count must be padded to a multiple of 128")
    num_blocks = num_records // 128
    levels = max(0, (num_blocks - 1).bit_length())
    if num_blocks != 1 << levels:
        raise ValueError("block count must be a power of two")
    order = streaming_block_order(levels, cut_levels)
    return (
        db_host.reshape(num_blocks, 128, -1)[order]
        .reshape(num_records, -1)
    )


def _packed_levels(cw_seeds, cw_left, cw_right, lo: int, hi: int):
    """Pre-pack per-level correction operands for doubling levels
    [lo, hi) into key-major plane form (kept outside scan bodies so the
    packing is not re-traced per step)."""
    cwp = [pack_key_planes_kg(cw_seeds[lvl]) for lvl in range(lo, hi)]
    cwl = [pack_key_bits(cw_left[lvl])[:, None] for lvl in range(lo, hi)]
    cwr = [pack_key_bits(cw_right[lvl])[:, None] for lvl in range(lo, hi)]
    return cwp, cwl, cwr


def streaming_cut_state(
    seeds0,
    control0,
    cw_seeds,
    cw_left,
    cw_right,
    *,
    walk_levels: int,
    cut_levels: int,
):
    """Walk the all-zeros prefix and expand the covering subtree down to
    the cut: the resumable state the streaming scan slices per step.

    Operands must already be 32-multiple padded (`_pad_keys32`). Returns
    (state [kg, 16, 8, 2^cut], ctrl [kg, 2^cut])."""
    seeds, control = _walk_zeros(
        seeds0, control0, cw_seeds[:walk_levels], cw_left[:walk_levels]
    )
    state = jnp.moveaxis(limbs_to_planes(seeds), -1, 0)[..., None]
    ctrl = pack_key_bits(control.astype(U32))[:, None]
    cwp, cwl, cwr = _packed_levels(
        cw_seeds, cw_left, cw_right, walk_levels, walk_levels + cut_levels
    )
    for level in range(cut_levels):
        state, ctrl = expand_level_planes_v2(
            state, ctrl, cwp[level], cwl[level], cwr[level]
        )
    return state, ctrl


def streaming_tail_selections(state, ctrl, tail_cwp, tail_cwl, tail_cwr, vc_p):
    """Resumable tail expansion: finish one tail subtree from its
    cut-level state slice and emit its packed selection blocks.

    state [kg, 16, 8, n] / ctrl [kg, n] (n = 1 inside the scan),
    tail_* are `_packed_levels` lists, vc_p = `pack_key_planes_kg` of
    the value correction. Returns uint32[kg*32, n << len(tail_cwp), 4]
    in single-subtree doubling (bit-reversed) leaf order."""
    for cwp, cwl, cwr in zip(tail_cwp, tail_cwl, tail_cwr):
        state, ctrl = expand_level_planes_v2(state, ctrl, cwp, cwl, cwr)
    values = _mmo_v(fixed_keys.RK_VALUE, state)
    values = values ^ (vc_p & ctrl[:, None, None, :])
    return _planes_leaves_to_blocks(values)


def streaming_scan_accumulate(
    state,
    ctrl,
    db_chunks,
    tail_cwp,
    tail_cwl,
    tail_cwr,
    vc_p,
    *,
    ip: str = "jnp",
    interpret: bool = False,
    vma=(),
):
    """Scan the cut-state lanes against the streaming-staged database
    chunks, fusing tail expansion with the XOR inner product.

    db_chunks: uint32[n, chunk_records, W] row-major (ip="jnp") or
    uint32[n, 32, Gc, W] bit-major (ip="pallas2"), where n matches the
    lane count of `state`. Returns uint32[kg*32, W] accumulators."""
    num_lanes = state.shape[-1]
    if db_chunks.shape[0] != num_lanes:
        raise ValueError(
            f"db_chunks leading axis {db_chunks.shape[0]} != cut-state "
            f"lane count {num_lanes}"
        )
    st_x = jnp.moveaxis(state, -1, 0)[..., None]  # [n, kg, 16, 8, 1]
    ct_x = jnp.moveaxis(ctrl, -1, 0)[..., None]  # [n, kg, 1]

    def body(acc, xs):
        db_c, st, ct = xs
        sel = streaming_tail_selections(
            st, ct, tail_cwp, tail_cwl, tail_cwr, vc_p
        )
        if ip == "pallas2":
            acc = xor_inner_product_pallas2_accumulate(
                acc, db_c, sel, interpret=interpret, vma=vma
            )
        else:
            acc = xor_inner_product_accumulate(acc, db_c, sel)
        return acc, None

    nkp = state.shape[0] * 32
    acc0 = jnp.zeros((nkp, db_chunks.shape[-1]), U32)
    acc, _ = jax.lax.scan(body, acc0, (db_chunks, st_x, ct_x))
    return acc


@functools.partial(
    jax.jit,
    static_argnames=("walk_levels", "cut_levels", "chunk_levels", "ip", "interpret"),
)
def streaming_pir_inner_products_v2(
    seeds0,
    control0,
    cw_seeds,
    cw_left,
    cw_right,
    last_vc,
    db_chunks,
    *,
    walk_levels: int,
    cut_levels: int,
    chunk_levels: int,
    ip: str = "jnp",
    interpret: bool = False,
):
    """One jitted streaming serving step: expansion fused with the XOR
    inner product, never materializing the selection matrix.

    The database must be staged in streaming block order
    (`streaming_block_permute_records` with the same `cut_levels`) and
    split into `2^cut_levels` chunks along the leading axis — bit-major
    per chunk for ip="pallas2". Returns uint32[num_keys, W] XOR-share
    inner products, bit-identical to the materialized path."""
    levels = walk_levels + cut_levels + chunk_levels
    if cw_seeds.shape[0] != levels:
        raise ValueError(
            f"key has {cw_seeds.shape[0]} correction levels; plan needs "
            f"walk {walk_levels} + cut {cut_levels} + chunk {chunk_levels}"
        )
    if db_chunks.shape[0] != 1 << cut_levels:
        raise ValueError(
            f"expected {1 << cut_levels} database chunks, got "
            f"{db_chunks.shape[0]}"
        )
    nk = seeds0.shape[0]
    seeds0, control0, cw_seeds, cw_left, cw_right, last_vc = _pad_keys32(
        seeds0, control0, cw_seeds, cw_left, cw_right, last_vc
    )
    state, ctrl = streaming_cut_state(
        seeds0,
        control0,
        cw_seeds,
        cw_left,
        cw_right,
        walk_levels=walk_levels,
        cut_levels=cut_levels,
    )
    tail_cwp, tail_cwl, tail_cwr = _packed_levels(
        cw_seeds, cw_left, cw_right, walk_levels + cut_levels, levels
    )
    vc_p = pack_key_planes_kg(last_vc)
    acc = streaming_scan_accumulate(
        state,
        ctrl,
        db_chunks,
        tail_cwp,
        tail_cwl,
        tail_cwr,
        vc_p,
        ip=ip,
        interpret=interpret,
    )
    return acc[:nk]
