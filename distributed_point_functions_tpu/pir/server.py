"""PIR servers: role logic (Plain / Leader / Helper) and the dense server.

`DpfPirServer` reimplements the deployment-role superclass of
`pir/dpf_pir_server.h:42-65`, `.cc:30-193`:

* **Plain** answers unencrypted requests directly.
* **Leader** receives a `LeaderRequest`, forwards the encrypted helper
  request through an injected `sender` callback while computing its own
  response in the `while_waiting` callback, then XOR-combines both masked
  responses.
* **Helper** decrypts its request via an injected `decrypter` callback,
  computes the response, and masks it with an AES-CTR one-time pad expanded
  from the client's seed.

Transport and encryption stay injected callbacks (the reference's
`ForwardHelperRequestFn` / `DecryptHelperRequestFn` seam,
`pir/dpf_pir_server.h:92-109`), so any RPC stack and hybrid-encryption
scheme plug in unchanged.

`DenseDpfPirServer` (`pir/dense_dpf_pir_server.h:32-74`) binds the role
logic to the dense database: each request's DPF keys are evaluated in one
fused, batched TPU pipeline (`dense_eval.py`) and pushed through the XOR
inner product.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Optional

from ..dpf import DistributedPointFunction, DpfParameters
from ..observability import events as events_mod
from ..observability import tracing
from ..observability import phases as phases_mod
from ..observability.device import default_telemetry, shape_key
from ..prng import Aes128CtrSeededPrng, xor_bytes
from ..value_types import XorType
from . import messages
from .database import DenseDpfPirDatabase, words_to_record_bytes
from .dense_eval import (
    donation_enabled,
    evaluate_selection_blocks,
    evaluate_selection_blocks_donated,
    serving_expansion,
    stage_keys,
    stage_keys_host,
    stage_keys_walked,
)
from .planner import (
    ServingPlan,
    plan_dense_serving,
    selection_budget_bytes,
    streaming_ip,
)

# sender(helper_request: PirRequest, while_waiting: Callable[[], None])
#   -> PirResponse
ForwardHelperRequestFn = Callable[..., "messages.PirResponse"]
# decrypter(ciphertext: bytes, context_info: bytes) -> bytes
DecryptHelperRequestFn = Callable[[bytes, bytes], bytes]

ENCRYPTION_CONTEXT_INFO = b"DpfPirServer"


# -- process-wide brownout tier floor ----------------------------------------
#
# The brownout ladder (`capacity/brownout.py`, wired by serving's
# `attach_brownout`) forces *every* server in the process to plan at or
# below a tier: cheaper tiers shrink peak HBM so concurrent sweeps and
# serving stop fighting for memory under SLO burn. 0 = unconstrained.
# Per-shape OOM demotion (`_tier_floor`) composes with it — the planner
# honors whichever floor is lower-tier.

_GLOBAL_TIER_FLOOR = 0
_GLOBAL_TIER_FLOOR_LOCK = threading.Lock()


def set_tier_floor(mode: Optional[str]) -> None:
    """Force every dense-PIR plan in this process to `mode` or cheaper
    ("materialized" / "streaming" / "chunked"); None or "materialized"
    clears the constraint."""
    global _GLOBAL_TIER_FLOOR
    tiers = DenseDpfPirServer._TIERS
    floor = 0 if mode is None else tiers.index(mode)
    with _GLOBAL_TIER_FLOOR_LOCK:
        _GLOBAL_TIER_FLOOR = floor
    tracing.runtime_counters.inc(
        f"pir.tier_floor.{'cleared' if floor == 0 else mode}"
    )


def clear_tier_floor() -> None:
    set_tier_floor(None)


def tier_floor() -> str:
    return DenseDpfPirServer._TIERS[_GLOBAL_TIER_FLOOR]


class DpfPirServer:
    """Role dispatch shared by all DPF-based PIR servers."""

    def __init__(self):
        self._role = "plain"
        self._sender: Optional[ForwardHelperRequestFn] = None
        self._decrypter: Optional[DecryptHelperRequestFn] = None
        self._encryption_context_info = ENCRYPTION_CONTEXT_INFO
        self._plain_handler: Optional[Callable] = None

    # -- role setup ---------------------------------------------------------

    def make_leader(self, sender: ForwardHelperRequestFn) -> None:
        if sender is None:
            raise ValueError("sender may not be None")
        self._sender = sender
        self._role = "leader"

    def make_helper(
        self,
        decrypter: DecryptHelperRequestFn,
        encryption_context_info: bytes = ENCRYPTION_CONTEXT_INFO,
    ) -> None:
        if decrypter is None:
            raise ValueError("decrypter may not be None")
        self._decrypter = decrypter
        self._encryption_context_info = encryption_context_info
        self._role = "helper"

    @property
    def role(self) -> str:
        return self._role

    def set_plain_handler(self, handler: Optional[Callable]) -> None:
        """Batch-entry hook: route every internal plain evaluation (the
        plain role's requests, the Leader's own share inside
        `while_waiting`, the Helper's decrypted request) through
        `handler(request) -> PirResponse` instead of calling
        `handle_plain_request` directly. `serving/` installs its dynamic
        batcher here; `None` restores the direct path. The handler may
        itself call `handle_plain_request` (the real evaluator) — the
        hook only intercepts the role-dispatch call sites."""
        self._plain_handler = handler

    def _dispatch_plain(self, request):
        if self._plain_handler is not None:
            return self._plain_handler(request)
        return self.handle_plain_request(request)

    def get_public_params(self):
        """`PirServerPublicParams` proto to send to a client before any
        queries (`pir/pir_server.h:31`, `dense_dpf_pir_server.cc:87-89`).
        The dense server has none, so the base returns the empty message;
        the sparse server fills in its cuckoo params."""
        from .. import serialization

        return serialization.public_params_to_proto(None)

    # -- request handling ---------------------------------------------------

    def handle_request(
        self, request: "messages.PirRequest"
    ) -> "messages.PirResponse":
        if self._role == "plain":
            return self._dispatch_plain(request)
        if self._role == "leader":
            return self._handle_leader_request(request)
        return self._handle_helper_request(request)

    def handle_plain_request(self, request):
        raise NotImplementedError

    def _parse_helper_request(self, data: bytes) -> "messages.HelperRequest":
        """Decode the decrypted helper request (subclass knows the DPF)."""
        raise NotImplementedError

    def _handle_leader_request(self, request):
        if request.leader_request is None:
            raise ValueError("request must be a valid LeaderRequest")
        leader_request = request.leader_request
        if leader_request.plain_request is None:
            raise ValueError("plain_request must be set")
        if leader_request.encrypted_helper_request is None:
            raise ValueError("encrypted_helper_request must be set")

        plain_request = messages.PirRequest(
            plain_request=leader_request.plain_request
        )
        helper_request = messages.PirRequest(
            encrypted_helper_request=leader_request.encrypted_helper_request
        )

        # The sender must invoke while_waiting (which computes the leader's
        # own share) — detect misbehaving senders like the reference does
        # (`dpf_pir_server.cc:111-115`).
        state = {"has_run": False, "response": None, "error": None}

        def while_waiting():
            try:
                state["response"] = self._dispatch_plain(plain_request)
            except Exception as e:  # surfaced after the sender returns
                state["error"] = e
            state["has_run"] = True

        helper_response = self._sender(helper_request, while_waiting)
        if not state["has_run"]:
            raise RuntimeError(
                "handle_request: while_waiting was not called from the "
                "sender passed at construction"
            )
        if state["error"] is not None:
            raise state["error"]
        leader_response = state["response"]

        hr = helper_response.dpf_pir_response.masked_response
        lr = leader_response.dpf_pir_response.masked_response
        if len(hr) != len(lr):
            raise RuntimeError(
                f"number of responses from Helper (={len(hr)}) does not "
                f"match the number of responses from Leader (={len(lr)})"
            )
        with tracing.span("combine"):
            combined = []
            for i, (h, l) in enumerate(zip(hr, lr)):
                if len(h) != len(l):
                    raise RuntimeError(
                        f"response size mismatch at index {i}: got {len(h)} "
                        f"(Helper) vs. {len(l)} (Leader)"
                    )
                combined.append(xor_bytes(h, l))
        return messages.PirResponse(
            dpf_pir_response=messages.DpfPirResponse(masked_response=combined)
        )

    def _handle_helper_request(self, request):
        if request.encrypted_helper_request is None:
            raise ValueError("request must be a valid EncryptedHelperRequest")
        with tracing.span("helper_decrypt"):
            decrypted = self._decrypter(
                request.encrypted_helper_request.encrypted_request,
                self._encryption_context_info,
            )
            inner = self._parse_helper_request(decrypted)
        response = self._dispatch_plain(
            messages.PirRequest(plain_request=inner.plain_request)
        )
        with tracing.span("mask"):
            prng = Aes128CtrSeededPrng(inner.one_time_pad_seed)
            masked = [
                xor_bytes(r, prng.get_random_bytes(len(r)))
                for r in response.dpf_pir_response.masked_response
            ]
        return messages.PirResponse(
            dpf_pir_response=messages.DpfPirResponse(masked_response=masked)
        )


class DenseDpfPirServer(DpfPirServer):
    """PIR over a dense index space (`pir/dense_dpf_pir_server.h:32`).

    Pass a `jax.sharding.Mesh` to serve across chips: the database is
    record-sharded over the mesh and every request runs the sharded
    expand+inner-product step (`parallel/sharded.py`) with XLA collectives
    over ICI; without a mesh, requests run the single-device fused
    pipeline (with the Pallas MXU inner product on TPU).
    """

    def __init__(self, database: DenseDpfPirDatabase, mesh=None):
        super().__init__()
        if database is None:
            raise ValueError("database cannot be None")
        if database.size <= 0:
            raise ValueError("database must not be empty")
        self._database = database
        self._mesh = mesh
        self._sharded_step = None
        self._sharded_db = None
        # 2-D mesh serving plan (shard axis x key axis): built lazily on
        # the first request; a build failure or device OOM parks the
        # error here and the tier-demotion chain falls back to
        # single-device for the rest of the process.
        self._mesh_plan = None
        self._mesh_db = None
        self._mesh_plan_error = None
        self._mesh_lock = threading.Lock()
        # Only one shard_map program may be in flight on the device
        # set at a time: the entry's cross-shard psum rendezvous
        # deadlocks if a second program (e.g. an unbatched prober
        # probe racing the batcher worker) interleaves its collectives
        # on the same devices.
        self._mesh_exec_lock = threading.Lock()
        self._chunked_db = None
        self._chunked_db_lock = threading.Lock()
        self._streaming_ip_failed = False
        # Runtime tier demotion: num_keys -> minimum tier index in
        # _TIERS after a device OOM proved the budget model optimistic
        # for that batch shape.
        self._tier_floor: dict[int, int] = {}
        self._log_domain_size = max(
            0, math.ceil(math.log2(database.size))
        )
        self._dpf = DistributedPointFunction.create(
            DpfParameters(
                log_domain_size=self._log_domain_size,
                value_type=XorType(128),
            )
        )
        # Only the first ceil(size/128) leaf blocks carry selection bits;
        # expand just the covering subtree (see dense_eval.py).
        self._num_blocks = database.num_selection_blocks
        k = max(0, (self._num_blocks - 1).bit_length())
        # Branching levels = number of correction words (the root level in
        # `_tree_levels_needed` does not branch).
        total_levels = self._dpf._tree_levels_needed - 1
        self._expand_levels = min(k, total_levels)
        self._walk_levels = total_levels - self._expand_levels
        # Build/load the native oracle for the host zeros-walk here, not
        # on the first request (a cold checkout spawns the g++ build).
        # Warm whenever a walk exists, regardless of the current
        # DPF_TPU_HOST_WALK value: handle_request re-reads the env per
        # request, so the flag may be flipped on after construction and
        # the first live request must not pay the g++ build.
        from .dense_eval import warm_host_walk

        if self._walk_levels > 0:
            warm_host_walk()

    # -- constructors mirroring CreatePlain/Leader/Helper -------------------

    @classmethod
    def create_plain(
        cls, database: DenseDpfPirDatabase, mesh=None
    ) -> "DenseDpfPirServer":
        return cls(database, mesh=mesh)

    @classmethod
    def create_leader(
        cls,
        database: DenseDpfPirDatabase,
        sender: ForwardHelperRequestFn,
        mesh=None,
    ) -> "DenseDpfPirServer":
        server = cls(database, mesh=mesh)
        server.make_leader(sender)
        return server

    @classmethod
    def create_helper(
        cls,
        database: DenseDpfPirDatabase,
        decrypter: DecryptHelperRequestFn,
        mesh=None,
    ) -> "DenseDpfPirServer":
        server = cls(database, mesh=mesh)
        server.make_helper(decrypter, ENCRYPTION_CONTEXT_INFO)
        return server

    @property
    def dpf(self) -> DistributedPointFunction:
        return self._dpf

    @property
    def database(self) -> DenseDpfPirDatabase:
        return self._database

    def swap_database(
        self, database: DenseDpfPirDatabase
    ) -> DenseDpfPirDatabase:
        """Atomically replace the served database with a new generation.

        Only safe at a batch boundary: `handle_plain_request` reads
        `self._database` several times per call, so the caller
        (`serving/snapshots.py`) must guarantee no evaluation is in
        flight. Geometry must match — the DPF parameters, expand/walk
        split, and sharded/chunked plans are all derived from the
        original database and are kept; same-geometry replacements only
        need the staged-buffer caches dropped.

        Returns the old database (still staged; the caller drains and
        frees it via `release_stagings()`).
        """
        if database is None:
            raise ValueError("database cannot be None")
        old = self._database
        if database.size != old.size:
            raise ValueError(
                f"swap_database size mismatch: {database.size} != {old.size}"
            )
        if database.num_selection_blocks != old.num_selection_blocks:
            raise ValueError(
                "swap_database selection-block mismatch: "
                f"{database.num_selection_blocks} != "
                f"{old.num_selection_blocks}"
            )
        if database.max_value_size != old.max_value_size:
            raise ValueError(
                "swap_database max_value_size mismatch: "
                f"{database.max_value_size} != {old.max_value_size}"
            )
        self._database = database
        with self._chunked_db_lock:
            self._chunked_db = None
        # The sharded step (a compiled function of the geometry) is
        # reusable; only the placed database must restage.
        self._sharded_db = None
        if self._sharded_step is not None:
            from ..parallel.sharded import pad_rows_to_mesh, shard_database

            ndev = self._mesh.devices.size
            self._sharded_db = shard_database(
                self._mesh, pad_rows_to_mesh(database.db_words, ndev)
            )
        with self._mesh_lock:
            plan = self._mesh_plan
        if plan is not None:
            # All shards flip in this one reference assignment: the new
            # generation's sharded staging is assembled in full (a cache
            # hit when `prestage_database` ran during snapshot staging)
            # before any request can observe it, so no request ever sees
            # shard i from generation N and shard j from N+1.
            self._mesh_db = database.streaming_chunks(
                cut_levels=plan.cut_levels,
                bitmajor=plan.bitmajor,
                mesh=self._mesh,
                shard_axis=plan.shard_axis,
            )
        return old

    def _parse_helper_request(self, data: bytes) -> "messages.HelperRequest":
        return messages.parse_helper_request(self._dpf, data)

    def handle_plain_request(
        self, request: "messages.PirRequest"
    ) -> "messages.PirResponse":
        if request.plain_request is None:
            raise ValueError("request must contain a valid PlainRequest")
        keys = request.plain_request.dpf_keys
        if not keys:
            raise ValueError("dpf_keys must not be empty")
        expected_cw = self._dpf._tree_levels_needed - 1
        for key in keys:
            if key.party not in (0, 1):
                raise ValueError("key.party must be 0 or 1")
            if len(key.correction_words) != expected_cw:
                raise ValueError(
                    f"key has {len(key.correction_words)} correction words, "
                    f"expected {expected_cw}"
                )
        impl, bitrev = serving_expansion()
        if impl is evaluate_selection_blocks and donation_enabled():
            # ROADMAP 3c: the materialized single-device entry donates
            # its per-request staged key tensors (freshly placed by
            # `stage_keys_walked`, dead after the call) so XLA can
            # reuse their HBM for the selection matrix. The resident
            # database buffer is a different argument path entirely
            # (`inner_product_with`) and is never donated.
            impl = evaluate_selection_blocks_donated
        if bitrev and (1 << self._expand_levels) < self._num_blocks:
            # The tree cannot cover the padded block count (domain
            # smaller than the database): the bitrev staging has no
            # zero-extension story there, so serve natural order.
            bitrev = False
        telemetry = default_telemetry()
        # Phase attribution: the first dispatch of `pir.plain` at a new
        # shape is dominated by trace+compile ("compile"); re-dispatches
        # are the steady-state device step ("device_compute"). `seen` is
        # checked BEFORE entering dispatch() — dispatch registers the
        # shape on exit.
        seen = telemetry.compile_tracker.seen
        if self._mesh_is_2d():
            inner_products = self._serve_mesh(keys, telemetry, seen)
            if inner_products is None:  # plan infeasible / device OOM
                inner_products = self._serve_single_device(
                    keys, bitrev, impl, telemetry, seen
                )
        elif self._mesh is not None:
            with phases_mod.phase("h2d_transfer"):
                staged = stage_keys(keys)
            key = shape_key(
                ("m", "sharded"), ("q", len(keys)), ("b", self._num_blocks)
            )
            step = "device_compute" if seen("pir.plain", key) else "compile"
            with tracing.span("evaluate_sharded", num_keys=len(keys)), \
                    telemetry.hbm.phase("selection"), \
                    telemetry.compile_tracker.dispatch("pir.plain", key), \
                    phases_mod.phase(step):
                inner_products = self._inner_products_sharded(
                    staged, len(keys)
                )
        else:
            inner_products = self._serve_single_device(
                keys, bitrev, impl, telemetry, seen
            )
        return messages.PirResponse(
            dpf_pir_response=messages.DpfPirResponse(
                masked_response=inner_products
            )
        )

    # -- single-device serving with runtime tier demotion --------------------

    # Planner tiers ordered by decreasing peak HBM appetite; a device
    # OOM at dispatch demotes the shape to the next tier and retries.
    _TIERS = ("materialized", "streaming", "chunked")

    def _serve_single_device(self, keys, bitrev, impl, telemetry, seen):
        """Plan and execute one single-device batch, retrying at the
        next planner tier down when the device reports OOM at dispatch
        (the budget model is an estimate; the device is the truth)."""
        while True:
            plan = self._plan_serving(len(keys), bitrev)
            try:
                return self._execute_plan(
                    plan, keys, bitrev, impl, telemetry, seen
                )
            except Exception as exc:  # noqa: BLE001 - OOM-gated below
                if not self._demote_tier_on_oom(plan, len(keys), exc):
                    raise

    def _execute_plan(self, plan, keys, bitrev, impl, telemetry, seen):
        # Stamp the executed planner tier onto the enclosing phase
        # record (the batcher's batch-scoped record during batched
        # serving): the cost-ledger join reads `serving_plan` back to
        # key its predicted-vs-actual residual cell by tier.
        record = phases_mod.current_request()
        if record is not None:
            record.set_meta(
                "serving_plan",
                {"mode": plan.mode, "num_keys": plan.num_keys},
            )
        if plan.mode == "streaming":
            key = shape_key(
                ("m", f"streaming-{plan.ip}"),
                ("q", plan.num_keys),
                ("b", self._num_blocks),
                ("c", plan.cut_levels),
            )
            step = (
                "device_compute" if seen("pir.plain", key) else "compile"
            )
            with tracing.span(
                "evaluate_streaming", num_keys=plan.num_keys, ip=plan.ip
            ), telemetry.hbm.phase("selection"), \
                    telemetry.compile_tracker.dispatch("pir.plain", key), \
                    phases_mod.phase(step):
                return self._inner_products_streaming(plan, keys)
        if plan.mode == "chunked":
            with phases_mod.phase("h2d_transfer"):
                staged = stage_keys(keys)
            key = shape_key(
                ("m", "chunked"),
                ("q", plan.num_keys),
                ("b", self._num_blocks),
                ("c", plan.chunk_levels),
            )
            step = (
                "device_compute" if seen("pir.plain", key) else "compile"
            )
            with tracing.span("evaluate_chunked", num_keys=plan.num_keys), \
                    telemetry.hbm.phase("selection"), \
                    telemetry.compile_tracker.dispatch("pir.plain", key), \
                    phases_mod.phase(step):
                return self._inner_products_chunked(
                    staged, plan.num_keys, plan
                )
        # Walk the shared all-zeros prefix on the host during staging
        # (sub-ms there vs ~1.4 ms of dispatch-bound device AES per
        # batch); the device step starts at the expansion root.
        # DPF_TPU_HOST_WALK=0 restores the on-device walk.
        key = shape_key(
            ("m", "bitrev" if bitrev else "materialized"),
            ("q", plan.num_keys),
            ("b", self._num_blocks),
        )
        step = "device_compute" if seen("pir.plain", key) else "compile"
        with tracing.span(
            "evaluate_materialized", num_keys=plan.num_keys
        ), telemetry.hbm.phase("selection"), \
                telemetry.compile_tracker.dispatch("pir.plain", key), \
                phases_mod.phase(step):
            # Nested bracket: staging time lands in h2d_transfer
            # and is deducted from the enclosing compute phase
            # (exclusive-time semantics).
            with phases_mod.phase("h2d_transfer"):
                staged, device_walk = stage_keys_walked(
                    keys, self._walk_levels
                )
            selections = impl(
                *staged,
                walk_levels=device_walk,
                expand_levels=self._expand_levels,
                num_blocks=self._num_blocks,
                **({"bitrev_leaves": True} if bitrev else {}),
            )
            return self._database.inner_product_with(
                selections, bitrev_blocks=bitrev
            )

    @staticmethod
    def _is_resource_exhausted(exc: BaseException) -> bool:
        text = f"{type(exc).__name__}: {exc}"
        return any(
            marker in text
            for marker in ("RESOURCE_EXHAUSTED", "Out of memory", "OOM")
        )

    def _demote_tier_on_oom(
        self, plan: ServingPlan, num_keys: int, exc: BaseException
    ) -> bool:
        """Record a device OOM against `num_keys` and say whether the
        batch can retry one tier down. Non-OOM errors never demote."""
        if not self._is_resource_exhausted(exc):
            return False
        current = self._TIERS.index(plan.mode)
        if current + 1 >= len(self._TIERS) or self._expand_levels <= 0:
            return False  # already at the floor tier; nothing below
        floor = max(self._tier_floor.get(num_keys, 0), current + 1)
        self._tier_floor[num_keys] = floor
        demoted = self._TIERS[floor]
        tracing.runtime_counters.inc("pir.tier_demotions")
        tracing.runtime_counters.inc(
            f"pir.tier_demote.{plan.mode}_to_{demoted}"
        )
        events_mod.emit(
            "pir.tier_demotion",
            f"{num_keys} keys: {plan.mode} -> {demoted} after device OOM",
            severity="warning",
            num_keys=num_keys,
            from_tier=plan.mode,
            to_tier=demoted,
        )
        import warnings

        warnings.warn(
            f"device OOM serving {num_keys} keys in {plan.mode} mode; "
            f"demoting this shape to the {demoted} tier "
            f"({str(exc).splitlines()[0][:200]})"
        )
        return True

    # -- over-budget serving (selection tensor larger than the HBM budget) ---

    def _plan_serving(self, num_keys: int, bitrev: bool) -> ServingPlan:
        """One planner call decides materialized / streaming / chunked
        and the streaming cut/chunk split (see `planner.py` for the HBM
        budget model). A remembered streaming inner-product failure
        (e.g. a Mosaic compile crash) demotes the scan tier to jnp for
        the rest of the process, and a remembered device OOM for this
        batch shape pins the planner at a lower tier."""
        import jax

        plan = plan_dense_serving(
            num_keys=num_keys,
            num_blocks=self._num_blocks,
            expand_levels=self._expand_levels,
            serving_bitrev=bitrev,
            backend=jax.default_backend(),
        )
        floor = max(self._tier_floor.get(num_keys, 0), _GLOBAL_TIER_FLOOR)
        if floor and self._TIERS.index(plan.mode) < floor:
            plan = plan_dense_serving(
                num_keys=num_keys,
                num_blocks=self._num_blocks,
                expand_levels=self._expand_levels,
                serving_bitrev=bitrev,
                backend=jax.default_backend(),
                force_mode=self._TIERS[floor],
            )
        if plan.mode == "streaming" and self._streaming_ip_failed:
            import dataclasses

            plan = dataclasses.replace(plan, ip="jnp")
        return plan

    def _selection_budget_bytes(self) -> int:
        return selection_budget_bytes()

    def _needs_chunking(self, num_keys: int, blocks: int = None) -> bool:
        """Whether a batch of `num_keys` exceeds the materialized HBM
        budget (planner-backed shim; the planner also picks WHICH
        over-budget mode serves it)."""
        if blocks is None:
            blocks = self._num_blocks
        return (
            num_keys * blocks * 16 > self._selection_budget_bytes()
            and self._expand_levels > 0
        )

    def _inner_products_streaming(self, plan: ServingPlan, keys):
        """Serve via the fused streaming scan: tail expansion and XOR
        inner product per chunk, no materialized selection matrix
        (`dense_eval_planes_v2.streaming_pir_inner_products_v2`)."""
        import numpy as np

        from .dense_eval_planes_v2 import streaming_pir_inner_products_v2

        num_keys = len(keys)
        with phases_mod.phase("h2d_transfer"):
            staged, device_walk = stage_keys_walked(keys, self._walk_levels)

        def run(ip: str):
            db_chunks = self._database.streaming_chunks(
                cut_levels=plan.cut_levels, bitmajor=(ip == "pallas2")
            )
            return np.asarray(
                streaming_pir_inner_products_v2(
                    *staged,
                    db_chunks,
                    walk_levels=device_walk,
                    cut_levels=plan.cut_levels,
                    chunk_levels=plan.chunk_levels,
                    ip=ip,
                )
            )

        try:
            out = run(plan.ip)
        except Exception as e:  # noqa: BLE001 - demote the scan tier once
            if plan.ip == "jnp":
                raise
            self._streaming_ip_failed = True
            tracing.runtime_counters.inc("pir.streaming_ip_demotions")
            import warnings

            warnings.warn(
                f"streaming {plan.ip} inner product failed; falling back "
                f"to the jnp scan tier ({str(e).splitlines()[0][:200]})"
            )
            out = run("jnp")
        return words_to_record_bytes(
            out, num_keys, self._database.max_value_size
        )

    # Chunk-granule cap: the chunked database is padded to a multiple of
    # 2^_CHUNK_GRANULE_LEVELS blocks once, so the padded buffer (and with
    # it the scan's chunk arithmetic) is independent of the request's
    # batch size — alternating batch sizes must not re-pad the database.
    _CHUNK_GRANULE_LEVELS = 10  # 1024 blocks = 2^17 records per granule

    def _chunked_database(self):
        """The padded chunked-db buffer (built once, under a lock —
        handle_plain_request supports concurrent callers)."""
        with self._chunked_db_lock:
            if self._chunked_db is None:
                import jax.numpy as jnp

                granule = 1 << min(
                    self._expand_levels, self._CHUNK_GRANULE_LEVELS
                )
                padded_blocks = -(-self._num_blocks // granule) * granule
                db = self._database.db_words
                pad = padded_blocks * 128 - db.shape[0]
                if pad > 0:
                    db = jnp.concatenate(
                        [db, jnp.zeros((pad, db.shape[1]), db.dtype)]
                    )
                self._chunked_db = (padded_blocks, db)
        return self._chunked_db

    def _inner_products_chunked(
        self, staged, num_keys: int, plan: ServingPlan
    ):
        """Serve via the legacy `chunked_pir_inner_products` loop: only
        one chunk's selection blocks are ever live. Kept for geometries
        the streaming scan cannot serve (trees that do not cover the
        padded block count) and for `DPF_TPU_STREAMING=0`.

        The budget bounds the live *packed* leaf tensor
        (nq * chunk_blocks * 16 bytes); the inner product itself runs
        through the row-chunked kernel, so its intermediates are bounded
        independently of chunk size.
        """
        import numpy as np

        from .dense_eval import (
            chunked_pir_inner_products,
            chunked_pir_inner_products_donated,
        )

        kernel = (
            chunked_pir_inner_products_donated
            if donation_enabled()
            else chunked_pir_inner_products
        )
        padded_blocks, db = self._chunked_database()
        # The planner caps chunk_expand_levels by budget and granule;
        # the chunk count re-derives from the granule-padded block
        # count (plan.num_chunks is the unpadded lower bound).
        cel = min(plan.chunk_levels, self._CHUNK_GRANULE_LEVELS)
        chunk_bits = self._expand_levels - cel
        num_chunks = padded_blocks >> cel

        out = np.asarray(
            kernel(
                *staged,
                db,
                walk_levels=self._walk_levels,
                chunk_bits=chunk_bits,
                chunk_expand_levels=cel,
                num_chunks=num_chunks,
            )
        )
        return words_to_record_bytes(
            out, num_keys, self._database.max_value_size
        )

    # -- mesh serving (2-D shard x key mesh) ----------------------------------

    def _mesh_is_2d(self) -> bool:
        return (
            self._mesh is not None
            and len(getattr(self._mesh, "axis_names", ())) == 2
        )

    def batch_key_multiple(self) -> int:
        """Key-batch granularity the serving runtime should pad buckets
        to: the key-axis size on a 2-D mesh (so batches land
        pre-partitioned without a gather), 1 otherwise."""
        if not self._mesh_is_2d():
            return 1
        return int(self._mesh.shape[tuple(self._mesh.axis_names)[1]])

    def _ensure_mesh_plan(self, num_keys_hint: int):
        """Build (once) the 2-D serving plan and the mesh-sharded
        database staging. Returns the plan, or None when the geometry
        is infeasible — the caller then serves single-device, and the
        error sticks so the fallback is decided once, not per request."""
        with self._mesh_lock:
            if self._mesh_plan is not None:
                return self._mesh_plan
            if self._mesh_plan_error is not None:
                return None
            try:
                plan = self._build_mesh_plan(num_keys_hint)
                db = self._database.streaming_chunks(
                    cut_levels=plan.cut_levels,
                    bitmajor=plan.bitmajor,
                    mesh=self._mesh,
                    shard_axis=plan.shard_axis,
                )
            except Exception as exc:  # noqa: BLE001 - sticky fallback
                self._mesh_plan_error = exc
                self._note_mesh_fallback("plan", exc)
                return None
            self._mesh_plan = plan
            self._mesh_db = db
            return plan

    def _build_mesh_plan(self, num_keys_hint: int):
        import jax

        from ..capacity.model import default_capacity_model
        from ..parallel.sharded import ShardedServingPlan

        axis_names = tuple(self._mesh.axis_names)
        shards = int(self._mesh.shape[axis_names[0]])
        key_devices = int(self._mesh.shape[axis_names[1]])
        s_levels = max(0, (shards - 1).bit_length())
        if (1 << s_levels) != shards:
            raise ValueError(
                f"shard axis must be a power of two, got {shards}"
            )
        # The streaming staging pads rows to the full covering subtree
        # (2^expand blocks), so the scan geometry must cover it exactly.
        expand = max(0, (self._num_blocks - 1).bit_length())
        total_levels = self._dpf._tree_levels_needed - 1
        if expand > total_levels:
            raise ValueError(
                f"tree depth {total_levels} cannot cover 2^{expand} "
                "padded blocks"
            )
        if expand < s_levels:
            raise ValueError(
                f"2^{expand} chunk lanes cannot split over {shards} "
                "shards"
            )
        model = default_capacity_model()
        local_keys = -(-max(1, num_keys_hint) // key_devices)
        chunk = min(
            model.pick_streaming_split(local_keys, expand),
            expand - s_levels,
        )
        cut = expand - chunk
        return ShardedServingPlan(
            self._mesh,
            walk_levels=total_levels - expand,
            cut_levels=cut,
            chunk_levels=chunk,
            ip=streaming_ip(jax.default_backend()),
        )

    def _note_mesh_fallback(self, stage: str, exc: BaseException) -> None:
        import warnings

        tracing.runtime_counters.inc("pir.mesh_fallbacks")
        events_mod.emit(
            "pir.mesh_fallback",
            f"mesh serving disabled after {stage} failure; serving "
            "single-device",
            severity="warning",
            stage=stage,
            error=str(exc).splitlines()[0][:200],
        )
        warnings.warn(
            f"mesh serving {stage} failed; falling back to single-device "
            f"({str(exc).splitlines()[0][:200]})"
        )

    def _serve_mesh(self, keys, telemetry, seen):
        """One batch through the 2-D mesh plan. Returns the response
        list, or None to fall back to single-device (infeasible
        geometry, or a device OOM that permanently demotes the mesh)."""
        plan = self._ensure_mesh_plan(len(keys))
        if plan is None:
            return None
        record = phases_mod.current_request()
        if record is not None:
            record.set_meta(
                "serving_plan", {"mode": "mesh", "num_keys": len(keys)}
            )
        # Host-side assembly only: the placement is the plan's sharded
        # stage_keys, so keys go straight to their key-axis devices
        # pre-partitioned (no single-device detour, no dispatch-time
        # relayout).
        with self._mesh_exec_lock:
            with phases_mod.phase("h2d_transfer"):
                staged_host = stage_keys_host(keys)
                staged = plan.stage_keys(staged_host)
            key = shape_key(
                ("m", f"mesh-{plan.ip}"),
                ("q", int(staged[0].shape[0])),
                ("b", self._num_blocks),
                ("c", plan.cut_levels),
            )
            step = (
                "device_compute" if seen("pir.plain", key) else "compile"
            )
            mesh_db = self._mesh_db
            try:
                with tracing.span(
                    "evaluate_mesh",
                    num_keys=len(keys),
                    shards=plan.num_shards,
                    key_devices=plan.num_key_devices,
                ), telemetry.hbm.phase("selection"), \
                        telemetry.compile_tracker.dispatch(
                            "pir.plain", key
                        ), \
                        phases_mod.phase(step):
                    out_dev = plan.run(staged, mesh_db)
                    out = telemetry.transfers.to_host(
                        out_dev, phase="result_readback"
                    )
            except Exception as exc:  # noqa: BLE001 - OOM-gated below
                if not self._is_resource_exhausted(exc):
                    raise
                with self._mesh_lock:
                    self._mesh_plan = None
                    self._mesh_db = None
                    self._mesh_plan_error = exc
                self._note_mesh_fallback("dispatch", exc)
                return None
        return words_to_record_bytes(
            out, len(keys), self._database.max_value_size
        )

    def prestage_database(self, database: DenseDpfPirDatabase) -> int:
        """Stage `database` exactly the way THIS server will serve it
        (snapshots call this for generation N+1 so the flip is a cache
        hit): the mesh-sharded streaming staging when a 2-D plan is
        active, the row-major single-device buffer otherwise. Returns
        bytes staged."""
        if self._mesh_is_2d():
            plan = self._ensure_mesh_plan(num_keys_hint=64)
            if plan is not None:
                return database.prestage(
                    mesh=self._mesh,
                    cut_levels=plan.cut_levels,
                    bitmajor=plan.bitmajor,
                    shard_axis=plan.shard_axis,
                )
        return database.prestage()

    def mesh_export(self) -> dict:
        """The /statusz "Mesh" view: mesh shape, plan geometry, scratch
        pool and donation state, per-shard staging detail, per-shard
        HBM watermarks."""
        if self._mesh is None:
            return {"configured": False}
        axis_names = tuple(getattr(self._mesh, "axis_names", ()))
        out = {
            "configured": True,
            "axis_names": list(axis_names),
            "shape": {
                str(name): int(self._mesh.shape[name])
                for name in axis_names
            },
            "devices": int(self._mesh.devices.size),
            "two_dee": len(axis_names) == 2,
        }
        with self._mesh_lock:
            plan = self._mesh_plan
            err = self._mesh_plan_error
        if err is not None:
            out["fallback_error"] = str(err).splitlines()[0][:200]
        if plan is not None:
            out["plan"] = plan.export()
        info = self._database.mesh_staging_info()
        if info is not None:
            watermarks = default_telemetry().hbm.export().get(
                "watermark_bytes", {}
            )
            for shard in info.get("shards", ()):
                shard["hbm_watermark_bytes"] = watermarks.get(
                    f"db_staging/dev{shard['device']}"
                )
            out["staging"] = info
        return out

    # -- multi-chip serving ---------------------------------------------------

    def _ensure_sharded(self):
        """Build the sharded step and place the record-sharded database
        (once): rows pad to 128 * mesh size, and the expansion produces the
        padded block count so every device's bit range is covered."""
        if self._sharded_step is not None:
            return
        from ..parallel.sharded import (
            pad_rows_to_mesh,
            shard_database,
            sharded_dense_pir_step,
        )

        ndev = self._mesh.devices.size
        db = pad_rows_to_mesh(self._database.db_words, ndev)
        num_blocks = db.shape[0] // 128
        total_levels = self._dpf._tree_levels_needed - 1
        expand_levels = min(
            max(0, (num_blocks - 1).bit_length()), total_levels
        )
        self._sharded_step = sharded_dense_pir_step(
            self._mesh,
            walk_levels=total_levels - expand_levels,
            expand_levels=expand_levels,
            num_blocks=num_blocks,
            real_num_blocks=self._database.num_selection_blocks,
        )
        self._sharded_db = shard_database(self._mesh, db)

    def _inner_products_sharded(self, staged, num_keys: int):
        import numpy as np

        from ..parallel.sharded import pad_staged_queries

        self._ensure_sharded()
        staged = pad_staged_queries(staged, self._mesh.devices.size)
        out = np.asarray(self._sharded_step(*staged, self._sharded_db))
        return words_to_record_bytes(
            out, num_keys, self._database.max_value_size
        )
