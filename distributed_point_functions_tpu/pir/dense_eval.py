"""Fused multi-key selection-vector evaluation for dense PIR.

The reference server runs, per DPF key, a *full-domain* expansion of a
`2^ceil(log2(n))`-leaf tree whose leaves are 128-bit selection blocks
(`dense_dpf_pir_server.cc:92-127`) — even though only the first
`ceil(n/128)` blocks carry selection bits (the inner product stops at the
database size, `inner_product_hwy.cc:279-281`). Since the client puts the
query's block index in `alpha = index/128` (`dense_dpf_pir_client.cc:92-95`),
all the *useful* leaves live in the subtree under the all-zeros prefix of
depth `log_domain_size - ceil(log2(num_blocks))`.

The TPU pipeline exploits that: walk the all-zeros path down the shared
prefix (a `lax.scan` — one AES per key per level), then breadth-first
expand only the needed subtree (width-doubling, all keys batched), then hash
leaves to value blocks. Output is bit-identical to the reference's full
expansion restricted to the first `num_blocks` blocks, at ~1/128 of the AES
work for large domains.

All queries in a batch are evaluated together: seeds are stacked on a key
axis and correction words looked up per key, mirroring the per-seed
correction-word mode of `evaluate_prg_hwy.h:58-65`.
"""

from __future__ import annotations

import functools
import os
import subprocess
import warnings
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import keys as fixed_keys
from ..dpf import DpfKey
from ..observability.device import default_telemetry
from ..ops import aes

U32 = jnp.uint32

_CLEAR_LSB = np.array(
    [0xFFFFFFFE, 0xFFFFFFFF, 0xFFFFFFFF, 0xFFFFFFFF], dtype=np.uint32
)


def donation_enabled() -> bool:
    """Whether single-device serving donates its per-request staged key
    tensors into the jit (`DPF_TPU_DONATE`, default on — the same knob
    and default as the mesh plan's scratch donation). Read at call time
    so tests can flip the env per arm."""
    return os.environ.get("DPF_TPU_DONATE", "1") != "0"


# On backends without donation support (CPU) every donated dispatch
# warns; the control arm is the env knob, not the warning stream. Same
# filter the mesh plan installs.
warnings.filterwarnings(
    "ignore", message=".*donated buffers were not usable.*"
)


def _evaluate_selection_blocks(
    seeds0: jnp.ndarray,
    control0: jnp.ndarray,
    cw_seeds: jnp.ndarray,
    cw_left: jnp.ndarray,
    cw_right: jnp.ndarray,
    last_vc: jnp.ndarray,
    *,
    walk_levels: int,
    expand_levels: int,
    num_blocks: int,
) -> jnp.ndarray:
    """Selection blocks for a batch of single-level 128-bit-XOR DPF keys.

    seeds0: uint32[nk, 4] root seeds; control0: uint32[nk] (= party);
    cw_seeds: uint32[L, nk, 4], cw_left/right: uint32[L, nk] with
    L = walk_levels + expand_levels (level-major for the scan);
    last_vc: uint32[nk, 4] last-level value correction.
    Returns uint32[nk, num_blocks, 4] selection blocks (the first
    `num_blocks` leaves of each key's tree).
    """
    seeds, control = _walk_zeros(
        seeds0, control0, cw_seeds[:walk_levels], cw_left[:walk_levels]
    )
    seeds, control = _expand_subtree(
        seeds, control, cw_seeds, cw_left, cw_right,
        first_level=walk_levels, num_levels=expand_levels,
    )
    sel = _leaf_blocks(seeds, control, last_vc)[:, :num_blocks, :]
    if sel.shape[1] < num_blocks:
        # num_blocks beyond the tree's 2^expand_levels leaf capacity can
        # only arise from padding the database rows (e.g. to a mesh-size
        # multiple): those rows are guaranteed all-zero, so zero selection
        # blocks serve them correctly.
        sel = jnp.pad(
            sel, ((0, 0), (0, num_blocks - sel.shape[1]), (0, 0))
        )
    return sel


evaluate_selection_blocks = functools.partial(
    jax.jit, static_argnames=("walk_levels", "expand_levels", "num_blocks")
)(_evaluate_selection_blocks)

# Donating twin for the serving hot path: the six staged key tensors
# are freshly placed per batch (`stage_keys`) and dead after this call,
# so XLA may reuse their HBM for the selection matrix instead of
# holding both live. Deliberately a separate entry — the differential
# tests feed ONE staging to several implementations, which donation
# would invalidate — so only `DenseDpfPirServer` (via
# `donation_enabled()`) dispatches here.
evaluate_selection_blocks_donated = jax.jit(
    _evaluate_selection_blocks,
    static_argnames=("walk_levels", "expand_levels", "num_blocks"),
    donate_argnums=(0, 1, 2, 3, 4, 5),
)


def _walk_zeros(seeds, control, cw_seeds_w, cw_left_w):
    """Walk the all-zeros prefix (left child each level): one `lax.scan`
    over the leading `walk_levels` correction words."""
    if cw_seeds_w.shape[0] == 0:
        return seeds, control
    clear = jnp.asarray(_CLEAR_LSB)

    def walk_body(carry, x):
        s, t = carry
        cw_s, cw_l = x  # [nk, 4], [nk]
        h = aes.mmo_hash(fixed_keys.RK_LEFT, s)
        h = h ^ jnp.where(t[:, None] != 0, cw_s, U32(0))
        t_new = h[:, 0] & U32(1)
        h = h & clear
        t_new = t_new ^ (t * cw_l)
        return (h, t_new), None

    (seeds, control), _ = lax.scan(
        walk_body, (seeds, control), (cw_seeds_w, cw_left_w)
    )
    return seeds, control


def _expand_subtree(
    seeds, control, cw_seeds, cw_left, cw_right, *, first_level, num_levels
):
    """Width-doubling expansion of the subtree, all keys batched.

    seeds: uint32[nk, 4] subtree roots -> uint32[nk, 2^num_levels, 4].
    Left and right children are produced by ONE key-selected AES pass per
    level (even lanes pick the left PRG key, odd lanes the right), halving
    the compiled graph size vs. two separate hashes — the TPU analog of
    the reference's per-lane key masking
    (`aes_128_fixed_key_hash_hwy.h:123-155`).
    """
    clear = jnp.asarray(_CLEAR_LSB)
    seeds = seeds[:, None, :]  # [nk, w, 4]
    control = control[:, None]  # [nk, w]
    for i in range(num_levels):
        lvl = first_level + i
        w = seeds.shape[1]
        cw_s = cw_seeds[lvl][:, None, :]  # [nk, 1, 4]
        cw_l = cw_left[lvl][:, None]
        cw_r = cw_right[lvl][:, None]
        doubled = jnp.repeat(seeds, 2, axis=1)  # [nk, 2w, 4]
        sel = jnp.tile(jnp.arange(2, dtype=U32), w)[None, :]  # [1, 2w]
        h = aes.mmo_hash_select(
            fixed_keys.RK_LEFT, fixed_keys.RK_RIGHT, sel, doubled
        )
        control2 = jnp.repeat(control, 2, axis=1)  # [nk, 2w]
        h = h ^ jnp.where(control2[..., None] != 0, cw_s, U32(0))
        t_new = h[..., 0] & U32(1)
        h = h & clear
        cw_dir = jnp.where(sel != 0, cw_r, cw_l)  # [nk, 2w]
        t_new = t_new ^ (control2 * cw_dir)
        seeds = h
        control = t_new
    return seeds, control


def _leaf_blocks(seeds, control, last_vc):
    """Leaf value blocks (output PRG + XOR value correction; party negation
    is the identity for XOR shares)."""
    v = aes.mmo_hash(fixed_keys.RK_VALUE, seeds)
    return v ^ jnp.where(control[..., None] != 0, last_vc[:, None, :], U32(0))


def expansion_impl():
    """The selection-block expansion implementation for the serving path.

    `DPF_TPU_EXPANSION`: `limb` — the per-level kernel re-entry above;
    `planes` — the plane-resident expansion
    (`dense_eval_planes.evaluate_selection_blocks_planes`, bit-identical,
    no per-level transposes); `v2` — the key-major layout-clean rewrite
    (`dense_eval_planes_v2`, natural-order exit here — the gather-free
    bitrev exit needs database coordination, see `serving_expansion`);
    `auto` (default) — planes on TPU, limb elsewhere (the plane path's
    win is VPU work; CPU compile times favor the limb path in the
    hermetic suite).
    """
    import functools
    import os

    from ..utils.runtime import planes_selected

    if os.environ.get("DPF_TPU_EXPANSION") == "v2":
        from .dense_eval_planes_v2 import (
            evaluate_selection_blocks_planes_v2,
        )

        return evaluate_selection_blocks_planes_v2
    if planes_selected("DPF_TPU_EXPANSION"):
        from .dense_eval_planes import evaluate_selection_blocks_planes

        if os.environ.get("DPF_TPU_EXPANSION") == "planes":
            # Explicitly forced: bypass the small-batch padding guard.
            return functools.partial(
                evaluate_selection_blocks_planes, force_planes=True
            )
        return evaluate_selection_blocks_planes
    return evaluate_selection_blocks


def serving_expansion():
    """(expansion fn, wants_bitrev) for the dense server's plain path.

    In `DPF_TPU_EXPANSION=v2` mode the server serves the gather-free
    exit: the expansion keeps its doubling-order leaves
    (`bitrev_leaves=True`) and the database runs the inner product
    against its bitrev-block staging — the caller passes
    `bitrev_blocks=True` through `inner_product_with`. Every other mode
    serves natural-order selections against the natural staging."""
    import os

    fn = expansion_impl()
    return fn, os.environ.get("DPF_TPU_EXPANSION") == "v2"


def selection_blocks_for_keys(dpf, keys: Sequence[DpfKey], num_blocks: int):
    """Evaluate a batch of single-level 128-bit-XOR DPF keys to the first
    `num_blocks` selection blocks.

    `dpf` supplies the tree depth; the walk/expand split is derived so only
    the covering subtree is expanded. Returns uint32[nk, num_blocks, 4].
    """
    total_levels = dpf._tree_levels_needed - 1
    expand_levels = min(max(0, (num_blocks - 1).bit_length()), total_levels)
    walk_levels = total_levels - expand_levels
    staged, device_walk = stage_keys_walked(keys, walk_levels)
    return expansion_impl()(
        *staged,
        walk_levels=device_walk,
        expand_levels=expand_levels,
        num_blocks=num_blocks,
    )


def stage_keys_walked(keys: Sequence[DpfKey], walk_levels: int):
    """Stage a key batch with the host-side zeros-walk applied when
    enabled (`DPF_TPU_HOST_WALK`, default on): returns `(staged,
    device_walk_levels)` where `device_walk_levels` is what the device
    step must still walk. Callers must pass the second element through —
    deriving it independently walks already-consumed correction words."""
    from ..utils.runtime import host_walk_enabled

    host_walk = walk_levels if host_walk_enabled() else 0
    return stage_keys(keys, host_walk_levels=host_walk), (
        walk_levels - host_walk
    )


_HOST_WALK_NATIVE_UNAVAILABLE = False


def warm_host_walk() -> None:
    """Build/load the native oracle outside the request path.

    The first `native.get_lib()` on a cold checkout spawns the g++ build
    (seconds); servers call this at construction so no live request pays
    it. A failure is remembered (the numpy walk serves instead) and
    warned about once."""
    global _HOST_WALK_NATIVE_UNAVAILABLE
    if _HOST_WALK_NATIVE_UNAVAILABLE:
        return
    try:
        from .. import native

        native.get_lib()
    except (
        ImportError,
        OSError,
        RuntimeError,
        subprocess.CalledProcessError,
    ) as e:
        _HOST_WALK_NATIVE_UNAVAILABLE = True
        warnings.warn(
            "native oracle unavailable for the host zeros-walk; "
            f"using the numpy path ({str(e).splitlines()[0][:120]})"
        )


def _walk_zeros_host(seeds0, control0, cw_seeds, cw_left, cw_right, levels):
    """Host-side twin of `_walk_zeros` (numpy in, numpy out).

    The device walk costs ~1.4 ms per 64-query batch — seven sequential
    bitsliced-AES levels on [nk, 4] arrays are pure dispatch latency on
    TPU — while the same ~nk*levels scalar AES calls take ~0.5 ms on the
    host, so staging walks the shared all-zeros prefix before the arrays
    ever reach the device. Uses the native C++ oracle when built, else
    the numpy MMO oracle. A failed native load is remembered (it spawns
    the g++ build) and warned about once — never retried per request,
    and genuine native-path errors are not masked."""
    warm_host_walk()
    if not _HOST_WALK_NATIVE_UNAVAILABLE:
        from .. import native

        sb = aes.limbs_to_bytes_np(seeds0)
        cw_b = aes.limbs_to_bytes_np(
            cw_seeds[:levels].reshape(-1, 4)
        ).reshape(levels, -1, 16)
        s, c = native.evaluate_seeds(
            sb,
            control0.astype(np.uint8),
            np.zeros_like(sb),
            cw_b,
            cw_left[:levels].astype(np.uint8),
            cw_right[:levels].astype(np.uint8),
            per_seed_cw=True,
        )
        return aes.bytes_to_limbs_np(s), c.astype(np.uint32)
    seeds = seeds0.copy()
    control = control0.copy()
    for lvl in range(levels):
        h = aes.mmo_hash_np(fixed_keys.RK_LEFT, seeds)
        h ^= np.where(control[:, None] != 0, cw_seeds[lvl], 0).astype(
            np.uint32
        )
        t_new = h[:, 0] & np.uint32(1)
        h &= _CLEAR_LSB
        control = t_new ^ (control * cw_left[lvl])
        seeds = h
    return seeds, control


def stage_keys_host(keys: Sequence[DpfKey], host_walk_levels: int = 0):
    """Host half of `stage_keys`: stack a batch of dense-PIR DPF keys
    into six numpy arrays without placing them on any device.

    Callers that serve from a mesh use this directly and do the
    placement themselves with a `NamedSharding` matching the step's
    in_specs (`ShardedServingPlan.stage_keys`), so keys never take a
    single-device detour before being resharded at dispatch.
    """
    nk = len(keys)
    num_levels = len(keys[0].correction_words)
    seeds0 = np.zeros((nk, 4), dtype=np.uint32)
    control0 = np.zeros((nk,), dtype=np.uint32)
    cw_seeds = np.zeros((num_levels, nk, 4), dtype=np.uint32)
    cw_left = np.zeros((num_levels, nk), dtype=np.uint32)
    cw_right = np.zeros((num_levels, nk), dtype=np.uint32)
    last_vc = np.zeros((nk, 4), dtype=np.uint32)
    for k, key in enumerate(keys):
        if len(key.correction_words) != num_levels:
            raise ValueError("all keys must have the same number of levels")
        if len(key.last_level_value_correction) != 1:
            raise ValueError("dense PIR keys carry exactly one leaf value")
        seeds0[k] = aes.u128_to_limbs(key.seed)
        control0[k] = key.party
        last_vc[k] = aes.u128_to_limbs(
            int(key.last_level_value_correction[0])
        )
        for lvl, cw in enumerate(key.correction_words):
            cw_seeds[lvl, k] = aes.u128_to_limbs(cw.seed)
            cw_left[lvl, k] = cw.control_left
            cw_right[lvl, k] = cw.control_right
    if host_walk_levels:
        if host_walk_levels > num_levels:
            raise ValueError(
                f"host_walk_levels={host_walk_levels} exceeds the keys' "
                f"{num_levels} correction-word levels"
            )
        seeds0, control0 = _walk_zeros_host(
            seeds0, control0, cw_seeds, cw_left, cw_right, host_walk_levels
        )
        cw_seeds = cw_seeds[host_walk_levels:]
        cw_left = cw_left[host_walk_levels:]
        cw_right = cw_right[host_walk_levels:]
    return seeds0, control0, cw_seeds, cw_left, cw_right, last_vc


def stage_keys(keys: Sequence[DpfKey], host_walk_levels: int = 0):
    """Stack a batch of dense-PIR DPF keys into device-ready arrays.

    All keys must have the same number of correction words and a single
    128-bit last-level value correction. With `host_walk_levels > 0` the
    shared all-zeros prefix is walked on the host during staging (see
    `_walk_zeros_host`): the returned seeds/control sit at that depth and
    the correction-word arrays drop the walked levels, so the device step
    runs with `walk_levels=0`.
    """
    seeds0, control0, cw_seeds, cw_left, cw_right, last_vc = (
        stage_keys_host(keys, host_walk_levels)
    )
    # One device_put for the whole staging: all six blocks are uint32,
    # so they pack into a single flat transfer and slice back apart on
    # device (value_types.host_const's batching note, applied). Six
    # per-array transfers cost six dispatches on the serving hot path;
    # the TransferLedger counts this as one h2d copy.
    blocks = (seeds0, control0, cw_seeds, cw_left, cw_right, last_vc)
    flat = np.concatenate([b.ravel() for b in blocks])
    dev = default_telemetry().transfers.device_put(
        flat, phase="key_staging"
    )
    out = []
    offset = 0
    for b in blocks:
        out.append(dev[offset:offset + b.size].reshape(b.shape))
        offset += b.size
    return tuple(out)


def _chunked_pir_inner_products(
    seeds0: jnp.ndarray,
    control0: jnp.ndarray,
    cw_seeds: jnp.ndarray,
    cw_left: jnp.ndarray,
    cw_right: jnp.ndarray,
    last_vc: jnp.ndarray,
    db_words: jnp.ndarray,
    *,
    walk_levels: int,
    chunk_bits: int,
    chunk_expand_levels: int,
    num_chunks: int,
) -> jnp.ndarray:
    """Dense-PIR inner products with chunked expansion (long-context mode).

    For databases whose full selection tensor would outgrow HBM
    (`nq * num_blocks * 16` bytes), the covering subtree is processed in
    `num_chunks` chunks of `2^chunk_expand_levels` blocks: one `lax.scan`
    step walks the chunk root's path bits (`chunk_bits` levels), expands
    only that chunk's subtree, hashes its leaves, and XOR-accumulates the
    partial inner product against the chunk's record rows — so only one
    chunk's selections are ever live (the TPU analog of SURVEY.md §5's
    chunked/blockwise expansion sized to HBM).

    This is the legacy limb-layout fallback: `pir.planner` now routes
    over-budget serving to the streaming plane-layout pipeline
    (`dense_eval_planes_v2.streaming_pir_inner_products_v2`) when the
    expansion tree covers the padded block count, and only falls back
    here otherwise. The materialized path doubles as the differential
    oracle for both.

    db_words: uint32[num_chunks * 2^chunk_expand_levels * 128, W] (zero
    rows beyond the real record count). Tree depth must satisfy
    walk_levels + chunk_bits + chunk_expand_levels == total levels.
    Returns uint32[nk, W].
    """
    from ..ops.inner_product import xor_inner_product

    clear = jnp.asarray(_CLEAR_LSB)
    # Phase 1: walk the all-zeros shared prefix.
    seeds, control = _walk_zeros(
        seeds0, control0, cw_seeds[:walk_levels], cw_left[:walk_levels]
    )

    chunk_records = (1 << chunk_expand_levels) * 128
    num_words = db_words.shape[1]
    db_chunks = db_words.reshape(num_chunks, chunk_records, num_words)
    nk = seeds0.shape[0]

    def chunk_step(acc, xs):
        c, db_chunk = xs
        s, t = seeds, control

        # Phase 2a: walk this chunk root's path (bit j of c, MSB first).
        for j in range(chunk_bits):
            lvl = walk_levels + j
            bit = ((c >> (chunk_bits - 1 - j)) & 1).astype(U32)
            pbit = jnp.broadcast_to(bit, (nk,))
            h = aes.mmo_hash_select(
                fixed_keys.RK_LEFT, fixed_keys.RK_RIGHT, pbit, s
            )
            h = h ^ jnp.where(t[:, None] != 0, cw_seeds[lvl], U32(0))
            t_new = h[:, 0] & U32(1)
            h = h & clear
            cw_dir = jnp.where(pbit != 0, cw_right[lvl], cw_left[lvl])
            s, t = h, t_new ^ (t * cw_dir)

        # Phase 2b/3: expand the chunk subtree and hash its leaves.
        s, t = _expand_subtree(
            s, t, cw_seeds, cw_left, cw_right,
            first_level=walk_levels + chunk_bits,
            num_levels=chunk_expand_levels,
        )
        v = _leaf_blocks(s, t, last_vc)  # [nk, chunk_blocks, 4]
        # Phase 4: partial XOR inner product against this chunk's rows —
        # via the row-chunked kernel so the masked intermediate stays
        # bounded (256 rows at a time) regardless of chunk size.
        return acc ^ xor_inner_product(db_chunk, v), None

    acc0 = jnp.zeros((nk, num_words), dtype=U32)
    acc, _ = lax.scan(
        chunk_step,
        acc0,
        (jnp.arange(num_chunks, dtype=jnp.uint32), db_chunks),
    )
    return acc


_CHUNKED_STATIC = (
    "walk_levels", "chunk_bits", "chunk_expand_levels", "num_chunks"
)

chunked_pir_inner_products = functools.partial(
    jax.jit, static_argnames=_CHUNKED_STATIC
)(_chunked_pir_inner_products)

# Donating twin: the six per-request staged key tensors (args 0-5) are
# dead after the scan; `db_words` (arg 6) is the resident chunked
# database buffer and must NEVER be donated — a consumed database
# would force a full re-staging on the next request, which the
# TransferLedger test pins at zero.
chunked_pir_inner_products_donated = jax.jit(
    _chunked_pir_inner_products,
    static_argnames=_CHUNKED_STATIC,
    donate_argnums=(0, 1, 2, 3, 4, 5),
)
