"""Serving-path planner for dense DPF PIR.

One place that decides how a dense-PIR batch is served, replacing the
scattered ``_needs_chunking`` heuristics.  Three modes:

``materialized``
    Full-domain expansion writes the packed selection matrix
    ``uint32[num_queries, num_blocks, 4]`` to HBM and the inner product
    re-reads it.  Cheapest to trace, and the differential-test oracle
    for the other two modes.  Chosen whenever that matrix fits the HBM
    selection budget.

``streaming``
    The fused expand->inner-product pipeline
    (:func:`..pir.dense_eval_planes_v2.streaming_pir_inner_products_v2`).
    The covering subtree is expanded down to ``cut_levels``; a jitted
    ``lax.scan`` then expands each of the ``2**cut_levels`` tail
    subtrees the remaining ``chunk_levels`` levels and immediately
    XOR/MXU-accumulates the matching database block span, so the full
    selection matrix never exists in HBM.  Requires the tree to cover
    the padded block count (``2**expand_levels >= num_blocks``) because
    the database is staged in streaming (blocked bit-reversed) block
    order.

``chunked``
    The legacy limb-space chunked loop
    (:func:`..pir.dense_eval.chunked_pir_inner_products`), kept for
    geometries streaming cannot serve (trees that do not cover the
    padded block count) and as a fallback when streaming is disabled
    via ``DPF_TPU_STREAMING=0``.

The HBM byte model (what each tier keeps live, and how big a
streaming/chunked split may be) lives in
:mod:`..capacity.model` — one `CapacityModel` shared with the
heavy-hitters level planner and the serving admission controller.
This module is a thin client: it asks the model for tier byte costs
and feasible splits, then encodes the mode decision tree
(materialized-if-it-fits, streaming when over budget or forced,
chunked as the floor).  The budget defaults to 1 GiB and is overridden
with ``DPF_TPU_SELECTION_BYTES_BUDGET``.  ``DPF_TPU_STREAMING`` gates
the streaming mode (``auto`` = use when over budget, ``1`` = use
whenever applicable even under budget, ``0`` = never).
``DPF_TPU_STREAMING_IP`` picks the inner-product tier inside the scan
(``auto`` = pallas2 on TPU, jnp elsewhere).
"""

from __future__ import annotations

import dataclasses
import os

from ..capacity.model import CapacityModel, default_capacity_model
from ..observability.tracing import runtime_counters

# Legacy chunked path: pad the block count so chunks stay at least this
# many doubling levels (keeps per-chunk tensors MXU-friendly).
CHUNK_GRANULE_LEVELS = 10


def selection_budget_bytes() -> int:
    """HBM budget for selection-attributable tensors (capacity model)."""
    return default_capacity_model().selection_budget_bytes()


def streaming_mode() -> str:
    mode = os.environ.get("DPF_TPU_STREAMING", "auto").strip().lower()
    return mode if mode in ("auto", "0", "1") else "auto"


def streaming_ip(backend: str | None) -> str:
    env = os.environ.get("DPF_TPU_STREAMING_IP", "auto").strip().lower()
    if env in ("jnp", "pallas2"):
        return env
    return "pallas2" if backend == "tpu" else "jnp"


def materialized_selection_bytes(num_keys: int, eff_blocks: int) -> int:
    return default_capacity_model().materialized_selection_bytes(
        num_keys, eff_blocks
    )


def streaming_selection_bytes(
    num_keys: int, cut_levels: int, chunk_levels: int
) -> int:
    return default_capacity_model().streaming_selection_bytes(
        num_keys, cut_levels, chunk_levels
    )


def chunked_selection_bytes(num_keys: int, chunk_expand_levels: int) -> int:
    return default_capacity_model().chunked_selection_bytes(
        num_keys, chunk_expand_levels
    )


@dataclasses.dataclass(frozen=True)
class ServingPlan:
    """Resolved serving decision for one dense-PIR batch."""

    mode: str  # "materialized" | "streaming" | "chunked"
    num_keys: int
    num_blocks: int
    expand_levels: int
    budget_bytes: int
    # Model of the peak live selection-attributable bytes for `mode`.
    selection_bytes_peak: int
    # Streaming split: expand_levels == cut_levels + chunk_levels and
    # num_chunks == 2**cut_levels.  For the legacy chunked mode,
    # chunk_levels carries chunk_expand_levels and cut_levels the path
    # bits walked per chunk root; num_chunks is a lower bound (the
    # server re-pads block count to the chunk granule).
    cut_levels: int = 0
    chunk_levels: int = 0
    num_chunks: int = 1
    # Inner-product tier used inside the streaming scan.
    ip: str = "jnp"


def _pick_streaming_split(num_keys: int, expand_levels: int, budget: int) -> int:
    """Largest chunk_levels whose modeled peak fits `budget`, else the
    peak-minimizing split (delegated to the capacity model)."""
    return default_capacity_model().pick_streaming_split(
        num_keys, expand_levels, budget_bytes=budget
    )


def plan_dense_serving(
    *,
    num_keys: int,
    num_blocks: int,
    expand_levels: int,
    serving_bitrev: bool = False,
    backend: str | None = None,
    budget_bytes: int | None = None,
    force_ip: str | None = None,
    force_mode: str | None = None,
    model: CapacityModel | None = None,
) -> ServingPlan:
    """Choose the serving mode and its parameters for one batch.

    ``serving_bitrev`` says whether the materialized path would expand
    the full padded domain (bitrev staging: ``2**expand_levels``
    blocks) or truncate to ``num_blocks``; it sets the materialized
    byte cost, not streaming applicability.

    ``force_mode`` pins the outcome regardless of the budget model:
    ``"streaming"`` forces the fused scan when the geometry allows it
    (falling through to chunked otherwise), ``"chunked"`` forces the
    legacy limb-space loop.  Runtime OOM demotion (`server.py`) uses
    it to step a shape down a tier after the budget model proved
    optimistic on the live device — and the brownout ladder uses the
    same floor to force cheaper tiers under SLO burn.

    ``model`` overrides the process-wide capacity model (tests).
    """
    cm = model if model is not None else default_capacity_model()
    budget = cm.selection_budget_bytes() if budget_bytes is None else budget_bytes
    mode = streaming_mode()
    streaming_ok = (
        mode != "0" and expand_levels > 0 and (1 << expand_levels) >= num_blocks
    )
    eff_blocks = (1 << expand_levels) if serving_bitrev else num_blocks
    mat_bytes = cm.materialized_selection_bytes(num_keys, eff_blocks)
    over_budget = mat_bytes > budget and expand_levels > 0
    if force_mode == "streaming" and not streaming_ok:
        # Geometry (or DPF_TPU_STREAMING=0) rules streaming out; the
        # next tier down is the legacy chunked loop.
        force_mode = "chunked"
    if force_mode == "chunked" and expand_levels > 0:
        over_budget = True
        streaming_ok = False

    common = dict(
        num_keys=num_keys,
        num_blocks=num_blocks,
        expand_levels=expand_levels,
        budget_bytes=budget,
    )
    if streaming_ok and (over_budget or mode == "1" or force_mode == "streaming"):
        chunk_levels = cm.pick_streaming_split(
            num_keys, expand_levels, budget_bytes=budget
        )
        cut_levels = expand_levels - chunk_levels
        ip = force_ip or streaming_ip(backend)
        runtime_counters.inc("pir.plan.streaming")
        runtime_counters.inc(f"pir.plan.streaming_ip.{ip}")
        return ServingPlan(
            mode="streaming",
            selection_bytes_peak=cm.streaming_selection_bytes(
                num_keys, cut_levels, chunk_levels
            ),
            cut_levels=cut_levels,
            chunk_levels=chunk_levels,
            num_chunks=1 << cut_levels,
            ip=ip,
            **common,
        )
    if over_budget:
        cel = cm.pick_chunked_expand_levels(
            num_keys, expand_levels, CHUNK_GRANULE_LEVELS, budget_bytes=budget
        )
        runtime_counters.inc("pir.plan.chunked")
        return ServingPlan(
            mode="chunked",
            selection_bytes_peak=cm.chunked_selection_bytes(num_keys, cel),
            cut_levels=expand_levels - cel,
            chunk_levels=cel,
            num_chunks=1 << (expand_levels - cel),
            **common,
        )
    runtime_counters.inc("pir.plan.materialized")
    return ServingPlan(
        mode="materialized",
        selection_bytes_peak=mat_bytes,
        **common,
    )
