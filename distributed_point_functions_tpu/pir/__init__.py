"""Two-server DPF-based private information retrieval, TPU-native."""

from .client import DenseDpfPirClient
from .database import DenseDpfPirDatabase
from .messages import (
    DpfPirResponse,
    EncryptedHelperRequest,
    HelperRequest,
    LeaderRequest,
    PirRequest,
    PirResponse,
    PlainRequest,
)
from .server import DenseDpfPirServer, DpfPirServer
from .cuckoo_database import CuckooHashedDpfPirDatabase, CuckooHashingParams
from .sparse_client import CuckooHashingSparseDpfPirClient, KeyNotFound
from .sparse_server import CuckooHashingSparseDpfPirServer

__all__ = [
    "CuckooHashedDpfPirDatabase",
    "CuckooHashingParams",
    "CuckooHashingSparseDpfPirClient",
    "CuckooHashingSparseDpfPirServer",
    "KeyNotFound",
    "DenseDpfPirClient",
    "DenseDpfPirDatabase",
    "DenseDpfPirServer",
    "DpfPirServer",
    "DpfPirResponse",
    "EncryptedHelperRequest",
    "HelperRequest",
    "LeaderRequest",
    "PirRequest",
    "PirResponse",
    "PlainRequest",
]
