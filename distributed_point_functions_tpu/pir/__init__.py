"""Two-server DPF-based private information retrieval, TPU-native."""

from .client import DenseDpfPirClient
from .database import DenseDpfPirDatabase
from .messages import (
    DpfPirResponse,
    EncryptedHelperRequest,
    HelperRequest,
    LeaderRequest,
    PirRequest,
    PirResponse,
    PlainRequest,
)
from .server import DenseDpfPirServer, DpfPirServer

__all__ = [
    "DenseDpfPirClient",
    "DenseDpfPirDatabase",
    "DenseDpfPirServer",
    "DpfPirServer",
    "DpfPirResponse",
    "EncryptedHelperRequest",
    "HelperRequest",
    "LeaderRequest",
    "PirRequest",
    "PirResponse",
    "PlainRequest",
]
