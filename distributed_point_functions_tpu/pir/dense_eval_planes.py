"""Plane-resident dense-PIR expansion: the whole subtree walk stays in
bitsliced plane layout.

`dense_eval.evaluate_selection_blocks` re-enters the bitsliced AES kernel
per level, paying a 32x32 bit-transpose into plane layout and another one
back out for every level's hash, plus per-level `repeat`/select-mask
round-key composition. But every *other* operation of the DPF expansion
recurrence is linear over GF(2):

* seed correction is an XOR under a control mask,
* sigma is a byte-axis rewiring (`aes_bitslice.sigma_planes`),
* the control bit is bit-plane (0, 0); clearing the seed LSB zeroes it,
* child doubling becomes concatenation when children are ordered
  [all-left; all-right] instead of interleaved.

So the expansion can stay in plane layout end to end: transpose the nk
subtree roots in once, run `expand_levels` levels of two fixed-key
plane-space hashes (left/right children of every node — same AES work as
the one-pass key-selected hash, with no per-level transposes, no
`repeat`, and plain all-ones round-key planes), hash the leaves with the
value key, and transpose out once.

The price is leaf order: appending [all-left; all-right] per level makes
the final node order the **bit-reversal** of the natural block index
(position of leaf with path bits b1..be is be..b1). The serving path
compensates for free by bit-reversal-permuting the database's record
*blocks* once at staging (`bitrev_permutation`); the drop-in wrapper
`evaluate_selection_blocks_planes` instead gathers leaves back to natural
order for bit-identity with `evaluate_selection_blocks`.

Lane layout: flattened node-major/key-minor (lane = node * nk + key) with
nk padded to a multiple of 32, so each packed uint32 word holds 32 keys
of one node and per-key correction words broadcast to [G] words by a
plain `tile` (`pack_key_planes` / `pack_key_bits`).

Reference semantics: `ExpandSeeds`
(`dpf/distributed_point_function.cc:289-372`) restricted to the covering
subtree, as in `dense_eval.py`.
"""

from __future__ import annotations

import contextlib
import functools
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from .. import keys as fixed_keys
from ..ops.aes_bitslice import (
    aes_rounds_planes,
    limbs_to_planes,
    mmo_hash_planes,
    planes_to_limbs,
    sigma_planes,
)
from ..ops.expand_planes_pallas import (
    expand_head_planes_pallas,
    expand_level_planes_pallas,
    expand_tail_planes_pallas,
    tail_node_permutation,
    value_hash_planes_pallas,
    walk_descend_planes_pallas,
)
from .dense_eval import _walk_zeros

U32 = jnp.uint32


def bitrev_permutation(levels: int) -> np.ndarray:
    """perm[g] = bit-reversal of g over `levels` bits (an involution)."""
    n = 1 << levels
    perm = np.zeros(n, dtype=np.int64)
    for g in range(n):
        r = 0
        x = g
        for _ in range(levels):
            r = (r << 1) | (x & 1)
            x >>= 1
        perm[g] = r
    return perm


def walk_leaf_order(entry_order: np.ndarray, r: int) -> np.ndarray:
    """Leaf order after a fixed-width walk-descent of `r` levels: each
    entry node's 2^r leaves exit consecutively in natural offset order
    (`walk_descend_planes_pallas`), so order[p * 2^r + off] =
    entry_order[p] * 2^r + off."""
    m = np.asarray(entry_order, dtype=np.int64)
    return (
        m[:, None] * (1 << r) + np.arange(1 << r, dtype=np.int64)[None, :]
    ).reshape(-1)


def _walk_compact_enabled() -> bool:
    """DPF_TPU_WALK_COMPACT=1 routes the walk kernels through the
    compact-entry mode (in-kernel replication, no full-width HBM
    staging of the replicated entry). Default off until the mode is
    hardware-proven; read at dispatch time like the other knobs."""
    return os.environ.get("DPF_TPU_WALK_COMPACT", "") == "1"


def _walk_phase(state, ctrl, cwp, cwl, cwr, vc, *, r, node_lanes,
                leaf_order, compact, value_hash=False):
    """One walk-descent phase (head or tail) plus its leaf-order
    composition. `compact` arrives as a trace-time-static flag (the
    dispatcher reads the env knob); walk_plan is the single source of
    truth for the tile/mode pair. Returns ((state, ctrl),
    new_leaf_order)."""
    from ..ops.expand_planes_pallas import (
        compose_walk_leaf_order,
        walk_plan,
    )

    kg = cwp.shape[-1]
    w = state.shape[-1] << r
    tile, compact, npt = walk_plan(w, kg, node_lanes, r, compact)
    out = walk_descend_planes_pallas(
        state, ctrl, cwp, cwl, cwr, vc,
        r=r, tile_lanes=tile, value_hash=value_hash,
        node_lanes=node_lanes, compact_entry=compact,
    )
    return out, compose_walk_leaf_order(leaf_order, r, compact, npt)


def pack_key_planes(cw: jnp.ndarray) -> jnp.ndarray:
    """uint32[nk, 4] per-key 128-bit words -> uint32[16, 8, nk/32] planes
    packed over the key axis (word m bit i = key 32m+i's bit).

    Plane (byte j, bit i) of limb l bit b sits at flat index 32l + b —
    the limb-little-endian bit order (`aes_bitslice.limbs_to_planes`).
    """
    nk = cw.shape[0]
    if nk % 32:
        raise ValueError("key count must be padded to a multiple of 32")
    shifts = jnp.arange(32, dtype=U32)
    bits = (cw[:, :, None] >> shifts) & U32(1)  # [nk, 4, 32]
    bits = bits.reshape(nk // 32, 32, 128)
    words = (bits << shifts[None, :, None]).sum(axis=1, dtype=U32)
    return jnp.moveaxis(words, 0, -1).reshape(16, 8, -1)


def pack_key_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """uint32[nk] 0/1 -> uint32[nk/32] packed (word m bit i = key 32m+i).

    Same packing as `aes_bitslice.pack_select_bits` (the single
    implementation), with the key-count contract checked."""
    if bits.shape[0] % 32:
        raise ValueError("key count must be padded to a multiple of 32")
    from ..ops.aes_bitslice import pack_select_bits

    return pack_select_bits(bits)


def _tile_keys(words: jnp.ndarray, num_groups: int) -> jnp.ndarray:
    """Broadcast per-key packed words over the node axis: [..., nk/32] ->
    [..., num_groups] (node-major lanes: group g covers keys of node
    g // (nk/32))."""
    reps = num_groups // words.shape[-1]
    return jnp.tile(words, (1,) * (words.ndim - 1) + (reps,))


def expand_level_planes(state, ctrl, cw_p, cwl_w, cwr_w):
    """One [all-left; all-right] plane-space expansion level — the shared
    recurrence body of this module's covering-subtree expansion and
    `dpf._expand_levels_planes_fn`.

    state: [16, 8, G] planes; ctrl: uint32[G] packed parent control bits;
    cw_p: [16, 8, 2G or 1] seed-correction planes for the doubled width;
    cwl_w / cwr_w: packed direction-correction words broadcastable to [G]
    (one half each). Returns (state [16, 8, 2G], ctrl [2G])."""
    sig = sigma_planes(state)
    left = aes_rounds_planes(fixed_keys.RK_LEFT, sig) ^ sig
    right = aes_rounds_planes(fixed_keys.RK_RIGHT, sig) ^ sig
    state = jnp.concatenate([left, right], axis=-1)
    ctrl2 = jnp.concatenate([ctrl, ctrl])  # parent bit, both halves
    state = state ^ (cw_p & ctrl2[None, None, :])
    t_new = state[0, 0]  # LSB plane = control bits
    state = state.at[0, 0].set(jnp.zeros_like(t_new))
    half = ctrl.shape[0]
    cw_dir = jnp.concatenate(
        [
            jnp.broadcast_to(cwl_w, (half,)),
            jnp.broadcast_to(cwr_w, (half,)),
        ]
    )
    return state, t_new ^ (ctrl2 & cw_dir)


def evaluate_selection_blocks_planes(
    seeds0: jnp.ndarray,
    control0: jnp.ndarray,
    cw_seeds: jnp.ndarray,
    cw_left: jnp.ndarray,
    cw_right: jnp.ndarray,
    last_vc: jnp.ndarray,
    *,
    walk_levels: int,
    expand_levels: int,
    num_blocks: int,
    bitrev_leaves: bool = False,
    force_planes: bool = False,
) -> jnp.ndarray:
    """Plane-resident expansion with a padding-ratio guard.

    The key axis is padded to a multiple of 32 and the dead lanes double
    along with the live ones at every level, so a batch of e.g. 3 queries
    would pay ~10x the AES work. When the padding overhead exceeds 25%,
    fall back to the limb kernel (which pads per 32-block hash call and
    reaches full occupancy once the width fills a word).
    `force_planes=True` bypasses the guard (differential tests)."""
    nk = seeds0.shape[0]
    padded = ((nk + 31) // 32) * 32
    if not force_planes and not bitrev_leaves and padded * 4 > nk * 5:
        from .dense_eval import evaluate_selection_blocks

        return evaluate_selection_blocks(
            seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
            walk_levels=walk_levels,
            expand_levels=expand_levels,
            num_blocks=num_blocks,
        )
    mode = _level_kernel_enabled()
    if mode:
        # Walk mode runs the fixed-width descent kernels (head + tail);
        # tail mode fuses the last levels + value hash per subtree tile
        # (one kernel launch each); the fused head covers the narrow
        # entry levels in one launch; the per-level kernels (if any
        # levels remain) cover the middle.
        tail_levels = tile_nodes = 0
        tail_kind = head_kind = "concat"
        kg = padded // 32
        if mode == "walk" and not bitrev_leaves:
            # The walk kernels exit in natural leaf order, which the
            # exit gather absorbs; the bitrev-staged serving path
            # (bitrev_leaves=True) assumes doubling order, so walk
            # stays off there until staging is order-aware.
            tail_kind = head_kind = "walk"
            tail_levels = min(_tail_levels_requested(), expand_levels)
            head_levels = _head_split(
                kg, expand_levels - tail_levels, gate_flags=False
            )
        else:
            if mode == "tail" and not bitrev_leaves:
                tail_levels, tile_nodes = _tail_split(kg, expand_levels)
            head_levels = _head_split(kg, expand_levels - tail_levels)
        forced = os.environ.get("DPF_TPU_LEVEL_KERNEL", "auto") in (
            "pallas", "tail", "walk"
        )
        global _HEAD_KERNEL_FAILED, _TAIL_KERNEL_FAILED
        global _WALK_KERNEL_FAILED
        try:
            return _evaluate_selection_blocks_planes_jit(
                seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
                walk_levels=walk_levels,
                expand_levels=expand_levels,
                num_blocks=num_blocks,
                bitrev_leaves=bitrev_leaves,
                level_kernel=True,
                tail_levels=tail_levels,
                tail_tile_nodes=tile_nodes,
                head_levels=head_levels,
                tail_kind=tail_kind,
                head_kind=head_kind,
                walk_compact=(
                    tail_kind == "walk" and _walk_compact_ok()
                ),
            )
        except Exception as e:  # noqa: BLE001 - degrade, don't die
            if forced:
                raise
            if tail_kind == "walk":
                # Walk-mode failure: re-enter the dispatcher without
                # the walk family (the concat/per-level tiers' own
                # degradation chain handles any further failures). The
                # demotion is persisted ONLY after the re-dispatch
                # succeeds — mirroring the head/tail attribution rule:
                # a shared/transient failure must not burn the fastest
                # tier's cross-process flag on zero walk-specific
                # evidence.
                _WALK_KERNEL_FAILED = True
                try:
                    out = evaluate_selection_blocks_planes(
                        seeds0, control0, cw_seeds, cw_left,
                        cw_right, last_vc,
                        walk_levels=walk_levels,
                        expand_levels=expand_levels,
                        num_blocks=num_blocks,
                        bitrev_leaves=bitrev_leaves,
                        force_planes=force_planes,
                    )
                except Exception:  # noqa: BLE001
                    _WALK_KERNEL_FAILED = False
                    raise
                record_kernel_verdicts()
                warnings.warn(
                    "walk-descent kernels failed at serving shape; "
                    "serving without them "
                    f"({str(e).splitlines()[0][:200]})"
                )
                return out
            if head_levels:
                # Retry without the head, keeping the tail. The head is
                # demoted ONLY when this retry succeeds — a shared
                # failure (e.g. the tail is the culprit) must not burn
                # the healthy head's process-wide flag on zero evidence.
                try:
                    out = _evaluate_selection_blocks_planes_jit(
                        seeds0, control0, cw_seeds, cw_left, cw_right,
                        last_vc,
                        walk_levels=walk_levels,
                        expand_levels=expand_levels,
                        num_blocks=num_blocks,
                        bitrev_leaves=bitrev_leaves,
                        level_kernel=True,
                        tail_levels=tail_levels,
                        tail_tile_nodes=tile_nodes,
                    )
                except Exception as e2:  # noqa: BLE001
                    e = e2
                else:
                    _HEAD_KERNEL_FAILED = True
                    record_kernel_verdicts()
                    warnings.warn(
                        "fused head kernel failed at serving shape; "
                        "serving without it "
                        f"({str(e).splitlines()[0][:200]})"
                    )
                    return out
            if tail_levels:
                # Retry on the per-level kernels alone (no head, no
                # tail); the tail is demoted only when that succeeds —
                # if this fails too, the level-kernel failure below
                # already disables the whole family.
                try:
                    out = _evaluate_selection_blocks_planes_jit(
                        seeds0, control0, cw_seeds, cw_left, cw_right,
                        last_vc,
                        walk_levels=walk_levels,
                        expand_levels=expand_levels,
                        num_blocks=num_blocks,
                        bitrev_leaves=bitrev_leaves,
                        level_kernel=True,
                    )
                except Exception as e2:  # noqa: BLE001
                    e = e2
                else:
                    _TAIL_KERNEL_FAILED = True
                    record_kernel_verdicts()
                    warnings.warn(
                        "fused tail kernel failed at serving shape; "
                        "serving with the per-level kernels "
                        f"({str(e).splitlines()[0][:200]})"
                    )
                    return out
            _remember_level_kernel_failure()
            warnings.warn(
                "pallas level kernel failed; serving via the XLA level "
                f"({str(e).splitlines()[0][:200]})"
            )
    return _evaluate_selection_blocks_planes_jit(
        seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
        walk_levels=walk_levels,
        expand_levels=expand_levels,
        num_blocks=num_blocks,
        bitrev_leaves=bitrev_leaves,
        level_kernel=False,
    )


def _trace_state_clean() -> bool:
    """True when no jax trace is active (private API, so fail open: a
    missing symbol just means the self-check runs as before)."""
    try:
        from jax._src import core as _core

        return bool(_core.trace_state_clean())
    except Exception:  # noqa: BLE001 - jax internals moved
        return True


_LEVEL_KERNEL_FAILED = False
_WARNED_TRACED_UNVERIFIED = False


def _remember_level_kernel_failure() -> None:
    """Disable the auto-mode Pallas level kernel for this process (a
    failed trace is not cached by jit, so retrying would pay it on every
    batch)."""
    global _LEVEL_KERNEL_FAILED
    _LEVEL_KERNEL_FAILED = True
    record_kernel_verdicts()


_VERDICTS_LOADED = False
_VERDICT_FLAGS = (
    "_LEVEL_KERNEL_VERIFIED", "_LEVEL_KERNEL_FAILED",
    "_TAIL_KERNEL_VERIFIED", "_TAIL_KERNEL_FAILED",
    "_HEAD_KERNEL_VERIFIED", "_HEAD_KERNEL_FAILED",
    "_WALK_KERNEL_VERIFIED", "_WALK_KERNEL_FAILED",
    "_WALK_COMPACT_VERIFIED", "_WALK_COMPACT_FAILED",
    "_WALK_HIER_VERIFIED", "_WALK_HIER_FAILED",
    "_TAIL_HIER_VERIFIED", "_TAIL_HIER_FAILED",
)


def _verdict_cache_path():
    """Where self-check verdicts persist across processes.

    A Mosaic compile *failure* costs minutes of doomed remote-compile
    per fresh process (r04 hardware: the failing tail self-check alone
    burned ~4 minutes of every bench run before this cache existed);
    XLA's compilation cache memoizes successes but never failures.
    DPF_TPU_VERDICT_CACHE overrides the location; 0/off disables."""
    raw = os.environ.get("DPF_TPU_VERDICT_CACHE", "")
    if raw.lower() in ("0", "off", "none"):
        return None
    if raw:
        return raw
    return os.path.join(
        os.environ.get(
            "BENCH_CACHE_DIR", os.path.expanduser("~/.cache/jax_bench")
        ),
        "kernel_verdicts.json",
    )


def _verdict_key():
    """Verdicts are only valid for the exact (device kind, jax/jaxlib/
    runtime version, kernel source) tuple — Mosaic lives in jaxlib and
    the platform runtime, so a toolchain upgrade must re-probe: a stale
    VERIFIED would skip the bit-identity check under a compiler that may
    now miscompile, and a stale FAILED would demote kernels forever
    after the upgrade fixes the compile."""
    try:
        import hashlib

        import jaxlib

        from ..ops import aes_bitslice as _abs
        from ..ops import expand_planes_pallas as _epp

        h = hashlib.sha256()
        for mod in (_epp, _abs):  # kernels + the gate circuit they call
            with open(mod.__file__, "rb") as f:
                h.update(f.read())
        dev = jax.devices()[0]
        try:
            runtime = dev.client.platform_version
        except Exception:  # noqa: BLE001 - backend without the attr
            runtime = ""
        return (
            f"{dev.device_kind}|{jax.__version__}|{jaxlib.__version__}"
            f"|{runtime}|{h.hexdigest()[:16]}"
        )
    except Exception:  # noqa: BLE001 - cache is best-effort
        return None


def _load_kernel_verdicts() -> None:
    """Apply persisted verdicts (once per process) before self-checks."""
    global _VERDICTS_LOADED
    if _VERDICTS_LOADED:
        return
    _VERDICTS_LOADED = True
    path = _verdict_cache_path()
    if not path:
        return
    key = _verdict_key()
    if not key:
        return
    try:
        import json

        with open(path) as f:
            stored = json.load(f).get(key)
    except Exception:  # noqa: BLE001 - missing/corrupt cache = re-probe
        return
    if not isinstance(stored, dict):
        return
    for flag in _VERDICT_FLAGS:
        if stored.get(flag) is True:
            globals()[flag] = True


_LAST_RECORDED = None
_RECORD_SUSPENDED = False


@contextlib.contextmanager
def suspend_verdict_recording():
    """Silence the persistent verdict cache while a caller holds
    SPECULATIVE flag state (the bench demotion ladder sets a tier's
    FAILED flag before its attribution retry, and the retry itself
    triggers record_kernel_verdicts via warm_level_kernels /
    _level_kernel_enabled — without this guard a budget abort would
    leave an evidence-free demotion on disk forever)."""
    global _RECORD_SUSPENDED
    prev = _RECORD_SUSPENDED
    _RECORD_SUSPENDED = True
    try:
        yield
    finally:
        _RECORD_SUSPENDED = prev


def record_kernel_verdicts() -> None:
    """Merge the current self-check flags into the persistent cache.

    Called after every verdict change (self-check pass/fail and
    serve-shape demotions, including dpf.py's hierarchical path), so
    the next process skips known-failing Mosaic compiles instantly."""
    global _LAST_RECORDED
    if _RECORD_SUSPENDED:
        return
    snapshot = tuple(bool(globals()[f]) for f in _VERDICT_FLAGS)
    if snapshot == _LAST_RECORDED:
        # Repeated eager dispatches land here after every successful
        # _level_kernel_enabled(); skip the re-hash + rewrite when
        # nothing changed.
        return
    path = _verdict_cache_path()
    if not path:
        return
    key = _verdict_key()
    if not key:
        return
    try:
        import json

        try:
            with open(path) as f:
                data = json.load(f)
        except Exception:  # noqa: BLE001
            data = {}
        if not isinstance(data, dict):
            data = {}
        entry = data.setdefault(key, {})
        for flag in _VERDICT_FLAGS:
            if globals()[flag]:
                entry[flag] = True
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1)
        os.replace(tmp, path)
        _LAST_RECORDED = snapshot
    except Exception:  # noqa: BLE001 - cache is best-effort
        pass


_LEVEL_KERNEL_VERIFIED = False


def _level_kernel_selfcheck() -> bool:
    """One-time on-device bit-identity check of the fused level kernels
    against their XLA twins on a small random instance (the serving-path
    analog of bench.py's inner-product verification): auto mode must
    never serve a kernel Mosaic compiles incorrectly."""
    global _LEVEL_KERNEL_VERIFIED
    if _LEVEL_KERNEL_VERIFIED:
        return True
    import numpy as _np

    from ..ops.expand_planes_pallas import (
        expand_level_planes_pallas,
        value_hash_planes_pallas,
    )

    rng = _np.random.default_rng(1234)
    g, nk = 64, 64
    state = jnp.asarray(
        rng.integers(0, 1 << 32, (16, 8, g), dtype=_np.uint32)
    )
    ctrl = jnp.asarray(rng.integers(0, 1 << 32, (g,), dtype=_np.uint32))
    cwp = pack_key_planes(
        jnp.asarray(rng.integers(0, 1 << 32, (nk, 4), dtype=_np.uint32))
    )
    cwl = pack_key_bits(
        jnp.asarray(rng.integers(0, 2, (nk,), dtype=_np.uint32))
    )
    cwr = pack_key_bits(
        jnp.asarray(rng.integers(0, 2, (nk,), dtype=_np.uint32))
    )
    want_s, want_c = expand_level_planes(
        state, ctrl, _tile_keys(cwp, 2 * g), _tile_keys(cwl, g),
        _tile_keys(cwr, g),
    )
    got_s, got_c = expand_level_planes_pallas(state, ctrl, cwp, cwl, cwr)
    if not (
        _np.array_equal(_np.asarray(got_s), _np.asarray(want_s))
        and _np.array_equal(_np.asarray(got_c), _np.asarray(want_c))
    ):
        raise RuntimeError("level kernel/XLA bit mismatch on this device")
    # Chunked path (serving widths over _TILE_LANES run one grid-(1,)
    # call per lane slice): force sub-width tiles so the multi-call
    # assembly and its [all-left; all-right] order are checked on device.
    got_s, got_c = expand_level_planes_pallas(
        state, ctrl, cwp, cwl, cwr, tile_lanes=16
    )
    if not (
        _np.array_equal(_np.asarray(got_s), _np.asarray(want_s))
        and _np.array_equal(_np.asarray(got_c), _np.asarray(want_c))
    ):
        raise RuntimeError(
            "chunked level kernel/XLA bit mismatch on this device"
        )
    want_v = mmo_hash_planes(fixed_keys.RK_VALUE, state) ^ (
        _tile_keys(cwp, g) & ctrl[None, None, :]
    )
    got_v = value_hash_planes_pallas(state, ctrl, cwp)
    if not _np.array_equal(_np.asarray(got_v), _np.asarray(want_v)):
        raise RuntimeError("value kernel/XLA bit mismatch on this device")

    from ..ops.aes_bitslice import aes_rounds_select_planes
    from ..ops.expand_planes_pallas import path_level_planes_pallas

    sel = jnp.asarray(rng.integers(0, 1 << 32, (g,), dtype=_np.uint32))
    sig = sigma_planes(state)
    h = aes_rounds_select_planes(
        fixed_keys.RK_LEFT, fixed_keys.RK_RIGHT, sel, sig
    ) ^ sig
    h = h ^ (_tile_keys(cwp, g) & ctrl[None, None, :])
    t_new = h[0, 0]
    want_ps = h.at[0, 0].set(jnp.zeros_like(t_new))
    cw_dir = (sel & _tile_keys(cwr, g)) | (~sel & _tile_keys(cwl, g))
    want_pc = t_new ^ (ctrl & cw_dir)
    got_ps, got_pc = path_level_planes_pallas(
        state, ctrl, sel, cwp, cwl, cwr, per_seed=False
    )
    if not (
        _np.array_equal(_np.asarray(got_ps), _np.asarray(want_ps))
        and _np.array_equal(_np.asarray(got_pc), _np.asarray(want_pc))
    ):
        raise RuntimeError("path kernel/XLA bit mismatch on this device")
    _LEVEL_KERNEL_VERIFIED = True
    return True


_TAIL_KERNEL_VERIFIED = False
_TAIL_KERNEL_FAILED = False
_HEAD_KERNEL_VERIFIED = False
_HEAD_KERNEL_FAILED = False


def _head_max_lanes() -> int:
    """Exit-width cap for the fused head kernel (DPF_TPU_HEAD_MAX_LANES,
    default 2048: the in-kernel working set is ~6 copies of the widest
    [16, 8, W] u32 state, ~3 MB at 2048 lanes — comfortably inside the
    ~16 MB/core VMEM)."""
    try:
        return max(0, int(os.environ.get("DPF_TPU_HEAD_MAX_LANES", "2048")))
    except ValueError:
        return 2048


def _auto_head_count(cap: int, entry_lanes: int, avail: int) -> int:
    """Pure auto-sizing rule for the fused head, shared by the serving
    path (`_head_split`) and the hierarchical dispatch (`dpf.py`): fill
    levels until the exit width reaches `cap` lanes; a 1-level head is
    just a worse per-level launch, so the minimum is 2."""
    if avail <= 0 or entry_lanes <= 0 or cap < 2 * entry_lanes:
        return 0
    head = min(avail, (cap // entry_lanes).bit_length() - 1)
    return head if head >= 2 else 0


def _head_split(
    key_groups: int, a_levels: int, gate_flags: bool = True
) -> int:
    """How many entry levels the fused head kernel covers (0 = no head).

    The head runs from `key_groups` lanes until its exit width reaches
    the VMEM lane cap (or the per-level/tail boundary). A 1-level head
    is just a worse per-level launch, so the minimum is 2.
    DPF_TPU_HEAD_LEVELS forces the count (0 disables) — honored even
    before the self-check has run, so forced A/B legs
    (DPF_TPU_LEVEL_KERNEL=pallas|tail|walk) can measure the head; a
    failure then propagates (forced) or demotes the head (auto).
    `gate_flags=False` skips the concat-head verification gate — walk
    mode's head runs the walk kernel family, gated by the walk flags
    through the mode itself."""
    if a_levels <= 0:
        return 0
    raw = os.environ.get("DPF_TPU_HEAD_LEVELS", "auto")
    if raw != "auto":
        try:
            return max(0, min(int(raw), a_levels))
        except ValueError:
            pass
    if gate_flags and (_HEAD_KERNEL_FAILED or not _HEAD_KERNEL_VERIFIED):
        return 0
    return _auto_head_count(_head_max_lanes(), key_groups, a_levels)


def _head_kernel_selfcheck() -> bool:
    """One-time on-device bit-identity check of the fused head kernel
    against sequential XLA levels. The head's serving entry is naturally
    narrow (key_groups lanes), so the check runs at a matching narrow
    entry — the shape family it actually serves."""
    global _HEAD_KERNEL_VERIFIED, _HEAD_KERNEL_FAILED
    if _HEAD_KERNEL_FAILED:
        return False
    if _HEAD_KERNEL_VERIFIED:
        return True
    import numpy as _np

    rng = _np.random.default_rng(9876)
    g0, nk, r = 2, 64, 3
    state = jnp.asarray(
        rng.integers(0, 1 << 32, (16, 8, g0), dtype=_np.uint32)
    )
    ctrl = jnp.asarray(rng.integers(0, 1 << 32, (g0,), dtype=_np.uint32))
    cwp = [
        pack_key_planes(jnp.asarray(
            rng.integers(0, 1 << 32, (nk, 4), dtype=_np.uint32)
        ))
        for _ in range(r)
    ]
    cwl = [
        pack_key_bits(jnp.asarray(
            rng.integers(0, 2, (nk,), dtype=_np.uint32)
        ))
        for _ in range(r)
    ]
    cwr = [
        pack_key_bits(jnp.asarray(
            rng.integers(0, 2, (nk,), dtype=_np.uint32)
        ))
        for _ in range(r)
    ]
    s, c = state, ctrl
    for i in range(r):
        g2 = 2 * s.shape[-1]
        s, c = expand_level_planes(
            s, c, _tile_keys(cwp[i], g2), _tile_keys(cwl[i], g2 // 2),
            _tile_keys(cwr[i], g2 // 2),
        )
    got_s, got_c = expand_head_planes_pallas(
        state, ctrl, jnp.stack(cwp), jnp.stack(cwl), jnp.stack(cwr)
    )
    if not (
        _np.array_equal(_np.asarray(got_s), _np.asarray(s))
        and _np.array_equal(_np.asarray(got_c), _np.asarray(c))
    ):
        raise RuntimeError("head kernel/XLA bit mismatch on this device")
    _HEAD_KERNEL_VERIFIED = True
    return True


_WALK_KERNEL_VERIFIED = False
_WALK_KERNEL_FAILED = False
# Mosaic legality/miscompiles are shape- and mode-dependent (the walk
# redesign exists because of that), so the base walk verdict must NOT
# green-light geometries it never executed: compact-entry mode and the
# hierarchical kg=1/node_lanes=prefix-words layout carry their own
# verdicts, each bit-verified in exactly the mode the dispatcher would
# launch (ADVICE r04).
_WALK_COMPACT_VERIFIED = False
_WALK_COMPACT_FAILED = False
_WALK_HIER_VERIFIED = False
_WALK_HIER_FAILED = False
# Tail kernel at the HIERARCHICAL operand geometry (kg=1 shared
# corrections, zero value correction — dpf.py's fused program): its own
# verdict pair, because the dense-tile _TAIL_KERNEL_VERIFIED never
# executed those operand shapes and Mosaic legality is shape-dependent.
_TAIL_HIER_VERIFIED = False
_TAIL_HIER_FAILED = False


def _walk_twin_instance(rng, g0, nk, r):
    """Random walk-phase instance + its doubling-twin result: returns
    (state, ctrl, cwp[r], cwl[r], cwr[r], vc, want_v, want_c). The twin
    runs the sequential XLA levels and the leaf value hash — the ground
    truth every walk-geometry self-check compares against."""
    import numpy as _np

    state = jnp.asarray(
        rng.integers(0, 1 << 32, (16, 8, g0), dtype=_np.uint32)
    )
    ctrl = jnp.asarray(rng.integers(0, 1 << 32, (g0,), dtype=_np.uint32))
    if nk is None:
        # Hierarchical layout: one key, shared correction words.
        from ..ops.aes_bitslice import broadcast_cw_planes

        cwp = [
            broadcast_cw_planes(jnp.asarray(
                rng.integers(0, 1 << 32, (4,), dtype=_np.uint32)
            ))
            for _ in range(r)
        ]
        cwl = [
            (U32(0) - jnp.asarray(rng.integers(0, 2), dtype=U32))[None]
            for _ in range(r)
        ]
        cwr = [
            (U32(0) - jnp.asarray(rng.integers(0, 2), dtype=U32))[None]
            for _ in range(r)
        ]
        vc = jnp.zeros((16, 8, 1), dtype=U32)  # dpf.py's zero-vc tail
    else:
        cwp = [
            pack_key_planes(jnp.asarray(
                rng.integers(0, 1 << 32, (nk, 4), dtype=_np.uint32)
            ))
            for _ in range(r)
        ]
        cwl = [
            pack_key_bits(jnp.asarray(
                rng.integers(0, 2, (nk,), dtype=_np.uint32)
            ))
            for _ in range(r)
        ]
        cwr = [
            pack_key_bits(jnp.asarray(
                rng.integers(0, 2, (nk,), dtype=_np.uint32)
            ))
            for _ in range(r)
        ]
        vc = pack_key_planes(jnp.asarray(
            rng.integers(0, 1 << 32, (nk, 4), dtype=_np.uint32)
        ))
    s, c = state, ctrl
    for i in range(r):
        g2 = 2 * s.shape[-1]
        s, c = expand_level_planes(
            s, c, _tile_keys(cwp[i], g2), _tile_keys(cwl[i], g2 // 2),
            _tile_keys(cwr[i], g2 // 2),
        )
    want_v = mmo_hash_planes(fixed_keys.RK_VALUE, s) ^ (
        _tile_keys(vc, s.shape[-1]) & c[None, None, :]
    )
    return state, ctrl, cwp, cwl, cwr, vc, want_v, c


def _walk_twin_lanes(exit_order, r, n_entry, node_lanes):
    """Lane gather mapping a walk exit order onto the doubling twin:
    lane block p of the walk output holds leaf `exit_order[p]`, which
    the twin placed at position argsort(doubling order)[leaf]."""
    import numpy as _np

    order = tail_node_permutation(
        _np.arange(n_entry), r, n_entry
    )[0]
    pos_of_leaf = _np.argsort(order)
    pos = pos_of_leaf[_np.asarray(exit_order)]
    return (
        pos[:, None] * node_lanes + _np.arange(node_lanes)[None, :]
    ).reshape(-1)


# Self-check instance shapes. Hardware verdicts must come from the
# SERVING tile geometry (Mosaic legality is shape-dependent), so these
# stay at the production widths; the CPU interpret-mode tests shrink
# them via monkeypatch (an interpret kernel call costs ~15-30 s
# regardless of correctness).
_WALK_SELFCHECK_SHAPE = dict(g0=1024, nk=64, r=2, tile=2048)
_WALK_COMPACT_SELFCHECK_SHAPE = dict(g0=1024, nk=64, r=2)
_WALK_HIER_SELFCHECK_SHAPE = dict(nl=4, n_entry=64, r=2)
_TAIL_SELFCHECK_SHAPE = dict(g0=256, nk=64, r=2, tile=128)
_TAIL_HIER_SELFCHECK_SHAPE = dict(g0=256, r=2, tile=128)


def _walk_kernel_selfcheck() -> bool:
    """One-time on-device bit-identity check of the fixed-width
    walk-descent kernel (2 levels + value hash, 2 tiles) against the
    doubling XLA twin, at the SERVING tile width (2048 lanes — Mosaic
    legality is shape-dependent, so a verdict from a smaller tile would
    not cover the geometry the dispatcher actually picks)."""
    global _WALK_KERNEL_VERIFIED, _WALK_KERNEL_FAILED
    if _WALK_KERNEL_FAILED:
        return False
    if _WALK_KERNEL_VERIFIED:
        return True
    import numpy as _np

    rng = _np.random.default_rng(2468)
    s = _WALK_SELFCHECK_SHAPE
    g0, nk, r, tile = s["g0"], s["nk"], s["r"], s["tile"]
    kg = nk // 32
    state, ctrl, cwp, cwl, cwr, vc, want_v, want_c = _walk_twin_instance(
        rng, g0, nk, r
    )
    # Replicated mode exits in natural leaf order.
    lanes = _walk_twin_lanes(
        _np.arange((g0 // kg) << r), r, g0 // kg, kg
    )
    got_v, got_c = walk_descend_planes_pallas(
        state, ctrl, jnp.stack(cwp), jnp.stack(cwl), jnp.stack(cwr),
        vc, r=r, tile_lanes=tile, value_hash=True,
    )
    if not (
        _np.array_equal(
            _np.asarray(got_v), _np.asarray(want_v)[:, :, lanes]
        )
        and _np.array_equal(
            _np.asarray(got_c), _np.asarray(want_c)[lanes]
        )
    ):
        raise RuntimeError("walk kernel/XLA bit mismatch on this device")
    _WALK_KERNEL_VERIFIED = True
    return True


def _walk_compact_selfcheck() -> bool:
    """One-time on-device bit-identity check of the walk kernel's
    COMPACT-ENTRY mode at the dense-serving geometry (node_lanes = kg),
    in exactly the tile/mode `walk_plan` would pick. The base walk
    verdict never executed this mode, and a compact-mode miscompile
    would serve wrong PIR shares under a 'verified' flag."""
    global _WALK_COMPACT_VERIFIED, _WALK_COMPACT_FAILED
    if _WALK_COMPACT_FAILED:
        return False
    if _WALK_COMPACT_VERIFIED:
        return True
    import numpy as _np

    from ..ops.expand_planes_pallas import (
        compose_walk_leaf_order,
        walk_plan,
    )

    rng = _np.random.default_rng(97531)
    s = _WALK_COMPACT_SELFCHECK_SHAPE
    g0, nk, r = s["g0"], s["nk"], s["r"]
    kg = nk // 32
    tile, compact, npt = walk_plan(g0 << r, kg, kg, r, True)
    if not compact:
        # walk_plan declined compact at this geometry (tile cap): the
        # mode cannot launch here, so there is nothing to verify — and
        # nothing FAILED. Returning False (instead of raising into the
        # caller's except clause) keeps the cross-process FAILED verdict
        # reserved for genuine kernel evidence; a decline is a planner
        # decision that can change with tile knobs or jax versions.
        return False
    state, ctrl, cwp, cwl, cwr, vc, want_v, want_c = _walk_twin_instance(
        rng, g0, nk, r
    )
    n_entry = g0 // kg
    exit_order = compose_walk_leaf_order(
        _np.arange(n_entry, dtype=_np.int64), r, True, npt
    )
    lanes = _walk_twin_lanes(exit_order, r, n_entry, kg)
    got_v, got_c = walk_descend_planes_pallas(
        state, ctrl, jnp.stack(cwp), jnp.stack(cwl), jnp.stack(cwr),
        vc, r=r, tile_lanes=tile, value_hash=True, compact_entry=True,
    )
    if not (
        _np.array_equal(
            _np.asarray(got_v), _np.asarray(want_v)[:, :, lanes]
        )
        and _np.array_equal(
            _np.asarray(got_c), _np.asarray(want_c)[lanes]
        )
    ):
        raise RuntimeError(
            "compact walk kernel/XLA bit mismatch on this device"
        )
    _WALK_COMPACT_VERIFIED = True
    return True


def _walk_hier_selfcheck() -> bool:
    """One-time on-device bit-identity check of the walk kernel at the
    HIERARCHICAL geometry (kg=1 shared corrections, node_lanes =
    prefix words, zero value correction — `dpf._expand_levels_planes_fn`'s
    layout), in exactly the tile/mode its `walk_order` would plan."""
    global _WALK_HIER_VERIFIED, _WALK_HIER_FAILED
    if _WALK_HIER_FAILED:
        return False
    if _WALK_HIER_VERIFIED:
        return True
    import numpy as _np

    from ..ops.expand_planes_pallas import (
        compose_walk_leaf_order,
        walk_plan,
    )

    rng = _np.random.default_rng(86420)
    s = _WALK_HIER_SELFCHECK_SHAPE
    nl, n_entry, r = s["nl"], s["n_entry"], s["r"]
    g0 = nl * n_entry
    state, ctrl, cwp, cwl, cwr, vc, want_v, want_c = _walk_twin_instance(
        rng, g0, None, r
    )
    # Verify every mode the hierarchical dispatch could launch —
    # replicated AND compact, regardless of the env knob: the persisted
    # _WALK_HIER_VERIFIED flag outlives this process, and a later
    # process with DPF_TPU_WALK_COMPACT=1 would otherwise dispatch a
    # tile/mode combination no self-check ever executed. (walk_plan may
    # decline compact at this geometry, collapsing both plans into one.)
    plans = []
    for want_compact in (False, True):
        plan = walk_plan(g0 << r, 1, nl, r, want_compact)
        if plan not in plans:
            plans.append(plan)
    for tile, compact, npt in plans:
        exit_order = compose_walk_leaf_order(
            _np.arange(n_entry, dtype=_np.int64), r, compact, npt
        )
        lanes = _walk_twin_lanes(exit_order, r, n_entry, nl)
        got_v, got_c = walk_descend_planes_pallas(
            state, ctrl, jnp.stack(cwp), jnp.stack(cwl), jnp.stack(cwr),
            vc, r=r, tile_lanes=tile, value_hash=True, node_lanes=nl,
            compact_entry=compact,
        )
        if not (
            _np.array_equal(
                _np.asarray(got_v), _np.asarray(want_v)[:, :, lanes]
            )
            and _np.array_equal(
                _np.asarray(got_c), _np.asarray(want_c)[lanes]
            )
        ):
            raise RuntimeError(
                "hierarchical walk kernel/XLA bit mismatch on this "
                f"device (compact={compact})"
            )
    _WALK_HIER_VERIFIED = True
    return True


def _walk_compact_ok() -> bool:
    """Gate for compact-entry walk mode at dispatch time: requested via
    the env knob AND bit-verified in that exact mode. Under an active
    trace the self-check cannot run; only a prior eager verification
    counts (mirroring `_level_kernel_enabled`'s trace rule)."""
    global _WALK_COMPACT_FAILED
    if not _walk_compact_enabled():
        return False
    if _WALK_COMPACT_FAILED:
        return False
    if _WALK_COMPACT_VERIFIED:
        return True
    if not _trace_state_clean():
        return False
    try:
        return _walk_compact_selfcheck()
    except Exception as e:  # noqa: BLE001 - never break serving
        _WALK_COMPACT_FAILED = True
        record_kernel_verdicts()
        warnings.warn(
            "compact-entry walk mode failed its on-device self-check; "
            f"serving replicated entries ({str(e).splitlines()[0][:200]})"
        )
        return False


def _walk_hier_ok() -> bool:
    """Gate for the hierarchical walk geometry at dispatch time (same
    trace/verification rules as `_walk_compact_ok`)."""
    global _WALK_HIER_FAILED
    if _WALK_HIER_FAILED:
        return False
    if _WALK_HIER_VERIFIED:
        return True
    if not _trace_state_clean():
        return False
    try:
        return _walk_hier_selfcheck()
    except Exception as e:  # noqa: BLE001 - never break serving
        _WALK_HIER_FAILED = True
        record_kernel_verdicts()
        warnings.warn(
            "hierarchical walk geometry failed its on-device "
            f"self-check; serving the concat/per-level tiers there "
            f"({str(e).splitlines()[0][:200]})"
        )
        return False


def _tail_kernel_selfcheck() -> bool:
    """One-time on-device bit-identity check of the fused tail kernel
    (2 levels + value hash over 2 tiles) against the XLA twin. Separate
    from `_level_kernel_selfcheck` so a tail-only failure degrades auto
    mode to the per-level kernels instead of all the way to XLA."""
    global _TAIL_KERNEL_VERIFIED, _TAIL_KERNEL_FAILED
    # FAILED wins over VERIFIED: a serving-shape failure recorded after a
    # successful self-check must demote the tail for the whole process
    # (jit does not cache failed traces, so re-attempting pays the full
    # compile on every request).
    if _TAIL_KERNEL_FAILED:
        return False
    if _TAIL_KERNEL_VERIFIED:
        return True
    import numpy as _np

    rng = _np.random.default_rng(4321)
    # Entry tile of 128 lanes (2 tiles, so the multi-tile assembly is
    # exercised): serving tiles are >=128 lanes by _tail_split's floor,
    # and Mosaic's known crash regime is narrow lanes — a self-check at
    # 4-lane tiles could fail (and permanently demote the tail) at a
    # shape the tail never serves.
    s = _TAIL_SELFCHECK_SHAPE
    g0, nk, r, tile = s["g0"], s["nk"], s["r"], s["tile"]
    state = jnp.asarray(
        rng.integers(0, 1 << 32, (16, 8, g0), dtype=_np.uint32)
    )
    ctrl = jnp.asarray(rng.integers(0, 1 << 32, (g0,), dtype=_np.uint32))
    cwp = [
        pack_key_planes(jnp.asarray(
            rng.integers(0, 1 << 32, (nk, 4), dtype=_np.uint32)
        ))
        for _ in range(r)
    ]
    cwl = [
        pack_key_bits(jnp.asarray(
            rng.integers(0, 2, (nk,), dtype=_np.uint32)
        ))
        for _ in range(r)
    ]
    cwr = [
        pack_key_bits(jnp.asarray(
            rng.integers(0, 2, (nk,), dtype=_np.uint32)
        ))
        for _ in range(r)
    ]
    vc = pack_key_planes(jnp.asarray(
        rng.integers(0, 1 << 32, (nk, 4), dtype=_np.uint32)
    ))
    want_vs, want_cs = [], []
    for lo in range(0, g0, tile):
        s = state[:, :, lo:lo + tile]
        c = ctrl[lo:lo + tile]
        for i in range(r):
            g2 = 2 * s.shape[-1]
            s, c = expand_level_planes(
                s, c, _tile_keys(cwp[i], g2), _tile_keys(cwl[i], g2 // 2),
                _tile_keys(cwr[i], g2 // 2),
            )
        want_vs.append(
            mmo_hash_planes(fixed_keys.RK_VALUE, s)
            ^ (_tile_keys(vc, s.shape[-1]) & c[None, None, :])
        )
        want_cs.append(c)
    got_v, got_c = expand_tail_planes_pallas(
        state, ctrl, jnp.stack(cwp), jnp.stack(cwl), jnp.stack(cwr), vc,
        tile_lanes=tile,
    )
    if not (
        _np.array_equal(
            _np.asarray(got_v),
            _np.asarray(jnp.concatenate(want_vs, axis=-1)),
        )
        and _np.array_equal(
            _np.asarray(got_c), _np.asarray(jnp.concatenate(want_cs))
        )
    ):
        raise RuntimeError("tail kernel/XLA bit mismatch on this device")
    _TAIL_KERNEL_VERIFIED = True
    return True


def _tail_hier_selfcheck() -> bool:
    """One-time on-device bit-identity check of the fused tail kernel at
    the HIERARCHICAL operand geometry (`dpf._expand_levels_planes_fn`'s
    tail: kg=1 broadcast correction planes, [1]-shaped direction words,
    zero value correction) against the XLA twin. `_TAIL_KERNEL_VERIFIED`
    comes from per-key dense-tile operands and does not cover these
    shapes."""
    global _TAIL_HIER_VERIFIED, _TAIL_HIER_FAILED
    if _TAIL_HIER_FAILED:
        return False
    if _TAIL_HIER_VERIFIED:
        return True
    import numpy as _np

    from ..ops.aes_bitslice import broadcast_cw_planes

    rng = _np.random.default_rng(8642)
    s = _TAIL_HIER_SELFCHECK_SHAPE
    g0, r, tile = s["g0"], s["r"], s["tile"]
    state = jnp.asarray(
        rng.integers(0, 1 << 32, (16, 8, g0), dtype=_np.uint32)
    )
    ctrl = jnp.asarray(rng.integers(0, 1 << 32, (g0,), dtype=_np.uint32))
    cwp = [
        broadcast_cw_planes(jnp.asarray(
            rng.integers(0, 1 << 32, (4,), dtype=_np.uint32)
        ))
        for _ in range(r)
    ]
    cwl = [
        (U32(0) - jnp.asarray(rng.integers(0, 2), dtype=U32))[None]
        for _ in range(r)
    ]
    cwr = [
        (U32(0) - jnp.asarray(rng.integers(0, 2), dtype=U32))[None]
        for _ in range(r)
    ]
    vc = jnp.zeros((16, 8, 1), dtype=U32)
    want_vs, want_cs = [], []
    for lo in range(0, g0, tile):
        st = state[:, :, lo:lo + tile]
        c = ctrl[lo:lo + tile]
        for i in range(r):
            g2 = 2 * st.shape[-1]
            st, c = expand_level_planes(
                st, c, _tile_keys(cwp[i], g2), _tile_keys(cwl[i], g2 // 2),
                _tile_keys(cwr[i], g2 // 2),
            )
        want_vs.append(
            mmo_hash_planes(fixed_keys.RK_VALUE, st)
            ^ (_tile_keys(vc, st.shape[-1]) & c[None, None, :])
        )
        want_cs.append(c)
    got_v, got_c = expand_tail_planes_pallas(
        state, ctrl, jnp.stack(cwp), jnp.stack(cwl), jnp.stack(cwr), vc,
        tile_lanes=tile,
    )
    if not (
        _np.array_equal(
            _np.asarray(got_v),
            _np.asarray(jnp.concatenate(want_vs, axis=-1)),
        )
        and _np.array_equal(
            _np.asarray(got_c), _np.asarray(jnp.concatenate(want_cs))
        )
    ):
        raise RuntimeError(
            "hierarchical-geometry tail kernel/XLA bit mismatch on this "
            "device"
        )
    _TAIL_HIER_VERIFIED = True
    return True


def _tail_hier_ok() -> bool:
    """Gate for the tail kernel at the hierarchical operand geometry
    (same trace/verification rules as `_walk_hier_ok`): dpf.py's
    walk-mode fallback must not trust the dense-tile tail verdict across
    geometries."""
    global _TAIL_HIER_FAILED
    if _TAIL_HIER_FAILED:
        return False
    if _TAIL_HIER_VERIFIED:
        return True
    if not _trace_state_clean():
        return False
    try:
        return _tail_hier_selfcheck()
    except Exception as e:  # noqa: BLE001 - never break serving
        _TAIL_HIER_FAILED = True
        record_kernel_verdicts()
        warnings.warn(
            "hierarchical-geometry tail kernel failed its on-device "
            f"self-check; serving the per-level tiers there "
            f"({str(e).splitlines()[0][:200]})"
        )
        return False


def warm_level_kernels():
    """Eagerly run the kernel self-checks (and return the serving mode).

    `_level_kernel_enabled` cannot self-check while an outer jit/shard_map
    trace is active — it then reports the last *eager* verification, which
    on a fresh process is "nothing verified" and silently serves the XLA
    levels. Callers that trace the expansion into a bigger program
    (bench.py's fused step, the sharded mesh step) call this once, from
    eager context, before building the traced program."""
    mode = _level_kernel_enabled()
    if mode == "walk":
        # The compact-entry and hierarchical geometries carry their own
        # verdicts: warm them here so traced programs (the fused serving
        # step, the sharded mesh step, bench's ns/leaf hierarchical
        # stage) can dispatch them — the in-trace gates only honor a
        # prior eager verification.
        if _walk_compact_enabled():
            _walk_compact_ok()
        if not _walk_hier_ok():
            # dpf.py's walk fallback re-dispatches the hierarchical tail
            # through the fused tail kernel when ITS geometry verdict
            # holds; warm that verdict too so the traced program can
            # still take the tail tier.
            _tail_hier_ok()
    elif mode == "tail":
        _tail_hier_ok()
    return mode


def level_kernel_status() -> dict:
    """Public observability snapshot for benches/captures: the serving
    mode knob and the one-time self-check flags."""
    return {
        "mode": os.environ.get("DPF_TPU_LEVEL_KERNEL", "auto"),
        "verified": _LEVEL_KERNEL_VERIFIED,
        "failed": _LEVEL_KERNEL_FAILED,
        "tail_verified": _TAIL_KERNEL_VERIFIED,
        "tail_failed": _TAIL_KERNEL_FAILED,
        "head_verified": _HEAD_KERNEL_VERIFIED,
        "head_failed": _HEAD_KERNEL_FAILED,
        "walk_verified": _WALK_KERNEL_VERIFIED,
        "walk_failed": _WALK_KERNEL_FAILED,
        "walk_compact_verified": _WALK_COMPACT_VERIFIED,
        "walk_compact_failed": _WALK_COMPACT_FAILED,
        "walk_hier_verified": _WALK_HIER_VERIFIED,
        "walk_hier_failed": _WALK_HIER_FAILED,
        "tail_hier_verified": _TAIL_HIER_VERIFIED,
        "tail_hier_failed": _TAIL_HIER_FAILED,
    }


def _tail_levels_requested() -> int:
    """How many final levels the fused tail kernel should cover
    (DPF_TPU_TAIL_LEVELS, default 4: the measured hot levels are the
    last ~4 plus the value hash — expand_profile 2026-07-31)."""
    try:
        return max(1, int(os.environ.get("DPF_TPU_TAIL_LEVELS", "4")))
    except ValueError:
        return 4


def _tail_tile_target() -> int:
    """Target entry-tile lane count — the one place the
    DPF_TPU_TAIL_TILE_LANES knob is parsed."""
    try:
        target = int(os.environ.get("DPF_TPU_TAIL_TILE_LANES", "128"))
    except ValueError:
        target = 128
    return target


def _tail_split(
    key_groups: int,
    expand_levels: int,
    requested_levels: int | None = None,
    target_lanes: int | None = None,
) -> tuple:
    """(tail_levels, tile_nodes) for the fused tail: shrink the tail
    until the entry tile reaches the width floor — min(128 lanes, the
    explicit DPF_TPU_TAIL_TILE_LANES target, what the key-group packing
    can express, the whole tree) — so default-config in-kernel widths
    stay clear of the narrow-lane Mosaic regime while small probe/test
    tiles remain honored. Env knobs are read here, OUTSIDE the jit, and
    passed as static args — changing them between calls with identical
    shapes must not be silently ignored."""
    if requested_levels is None:
        requested_levels = _tail_levels_requested()
    if target_lanes is None:
        target_lanes = _tail_tile_target()
    best = 1 << (max(1, target_lanes // key_groups).bit_length() - 1)
    tail = min(requested_levels, expand_levels)
    if tail <= 0:
        return 0, 0
    floor = min(
        128, target_lanes, best * key_groups,
        key_groups << expand_levels,
    )
    def tile_nodes(a_levels):
        return min(best, 1 << a_levels)

    while (
        tail > 1
        and tile_nodes(expand_levels - tail) * key_groups < floor
    ):
        tail -= 1
    return tail, tile_nodes(expand_levels - tail)


def _level_kernel_enabled():
    """Whether (and how) the fused Pallas kernels serve the expansion:
    False, "pallas" (per-level kernels), "tail" (per-level kernels plus
    the fused multi-level tail + value hash), or "walk" (fixed-width
    walk-descent head + tail).

    DPF_TPU_LEVEL_KERNEL=pallas|tail|walk forces the mode (errors
    propagate), =xla disables it; auto prefers walk > tail > per-level
    on TPU after one-time on-device bit-identity self-checks, until a
    remembered failure."""
    global _TAIL_KERNEL_FAILED, _WALK_KERNEL_FAILED
    mode = os.environ.get("DPF_TPU_LEVEL_KERNEL", "auto")
    if mode in ("pallas", "tail", "walk"):
        return mode
    if mode == "xla":
        return False
    if jax.default_backend() != "tpu":
        return False
    _load_kernel_verdicts()
    if _LEVEL_KERNEL_FAILED:
        return False
    if not _trace_state_clean():
        # Reached while an outer jit is being traced (e.g. the fused DCF
        # program calling the path walk): the self-check cannot run here —
        # its jitted twins would be traced into the outer program and the
        # comparisons would explode on tracers. Report the last *eager*
        # verification result; never record a failure from this path.
        # Forgetting to warm is a silent perf cliff (the r02 headline
        # served XLA levels this way), so make it loud exactly once.
        if not _LEVEL_KERNEL_VERIFIED:
            global _WARNED_TRACED_UNVERIFIED
            if not _WARNED_TRACED_UNVERIFIED:
                _WARNED_TRACED_UNVERIFIED = True
                warnings.warn(
                    "expansion traced before warm_level_kernels(): the "
                    "Pallas level kernels are unverified in this process "
                    "and this program will serve the XLA levels — call "
                    "dense_eval_planes.warm_level_kernels() from eager "
                    "context before building traced programs"
                )
            return False
        if _WALK_KERNEL_VERIFIED and not _WALK_KERNEL_FAILED:
            return "walk"
        return (
            "tail"
            if _TAIL_KERNEL_VERIFIED and not _TAIL_KERNEL_FAILED
            else "pallas"
        )
    try:
        if not _level_kernel_selfcheck():
            return False
    except Exception as e:  # noqa: BLE001 - never break serving
        _remember_level_kernel_failure()
        warnings.warn(
            "pallas level kernels failed their on-device self-check; "
            f"serving via the XLA levels ({str(e).splitlines()[0][:200]})"
        )
        return False
    # The fused head is orthogonal to the tail/per-level choice: verify
    # it here so `_head_split` can enable it inside traced programs. A
    # head-only failure costs nothing but the head.
    global _HEAD_KERNEL_FAILED
    try:
        _head_kernel_selfcheck()
    except Exception as e:  # noqa: BLE001 - never break serving
        _HEAD_KERNEL_FAILED = True
        warnings.warn(
            "fused head kernel failed its on-device self-check; "
            f"serving without it ({str(e).splitlines()[0][:200]})"
        )
    # Prefer the walk-descent kernels (fixed-width, no doubling
    # constructs) when they verify on this device; then the fused tail;
    # a fused-kernel failure degrades to the per-level kernels, not XLA.
    try:
        if _walk_kernel_selfcheck():
            record_kernel_verdicts()
            return "walk"
    except Exception as e:  # noqa: BLE001 - never break serving
        _WALK_KERNEL_FAILED = True
        warnings.warn(
            "walk-descent kernel failed its on-device self-check; "
            f"trying the fused tail ({str(e).splitlines()[0][:200]})"
        )
    try:
        if _tail_kernel_selfcheck():
            record_kernel_verdicts()
            return "tail"
    except Exception as e:  # noqa: BLE001 - never break serving
        _TAIL_KERNEL_FAILED = True
        warnings.warn(
            "fused tail kernel failed its on-device self-check; "
            f"serving via the per-level kernels "
            f"({str(e).splitlines()[0][:200]})"
        )
    record_kernel_verdicts()
    return "pallas"


@functools.partial(
    jax.jit,
    static_argnames=(
        "walk_levels", "expand_levels", "num_blocks", "bitrev_leaves",
        "level_kernel", "tail_levels", "tail_tile_nodes", "head_levels",
        "tail_kind", "head_kind", "walk_compact",
    ),
)
def _evaluate_selection_blocks_planes_jit(
    seeds0: jnp.ndarray,
    control0: jnp.ndarray,
    cw_seeds: jnp.ndarray,
    cw_left: jnp.ndarray,
    cw_right: jnp.ndarray,
    last_vc: jnp.ndarray,
    *,
    walk_levels: int,
    expand_levels: int,
    num_blocks: int,
    bitrev_leaves: bool = False,
    level_kernel: bool = False,
    tail_levels: int = 0,
    tail_tile_nodes: int = 0,
    head_levels: int = 0,
    tail_kind: str = "concat",
    head_kind: str = "concat",
    walk_compact: bool = False,
) -> jnp.ndarray:
    """Drop-in for `dense_eval.evaluate_selection_blocks` (bit-identical
    output), computed with the plane-resident expansion.

    With `bitrev_leaves=True` the leaf axis stays in plane order (natural
    block g at position bitrev(g)) and is NOT truncated to `num_blocks` —
    for serving paths that bit-reverse the database instead.
    """
    nk = seeds0.shape[0]
    pad_keys = (-nk) % 32
    if pad_keys:
        seeds0 = jnp.pad(seeds0, ((0, pad_keys), (0, 0)))
        control0 = jnp.pad(control0, ((0, pad_keys),))
        cw_seeds = jnp.pad(cw_seeds, ((0, 0), (0, pad_keys), (0, 0)))
        cw_left = jnp.pad(cw_left, ((0, 0), (0, pad_keys)))
        cw_right = jnp.pad(cw_right, ((0, 0), (0, pad_keys)))
        last_vc = jnp.pad(last_vc, ((0, pad_keys), (0, 0)))
    nkp = nk + pad_keys
    key_groups = nkp // 32

    # Phase 1 (limb space, [nk, 4] only): walk the all-zeros prefix.
    seeds, control = _walk_zeros(
        seeds0, control0, cw_seeds[:walk_levels], cw_left[:walk_levels]
    )

    # Enter plane space once.
    state = limbs_to_planes(seeds)  # [16, 8, key_groups]
    ctrl = pack_key_bits(control.astype(U32))  # [key_groups]

    a_levels = expand_levels - tail_levels
    # Leaf order bookkeeping (static numpy): each phase appends its own
    # node order; the exit gather is argsort of the composition.
    leaf_order = np.zeros(1, dtype=np.int64)
    start = 0
    if head_levels:
        # Fused head: the first levels in ONE launch over the (narrow)
        # full width. The concat head is bit-identical to the per-level
        # sequence (doubling order); the walk head exits in natural
        # order, which the exit gather absorbs.
        hs = walk_levels
        cwp_head = jnp.stack(
            [pack_key_planes(cw_seeds[hs + j])
             for j in range(head_levels)]
        )
        cwl_head = jnp.stack(
            [pack_key_bits(cw_left[hs + j])
             for j in range(head_levels)]
        )
        cwr_head = jnp.stack(
            [pack_key_bits(cw_right[hs + j])
             for j in range(head_levels)]
        )
        if head_kind == "walk":
            (state, ctrl), leaf_order = _walk_phase(
                state, ctrl, cwp_head, cwl_head, cwr_head, None,
                r=head_levels, node_lanes=key_groups,
                leaf_order=leaf_order, compact=walk_compact,
            )
        else:
            state, ctrl = expand_head_planes_pallas(
                state, ctrl, cwp_head, cwl_head, cwr_head
            )
            leaf_order = tail_node_permutation(
                leaf_order, head_levels, leaf_order.size
            )[0]
        start = head_levels
    for i in range(start, a_levels):
        lvl = walk_levels + i
        if level_kernel:
            state, ctrl = expand_level_planes_pallas(
                state,
                ctrl,
                pack_key_planes(cw_seeds[lvl]),
                pack_key_bits(cw_left[lvl]),
                pack_key_bits(cw_right[lvl]),
            )
            continue
        groups2 = 2 * state.shape[-1]
        state, ctrl = expand_level_planes(
            state,
            ctrl,
            _tile_keys(pack_key_planes(cw_seeds[lvl]), groups2),
            _tile_keys(pack_key_bits(cw_left[lvl]), groups2 // 2),
            _tile_keys(pack_key_bits(cw_right[lvl]), groups2 // 2),
        )

    # The per-level phase appends [all-left; all-right] once per level.
    if a_levels > start:
        leaf_order = tail_node_permutation(
            leaf_order, a_levels - start, leaf_order.size
        )[0]

    # Leaf value blocks: output PRG + XOR value correction (party
    # negation is the identity for XOR shares).
    tile_nodes = tail_tile_nodes
    if tail_levels:
        # Fused tail: the last `tail_levels` levels AND the value hash,
        # one kernel launch per independent subtree tile.
        base = walk_levels + a_levels
        cwp_tail = jnp.stack(
            [pack_key_planes(cw_seeds[base + j])
             for j in range(tail_levels)]
        )
        cwl_tail = jnp.stack(
            [pack_key_bits(cw_left[base + j]) for j in range(tail_levels)]
        )
        cwr_tail = jnp.stack(
            [pack_key_bits(cw_right[base + j])
             for j in range(tail_levels)]
        )
        if tail_kind == "walk":
            (values, _), leaf_order = _walk_phase(
                state, ctrl, cwp_tail, cwl_tail, cwr_tail,
                pack_key_planes(last_vc),
                r=tail_levels, node_lanes=key_groups,
                leaf_order=leaf_order, compact=walk_compact,
                value_hash=True,
            )
        else:
            values, _ = expand_tail_planes_pallas(
                state,
                ctrl,
                cwp_tail,
                cwl_tail,
                cwr_tail,
                pack_key_planes(last_vc),
                tile_lanes=tile_nodes * key_groups,
            )
            leaf_order = tail_node_permutation(
                leaf_order, tail_levels, tile_nodes
            )[0]
    elif level_kernel:
        values = value_hash_planes_pallas(
            state, ctrl, pack_key_planes(last_vc)
        )
    else:
        values = mmo_hash_planes(fixed_keys.RK_VALUE, state)
        vc_p = _tile_keys(pack_key_planes(last_vc), values.shape[-1])
        values = values ^ (vc_p & ctrl[None, None, :])

    # Leave plane space once: [w * nkp, 4] node-major -> [nkp, w, 4].
    w = 1 << expand_levels
    out = planes_to_limbs(values).reshape(w, nkp, 4)
    out = jnp.moveaxis(out, 0, 1)
    if not bitrev_leaves:
        # The exit gather is argsort of the composed per-phase leaf
        # order (doubling phases append [all-left; all-right]; walk
        # phases emit natural offsets) — for pure doubling this equals
        # the classic bit-reversal permutation.
        perm = jnp.asarray(np.argsort(leaf_order))
        out = out[:, perm, :][:, :num_blocks, :]
        if out.shape[1] < num_blocks:
            # Blocks beyond the tree's capacity (mesh-padded databases)
            # can only select guaranteed-zero rows.
            out = jnp.pad(
                out, ((0, 0), (0, num_blocks - out.shape[1]), (0, 0))
            )
    return out[:nk]
