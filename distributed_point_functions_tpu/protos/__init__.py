"""Generated proto modules (wire-compatible with the reference schemas).

Regenerate with `protos/generate.sh`. The generated modules import each
other by flat module name, so this package directory is put on `sys.path`
before loading them.
"""

import os as _os
import sys as _sys

_here = _os.path.dirname(_os.path.abspath(__file__))
if _here not in _sys.path:
    _sys.path.insert(0, _here)

import distributed_point_function_pb2 as dpf_pb2  # noqa: E402
import hash_family_config_pb2 as hash_family_config_pb2  # noqa: E402
import distributed_comparison_function_pb2 as dcf_pb2  # noqa: E402
import multiple_interval_containment_pb2 as mic_pb2  # noqa: E402
import private_information_retrieval_pb2 as pir_pb2  # noqa: E402

__all__ = [
    "dpf_pb2",
    "hash_family_config_pb2",
    "dcf_pb2",
    "mic_pb2",
    "pir_pb2",
]
