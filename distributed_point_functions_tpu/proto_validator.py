"""Validation of wire protos (`dpf/internal/proto_validator.{h,cc}`).

Validates `DpfParameters`, `ValueType`, `Value`, `DpfKey`, and
`EvaluationContext` protos before they touch the evaluation engine,
mirroring the reference's rules (`proto_validator.cc:160-333`):

* parameters: non-empty, `log_domain_size` in [0, 128] strictly ascending,
  value type present/valid, `security_parameter` in [0, 128] and not NaN;
* keys: seed + last-level value correction present, exactly
  `tree_levels_needed - 1` correction words, a value correction at every
  intermediate output level;
* contexts: parameters match, key valid, not already fully evaluated,
  `partial_evaluations_level <= previous_hierarchy_level`.
"""

from __future__ import annotations

import math
from typing import Sequence

from .dpf import DistributedPointFunction
from .serialization import parameters_from_proto, value_type_from_proto

_ALLOWED_BITSIZES = (8, 16, 32, 64, 128)


class ProtoValidator:
    """Validator bound to one parameter vector."""

    def __init__(self, parameters_protos: Sequence):
        self.validate_parameters(parameters_protos)
        # Reuse the framework's level mapping by constructing the DPF.
        self.dpf = DistributedPointFunction.create_incremental(
            [parameters_from_proto(p) for p in parameters_protos]
        )
        self.parameters = list(parameters_protos)

    @classmethod
    def create(cls, parameters_protos: Sequence) -> "ProtoValidator":
        return cls(parameters_protos)

    # -- static message validation ------------------------------------------

    @staticmethod
    def _validate_integer_type(integer) -> None:
        """Mirrors `ValidateIntegerType` (`proto_validator.cc:74-87`) with
        the framework's supported-width restriction on top."""
        bitsize = integer.bitsize
        if bitsize < 1:
            raise ValueError("bitsize must be positive")
        if bitsize > 128:
            raise ValueError("bitsize must be less than or equal to 128")
        if bitsize & (bitsize - 1):
            raise ValueError("bitsize must be a power of 2")
        if bitsize not in _ALLOWED_BITSIZES:
            raise ValueError(
                f"integer bitsize must be one of {_ALLOWED_BITSIZES}"
            )

    @staticmethod
    def _integer_value_as_int(value_integer) -> int:
        kind = value_integer.WhichOneof("value")
        if kind == "value_uint64":
            return value_integer.value_uint64
        if kind == "value_uint128":
            b = value_integer.value_uint128
            return (b.high << 64) | b.low
        raise ValueError("Unknown value case for Value.Integer")

    @staticmethod
    def _validate_integer_value(value_integer, integer_type) -> None:
        """Mirrors `ValidateIntegerValue` (`proto_validator.cc:89-100`)."""
        v = ProtoValidator._integer_value_as_int(value_integer)
        if integer_type.bitsize < 128 and v >= 1 << integer_type.bitsize:
            raise ValueError(
                f"Value (= {v}) too large for ValueType with bitsize = "
                f"{integer_type.bitsize}"
            )

    @staticmethod
    def validate_value_type(value_type) -> None:
        kind = value_type.WhichOneof("type")
        if kind == "integer":
            ProtoValidator._validate_integer_type(value_type.integer)
        elif kind == "xor_wrapper":
            ProtoValidator._validate_integer_type(value_type.xor_wrapper)
        elif kind == "int_mod_n":
            ProtoValidator._validate_integer_type(
                value_type.int_mod_n.base_integer
            )
            ProtoValidator._validate_integer_value(
                value_type.int_mod_n.modulus,
                value_type.int_mod_n.base_integer,
            )
            value_type_from_proto(value_type)  # range-checks the modulus
        elif kind == "tuple":
            for e in value_type.tuple.elements:
                ProtoValidator.validate_value_type(e)
        else:
            raise ValueError("ValueType must have its type set")

    @staticmethod
    def validate_value(value, value_type) -> None:
        """Value-vs-type check (`proto_validator.cc:289-333`): the value's
        oneof case must match the type, integers must fit the bitsize,
        tuples must match element-wise, IntModN values must be reduced."""
        kind = value_type.WhichOneof("type")
        if kind == "integer":
            if value.WhichOneof("value") != "integer":
                raise ValueError("Expected integer value")
            ProtoValidator._validate_integer_value(
                value.integer, value_type.integer
            )
        elif kind == "tuple":
            if value.WhichOneof("value") != "tuple":
                raise ValueError("Expected tuple value")
            want = len(value_type.tuple.elements)
            got = len(value.tuple.elements)
            if got != want:
                raise ValueError(
                    f"Expected tuple value of size {want} but got size {got}"
                )
            for v, t in zip(value.tuple.elements, value_type.tuple.elements):
                ProtoValidator.validate_value(v, t)
        elif kind == "int_mod_n":
            if value.WhichOneof("value") != "int_mod_n":
                raise ValueError("Expected IntModN value")
            ProtoValidator._validate_integer_value(
                value.int_mod_n, value_type.int_mod_n.base_integer
            )
            v = ProtoValidator._integer_value_as_int(value.int_mod_n)
            m = ProtoValidator._integer_value_as_int(
                value_type.int_mod_n.modulus
            )
            if v >= m:
                raise ValueError(
                    f"Value (= {v}) is too large for modulus (= {m})"
                )
        elif kind == "xor_wrapper":
            if value.WhichOneof("value") != "xor_wrapper":
                raise ValueError("Expected XorWrapper value")
            ProtoValidator._validate_integer_value(
                value.xor_wrapper, value_type.xor_wrapper
            )
        else:
            raise ValueError(
                f"ValidateValue: Unsupported ValueType: {value_type}"
            )

    @staticmethod
    def validate_parameters(parameters: Sequence) -> None:
        if not parameters:
            raise ValueError("parameters must not be empty")
        previous_lds = 0
        for i, p in enumerate(parameters):
            lds = p.log_domain_size
            if lds < 0:
                raise ValueError("log_domain_size must be non-negative")
            if lds > 128:
                raise ValueError("log_domain_size must be <= 128")
            if i > 0 and lds <= previous_lds:
                raise ValueError(
                    "log_domain_size fields must be in ascending order"
                )
            previous_lds = lds
            if not p.HasField("value_type"):
                raise ValueError("value_type is required")
            ProtoValidator.validate_value_type(p.value_type)
            sec = p.security_parameter
            if math.isnan(sec):
                raise ValueError("security_parameter must not be NaN")
            if sec < 0 or sec > 128:
                raise ValueError("security_parameter must be in [0, 128]")

    # -- bound validation ---------------------------------------------------

    def validate_dpf_key(self, key) -> None:
        if not key.HasField("seed"):
            raise ValueError("key.seed must be present")
        if len(key.last_level_value_correction) == 0:
            raise ValueError("key.last_level_value_correction must be present")
        expected = self.dpf._tree_levels_needed - 1
        if len(key.correction_words) != expected:
            raise ValueError(
                f"malformed DpfKey: expected {expected} correction words, "
                f"but got {len(key.correction_words)}"
            )
        for i, tree_level in enumerate(self.dpf._hierarchy_to_tree):
            if tree_level == self.dpf._tree_levels_needed - 1:
                continue  # stored in last_level_value_correction
            if len(key.correction_words[tree_level].value_correction) == 0:
                raise ValueError(
                    f"malformed DpfKey: expected correction_words"
                    f"[{tree_level}] to contain the value correction of "
                    f"hierarchy level {i}"
                )

    def validate_evaluation_context(self, ctx) -> None:
        if len(ctx.parameters) != len(self.parameters):
            raise ValueError("number of parameters in ctx doesn't match")
        for i, (a, b) in enumerate(zip(self.parameters, ctx.parameters)):
            pa = parameters_from_proto(a)
            pb = parameters_from_proto(b)
            # Default the security parameter like the reference does before
            # comparing (`proto_validator.cc:117-125`).
            sa = pa.security_parameter or (40 + pa.log_domain_size)
            sb = pb.security_parameter or (40 + pb.log_domain_size)
            if (
                pa.log_domain_size != pb.log_domain_size
                or pa.value_type != pb.value_type
                or abs(sa - sb) > 1e-9
            ):
                raise ValueError(f"parameter {i} in ctx doesn't match")
        if not ctx.HasField("key"):
            raise ValueError("ctx.key must be present")
        self.validate_dpf_key(ctx.key)
        if ctx.previous_hierarchy_level >= len(ctx.parameters) - 1:
            raise ValueError("this context has already been fully evaluated")
        if (
            len(ctx.partial_evaluations) > 0
            and ctx.partial_evaluations_level > ctx.previous_hierarchy_level
        ):
            raise ValueError(
                "ctx.partial_evaluations_level must be less than or equal "
                "to ctx.previous_hierarchy_level"
            )
