"""Hashing sublibrary (reference: `pir/hashing/`)."""

from .hash_family import HashFamily, create_hash_functions, wrap_with_seed
from .farm_hash_family import FarmHashFunction, farm_hash_family
from .sha256_hash_family import SHA256HashFamily, sha256_hash_function
from .hash_family_config import (
    HASH_FAMILY_SHA256,
    HASH_FAMILY_UNSPECIFIED,
    HashFamilyConfig,
    create_hash_family_from_config,
)
from .cuckoo_hash_table import CuckooHashTable
from .multiple_choice_hash_table import MultipleChoiceHashTable
from .simple_hash_table import SimpleHashTable

__all__ = [
    "HashFamily",
    "create_hash_functions",
    "wrap_with_seed",
    "FarmHashFunction",
    "farm_hash_family",
    "SHA256HashFamily",
    "sha256_hash_function",
    "HashFamilyConfig",
    "HASH_FAMILY_SHA256",
    "HASH_FAMILY_UNSPECIFIED",
    "create_hash_family_from_config",
    "CuckooHashTable",
    "MultipleChoiceHashTable",
    "SimpleHashTable",
]
