"""Simple hashing: every element stored under all hash functions
(`pir/hashing/simple_hash_table.{h,cc}`). Inserts are all-or-nothing when a
bucket bound is set (`simple_hash_table.cc:55-70`)."""

from __future__ import annotations

from typing import List, Optional, Sequence

from .hash_family import HashFunction


class SimpleHashTable:
    def __init__(
        self,
        hash_functions: Sequence[HashFunction],
        num_buckets: int,
        max_bucket_size: Optional[int] = None,
    ):
        if num_buckets <= 0:
            raise ValueError("num_buckets must be positive")
        if not hash_functions:
            raise ValueError("hash_functions must not be empty")
        if max_bucket_size is not None and max_bucket_size <= 0:
            raise ValueError("max_bucket_size must be positive")
        self.num_buckets = num_buckets
        self.max_bucket_size = max_bucket_size
        self.hash_functions = list(hash_functions)
        self.table: List[List[bytes]] = [[] for _ in range(num_buckets)]

    def insert(self, element: bytes) -> None:
        element = element.encode() if isinstance(element, str) else bytes(element)
        buckets = [
            fn(element, self.num_buckets) for fn in self.hash_functions
        ]
        if self.max_bucket_size is not None:
            for b in buckets:
                if len(self.table[b]) >= self.max_bucket_size:
                    raise RuntimeError(
                        "cannot insert element: maximum bucket size reached"
                    )
        for b in buckets:
            self.table[b].append(element)

    def get_table(self) -> List[List[bytes]]:
        return self.table
