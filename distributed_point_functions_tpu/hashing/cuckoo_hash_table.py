"""Cuckoo hash table with random eviction (`pir/hashing/cuckoo_hash_table.{h,cc}`).

Insertion picks a random hash function; if the bucket is occupied the
resident element is evicted and re-inserted, up to `max_relocations` times,
after which the element goes to the (optionally bounded) stash
(`cuckoo_hash_table.cc:66-91`).
"""

from __future__ import annotations

import random
from typing import List, Optional, Sequence

from .hash_family import HashFunction


class CuckooHashTable:
    def __init__(
        self,
        hash_functions: Sequence[HashFunction],
        num_buckets: int,
        max_relocations: int,
        max_stash_size: Optional[int] = None,
        rng_seed: int = 5489,  # mt19937's fixed default seed: two builds
        # with the same inputs produce the same layout, like the reference.
    ):
        if num_buckets <= 0:
            raise ValueError("num_buckets must be positive")
        if len(hash_functions) < 2:
            raise ValueError("hash_functions must have at least 2 entries")
        if max_relocations < 0:
            raise ValueError("max_relocations must be non-negative")
        if max_stash_size is not None and max_stash_size < 0:
            raise ValueError("max_stash_size must be non-negative")
        self.num_buckets = num_buckets
        self.max_relocations = max_relocations
        self.max_stash_size = max_stash_size
        self.hash_functions = list(hash_functions)
        # Each occupied slot holds (element, its hash buckets) so
        # relocations never rehash; `get_table()` exposes elements only.
        self.table: List[Optional[tuple]] = [None] * num_buckets
        self.stash: List[bytes] = []
        self._rng = random.Random(rng_seed)

    @classmethod
    def create(cls, hash_functions, num_buckets, max_relocations,
               max_stash_size=None):
        return cls(hash_functions, num_buckets, max_relocations,
                   max_stash_size)

    def insert(self, element: bytes, buckets=None) -> None:
        """Insert `element`; `buckets` optionally pre-supplies its hash
        values (one per hash function) so bulk builders can hash in a
        tight loop up front.

        Each element's buckets are computed once and carried through
        evictions — the relocation loop would otherwise recompute a
        hash per hop (SHA256 per relocation adds minutes at the 2^24-key
        benchmark scale).
        """
        current = element.encode() if isinstance(element, str) else bytes(element)
        for _ in range(self.max_relocations):
            if buckets is None:
                # Lazily hashed: a preseeded slot (see `preseed`) stores
                # no buckets, so an evicted preseeded element rehashes
                # here on its first hop only.
                buckets = tuple(
                    fn(current, self.num_buckets)
                    for fn in self.hash_functions
                )
            h = self._rng.randrange(len(self.hash_functions))
            bucket = buckets[h]
            if self.table[bucket] is not None:
                (current, buckets), self.table[bucket] = (
                    self.table[bucket],
                    (current, buckets),
                )
            else:
                self.table[bucket] = (current, buckets)
                return
        if (
            self.max_stash_size is not None
            and len(self.stash) >= self.max_stash_size
        ):
            raise RuntimeError("cannot insert element: stash is full")
        self.stash.append(current)

    def preseed(self, bucket: int, element: bytes) -> None:
        """Pin `element` into `bucket` without hashing — used by delta
        builds to reproduce a prior build's slot assignment before
        inserting only the new keys. The slot stores no bucket tuple;
        if a later insert evicts a preseeded element, `insert` rehashes
        it lazily on its first relocation hop.
        """
        if not (0 <= bucket < self.num_buckets):
            raise ValueError(f"bucket {bucket} out of range")
        if self.table[bucket] is not None:
            raise ValueError(f"bucket {bucket} already occupied")
        current = element.encode() if isinstance(element, str) else bytes(element)
        self.table[bucket] = (current, None)

    def get_table(self) -> List[Optional[bytes]]:
        return [
            slot[0] if slot is not None else None for slot in self.table
        ]

    def get_stash(self) -> List[bytes]:
        return self.stash
