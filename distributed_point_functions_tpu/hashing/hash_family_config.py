"""Hash family configuration + factory
(`pir/hashing/hash_family_config.{proto,h,cc}`).

Like the reference, SHA256 is the only wired-up family
(`hash_family_config.cc:36-44`).
"""

from __future__ import annotations

import dataclasses

from .hash_family import HashFamily, wrap_with_seed
from .sha256_hash_family import SHA256HashFamily

HASH_FAMILY_UNSPECIFIED = 0
HASH_FAMILY_SHA256 = 1

HASH_FUNCTION_SEED_LENGTH_BYTES = 16


@dataclasses.dataclass(frozen=True)
class HashFamilyConfig:
    hash_family: int = HASH_FAMILY_UNSPECIFIED
    seed: bytes = b""


def create_hash_family_from_config(config: HashFamilyConfig) -> HashFamily:
    if not config.seed:
        raise ValueError("seed must not be empty")
    if config.hash_family == HASH_FAMILY_SHA256:
        family = SHA256HashFamily()
    elif config.hash_family == HASH_FAMILY_UNSPECIFIED:
        raise ValueError("hash family unspecified")
    else:
        raise ValueError("unknown hash family specified")
    return wrap_with_seed(family, config.seed)
