"""d-choice hashing: insert into the least-occupied candidate bucket
(`pir/hashing/multiple_choice_hash_table.{h,cc}`)."""

from __future__ import annotations

from typing import List, Optional, Sequence

from .hash_family import HashFunction


class MultipleChoiceHashTable:
    def __init__(
        self,
        hash_functions: Sequence[HashFunction],
        num_buckets: int,
        max_bucket_size: Optional[int] = None,
    ):
        if num_buckets <= 0:
            raise ValueError("num_buckets must be positive")
        if len(hash_functions) < 2:
            raise ValueError("hash_functions must have at least 2 entries")
        if max_bucket_size is not None and max_bucket_size <= 0:
            raise ValueError("max_bucket_size must be positive")
        self.num_buckets = num_buckets
        self.max_bucket_size = max_bucket_size
        self.hash_functions = list(hash_functions)
        self.table: List[List[bytes]] = [[] for _ in range(num_buckets)]

    def insert(self, element: bytes) -> None:
        element = element.encode() if isinstance(element, str) else bytes(element)
        smallest = None
        for fn in self.hash_functions:
            bucket = fn(element, self.num_buckets)
            if smallest is None or len(self.table[bucket]) < len(
                self.table[smallest]
            ):
                smallest = bucket
        if (
            self.max_bucket_size is not None
            and len(self.table[smallest]) >= self.max_bucket_size
        ):
            raise RuntimeError(
                "cannot insert element: maximum bucket size reached"
            )
        self.table[smallest].append(element)

    def get_table(self) -> List[List[bytes]]:
        return self.table
