"""FarmHash-based hash family.

Mirrors the reference's `pir/hashing/farm_hash_family.h:28-44`: a
`HashFunction` whose seed is `util::Hash128(seed_string)` and whose value is
`util::Hash128WithSeed(input, seed) mod upper_bound` (taking the 128-bit
hash as `MakeUint128(hash.second, hash.first)`,
`farm_hash_family.cc:25-30`).

farmhash's `Hash128` / `Hash128WithSeed` are the farmhashcc variants, i.e.
the CityHash128 algorithm (cityhash v1.1); this module implements that
algorithm in pure Python over 64-bit masked integers. Hashing here is
host-side table-construction work (cuckoo hashing), not a TPU hot path.
"""

from __future__ import annotations

from typing import Tuple

M64 = (1 << 64) - 1

K0 = 0xC3A5C85C97CB3127
K1 = 0xB492B66FBE98F273
K2 = 0x9AE16A3B2F90404F
K_MUL = 0x9DDFEA08EB382D69


def _fetch64(s: bytes, i: int = 0) -> int:
    return int.from_bytes(s[i : i + 8], "little")


def _fetch32(s: bytes, i: int = 0) -> int:
    return int.from_bytes(s[i : i + 4], "little")


def _rotate(val: int, shift: int) -> int:
    if shift == 0:
        return val
    return ((val >> shift) | (val << (64 - shift))) & M64


def _shift_mix(val: int) -> int:
    return (val ^ (val >> 47)) & M64


def _hash_len_16_mul(u: int, v: int, mul: int) -> int:
    a = ((u ^ v) * mul) & M64
    a ^= a >> 47
    b = ((v ^ a) * mul) & M64
    b ^= b >> 47
    return (b * mul) & M64


def _hash_len_16(u: int, v: int) -> int:
    return _hash_len_16_mul(u, v, K_MUL)


def _hash_len_0_to_16(s: bytes) -> int:
    n = len(s)
    if n >= 8:
        mul = (K2 + n * 2) & M64
        a = (_fetch64(s) + K2) & M64
        b = _fetch64(s, n - 8)
        c = (_rotate(b, 37) * mul + a) & M64
        d = ((_rotate(a, 25) + b) * mul) & M64
        return _hash_len_16_mul(c, d, mul)
    if n >= 4:
        mul = (K2 + n * 2) & M64
        a = _fetch32(s)
        return _hash_len_16_mul(
            (n + (a << 3)) & M64, _fetch32(s, n - 4), mul
        )
    if n > 0:
        a, b, c = s[0], s[n >> 1], s[n - 1]
        y = (a + (b << 8)) & 0xFFFFFFFF
        z = (n + (c << 2)) & 0xFFFFFFFF
        return (_shift_mix((y * K2 ^ z * K0) & M64) * K2) & M64
    return K2


def _weak_hash_len_32_with_seeds(
    w: int, x: int, y: int, z: int, a: int, b: int
) -> Tuple[int, int]:
    a = (a + w) & M64
    b = _rotate((b + a + z) & M64, 21)
    c = a
    a = (a + x + y) & M64
    b = (b + _rotate(a, 44)) & M64
    return (a + z) & M64, (b + c) & M64


def _weak_hash_32_seeds_bytes(s: bytes, i: int, a: int, b: int):
    return _weak_hash_len_32_with_seeds(
        _fetch64(s, i),
        _fetch64(s, i + 8),
        _fetch64(s, i + 16),
        _fetch64(s, i + 24),
        a,
        b,
    )


def _city_murmur(s: bytes, seed: Tuple[int, int]) -> Tuple[int, int]:
    """(low, high) seed -> (low, high) hash, for inputs under 128 bytes."""
    a, b = seed
    n = len(s)
    l = n - 16
    if l <= 0:
        a = (_shift_mix((a * K1) & M64) * K1) & M64
        c = (b * K1 + _hash_len_0_to_16(s)) & M64
        d = _shift_mix((a + (_fetch64(s) if n >= 8 else c)) & M64)
    else:
        c = _hash_len_16((_fetch64(s, n - 8) + K1) & M64, a)
        d = _hash_len_16((b + n) & M64, (c + _fetch64(s, n - 16)) & M64)
        a = (a + d) & M64
        i = 0
        while True:
            a ^= (_shift_mix((_fetch64(s, i) * K1) & M64) * K1) & M64
            a = (a * K1) & M64
            b ^= a
            c ^= (_shift_mix((_fetch64(s, i + 8) * K1) & M64) * K1) & M64
            c = (c * K1) & M64
            d ^= c
            i += 16
            l -= 16
            if l <= 0:
                break
    a = _hash_len_16(a, c)
    b = _hash_len_16(d, b)
    return (a ^ b) & M64, _hash_len_16(b, a)


def hash128_with_seed(s: bytes, seed: Tuple[int, int]) -> Tuple[int, int]:
    """CityHash128WithSeed (farmhashcc `Hash128WithSeed`): (low, high)."""
    n = len(s)
    if n < 128:
        return _city_murmur(s, seed)
    x, y = seed
    z = (n * K1) & M64
    v0 = (_rotate(y ^ K1, 49) * K1 + _fetch64(s)) & M64
    v1 = (_rotate(v0, 42) * K1 + _fetch64(s, 8)) & M64
    w0 = (_rotate((y + z) & M64, 35) * K1 + x) & M64
    w1 = (_rotate((x + _fetch64(s, 88)) & M64, 53) * K1) & M64
    i = 0
    while True:
        for _ in range(2):
            x = (_rotate((x + y + v0 + _fetch64(s, i + 8)) & M64, 37) * K1) & M64
            y = (_rotate((y + v1 + _fetch64(s, i + 48)) & M64, 42) * K1) & M64
            x ^= w1
            y = (y + v0 + _fetch64(s, i + 40)) & M64
            z = (_rotate((z + w0) & M64, 33) * K1) & M64
            v0, v1 = _weak_hash_32_seeds_bytes(
                s, i, (v1 * K1) & M64, (x + w0) & M64
            )
            w0, w1 = _weak_hash_32_seeds_bytes(
                s, i + 32, (z + w1) & M64, (y + _fetch64(s, i + 16)) & M64
            )
            z, x = x, z
            i += 64
        n -= 128
        if n < 128:
            break
    x = (x + _rotate((v0 + z) & M64, 49) * K0) & M64
    y = (y * K0 + _rotate(w1, 37)) & M64
    z = (z * K0 + _rotate(w0, 27)) & M64
    w0 = (w0 * 9) & M64
    v0 = (v0 * K0) & M64
    tail_done = 0
    while tail_done < n:
        tail_done += 32
        y = (_rotate((x + y) & M64, 42) * K0 + v1) & M64
        w0 = (w0 + _fetch64(s, i + n - tail_done + 16)) & M64
        x = (x * K0 + w0) & M64
        z = (z + w1 + _fetch64(s, i + n - tail_done)) & M64
        w1 = (w1 + v0) & M64
        v0, v1 = _weak_hash_32_seeds_bytes(
            s, i + n - tail_done, (v0 + z) & M64, v1
        )
        v0 = (v0 * K0) & M64
    x = _hash_len_16(x, v0)
    y = _hash_len_16((y + z) & M64, w0)
    return (
        (_hash_len_16((x + v1) & M64, w1) + y) & M64,
        _hash_len_16((x + w1) & M64, (y + v1) & M64),
    )


def hash128(s: bytes) -> Tuple[int, int]:
    """CityHash128 (farmhashcc `Hash128`): (low, high)."""
    if len(s) >= 16:
        return hash128_with_seed(
            s[16:], (_fetch64(s), (_fetch64(s, 8) + K0) & M64)
        )
    return hash128_with_seed(s, (K0, K1))


class FarmHashFunction:
    """Seeded farmhash -> [0, upper_bound) (`farm_hash_family.h:28-37`)."""

    def __init__(self, seed: str | bytes):
        if isinstance(seed, str):
            seed = seed.encode()
        self._seed = hash128(seed)

    def __call__(self, value: str | bytes, upper_bound: int) -> int:
        if upper_bound <= 0:
            raise ValueError("upper_bound must be positive")
        if isinstance(value, str):
            value = value.encode()
        low, high = hash128_with_seed(value, self._seed)
        # `absl::MakeUint128(hash.second, hash.first)` — high word is the
        # second element (`farm_hash_family.cc:27-29`).
        return ((high << 64) | low) % upper_bound


def farm_hash_family(seed: str | bytes) -> FarmHashFunction:
    """`HashFamily`: seed -> seeded `FarmHashFunction`."""
    return FarmHashFunction(seed)
