"""SHA256-based hash family (`pir/hashing/sha256_hash_family.{h,cc}`).

Hashes `SHA256(seed || input)` and reduces the 256-bit digest modulo the
upper bound. The digest is interpreted exactly like the reference's
memcpy-into-uint128 long division (`sha256_hash_family.cc:69-88`): the low
16 digest bytes are the little-endian low 128 bits and the high 16 bytes
the little-endian high 128 bits of a 256-bit integer.
"""

from __future__ import annotations

import hashlib

from .hash_family import HashFunction, _as_bytes


def sha256_hash_function(seed) -> HashFunction:
    seed = _as_bytes(seed)
    base = hashlib.sha256(seed)

    def fn(data, upper_bound: int) -> int:
        if upper_bound <= 0:
            raise ValueError("upper_bound must be positive")
        ctx = base.copy()
        ctx.update(_as_bytes(data))
        digest = ctx.digest()
        lo = int.from_bytes(digest[:16], "little")
        hi = int.from_bytes(digest[16:], "little")
        return ((hi << 128) | lo) % upper_bound

    return fn


def SHA256HashFamily():
    return sha256_hash_function
