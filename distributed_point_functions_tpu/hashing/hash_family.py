"""Hash function / family abstractions (`pir/hashing/hash_family.h:37-53`).

A *hash function* maps `(data: bytes, upper_bound: int) -> int` in
`[0, upper_bound)`. A *hash family* maps a seed to a hash function.
`create_hash_functions` derives `n` functions from a family by seeding with
the decimal strings "0".."n-1" (`hash_family.cc:27-40`); `wrap_with_seed`
prepends a fixed family seed to every derivation seed
(`hash_family.h:42-53`).
"""

from __future__ import annotations

from typing import Callable, List

HashFunction = Callable[[bytes, int], int]
HashFamily = Callable[[bytes], HashFunction]


def _as_bytes(s) -> bytes:
    return s.encode() if isinstance(s, str) else bytes(s)


def wrap_with_seed(family: HashFamily, family_seed) -> HashFamily:
    family_seed = _as_bytes(family_seed)

    def wrapped(seed) -> HashFunction:
        return family(family_seed + _as_bytes(seed))

    return wrapped


def create_hash_functions(
    family: HashFamily, num_hash_functions: int
) -> List[HashFunction]:
    if num_hash_functions < 0:
        raise ValueError("num_hash_functions must not be negative")
    return [family(str(i).encode()) for i in range(num_hash_functions)]
