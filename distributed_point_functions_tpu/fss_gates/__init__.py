"""FSS gates built on DCF (reference: `dcf/fss_gates/`)."""

from .multiple_interval_containment import (
    Interval,
    MicKey,
    MicParameters,
    MultipleIntervalContainmentGate,
)

__all__ = [
    "Interval",
    "MicKey",
    "MicParameters",
    "MultipleIntervalContainmentGate",
]
