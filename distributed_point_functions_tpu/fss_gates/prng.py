"""Secure PRNG interface for FSS gate key generation
(`dcf/fss_gates/prng/prng.h:24-45`, `basic_rng.h:36-74`).

`SecurePrng` is the abstract sampling interface (8/64/128-bit draws);
`BasicRng` draws from the OS CSPRNG, the role OpenSSL `RAND_bytes` plays in
the reference. Gate key generation takes any `SecurePrng`, so tests can
inject a deterministic one.
"""

from __future__ import annotations

import secrets


class SecurePrng:
    """Abstract secure PRNG."""

    def rand8(self) -> int:
        raise NotImplementedError

    def rand64(self) -> int:
        raise NotImplementedError

    def rand128(self) -> int:
        raise NotImplementedError


class BasicRng(SecurePrng):
    """OS-CSPRNG-backed PRNG (the reference's `BasicRng`)."""

    def __init__(self, seed: bytes = b""):
        # The reference's BasicRng ignores its seed parameter and always
        # draws fresh OS randomness (`basic_rng.h:47-52`); kept for API
        # compatibility.
        del seed

    def rand8(self) -> int:
        return secrets.randbits(8)

    def rand64(self) -> int:
        return secrets.randbits(64)

    def rand128(self) -> int:
        return secrets.randbits(128)


class CounterPrng(SecurePrng):
    """Deterministic PRNG over the framework's AES-CTR stream — for tests."""

    def __init__(self, seed: bytes = b"\x00" * 16):
        from ..prng import Aes128CtrSeededPrng

        self._prng = Aes128CtrSeededPrng(seed)

    def rand8(self) -> int:
        return self._prng.get_random_bytes(1)[0]

    def rand64(self) -> int:
        return int.from_bytes(self._prng.get_random_bytes(8), "little")

    def rand128(self) -> int:
        return int.from_bytes(self._prng.get_random_bytes(16), "little")
