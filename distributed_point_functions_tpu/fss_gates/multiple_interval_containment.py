"""Multiple Interval Containment FSS gate (eprint 2020/1392, Fig. 14).

Secret shares of 1 for every public interval `[p_i, q_i]` containing the
masked input — rebuilt from the reference's
`dcf/fss_gates/multiple_interval_containment.{h,cc}`:

* `gen(r_in, r_out)` creates one DCF key at `gamma = (N-1+r_in) mod N` with
  `beta = 1`, plus per-interval additively-shared correction terms `z_i`
  that account for potential wrap-arounds of the masked bounds
  (`multiple_interval_containment.cc:110-209`, Lemmas 1-2 / Theorem 3 of
  the paper).
* `batch_eval(keys, x)` runs two DCF evaluations per (key, interval) at the
  shifted points `x + N - 1 - p` and `x + N - 1 - q'` and combines them with
  the mask shares (`multiple_interval_containment.cc:211-308`).

The group is Z_N with N = 2^log_group_size, so reductions are bit masks.
The DCF value type is a 128-bit integer, exactly like the reference.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax import tree_util

from ..dcf import DcfKey, DistributedComparisonFunction
from ..value_types import IntType


@dataclasses.dataclass(frozen=True)
class Interval:
    lower_bound: int
    upper_bound: int


@dataclasses.dataclass(frozen=True)
class MicParameters:
    log_group_size: int
    intervals: Tuple[Interval, ...]

    def __init__(self, log_group_size: int, intervals: Sequence[Interval]):
        object.__setattr__(self, "log_group_size", log_group_size)
        object.__setattr__(self, "intervals", tuple(intervals))


@dataclasses.dataclass
class MicKey:
    dcf_key: DcfKey
    output_mask_share: List[int]


class MultipleIntervalContainmentGate:
    """See module docstring; mirrors `MultipleIntervalContainmentGate`."""

    def __init__(self, parameters: MicParameters):
        if parameters.log_group_size < 1 or parameters.log_group_size > 127:
            raise ValueError("log_group_size must be in [1, 127]")
        if not parameters.intervals:
            raise ValueError("at least one interval is required")
        n = 1 << parameters.log_group_size
        for iv in parameters.intervals:
            if not (0 <= iv.lower_bound < n) or not (0 <= iv.upper_bound < n):
                raise ValueError(
                    "interval bounds should be between 0 and 2^log_group_size"
                )
            if iv.lower_bound > iv.upper_bound:
                raise ValueError(
                    "interval upper bounds should be >= lower bound"
                )
        self.parameters = parameters
        self._n = n
        self.dcf = DistributedComparisonFunction.create(
            parameters.log_group_size, IntType(128)
        )

    @classmethod
    def create(cls, parameters: MicParameters):
        return cls(parameters)

    def gen(self, r_in: int, r_out: Sequence[int],
            prng=None) -> Tuple[MicKey, MicKey]:
        """Generate the two parties' MIC keys for input mask r_in and
        per-interval output masks r_out.

        `prng` is an optional `SecurePrng` (defaults to the OS CSPRNG,
        mirroring `BasicRng`, `multiple_interval_containment.cc:186-191`).
        """
        if prng is None:
            from .prng import BasicRng

            prng = BasicRng()
        if len(r_out) != len(self.parameters.intervals):
            raise ValueError(
                "count of output masks should be equal to the number of "
                "intervals"
            )
        n = self._n
        if not (0 <= r_in < n):
            raise ValueError(
                "input mask should be between 0 and 2^log_group_size"
            )
        for r in r_out:
            if not (0 <= r < n):
                raise ValueError(
                    "output mask should be between 0 and 2^log_group_size"
                )

        gamma = (n - 1 + r_in) % n
        key0, key1 = self.dcf.generate_keys(gamma, 1)
        k0 = MicKey(dcf_key=key0, output_mask_share=[])
        k1 = MicKey(dcf_key=key1, output_mask_share=[])

        for i, iv in enumerate(self.parameters.intervals):
            p, q = iv.lower_bound, iv.upper_bound
            q_prime = (q + 1) % n
            alpha_p = (p + r_in) % n
            alpha_q = (q + r_in) % n
            alpha_q_prime = (q + 1 + r_in) % n
            z = (
                r_out[i]
                + (1 if alpha_p > alpha_q else 0)
                + (-1 if alpha_p > p else 0)
                + (1 if alpha_q_prime > q_prime else 0)
                + (1 if alpha_q == n - 1 else 0)
            ) % n
            z0 = prng.rand128() % n
            z1 = (z - z0) % n
            k0.output_mask_share.append(z0)
            k1.output_mask_share.append(z1)
        return k0, k1

    def eval(self, key: MicKey, x: int) -> List[int]:
        """Single-key evaluation: one share of containment per interval."""
        return self.batch_eval([key], [x])[0]

    def batch_eval(
        self, keys: Sequence[MicKey], evaluation_points: Sequence[int]
    ) -> List[List[int]]:
        """Evaluate each key at its own masked point.

        Returns, per key, one Z_N share per interval.
        """
        if len(keys) != len(evaluation_points):
            raise ValueError(
                "keys and evaluation_points must have the same size"
            )
        n = self._n
        for x in evaluation_points:
            if not (0 <= x < n):
                raise ValueError(
                    "masked input should be between 0 and 2^log_group_size"
                )
        intervals = self.parameters.intervals
        ni = len(intervals)
        p = [iv.lower_bound for iv in intervals]
        q_prime = [(iv.upper_bound + 1) % n for iv in intervals]

        x_p: List[int] = []
        x_q_prime: List[int] = []
        for i, x in enumerate(evaluation_points):
            for j in range(ni):
                x_p.append((x + n - 1 - p[j]) % n)
                x_q_prime.append((x + n - 1 - q_prime[j]) % n)

        # Stage each DCF key once, then tile per interval on device
        # (reference duplicates keys host-side per (key, interval) pair,
        # `multiple_interval_containment.cc:260-282`); the staged batch is
        # shared by both shifted evaluations.
        base = self.dcf.stage_keys([k.dcf_key for k in keys])
        staged = dataclasses.replace(
            base,
            n=base.n * ni,
            seeds=jnp.repeat(base.seeds, ni, axis=0),
            parties=jnp.repeat(base.parties, ni, axis=0),
            cw_seeds=jnp.repeat(base.cw_seeds, ni, axis=1),
            cw_left=jnp.repeat(base.cw_left, ni, axis=1),
            cw_right=jnp.repeat(base.cw_right, ni, axis=1),
            value_corrections=[
                tree_util.tree_map(lambda a: jnp.repeat(a, ni, axis=0), vc)
                for vc in base.value_corrections
            ],
        )
        s_p = np.asarray(self.dcf.batch_evaluate(None, x_p, staged=staged))
        s_q_prime = np.asarray(
            self.dcf.batch_evaluate(None, x_q_prime, staged=staged)
        )

        def u128(limbs) -> int:
            return sum(int(limbs[k]) << (32 * k) for k in range(4))

        results: List[List[int]] = []
        for i, x in enumerate(evaluation_points):
            key = keys[i]
            party = key.dcf_key.key.party
            shares = []
            for j in range(ni):
                index = i * ni + j
                sp = u128(s_p[index]) % n
                sq = u128(s_q_prime[index]) % n
                z = key.output_mask_share[j]
                y = (
                    (
                        (1 if x > p[j] else 0) - (1 if x > q_prime[j] else 0)
                        if party
                        else 0
                    )
                    - sp
                    + sq
                    + z
                ) % n
                shares.append(y)
            results.append(shares)
        return results
