"""TPU-native distributed point functions framework.

A ground-up JAX/XLA re-design of the capabilities of Google's
`distributed_point_functions` C++ library: incremental DPFs, distributed
comparison functions, FSS gates, and two-server PIR — with the hot paths
(AES PRG tree expansion, XOR inner products) built for TPU (bitsliced AES on
the VPU, parity matmuls on the MXU, `shard_map` scale-out over ICI).
"""

from . import keys  # noqa: F401
from .ops import aes  # noqa: F401

__version__ = "0.1.0"
