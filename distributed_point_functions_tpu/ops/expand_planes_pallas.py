"""Pallas TPU kernels for the plane-resident DPF expansion levels.

The XLA version of one expansion level (`pir/dense_eval_planes.py:
expand_level_planes`) runs the ~2000-gate bitsliced AES circuit as jnp
ops on `[16, 8, G]` plane tensors; every stack/reshape/fusion break
materializes the full state to HBM, and at the headline config the
measured cost (~8 ms per 64-query batch) is ~12x the VPU gate-work
roofline (~0.7 ms) — the level is HBM-bound on intermediates, not
compute-bound. These kernels run a whole level in VMEM per lane tile:

* `expand_level_planes_pallas` — sigma, BOTH fixed-key AES applications
  (left/right children), seed correction under the parent control mask,
  LSB extract/clear, and the direction-correction of the control bits,
  one input read + two output writes of HBM traffic per level;
* `value_hash_planes_pallas` — the leaf MMO output hash + value
  correction the same way.

Round keys are baked in as `[16, 8, 1]` all-ones/zeros constant masks
per round (fixed-key AES: AddRoundKey is XOR with a constant plane).
Per-key correction planes stay packed at `[16, 8, KG]` (KG = keys/32)
and are tiled across the node-major lane axis in VMEM via
`pltpu.repeat` — the lane layout guarantees lane = node * KG + keyword,
so a whole-array tile repeats every KG lanes.

Everything is differentially tested against the XLA twins in interpret
mode (`tests/test_expand_pallas.py`) and re-verified on hardware before
serving (`pir/dense_eval_planes.py` falls back to the XLA level on any
compile failure).

Reference semantics: `ExpandSeeds`
(`dpf/distributed_point_function.cc:289-372`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import keys as fixed_keys
from . import aes as _aes
from .aes_bitslice import _mix_columns_planes, _rk_bits, _sub_bytes_planes

U32 = jnp.uint32

_SHIFT_ROWS = list(_aes._SHIFT_ROWS)

# Default lane tile: [16, 8, 1024] u32 state = 512 KB; the kernel's
# working set (sigma + two AES states + temporaries) stays well under
# VMEM at this width.
_TILE_LANES = 1024


def _rk_masks(round_keys: np.ndarray) -> np.ndarray:
    """uint8[11, 16] schedule -> uint32[11, 16, 8, 1] all-ones/zeros
    plane masks (AddRoundKey with a fixed key = XOR with constants)."""
    bits = _rk_bits(round_keys).astype(np.uint32)  # [11, 16, 8]
    return (bits * np.uint32(0xFFFFFFFF))[..., None]

_MASKS_LEFT = _rk_masks(fixed_keys.RK_LEFT)
_MASKS_RIGHT = _rk_masks(fixed_keys.RK_RIGHT)
_MASKS_VALUE = _rk_masks(fixed_keys.RK_VALUE)
_MASKS_LR = np.stack([_MASKS_LEFT, _MASKS_RIGHT])  # [2, 11, 16, 8, 1]


def _shift_rows_static(state: jnp.ndarray) -> jnp.ndarray:
    """Byte-axis permutation as static slices + one concat (avoids a
    gather, which Mosaic may not lower)."""
    return jnp.concatenate(
        [state[j : j + 1] for j in _SHIFT_ROWS], axis=0
    )


def _aes_fixed_planes(masks: jnp.ndarray, state: jnp.ndarray) -> jnp.ndarray:
    """AES-128 rounds on [16, 8, T] planes; `masks` is the [11, 16, 8, 1]
    round-key plane-mask array (a kernel input: Pallas forbids captured
    array constants)."""
    state = state ^ masks[0]
    for rnd in range(1, 10):
        state = _sub_bytes_planes(state)
        state = _shift_rows_static(state)
        state = _mix_columns_planes(state)
        state = state ^ masks[rnd]
    state = _sub_bytes_planes(state)
    state = _shift_rows_static(state)
    return state ^ masks[10]


def _sigma(state: jnp.ndarray) -> jnp.ndarray:
    lo = state[:8]
    hi = state[8:]
    return jnp.concatenate([hi, hi ^ lo], axis=0)


def _aes_select_planes(
    masks: jnp.ndarray, selb: jnp.ndarray, sig: jnp.ndarray
) -> jnp.ndarray:
    """Select-key AES rounds + MMO feed-forward on [16, 8, T] planes:
    each lane's round keys come from `masks[0]` (left) or `masks[1]`
    (right) per its `selb` mask word — the per-lane key select of
    `dpf/internal/aes_128_fixed_key_hash_hwy.h:123-155`. Shared by the
    path-walk and walk-descent kernels."""

    def ark(st, rnd):
        m0 = masks[0, rnd]
        m1 = masks[1, rnd]
        return st ^ ((m0 & ~selb) | (m1 & selb))

    st = ark(sig, 0)
    for rnd in range(1, 10):
        st = _sub_bytes_planes(st)
        st = _shift_rows_static(st)
        st = _mix_columns_planes(st)
        st = ark(st, rnd)
    st = _sub_bytes_planes(st)
    st = _shift_rows_static(st)
    return ark(st, 10) ^ sig


def _zero_lsb_plane(state: jnp.ndarray) -> jnp.ndarray:
    """state with plane [0, 0] (the seed LSB = embedded control bit)
    zeroed, built from static slices + leading-axis concatenates:
    `.at[0, 0].set(...)` lowers to a scatter, which Mosaic rejects
    ('Unimplemented primitive in Pallas TPU lowering: scatter')."""
    zero = jnp.zeros_like(state[0, 0])
    row0 = jnp.concatenate([zero[None], state[0, 1:]], axis=0)
    return jnp.concatenate([row0[None], state[1:]], axis=0)


def _level_kernel(
    state_ref,
    ctrl_ref,
    cwp_ref,
    cwl_ref,
    cwr_ref,
    masks_ref,
    outl_ref,
    outr_ref,
    ctl_ref,
    ctr_ref,
    *,
    reps: int,
):
    sig = _sigma(state_ref[:])
    masks = masks_ref[:]  # [2, 11, 16, 8, 1]: left/right round-key planes
    left = _aes_fixed_planes(masks[0], sig) ^ sig
    right = _aes_fixed_planes(masks[1], sig) ^ sig

    ctrl = ctrl_ref[:]  # [1, T] packed parent control bits
    cwp = pltpu.repeat(cwp_ref[:], reps, axis=2)  # [16, 8, T]
    mask = cwp & ctrl[0][None, None, :]
    left = left ^ mask
    right = right ^ mask

    t_left = left[0, 0]  # LSB plane = child control bits
    t_right = right[0, 0]
    outl_ref[:] = _zero_lsb_plane(left)
    outr_ref[:] = _zero_lsb_plane(right)

    cwl = pltpu.repeat(cwl_ref[:], reps, axis=1)  # [1, T]
    cwr = pltpu.repeat(cwr_ref[:], reps, axis=1)
    ctl_ref[:] = (t_left ^ (ctrl[0] & cwl[0]))[None, :]
    ctr_ref[:] = (t_right ^ (ctrl[0] & cwr[0]))[None, :]


def _check_tile(tile: int, g: int, kg: int) -> None:
    """Fail fast on an illegal forced tile: every chunk width (tile, and
    the g % tile remainder if the probe/test caller passes one that does
    not divide g) must be a positive multiple of kg, or the in-kernel
    correction repeat silently truncates and dies in an opaque mid-trace
    broadcast error."""
    widths = {min(tile, g)} if tile > 0 else {0}
    if 0 < tile < g and g % tile:
        widths.add(g % tile)
    if tile <= 0 or any(w <= 0 or w % kg for w in widths):
        raise ValueError(
            f"tile_lanes={tile} must be a positive multiple of the key "
            f"group count {kg} (lanes={g}), as must any remainder chunk"
        )


def _pick_tile(
    num_lanes: int, key_groups: int, cap: int = _TILE_LANES
) -> int:
    tile = min(cap, num_lanes)
    while tile > key_groups and (
        num_lanes % tile != 0 or tile % key_groups != 0
    ):
        tile //= 2
    if num_lanes % tile != 0 or tile % key_groups != 0:
        tile = num_lanes
    return tile


# Walk-descent default tile: the working set is ~6 copies of a
# [16, 8, tile] u32 state (~6 MB at 2048) and the hardware probe
# validates the 2048-lane geometry, so the serving default matches it.
_WALK_TILE_LANES = 2048


def pick_walk_tile(
    w: int, kg: int, node_lanes: int, compact_entry: bool, r: int
) -> int:
    """The walk-descent wrapper's default tile choice, exposed so
    callers that must compose the exit order (which depends on the
    tile in compact mode) can compute the same value."""
    if not compact_entry:
        return _pick_tile(w, kg, cap=_WALK_TILE_LANES)
    # Compact tiles must cover whole node blocks; pick the largest
    # multiple of node_lanes<<r within the cap, or the whole width
    # when one block alone exceeds the cap.
    block = node_lanes << r
    tile = min(w, max(block, (_WALK_TILE_LANES // block) * block))
    while w % tile:
        tile -= block
    return tile


def walk_plan(
    w: int, kg: int, node_lanes: int, r: int, want_compact: bool
) -> tuple:
    """(tile, compact, nodes_per_tile) for one walk phase — the ONE
    place the tile/mode decision lives, so the kernel call and the
    exit-order composition can never disagree. Compact is declined
    when a single node block (node_lanes << r lanes) exceeds the tile
    cap: the compact tile would blow the probed VMEM envelope and fail
    a compile the replicated mode (which tiles freely) survives."""
    block = node_lanes << r
    if want_compact and block <= _WALK_TILE_LANES:
        tile = pick_walk_tile(w, kg, node_lanes, True, r)
        return tile, True, (tile >> r) // node_lanes
    return pick_walk_tile(w, kg, node_lanes, False, r), False, 0


def compose_walk_leaf_order(
    entry_order: np.ndarray, r: int, compact: bool, nodes_per_tile: int
) -> np.ndarray:
    """Exit leaf order of a walk phase planned by `walk_plan`: natural
    per-node offsets (replicated mode) or offset-major tiles (compact),
    composed over the entry order."""
    if compact:
        return walk_compact_leaf_order(entry_order, r, nodes_per_tile)
    m = np.asarray(entry_order, dtype=np.int64)
    return (
        m[:, None] * (1 << r) + np.arange(1 << r, dtype=np.int64)[None, :]
    ).reshape(-1)


@functools.partial(
    jax.jit, static_argnames=("interpret", "tile_lanes")
)
def expand_level_planes_pallas(
    state: jnp.ndarray,
    ctrl: jnp.ndarray,
    cwp_kg: jnp.ndarray,
    cwl_kg: jnp.ndarray,
    cwr_kg: jnp.ndarray,
    interpret: bool = False,
    tile_lanes: int | None = None,
):
    """One [all-left; all-right] expansion level, fused in VMEM.

    state: uint32[16, 8, G]; ctrl: uint32[G] packed parent control bits;
    cwp_kg: uint32[16, 8, KG] per-key seed-correction planes
    (`pack_key_planes`); cwl_kg / cwr_kg: uint32[KG] packed per-key
    direction-correction bits. Returns (state [16, 8, 2G], ctrl [2G])
    in [all-left; all-right] child order — the same contract as
    `dense_eval_planes.expand_level_planes` with untiled corrections.
    """
    _, _, g = state.shape
    kg = cwp_kg.shape[-1]
    tile = _pick_tile(g, kg) if tile_lanes is None else tile_lanes
    _check_tile(tile, g, kg)
    ctrl2 = ctrl[None, :]
    cwl2 = cwl_kg[None, :]
    cwr2 = cwr_kg[None, :]

    def call(state_c, ctrl_c):
        # One grid-(1,) pallas_call per lane chunk: multi-step lane grids
        # crash tpu_compile_helper on v5e (expand_profile 2026-07-31:
        # fine through G=1024 = one grid step, exit-1 at G=2048 = two),
        # so the chunking lives here in XLA instead of in the grid.
        t = state_c.shape[-1]
        reps = t // kg  # a chunk can be narrower than the nominal tile
        out_shapes = (
            jax.ShapeDtypeStruct((16, 8, t), U32),
            jax.ShapeDtypeStruct((16, 8, t), U32),
            jax.ShapeDtypeStruct((1, t), U32),
            jax.ShapeDtypeStruct((1, t), U32),
        )
        return pl.pallas_call(
            functools.partial(_level_kernel, reps=reps),
            grid=(1,),
            in_specs=[
                pl.BlockSpec((16, 8, t), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
                pl.BlockSpec((16, 8, kg), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, kg), lambda l: (0, 0)),
                pl.BlockSpec((1, kg), lambda l: (0, 0)),
                pl.BlockSpec(
                    (2, 11, 16, 8, 1), lambda l: (0, 0, 0, 0, 0)
                ),
            ],
            out_specs=(
                pl.BlockSpec((16, 8, t), lambda l: (0, 0, 0)),
                pl.BlockSpec((16, 8, t), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
            ),
            out_shape=out_shapes,
            interpret=interpret,
        )(state_c, ctrl_c, cwp_kg, cwl2, cwr2, _MASKS_LR)

    ls, rs, lc, rc = [], [], [], []
    for lo in range(0, g, tile):
        outl, outr, ctl, ctr = call(
            state[:, :, lo : lo + tile], ctrl2[:, lo : lo + tile]
        )
        ls.append(outl)
        rs.append(outr)
        lc.append(ctl[0])
        rc.append(ctr[0])
    # Global [all-left; all-right] child order across chunks.
    new_state = jnp.concatenate(ls + rs, axis=-1)
    new_ctrl = jnp.concatenate(lc + rc)
    return new_state, new_ctrl


def _value_kernel(state_ref, ctrl_ref, vc_ref, masks_ref, out_ref, *,
                  reps: int):
    sig = _sigma(state_ref[:])
    values = _aes_fixed_planes(masks_ref[:], sig) ^ sig
    vc = pltpu.repeat(vc_ref[:], reps, axis=2)
    out_ref[:] = values ^ (vc & ctrl_ref[:][0][None, None, :])


@functools.partial(
    jax.jit, static_argnames=("interpret", "tile_lanes")
)
def value_hash_planes_pallas(
    state: jnp.ndarray,
    ctrl: jnp.ndarray,
    vc_kg: jnp.ndarray,
    interpret: bool = False,
    tile_lanes: int | None = None,
) -> jnp.ndarray:
    """Leaf MMO output hash + value correction, fused in VMEM.

    state: uint32[16, 8, G]; ctrl: uint32[G]; vc_kg: uint32[16, 8, KG]
    per-key value-correction planes. Returns uint32[16, 8, G] — same
    math as `mmo_hash_planes(RK_VALUE, state) ^ (vc_tiled & ctrl)`.
    """
    _, _, g = state.shape
    kg = vc_kg.shape[-1]
    tile = _pick_tile(g, kg) if tile_lanes is None else tile_lanes
    _check_tile(tile, g, kg)
    ctrl2 = ctrl[None, :]
    masks = jnp.asarray(_MASKS_VALUE)

    def call(state_c, ctrl_c):
        # Grid-(1,) per lane chunk, like `expand_level_planes_pallas`:
        # multi-step lane grids crash tpu_compile_helper on v5e.
        t = state_c.shape[-1]
        reps = t // kg  # a chunk can be narrower than the nominal tile
        return pl.pallas_call(
            functools.partial(_value_kernel, reps=reps),
            grid=(1,),
            in_specs=[
                pl.BlockSpec((16, 8, t), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
                pl.BlockSpec((16, 8, kg), lambda l: (0, 0, 0)),
                pl.BlockSpec((11, 16, 8, 1), lambda l: (0, 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((16, 8, t), lambda l: (0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 8, t), U32),
            interpret=interpret,
        )(state_c, ctrl_c, vc_kg, masks)

    return jnp.concatenate(
        [
            call(state[:, :, lo : lo + tile], ctrl2[:, lo : lo + tile])
            for lo in range(0, g, tile)
        ],
        axis=-1,
    )


def _tail_kernel(
    state_ref,
    ctrl_ref,
    cwp_ref,
    cwl_ref,
    cwr_ref,
    vc_ref,
    masks_lr_ref,
    masks_v_ref,
    out_ref,
    outc_ref,
    *,
    kg: int,
    r: int,
):
    """Expand one lane tile through the last `r` levels AND the leaf
    value hash entirely in VMEM: one HBM read of the [16, 8, T] entry
    tile, one HBM write of the [16, 8, T * 2^r] value planes.

    Subtrees of distinct tiles are independent, so each tile runs the
    whole tail alone; the cross-tile leaf order is handled by
    `tail_node_permutation` at exit. Each level doubles the width with
    the in-tile [all-left; all-right] concatenation; every width stays
    >= the entry tile (chosen >= 128 lanes by the caller), clear of
    Mosaic's narrow-lane edge cases. Correction planes stay [16, 8, KG]
    and are repeated per level exactly like `_level_kernel`.
    """
    state = state_ref[:]
    ctrl = ctrl_ref[:][0]  # [T]
    masks = masks_lr_ref[:]  # [2, 11, 16, 8, 1]
    cwp_all = cwp_ref[:]  # [r, 16, 8, kg]
    cwl_all = cwl_ref[:]  # [r, kg]
    cwr_all = cwr_ref[:]  # [r, kg]
    for i in range(r):
        w = state.shape[-1]
        sig = _sigma(state)
        left = _aes_fixed_planes(masks[0], sig) ^ sig
        right = _aes_fixed_planes(masks[1], sig) ^ sig
        state = jnp.concatenate([left, right], axis=-1)
        ctrl2 = jnp.concatenate([ctrl, ctrl])
        cwp = pltpu.repeat(cwp_all[i], 2 * w // kg, axis=2)  # [16, 8, 2w]
        state = state ^ (cwp & ctrl2[None, None, :])
        t_new = state[0, 0]
        state = _zero_lsb_plane(state)
        cwl = pltpu.repeat(cwl_all[i][None, :], w // kg, axis=1)[0]
        cwr = pltpu.repeat(cwr_all[i][None, :], w // kg, axis=1)[0]
        cw_dir = jnp.concatenate(
            [ctrl & cwl, ctrl & cwr]
        )
        ctrl = t_new ^ cw_dir
    # Leaf value hash (MMO with the value key) + value correction.
    sig = _sigma(state)
    values = _aes_fixed_planes(masks_v_ref[:], sig) ^ sig
    wf = values.shape[-1]
    vc = pltpu.repeat(vc_ref[:], wf // kg, axis=2)
    out_ref[:] = values ^ (vc & ctrl[None, None, :])
    # Final packed control bits (hierarchical callers apply arithmetic
    # value corrections outside, per leaf control bit).
    outc_ref[:] = ctrl[None, :]


def _head_kernel(
    state_ref,
    ctrl_ref,
    cwp_ref,
    cwl_ref,
    cwr_ref,
    masks_lr_ref,
    out_ref,
    outc_ref,
    *,
    kg: int,
    r: int,
):
    """Expand the whole (narrow) entry width through the FIRST `r`
    levels in one launch: one HBM read of the [16, 8, G0] entry planes,
    one HBM write of the [16, 8, G0 << r] result.

    The narrow early levels are pure overhead off-chip: at the headline
    config they cost ~6 ms of XLA launches (or worse, per-level kernel
    launches) for microseconds of gate work (expand_profile 2026-07-31,
    levels 0-8). A single tile covers the full width, so the per-level
    in-kernel [all-left; all-right] concatenation is exactly the global
    level order — no exit permutation, unlike the tiled tail."""
    state = state_ref[:]
    ctrl = ctrl_ref[:][0]
    masks = masks_lr_ref[:]
    cwp_all = cwp_ref[:]
    cwl_all = cwl_ref[:]
    cwr_all = cwr_ref[:]
    for i in range(r):
        w = state.shape[-1]
        sig = _sigma(state)
        left = _aes_fixed_planes(masks[0], sig) ^ sig
        right = _aes_fixed_planes(masks[1], sig) ^ sig
        state = jnp.concatenate([left, right], axis=-1)
        ctrl2 = jnp.concatenate([ctrl, ctrl])
        cwp = pltpu.repeat(cwp_all[i], 2 * w // kg, axis=2)
        state = state ^ (cwp & ctrl2[None, None, :])
        t_new = state[0, 0]
        state = _zero_lsb_plane(state)
        cwl = pltpu.repeat(cwl_all[i][None, :], w // kg, axis=1)[0]
        cwr = pltpu.repeat(cwr_all[i][None, :], w // kg, axis=1)[0]
        ctrl = t_new ^ jnp.concatenate([ctrl & cwl, ctrl & cwr])
    out_ref[:] = state
    outc_ref[:] = ctrl[None, :]


@functools.partial(jax.jit, static_argnames=("interpret",))
def expand_head_planes_pallas(
    state: jnp.ndarray,
    ctrl: jnp.ndarray,
    cwp_head: jnp.ndarray,
    cwl_head: jnp.ndarray,
    cwr_head: jnp.ndarray,
    interpret: bool = False,
) -> tuple:
    """Fused head: the FIRST `r` expansion levels in one grid-(1,)
    launch over the full (narrow) width.

    state: uint32[16, 8, G0] entry planes (G0 = key_groups at the top
    of the expansion); ctrl: uint32[G0]; cwp_head: uint32[r, 16, 8, KG]
    per-level seed-correction planes; cwl_head / cwr_head: uint32[r, KG]
    per-level packed direction bits. Returns
    (state uint32[16, 8, G0 << r], ctrl uint32[G0 << r]) bit-identical
    to `r` successive `expand_level_planes` applications — single-tile,
    so no exit permutation. The caller bounds G0 << r so the in-kernel
    working set stays within VMEM (~16 MB/core)."""
    _, _, g0 = state.shape
    r = cwp_head.shape[0]
    kg = cwp_head.shape[-1]
    if g0 % kg:
        raise ValueError(
            f"entry lanes {g0} must be a multiple of key groups {kg}"
        )
    gf = g0 << r
    out, outc = pl.pallas_call(
        functools.partial(_head_kernel, kg=kg, r=r),
        grid=(1,),
        in_specs=[
            pl.BlockSpec((16, 8, g0), lambda l: (0, 0, 0)),
            pl.BlockSpec((1, g0), lambda l: (0, 0)),
            pl.BlockSpec((r, 16, 8, kg), lambda l: (0, 0, 0, 0)),
            pl.BlockSpec((r, kg), lambda l: (0, 0)),
            pl.BlockSpec((r, kg), lambda l: (0, 0)),
            pl.BlockSpec((2, 11, 16, 8, 1), lambda l: (0, 0, 0, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((16, 8, gf), lambda l: (0, 0, 0)),
            pl.BlockSpec((1, gf), lambda l: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((16, 8, gf), U32),
            jax.ShapeDtypeStruct((1, gf), U32),
        ),
        interpret=interpret,
    )(
        state, ctrl[None, :], cwp_head, cwl_head, cwr_head, _MASKS_LR
    )
    return out, outc[0]


def tail_node_permutation(
    entry_order: np.ndarray, r: int, tile_nodes: int
) -> tuple[np.ndarray, np.ndarray]:
    """Leaf order of the tiled tail expansion.

    entry_order[pos] = natural node index (at the split level) sitting
    at plane position `pos` when the tail starts. Each tile of
    `tile_nodes` entry nodes expands independently with per-level
    [all-left; all-right] concatenation; tiles' outputs concatenate in
    tile order. Returns (order, perm): order[pos] = natural leaf index
    at final position pos, and perm = argsort(order), i.e. perm[g] = the
    final position of natural leaf g (the exit-gather index vector).
    """
    chunks = []
    for lo in range(0, len(entry_order), tile_nodes):
        m = np.asarray(entry_order[lo : lo + tile_nodes], dtype=np.int64)
        for _ in range(r):
            m = np.concatenate([2 * m, 2 * m + 1])
        chunks.append(m)
    order = np.concatenate(chunks) if chunks else np.zeros(0, np.int64)
    return order, np.argsort(order)


@functools.partial(
    jax.jit, static_argnames=("interpret", "tile_lanes")
)
def expand_tail_planes_pallas(
    state: jnp.ndarray,
    ctrl: jnp.ndarray,
    cwp_tail: jnp.ndarray,
    cwl_tail: jnp.ndarray,
    cwr_tail: jnp.ndarray,
    vc_kg: jnp.ndarray,
    tile_lanes: int,
    interpret: bool = False,
) -> tuple:
    """Fused tail: the last `r` expansion levels + the leaf value hash,
    one kernel launch per entry tile (grid-(1,) each; multi-step lane
    grids crash tpu_compile_helper on v5e).

    state: uint32[16, 8, G0] planes at the split level; ctrl: uint32[G0];
    cwp_tail: uint32[r, 16, 8, KG] per-level seed-correction planes;
    cwl_tail / cwr_tail: uint32[r, KG] per-level packed direction bits;
    vc_kg: uint32[16, 8, KG] value-correction planes. Returns
    (value planes uint32[16, 8, G0 * 2^r], packed leaf control bits
    uint32[G0 * 2^r]) in TILED order — compose `tail_node_permutation`
    at exit to recover natural block order.
    """
    _, _, g0 = state.shape
    r = cwp_tail.shape[0]
    kg = cwp_tail.shape[-1]
    _check_tile(tile_lanes, g0, kg)
    ctrl2 = ctrl[None, :]
    masks_v = jnp.asarray(_MASKS_VALUE)

    def call(state_c, ctrl_c):
        t = state_c.shape[-1]
        return pl.pallas_call(
            functools.partial(_tail_kernel, kg=kg, r=r),
            grid=(1,),
            in_specs=[
                pl.BlockSpec((16, 8, t), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
                pl.BlockSpec((r, 16, 8, kg), lambda l: (0, 0, 0, 0)),
                pl.BlockSpec((r, kg), lambda l: (0, 0)),
                pl.BlockSpec((r, kg), lambda l: (0, 0)),
                pl.BlockSpec((16, 8, kg), lambda l: (0, 0, 0)),
                pl.BlockSpec(
                    (2, 11, 16, 8, 1), lambda l: (0, 0, 0, 0, 0)
                ),
                pl.BlockSpec((11, 16, 8, 1), lambda l: (0, 0, 0, 0)),
            ],
            out_specs=(
                pl.BlockSpec((16, 8, t << r), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, t << r), lambda l: (0, 0)),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((16, 8, t << r), U32),
                jax.ShapeDtypeStruct((1, t << r), U32),
            ),
            interpret=interpret,
        )(
            state_c, ctrl_c, cwp_tail, cwl_tail, cwr_tail, vc_kg,
            _MASKS_LR, masks_v,
        )

    vs, cs = [], []
    for lo in range(0, g0, tile_lanes):
        v, c = call(state[:, :, lo : lo + tile_lanes],
                    ctrl2[:, lo : lo + tile_lanes])
        vs.append(v)
        cs.append(c[0])
    return jnp.concatenate(vs, axis=-1), jnp.concatenate(cs)


def _walk_kernel(
    state_ref,
    ctrl_ref,
    off_ref,
    cwp_ref,
    cwl_ref,
    cwr_ref,
    vc_ref,
    masks_lr_ref,
    masks_v_ref,
    out_ref,
    outc_ref,
    *,
    kg: int,
    r: int,
    value_hash: bool,
    unroll: bool = True,
    compact: bool = False,
):
    """Constant-width descent: `r` levels + optional leaf value hash at a
    FIXED lane width, using the per-lane select-key AES of `_path_kernel`
    instead of the twin left/right hashes of `_tail_kernel`/`_head_kernel`.

    Entry seeds arrive pre-replicated (every lane already holds the seed
    of its leaf's ancestor at the split level), so the per-level
    [all-left; all-right] lane concatenation — the doubling-width
    construct Mosaic rejects at serving shapes on the 2026-08-01 v5e
    toolchain — disappears: every intermediate is the same [16, 8, W]
    tile-aligned shape, and leaves exit in NATURAL order (no exit
    permutation). Each level hashes once with per-lane key select
    (`dpf/internal/aes_128_fixed_key_hash_hwy.h:123-155` semantics), so
    the gate work per level is HALF the twin-hash kernels'; the
    replication inflates total gate work by ~r/2 over perfect doubling,
    which is noise against the HBM traffic both designs already save.

    off_ref: uint32[1, W] leaf offset of each lane within its entry
    node's 2^r block (precomputed outside; bit r-1-i selects the key at
    level i — MSB first). Everything else matches `_tail_kernel`.

    With `compact` the entry arrives UNREPLICATED ([16, 8, W >> r]) and
    the kernel replicates it 2^r-fold with the whole-array repeat (the
    construct every serving kernel already uses for corrections) — the
    tile's lane order is then offset-major, `off` is supplied to match,
    and the full-width replicated array never touches HBM.
    """
    state = state_ref[:]
    ctrl = ctrl_ref[:][0]  # [W] (or [W >> r] compact) packed bits
    if compact:
        state = pltpu.repeat(state, 1 << r, axis=2)
        ctrl = pltpu.repeat(ctrl[None, :], 1 << r, axis=1)[0]
    off = off_ref[:]  # [1, W]
    masks = masks_lr_ref[:]  # [2, 11, 16, 8, 1]
    cwp_all = cwp_ref[:]  # [r, 16, 8, kg]
    cwl_all = cwl_ref[:]  # [r, kg]
    cwr_all = cwr_ref[:]  # [r, kg]
    w = state.shape[-1]
    reps = w // kg
    zero = jnp.uint32(0)

    def level(i, state, ctrl, cwp_i, cwl_i, cwr_i):
        bit = (off >> (jnp.uint32(r - 1) - i)) & jnp.uint32(1)  # [1, W]
        selw = zero - bit  # 0x0 / 0xFFFFFFFF per lane
        selb = selw[0][None, None, :]
        h = _aes_select_planes(masks, selb, _sigma(state))
        cwp = pltpu.repeat(cwp_i, reps, axis=2)  # [16, 8, W]
        h = h ^ (cwp & ctrl[None, None, :])
        t_new = h[0, 0]
        state = _zero_lsb_plane(h)
        cwl = pltpu.repeat(cwl_i[None, :], reps, axis=1)[0]
        cwr = pltpu.repeat(cwr_i[None, :], reps, axis=1)[0]
        cw_dir = (cwl & ~selw[0]) | (cwr & selw[0])
        return state, t_new ^ (ctrl & cw_dir)

    if unroll:
        for i in range(r):
            state, ctrl = level(
                jnp.uint32(i), state, ctrl,
                cwp_all[i], cwl_all[i], cwr_all[i],
            )
    else:
        # Constant width makes the level loop a real fori_loop: the
        # program holds ONE select-key AES body regardless of depth,
        # where the unrolled form at r=9..13 carries 10-14 of them —
        # exactly the program-size regime where Mosaic has rejected or
        # hung on the doubling kernels.
        def body(i, carry):
            state, ctrl = carry
            return level(
                i.astype(jnp.uint32), state, ctrl,
                cwp_all[i], cwl_all[i], cwr_all[i],
            )

        state, ctrl = jax.lax.fori_loop(0, r, body, (state, ctrl))
    if value_hash:
        sig = _sigma(state)
        values = _aes_fixed_planes(masks_v_ref[:], sig) ^ sig
        vc = pltpu.repeat(vc_ref[:], reps, axis=2)
        out_ref[:] = values ^ (vc & ctrl[None, None, :])
    else:
        out_ref[:] = state
    outc_ref[:] = ctrl[None, :]


def replicate_entry_planes(
    state: jnp.ndarray, ctrl: jnp.ndarray, kg: int, times: int
) -> tuple:
    """[16, 8, n*kg] entry planes -> [16, 8, n*times*kg] with each
    node's kg-lane block repeated `times` consecutively (and likewise
    for the packed control words), so lane (node*times + j)*kg + kw
    holds node's seed for every j — the wide-walk entry layout."""
    p, q, g = state.shape
    n = g // kg
    state_r = jnp.broadcast_to(
        state.reshape(p, q, n, 1, kg), (p, q, n, times, kg)
    ).reshape(p, q, n * times * kg)
    ctrl_r = jnp.broadcast_to(
        ctrl.reshape(n, 1, kg), (n, times, kg)
    ).reshape(n * times * kg)
    return state_r, ctrl_r


@functools.partial(
    jax.jit,
    static_argnames=(
        "r", "tile_lanes", "value_hash", "node_lanes", "unroll",
        "compact_entry", "interpret",
    ),
)
def walk_descend_planes_pallas(
    state: jnp.ndarray,
    ctrl: jnp.ndarray,
    cwp_all: jnp.ndarray,
    cwl_all: jnp.ndarray,
    cwr_all: jnp.ndarray,
    vc_kg: jnp.ndarray | None = None,
    *,
    r: int,
    tile_lanes: int | None = None,
    value_hash: bool = False,
    node_lanes: int | None = None,
    unroll: bool = True,
    compact_entry: bool = False,
    interpret: bool = False,
) -> tuple:
    """Fixed-width fused descent of the last (or first) `r` expansion
    levels, optionally ending in the leaf value hash.

    state: uint32[16, 8, G0] planes at the split level; ctrl:
    uint32[G0]; cwp_all: uint32[r, 16, 8, KG]; cwl_all / cwr_all:
    uint32[r, KG]; vc_kg (with value_hash): uint32[16, 8, KG]. Returns
    (out uint32[16, 8, G0 << r], ctrl uint32[G0 << r]) in NATURAL leaf
    order (leaf g = entry_node * 2^r + offset) — no exit permutation.
    `node_lanes` is the lane width of one tree node's block (defaults
    to KG — the dense-serving layout where a node spans the per-key
    correction words; the hierarchical single-key layout packs 32
    prefixes per word instead, with KG=1 shared corrections, so a node
    spans prefix_words lanes there).

    Default mode replicates the entry 2^r-fold outside the kernel, then
    each `tile_lanes` output tile descends independently at constant
    width; the replication materializes full-width in HBM (one extra
    write+read of W lanes ~= the kernel's own output traffic — ~40 us
    at the q128 serving width, but ~0.7 ms at the ld24 hierarchical
    width). `compact_entry=True` removes it: each tile reads only its
    UNREPLICATED entry chunk ([16, 8, tile >> r]) and the kernel
    replicates in VMEM with the whole-array repeat; the tile's lanes
    are then offset-major and the RETURN IS NOT NATURAL ORDER — callers
    compose `walk_compact_leaf_order` into their exit gather. Requires
    tile % (node_lanes << r) == 0. Reference semantics: `ExpandSeeds` +
    `HashExpandedSeeds`
    (`dpf/distributed_point_function.cc:289-372,523-547`), evaluated as
    a per-leaf path walk (`dpf/internal/evaluate_prg_hwy.cc:150-539`).
    """
    _, _, g0 = state.shape
    kg = cwp_all.shape[-1]
    if node_lanes is None:
        node_lanes = kg
    if g0 % node_lanes or node_lanes % kg:
        raise ValueError(
            f"entry lanes {g0} must be a multiple of node lanes "
            f"{node_lanes}, which must be a multiple of key groups {kg}"
        )
    if value_hash and vc_kg is None:
        raise ValueError(
            "value_hash=True requires vc_kg (a zero correction would "
            "silently break share reconstruction)"
        )
    w = g0 << r
    if tile_lanes is None:
        tile = pick_walk_tile(w, kg, node_lanes, compact_entry, r)
    else:
        tile = tile_lanes
    _check_tile(tile, w, kg)
    if compact_entry:
        if tile % (node_lanes << r) or w % tile:
            raise ValueError(
                f"compact_entry requires tile {tile} to cover whole "
                f"node blocks: multiple of node_lanes<<r "
                f"({node_lanes << r}) dividing {w}"
            )
        # Offset-major within each tile, matching the in-kernel
        # whole-array repeat: lane = off * entry_chunk + entry_lane.
        e = tile >> r
        off_np = np.repeat(np.arange(1 << r, dtype=np.uint32), e)
        state_r, ctrl_r = state, ctrl
    else:
        state_r, ctrl_r = replicate_entry_planes(
            state, ctrl, node_lanes, 1 << r
        )
        # Leaf offset of each lane within its entry node's 2^r block.
        off_np = np.tile(
            np.repeat(np.arange(1 << r, dtype=np.uint32), node_lanes),
            g0 // node_lanes,
        )
    off = jnp.asarray(off_np[None, :])
    if vc_kg is None:
        vc_kg = jnp.zeros((16, 8, kg), U32)
    masks_v = jnp.asarray(_MASKS_VALUE)
    ctrl2 = ctrl_r[None, :]

    def call(state_c, ctrl_c, off_c):
        t = off_c.shape[-1]
        te = state_c.shape[-1]  # == t >> r when compact, else t
        return pl.pallas_call(
            functools.partial(
                _walk_kernel, kg=kg, r=r, value_hash=value_hash,
                unroll=unroll, compact=compact_entry,
            ),
            grid=(1,),
            in_specs=[
                pl.BlockSpec((16, 8, te), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, te), lambda l: (0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
                pl.BlockSpec((r, 16, 8, kg), lambda l: (0, 0, 0, 0)),
                pl.BlockSpec((r, kg), lambda l: (0, 0)),
                pl.BlockSpec((r, kg), lambda l: (0, 0)),
                pl.BlockSpec((16, 8, kg), lambda l: (0, 0, 0)),
                pl.BlockSpec(
                    (2, 11, 16, 8, 1), lambda l: (0, 0, 0, 0, 0)
                ),
                pl.BlockSpec((11, 16, 8, 1), lambda l: (0, 0, 0, 0)),
            ],
            out_specs=(
                pl.BlockSpec((16, 8, t), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((16, 8, t), U32),
                jax.ShapeDtypeStruct((1, t), U32),
            ),
            interpret=interpret,
        )(
            state_c, ctrl_c, off_c, cwp_all, cwl_all, cwr_all, vc_kg,
            _MASKS_LR, masks_v,
        )

    outs, cs = [], []
    for lo in range(0, w, tile):
        if compact_entry:
            e = tile >> r
            lo_e = lo >> r
            o, c = call(
                state_r[:, :, lo_e : lo_e + e],
                ctrl2[:, lo_e : lo_e + e],
                off,
            )
        else:
            o, c = call(
                state_r[:, :, lo : lo + tile],
                ctrl2[:, lo : lo + tile],
                off[:, lo : lo + tile],
            )
        outs.append(o)
        cs.append(c[0])
    return jnp.concatenate(outs, axis=-1), jnp.concatenate(cs)


def walk_compact_leaf_order(
    entry_order: np.ndarray, r: int, nodes_per_tile: int
) -> np.ndarray:
    """Leaf order after a compact-entry walk-descent: each tile of
    `nodes_per_tile` entry nodes exits offset-major (all nodes' offset
    0, then offset 1, ...), tiles concatenating in entry order:
    order[t * npt * 2^r + off * npt + p] =
    entry_order[t * npt + p] * 2^r + off."""
    npt = nodes_per_tile
    m = np.asarray(entry_order, dtype=np.int64)
    chunks = []
    for lo in range(0, len(m), npt):
        blk = m[lo : lo + npt]
        chunks.append(
            (blk[None, :] * (1 << r)
             + np.arange(1 << r, dtype=np.int64)[:, None]).reshape(-1)
        )
    return np.concatenate(chunks)


def _path_kernel(
    state_ref,
    ctrl_ref,
    sel_ref,
    cwp_ref,
    cwl_ref,
    cwr_ref,
    masks_ref,
    outs_ref,
    outc_ref,
    *,
    reps: int,
    per_seed: bool,
):
    """One path-walk level: select-key AES (per-lane left/right round
    keys from the packed path-bit mask — the reference's per-lane key
    select, `dpf/internal/aes_128_fixed_key_hash_hwy.h:123-155`), seed
    correction, LSB extract/clear, and the direction-corrected control
    update, fused in VMEM. With `per_seed` the correction refs are
    lane-aligned (batch-of-keys mode, `evaluate_and_apply`); otherwise
    they are [.., KG] per-key words tiled in-kernel."""
    sig = _sigma(state_ref[:])
    masks = masks_ref[:]  # [2, 11, 16, 8, 1] left/right plane masks
    sel = sel_ref[:]  # [1, T] packed path bits
    selb = sel[0][None, None, :]
    h = _aes_select_planes(masks, selb, sig)

    ctrl = ctrl_ref[:]  # [1, T]
    if per_seed:
        cwp = cwp_ref[:]
        cwl = cwl_ref[:]
        cwr = cwr_ref[:]
    else:
        cwp = pltpu.repeat(cwp_ref[:], reps, axis=2)
        cwl = pltpu.repeat(cwl_ref[:], reps, axis=1)
        cwr = pltpu.repeat(cwr_ref[:], reps, axis=1)
    h = h ^ (cwp & ctrl[0][None, None, :])
    t_new = h[0, 0]
    outs_ref[:] = _zero_lsb_plane(h)
    cw_dir = (sel[0] & cwr[0]) | (~sel[0] & cwl[0])
    outc_ref[:] = (t_new ^ (ctrl[0] & cw_dir))[None, :]


@functools.partial(
    jax.jit, static_argnames=("per_seed", "interpret", "tile_lanes")
)
def path_level_planes_pallas(
    state: jnp.ndarray,
    ctrl: jnp.ndarray,
    sel: jnp.ndarray,
    cwp: jnp.ndarray,
    cwl: jnp.ndarray,
    cwr: jnp.ndarray,
    per_seed: bool,
    interpret: bool = False,
    tile_lanes: int | None = None,
):
    """One path-walk level on [16, 8, G] planes.

    sel: uint32[G] packed path bits (1 -> right key). With per_seed,
    cwp is uint32[16, 8, G] lane-aligned correction planes and cwl/cwr
    are uint32[G]; otherwise cwp is [16, 8, KG] / cwl, cwr [KG] per-key
    words tiled across lanes in-kernel. Returns (state [16, 8, G],
    ctrl [G]) — the fused body of `dpf._eval_paths_planes`."""
    _, _, g = state.shape
    kg = g if per_seed else cwp.shape[-1]
    if tile_lanes is None:
        tile = _pick_tile(g, kg if not per_seed else 1)
    else:
        tile = tile_lanes
    _check_tile(tile, g, 1 if per_seed else kg)
    ctrl2 = ctrl[None, :]
    sel2 = sel[None, :]
    cwl2 = cwl[None, :]
    cwr2 = cwr[None, :]

    def call(state_c, ctrl_c, sel_c, cwp_c, cwl_c, cwr_c):
        # Grid-(1,) per lane chunk (multi-step lane grids crash
        # tpu_compile_helper on v5e — see `expand_level_planes_pallas`).
        t = state_c.shape[-1]
        # A chunk can be narrower than the nominal tile.
        reps = t // kg if not per_seed else 1
        if per_seed:
            cw_specs = [
                pl.BlockSpec((16, 8, t), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
            ]
        else:
            cw_specs = [
                pl.BlockSpec((16, 8, kg), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, kg), lambda l: (0, 0)),
                pl.BlockSpec((1, kg), lambda l: (0, 0)),
            ]
        return pl.pallas_call(
            functools.partial(_path_kernel, reps=reps, per_seed=per_seed),
            grid=(1,),
            in_specs=[
                pl.BlockSpec((16, 8, t), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
                *cw_specs,
                pl.BlockSpec(
                    (2, 11, 16, 8, 1), lambda l: (0, 0, 0, 0, 0)
                ),
            ],
            out_specs=(
                pl.BlockSpec((16, 8, t), lambda l: (0, 0, 0)),
                pl.BlockSpec((1, t), lambda l: (0, 0)),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((16, 8, t), U32),
                jax.ShapeDtypeStruct((1, t), U32),
            ),
            interpret=interpret,
        )(state_c, ctrl_c, sel_c, cwp_c, cwl_c, cwr_c, _MASKS_LR)

    ss, cs = [], []
    for lo in range(0, g, tile):
        sl = slice(lo, lo + tile)
        outs, outc = call(
            state[:, :, sl],
            ctrl2[:, sl],
            sel2[:, sl],
            cwp[:, :, sl] if per_seed else cwp,
            cwl2[:, sl] if per_seed else cwl2,
            cwr2[:, sl] if per_seed else cwr2,
        )
        ss.append(outs)
        cs.append(outc[0])
    return jnp.concatenate(ss, axis=-1), jnp.concatenate(cs)
