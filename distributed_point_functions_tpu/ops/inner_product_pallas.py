"""Pallas TPU kernel for the packed-XOR database inner product — on the MXU.

The XOR inner product (for each query, XOR of all database records whose
selection bit is 1 — `pir/internal/inner_product_hwy.cc:157-258`) is a
GF(2) matrix product: output bit j of word w is the *parity* of
``sum_r sel[q, r] * db_bit_j[r, w]``. That sum is an ordinary integer
matmul — exactly what the MXU does — so instead of VPU-style mask-and-XOR
(memory-layout hostile on TPU: measured 6 GB/s), the kernel computes
per-bit-plane bf16 matmuls with exact f32 accumulation (counts <= number
of records <= 2^24, all integers exact in f32) and takes parities at the
end.

Both operands stay **packed** in HBM; no `[nq, R]` mask is ever
materialized there:

* selections: ``uint32[nq, G]``, bit b of word g selects record 32g+b;
* database: staged once into bit-major order ``db_perm[b, g, w] =
  db_words[32g + b, w]`` (shape ``[32, G, W]``), so the kernel's
  fori-loop over the 32 bit-classes b only ever indexes the *leading*
  axis dynamically — the record class's selection bits fall out of the
  packed words as ``(words >> b) & 1`` with no lane reshuffle (Mosaic
  cannot lower minor-dim reshapes/repeats, which sank the VPU designs).

Grid: (query tiles, record-group tiles), record axis innermost; the f32
``[TQ, 32, W]`` count accumulator lives in VMEM across record tiles (the
revisiting-output pattern). Per step and bit-class, the DB tile's 32
value-bit-planes are peeled in VMEM (`(dbb >> j) & 1`) and hit the MXU as
``[TQ, TG] x [TG, W]`` bf16 dots. One database pass serves the whole
query batch.

Exactness bound: counts accumulate in f32, so the kernel requires
R <= 2^24 records (far above the 2^22 headline config); the caller falls
back to the jnp path beyond that.

Differentially tested against the jnp implementation and the numpy/native
oracles (tests/test_pallas.py); bit-identity vs the jnp path is re-checked
on hardware by bench.py before the kernel serves the measured run.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

U32 = jnp.uint32
I32 = jnp.int32

# jax < 0.6 has no varying-manual-axes metadata on ShapeDtypeStruct; its
# shard_map fallback runs with the replication checker off instead
# (parallel/sharded.py), so dropping the annotation there is consistent.
try:
    jax.ShapeDtypeStruct((1,), U32, vma=frozenset())
    _HAS_VMA = True
except TypeError:
    _HAS_VMA = False

_INTERPRET_REPEAT_TILES: bool | None = None


def _interpret_repeat_tiles() -> bool:
    """Whether interpret-mode `pltpu.repeat` tiles the source like Mosaic
    (`[a b] -> [a b a b]`).

    Old jax interpreted it as element-wise `np.repeat` (`[a a b b]`),
    silently corrupting every kernel below under interpret=True; those
    kernels swap in a concat-based tile when this probe says so. The
    compiled path always has Mosaic semantics and is never rerouted.
    """
    global _INTERPRET_REPEAT_TILES
    if _INTERPRET_REPEAT_TILES is None:
        def probe(x_ref, o_ref):
            o_ref[:] = pltpu.repeat(x_ref[:], 2, axis=1)

        # The probe must run eagerly even when first reached while
        # tracing the jitted caller.
        with jax.ensure_compile_time_eval():
            got = pl.pallas_call(
                probe,
                out_shape=jax.ShapeDtypeStruct((1, 4), U32),
                interpret=True,
            )(jnp.arange(2, dtype=U32)[None, :])
            _INTERPRET_REPEAT_TILES = bool(
                (got[0] == jnp.array([0, 1, 0, 1], dtype=U32)).all()
            )
    return _INTERPRET_REPEAT_TILES


def _tile_repeat(x, factor: int, axis: int):
    """Mosaic-semantics repeat (whole-source tiling along `axis`)."""
    if factor == 1:
        return x
    return jnp.concatenate([x] * factor, axis=axis)
I8 = jnp.int8
BF16 = jnp.bfloat16
F32 = jnp.float32

# Record-group tile (32 records per group): 128 groups = 4096 records per
# grid step; the packed-selection block's lane dim is then 128, the TPU
# lane width.
_TILE_GROUPS = 128
# f32 holds integers exactly up to 2^24 — the parity trick's hard cap.
MAX_RECORDS_EXACT = 1 << 24


def permute_db_bitmajor(db_words: jnp.ndarray) -> jnp.ndarray:
    """uint32[R, W] row-major -> uint32[32, G, W] bit-major.

    Row 32g+b lands at [b, g, :]: all records selected by bit b of a
    packed selection word are contiguous along axis 1. The record count
    is zero-padded to a multiple of 32*_TILE_GROUPS (= 4096) so the group
    axis always tiles evenly at the full 128-lane width (zero rows never
    contribute to a XOR). One XLA pad+transpose, done once when the
    database is staged.
    """
    num_records, num_words = db_words.shape
    chunk = 32 * _TILE_GROUPS
    padded = ((num_records + chunk - 1) // chunk) * chunk
    if padded != num_records:
        db_words = jnp.pad(db_words, ((0, padded - num_records), (0, 0)))
    return jnp.transpose(
        db_words.reshape(padded // 32, 32, num_words), (1, 0, 2)
    )


def _ip_kernel(sel_ref, db_ref, out_ref, *, num_value_bits: int):
    """sel_ref: uint32[TQ, TG] packed; db_ref: uint32[32, TG, W] bit-major;
    out_ref: float32[TQ, 32, W] per-value-bit selection counts."""

    @pl.when(pl.program_id(1) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    def body(b, carry):
        # Selection bits of record class b (records 32g+b), ready for the
        # MXU: [TQ, TG] bf16 of 0/1.
        # Mosaic has no direct u32->bf16 cast; hop via i32 -> f32 (values
        # are 0/1, so every step is exact).
        sel_b = (
            ((sel_ref[:] >> b.astype(U32)) & U32(1))
            .astype(jnp.int32)
            .astype(F32)
            .astype(BF16)
        )
        dbb = db_ref[b]  # [TG, W] u32 — dynamic index on the leading axis
        for j in range(num_value_bits):
            bits_j = (
                ((dbb >> U32(j)) & U32(1))
                .astype(jnp.int32)
                .astype(F32)
                .astype(BF16)
            )  # [TG, W]
            out_ref[:, j, :] += lax.dot_general(
                sel_b,
                bits_j,
                (((1,), (0,)), ((), ())),
                preferred_element_type=F32,
            )
        return carry

    lax.fori_loop(0, 32, body, 0)


def _pick_group_tile(
    num_groups: int, max_tile: int = _TILE_GROUPS, lane_step: int = 8
) -> int:
    """Largest tile <= max_tile that divides num_groups and is a
    multiple of `lane_step`, or the full axis for small databases.

    `lane_step` is 128 when the group axis is a block's *last* (lane)
    dimension — Mosaic requires last block dims to be 128-divisible or
    span the whole array axis — and 8 (sublane) otherwise.
    `permute_db_bitmajor` pads so num_groups % _TILE_GROUPS == 0; the
    search only matters for hand-built layouts. A large layout with no
    legal tile is rejected rather than compiled as one giant VMEM block.
    """
    tg = min(max_tile, num_groups)
    tg -= tg % lane_step
    while tg >= lane_step:
        if num_groups % tg == 0:
            return tg
        tg -= lane_step
    # No legal tile at or under the request: round UP to the smallest
    # legal one (a sub-lane_step request like the old tile_groups=32
    # default would otherwise be Mosaic-rejected on hardware).
    if lane_step < num_groups and num_groups % lane_step == 0:
        return lane_step
    if num_groups > max(max_tile, 4 * lane_step):
        raise ValueError(
            f"no legal group tile for {num_groups} groups; stage the "
            "database with permute_db_bitmajor (which pads)"
        )
    return num_groups


def _stage_selections(selections: jnp.ndarray, num_groups: int):
    """Flatten packed selection blocks to [nq_pad, num_groups] words.

    Extra words beyond the staged layout's (zero-padded) groups are
    dropped; missing words and the query count's non-multiple-of-8 tail
    are zero-padded (zero selection bits never contribute to a XOR).
    Returns (packed, nq) with nq the caller's true query count.
    """
    nq = selections.shape[0]
    packed = selections.reshape(nq, -1)
    if packed.shape[1] > num_groups:
        packed = packed[:, :num_groups]
    elif packed.shape[1] < num_groups:
        packed = jnp.pad(packed, ((0, 0), (0, num_groups - packed.shape[1])))
    nq_pad = ((nq + 7) // 8) * 8
    if nq_pad != nq:
        packed = jnp.pad(packed, ((0, nq_pad - nq), (0, 0)))
    return packed, nq


@functools.partial(
    jax.jit, static_argnames=("tile_queries", "interpret")
)
def _ip_pallas_staged(
    db_perm: jnp.ndarray,
    packed: jnp.ndarray,
    tile_queries: int = 64,
    interpret: bool = False,
) -> jnp.ndarray:
    _, num_groups, num_words = db_perm.shape
    nq = packed.shape[0]
    tg = _pick_group_tile(num_groups, lane_step=8 if interpret else 128)
    # Query tile: a multiple of 8 (TPU sublane) dividing the padded batch
    # (callers pad nq to a multiple of 8), or the whole batch if smaller.
    tq = min(tile_queries, nq)
    while tq > 8 and (nq % tq != 0 or tq % 8 != 0):
        tq -= 8 if tq % 8 == 0 else tq % 8
    if nq % tq != 0:
        tq = nq

    counts = pl.pallas_call(
        functools.partial(_ip_kernel, num_value_bits=32),
        grid=(nq // tq, num_groups // tg),
        in_specs=[
            pl.BlockSpec((tq, tg), lambda q, r: (q, r)),
            pl.BlockSpec((32, tg, num_words), lambda q, r: (0, r, 0)),
        ],
        out_specs=pl.BlockSpec((tq, 32, num_words), lambda q, r: (q, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nq, 32, num_words), F32),
        interpret=interpret,
    )(packed, db_perm)
    # Parity of each count is the output bit; recombine the 32 bit-planes.
    parity = counts.astype(jnp.int32).astype(U32) & U32(1)
    return (parity << jnp.arange(32, dtype=U32)[None, :, None]).sum(
        axis=1, dtype=U32
    )


def xor_inner_product_pallas_staged(
    db_perm: jnp.ndarray,
    selections: jnp.ndarray,
    tile_queries: int = 64,
    interpret: bool = False,
) -> jnp.ndarray:
    """Serving-path entry: bit-major staged database, packed selections.

    db_perm: uint32[32, G, W] from `permute_db_bitmajor` (R = 32*G
    records, R % 128 == 0); selections: uint32[nq, B, 4] packed blocks
    with 128*B >= R. Returns uint32[nq, W].
    """
    _, num_groups, _ = db_perm.shape
    num_records = 32 * num_groups
    if num_records > MAX_RECORDS_EXACT:
        raise ValueError(
            f"pallas inner product supports at most {MAX_RECORDS_EXACT} "
            f"records (f32-exact parity counts); got {num_records}"
        )
    packed, nq = _stage_selections(selections, num_groups)
    nq_pad = packed.shape[0]
    out = _ip_pallas_staged(
        db_perm, packed, tile_queries=tile_queries, interpret=interpret
    )
    return out[:nq] if nq_pad != nq else out


def _ip_kernel_v2(
    sel_ref, db_ref, out_ref, *, j_chunk: int, int8: bool,
    repeat=pltpu.repeat,
):
    """One large MXU dot per (grid step, value-bit chunk).

    v1 (`_ip_kernel`) issues 32x32 = 1024 tiny [TQ, TG] x [TG, W] dots per
    grid step; MXU pipeline fill dominates (measured 13.6 ms at the
    2^20 x 256B headline, ~10% MXU). Here the whole record tile is
    unpacked in VMEM into one [TQ, 32*TG] x [32*TG, j_chunk*W] dot per
    value-bit chunk: K grows 32x, the dot count per step drops from 1024
    to 32/j_chunk.

    Record order along K is b-major (k = b*TG + g, record 32g+b): the LHS
    tiles the packed selection words 32x along lanes (`pltpu.repeat`) and
    shifts by k//TG, the RHS is just `db_ref` flattened (major-dim merge
    [32, TG, W] -> [32TG, W] — no lane reshuffle, which Mosaic cannot
    lower). Value bits unpack the same way: repeat along lanes, shift by
    lane//W, so RHS column j*W + w matches the caller's [nq, 32, W]
    recombination.

    int8=True uses the int8 MXU path (i8 x i8 -> i32 dot): 2x the bf16
    rate and exact int32 counts — no f32 2^24-record exactness cap.

    sel_ref: uint32[TQ, TG]; db_ref: uint32[32, TG, W];
    out_ref: float32|int32[TQ, 32*W] counts.
    """

    @pl.when(pl.program_id(1) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    tq, tg = sel_ref.shape
    _, _, w = db_ref.shape
    tr = 32 * tg

    def to_mm(bits_u32):
        # Mosaic has no direct u32->bf16 cast; hop via i32 (exact 0/1s).
        as_i32 = bits_u32.astype(I32)
        return as_i32.astype(I8) if int8 else as_i32.astype(F32).astype(BF16)

    sel_rep = repeat(sel_ref[:], 32, axis=1)  # [TQ, 32*TG] tiled
    b_iota = lax.broadcasted_iota(U32, (tq, tr), 1) // U32(tg)
    lhs = to_mm((sel_rep >> b_iota) & U32(1))

    dbw = db_ref[:].reshape(tr, w)  # b-major record rows
    # j_chunk=1 repeats by factor 1 — expected to lower as an identity,
    # sidestepping Mosaic's narrow-source repeat miscompile (the entry
    # point drops to 1 for W<16 records; whether a factor-1 repeat on a
    # narrow source is really legal is UNPROBED on hardware —
    # benchmarks/kernel_smoke.py's W=8 case answers it, and the serving
    # tier chain degrades to the v1 kernel if it crashes). The repeat
    # also launders shard_map's varying-axes metadata exactly like the
    # multi-factor path: a direct ref read would carry the mesh axis and
    # mismatch the unvarying iotas and constants throughout the kernel
    # (the VMA checker runs at trace time on any backend; the declared
    # out_shape vma covers the result).
    db_rep = repeat(dbw, j_chunk, axis=1)
    acc_t = I32 if int8 else F32
    for jc in range(0, 32, j_chunk):
        if j_chunk == 1:
            # Narrow records: shift by the chunk's constant bit index.
            rhs = to_mm((db_rep >> U32(jc)) & U32(1))
            out_ref[:, jc * w : (jc + 1) * w] += lax.dot_general(
                lhs, rhs, (((1,), (0,)), ((), ())),
                preferred_element_type=acc_t,
            )
            continue
        j_iota = (
            lax.broadcasted_iota(U32, (tr, j_chunk * w), 1) // U32(w)
        ) + U32(jc)
        rhs = to_mm((db_rep >> j_iota) & U32(1))
        out_ref[:, jc * w : (jc + j_chunk) * w] += lax.dot_general(
            lhs, rhs, (((1,), (0,)), ((), ())), preferred_element_type=acc_t
        )


@functools.partial(
    jax.jit,
    static_argnames=("tile_queries", "tile_groups", "j_chunk", "int8",
                     "interpret", "vma"),
)
def _ip_pallas_staged_v2(
    db_perm: jnp.ndarray,
    packed: jnp.ndarray,
    tile_queries: int = 64,
    tile_groups: int = 128,
    j_chunk: int = 8,
    int8: bool = False,
    interpret: bool = False,
    vma: tuple = (),
) -> jnp.ndarray:
    _, num_groups, num_words = db_perm.shape
    nq = packed.shape[0]
    tg = _pick_group_tile(
        num_groups, max_tile=tile_groups,
        # Mosaic requires the selections block's lane dim (groups) to be
        # 128-divisible or span the axis; interpret mode has no such rule
        # (and the tile-variant tests exercise smaller tiles there).
        lane_step=8 if interpret else 128,
    )
    # Cap the query tile so the i32/f32 counts block stays ~<=2 MB in
    # VMEM (tq * 32W * 4 B): wide records would otherwise blow the
    # budget at large tiles (e.g. W=256 caps tq at 64).
    tq_cap = max(8, (2 << 20) // (32 * num_words * 4) // 8 * 8)
    tq = min(tile_queries, nq, tq_cap)
    while tq > 8 and (nq % tq != 0 or tq % 8 != 0):
        tq -= 8 if tq % 8 == 0 else tq % 8
    if nq % tq != 0:
        tq = nq

    acc_t = I32 if int8 else F32
    counts = pl.pallas_call(
        functools.partial(
            _ip_kernel_v2, j_chunk=j_chunk, int8=int8,
            repeat=(
                _tile_repeat
                if interpret and not _interpret_repeat_tiles()
                else pltpu.repeat
            ),
        ),
        grid=(nq // tq, num_groups // tg),
        in_specs=[
            pl.BlockSpec((tq, tg), lambda q, r: (q, r)),
            pl.BlockSpec((32, tg, num_words), lambda q, r: (0, r, 0)),
        ],
        out_specs=pl.BlockSpec(
            (tq, 32 * num_words), lambda q, r: (q, 0)
        ),
        # vma: required when called inside a shard_map with the sharding
        # checker on (the multi-chip MXU step, `parallel/sharded.py`).
        out_shape=jax.ShapeDtypeStruct(
            (nq, 32 * num_words), acc_t,
            **({"vma": frozenset(vma)} if (vma and _HAS_VMA) else {}),
        ),
        interpret=interpret,
    )(packed, db_perm)
    parity = counts.reshape(nq, 32, num_words).astype(I32).astype(U32) & U32(1)
    return (parity << jnp.arange(32, dtype=U32)[None, :, None]).sum(
        axis=1, dtype=U32
    )


def xor_inner_product_pallas2_staged(
    db_perm: jnp.ndarray,
    selections: jnp.ndarray,
    tile_queries: int = 256,
    tile_groups: int = 128,
    j_chunk: int = 32,
    int8: bool = True,
    interpret: bool = False,
    vma: tuple = (),
) -> jnp.ndarray:
    """v2 serving entry: same staged layout/signature as
    `xor_inner_product_pallas_staged`, one large dot per step.

    With int8=True the parity counts accumulate exactly in int32, so the
    record cap is the int32 range rather than f32's 2^24. The query tile
    defaults high (256) because the in-VMEM database-tile unpack repeats
    per query tile: large batches (dense_big's 1024 queries) pay it
    nq/tile_queries times.
    """
    _, num_groups, num_words = db_perm.shape
    num_records = 32 * num_groups
    if not int8 and num_records > MAX_RECORDS_EXACT:
        raise ValueError(
            f"bf16/f32 parity counts support at most {MAX_RECORDS_EXACT} "
            f"records; got {num_records} (use int8=True)"
        )
    if 32 % j_chunk != 0:
        raise ValueError(f"j_chunk must divide 32; got {j_chunk}")
    # In this kernel's 2-D axis-1 db repeat, Mosaic's `pltpu.repeat`
    # miscompiles (tpu_compile_helper exit 1) when
    # the source lane dim is below a half lane-tile and the factor exceeds
    # 8 — mapped on v5e 2026-07-31: W∈{4,8} × j_chunk∈{16,32} all crash,
    # W≥16 all legal. The 2026-07-31 kernel smoke then crashed at
    # W=8 x j_chunk=8 too (tpu_compile_helper exit 1), so the true
    # boundary is the SOURCE width, not the factor: for W<16 skip the
    # in-kernel db repeat entirely (j_chunk=1 needs no repeat). j_chunk
    # only affects throughput, so degrade loudly instead of crashing —
    # an A/B over j_chunk values must not silently time identical runs.
    if num_words < 16 and j_chunk > 1:
        if j_chunk != 32:  # 32 is the default, not an explicit request
            import warnings

            warnings.warn(
                f"narrow records ({num_words} words): j_chunk={j_chunk} "
                "dropped to 1 to dodge Mosaic's narrow-source repeat "
                "miscompile",
                stacklevel=2,
            )
        j_chunk = 1
    # The kernel's selections repeat has a fixed factor of 32, so a group
    # tile under 16 lanes hits the same miscompile with no knob to cap.
    # `permute_db_bitmajor` pads serving layouts to 128-group multiples;
    # only hand-built layouts can get here.
    if not interpret and num_groups < 16:
        raise ValueError(
            f"compiled v2 kernel needs >= 16 selection groups (512 "
            f"records); got {num_groups} — pad the staged layout or use "
            f"xor_inner_product_pallas_staged"
        )
    packed, nq = _stage_selections(selections, num_groups)
    nq_pad = packed.shape[0]
    out = _ip_pallas_staged_v2(
        db_perm,
        packed,
        tile_queries=tile_queries,
        tile_groups=tile_groups,
        j_chunk=j_chunk,
        int8=int8,
        interpret=interpret,
        vma=vma,
    )
    return out[:nq] if nq_pad != nq else out


def xor_inner_product_pallas2_accumulate(
    acc: jnp.ndarray,
    db_perm_span: jnp.ndarray,
    selections: jnp.ndarray,
    **kwargs,
) -> jnp.ndarray:
    """Partial-accumulate entry for the streaming serving scan: XOR one
    staged block span's MXU inner product into per-query accumulators.

    acc: uint32[nq, W]; db_perm_span: uint32[32, Gc, W] one bit-major
    staged span (`stage_db_chunks_bitmajor`); selections: uint32[nq, B, 4]
    covering exactly that span (`_stage_selections` pads the group axis to
    Gc). Extra kwargs pass through to `xor_inner_product_pallas2_staged`.
    """
    return acc ^ xor_inner_product_pallas2_staged(
        db_perm_span, selections, **kwargs
    )


def stage_db_chunks_bitmajor(
    db_words: jnp.ndarray, num_chunks: int
) -> jnp.ndarray:
    """Split a (permuted) row-major database into equal record spans and
    bit-major stage each: uint32[R, W] -> uint32[num_chunks, 32, Gc, W].

    Each chunk is independently padded to a 4096-record multiple by
    `permute_db_bitmajor`, so Gc >= 128 always satisfies the compiled v2
    kernel's 16-group floor regardless of chunk size.
    """
    num_records, num_words = db_words.shape
    if num_chunks <= 0 or num_records % num_chunks:
        raise ValueError(
            f"record count {num_records} is not divisible into "
            f"{num_chunks} chunks"
        )
    chunk_records = num_records // num_chunks
    return jax.vmap(permute_db_bitmajor)(
        db_words.reshape(num_chunks, chunk_records, num_words)
    )


def xor_inner_product_pallas(
    db_words: jnp.ndarray,
    selections: jnp.ndarray,
    tile_queries: int = 64,
    interpret: bool = False,
) -> jnp.ndarray:
    """Convenience entry from a row-major database (permutes per call;
    the serving path stages `permute_db_bitmajor` once instead).

    db_words: uint32[R, W], R a multiple of 128; selections:
    uint32[nq, B, 4]. Returns uint32[nq, W].
    """
    num_records, _ = db_words.shape
    if num_records % 128 != 0:
        raise ValueError("record count must be padded to a multiple of 128")
    return xor_inner_product_pallas_staged(
        permute_db_bitmajor(db_words),
        selections,
        tile_queries=tile_queries,
        interpret=interpret,
    )
