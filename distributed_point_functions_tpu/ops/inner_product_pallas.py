"""Pallas TPU kernel for the packed-XOR database inner product.

One pass over the database serves the whole query batch: the grid walks
record tiles; each step DMAs a `[TILE_RECORDS, W]` database tile into VMEM,
masks it with every query's selection bits, XOR-reduces over the tile's
record axis, and folds the partial into a VMEM-resident `[nq, W]`
accumulator (the revisiting-output accumulation pattern). This fuses the
bit-unpacking, masking, and reduction into a single HBM read of the
database — the kernel is purely HBM-bandwidth-bound, which is the design
target for the reference's hot loop
(`pir/internal/inner_product_hwy.cc:157-258`).

Differentially tested against the jnp implementation and the numpy/native
oracles (tests/test_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .inner_product import unpack_selection_bits

U32 = jnp.uint32


def _ip_kernel(bits_ref, db_ref, out_ref):
    """bits_ref: uint32[nq, TR]; db_ref: uint32[TR, W]; out_ref: uint32[nq, W]."""

    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    mask = (U32(0) - bits_ref[:])[:, :, None]  # 0 or 0xFFFFFFFF
    masked = mask & db_ref[:][None, :, :]  # [nq, TR, W]
    partial = lax.reduce(
        masked, U32(0), lambda a, b: lax.bitwise_xor(a, b), (1,)
    )
    out_ref[:] = out_ref[:] ^ partial


@functools.partial(
    jax.jit, static_argnames=("tile_records", "interpret")
)
def xor_inner_product_pallas(
    db_words: jnp.ndarray,
    selections: jnp.ndarray,
    tile_records: int = 1024,
    interpret: bool = False,
) -> jnp.ndarray:
    """XOR inner product on TPU via Pallas.

    db_words: uint32[R, W], R a multiple of 128; selections:
    uint32[nq, B, 4] with B*128 >= R. Returns uint32[nq, W].
    """
    num_records, num_words = db_words.shape
    if num_records % 128 != 0:
        raise ValueError("record count must be padded to a multiple of 128")
    nq = selections.shape[0]
    bits = unpack_selection_bits(selections)[:, :num_records]  # [nq, R]
    tr = min(tile_records, num_records)
    while num_records % tr != 0:  # R is a multiple of 128, so this ends
        tr //= 2
    grid = (num_records // tr,)
    return pl.pallas_call(
        _ip_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((nq, tr), lambda i: (0, i)),
            pl.BlockSpec((tr, num_words), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((nq, num_words), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((nq, num_words), jnp.uint32),
        interpret=interpret,
    )(bits, db_words)
