"""Pallas TPU kernel for the packed-XOR database inner product.

One pass over the database serves the whole query batch: the grid walks
(query tile, record tile) pairs with the record axis innermost; each step
DMAs a `[TILE_RECORDS, W]` database tile into VMEM, expands the *packed*
selection bits for that tile in-register (broadcast against a 32-lane
iota), masks the tile with every query's bits, XOR-reduces over the tile's
record axis by tree halving, and folds the partial into a VMEM-resident
`[TILE_QUERIES, W]` accumulator (the revisiting-output pattern).

Unlike the jnp path, the selection bits stay packed in HBM
(`uint32[nq, R/32]`, 32 records per word) — no `[nq, R]` mask is ever
materialized in HBM, so HBM traffic is one read of the database plus the
(negligible) packed bits. This matches the design of the reference's hot
loop, which also keeps bits packed 128/block
(`pir/internal/inner_product_hwy.cc:157-258`).

Differentially tested against the jnp implementation and the numpy/native
oracles (tests/test_pallas.py); bit-identity vs the jnp path is re-checked
on hardware by bench.py before the kernel serves the measured run.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

U32 = jnp.uint32


def _ip_kernel(sel_ref, db_ref, out_ref):
    """sel_ref: uint32[TQ, TR//32] packed; db_ref: uint32[TR, W]; out: [TQ, W].

    Grid is (query_tiles, record_tiles) with records innermost, so out_ref
    is revisited consecutively and accumulates across record tiles.
    """

    @pl.when(pl.program_id(1) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    words = sel_ref[:]  # [TQ, TW]
    tq, tw = words.shape
    tr = tw * 32
    # Expand packed bits in-register: record r's bit is bit r%32 of word
    # r//32. repeat-32 along the word axis, then shift by (lane % 32).
    expanded = jnp.repeat(words, 32, axis=1)  # [TQ, TR]
    shifts = lax.broadcasted_iota(U32, (tq, tr), 1) & U32(31)
    bits = (expanded >> shifts) & U32(1)
    mask = (U32(0) - bits)[:, :, None]  # 0 or 0xFFFFFFFF per (q, r)
    masked = mask & db_ref[:][None, :, :]  # [TQ, TR, W]
    # XOR-reduce over the record axis by tree halving (Mosaic-friendly:
    # every step is a plain elementwise XOR of two halves).
    while masked.shape[1] > 1:
        half = masked.shape[1] // 2
        masked = masked[:, :half] ^ masked[:, half:]
    out_ref[:] = out_ref[:] ^ masked[:, 0]


@functools.partial(
    jax.jit, static_argnames=("tile_records", "tile_queries", "interpret")
)
def xor_inner_product_pallas(
    db_words: jnp.ndarray,
    selections: jnp.ndarray,
    tile_records: int = 256,
    tile_queries: int = 64,
    interpret: bool = False,
) -> jnp.ndarray:
    """XOR inner product on TPU via Pallas, bits kept packed in HBM.

    db_words: uint32[R, W], R a multiple of 128; selections:
    uint32[nq, B, 4] with B*128 >= R. Returns uint32[nq, W].

    The VMEM working set per grid step is ~tile_queries * tile_records * W
    * 4 bytes (the masked intermediate); the defaults keep it ~4 MB for
    W=64 (256-byte records) against the ~16 MB/core budget.
    """
    num_records, num_words = db_words.shape
    if num_records % 128 != 0:
        raise ValueError("record count must be padded to a multiple of 128")
    nq = selections.shape[0]
    # Flatten packed blocks [nq, B, 4] -> words [nq, B*4]; word w covers
    # records 32w..32w+31 (the XorWrapper<uint128> bit order).
    packed = selections.reshape(nq, -1)[:, : num_records // 32]

    # Record tile: power of two (the kernel's tree reduction halves it) and
    # a divisor of R; R is a multiple of 128 so this reaches 128 at worst.
    tr = 1 << (min(tile_records, num_records).bit_length() - 1)
    while num_records % tr != 0:
        tr //= 2
    tq = min(tile_queries, nq)
    while nq % tq != 0:
        tq -= 1
    grid = (nq // tq, num_records // tr)
    return pl.pallas_call(
        _ip_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tq, tr // 32), lambda q, r: (q, r)),
            pl.BlockSpec((tr, num_words), lambda q, r: (r, 0)),
        ],
        out_specs=pl.BlockSpec((tq, num_words), lambda q, r: (q, 0)),
        out_shape=jax.ShapeDtypeStruct((nq, num_words), jnp.uint32),
        interpret=interpret,
    )(packed, db_words)
