from . import aes  # noqa: F401
