"""AES-128 primitives for the TPU-native DPF framework.

Two interchangeable implementations:

* A **numpy oracle** (`aes_encrypt_np`): straightforward table-based AES-128,
  validated against the FIPS-197 known-answer vectors. Used host-side (key
  generation is O(tree depth)) and as the differential-testing oracle for the
  device kernel — mirroring the scalar/`NoHwy` role of the reference's
  `dpf/internal/evaluate_prg_hwy.cc:552-634`.

* A **bitsliced JAX implementation** (`aes_encrypt`): TPUs have no AES
  instructions and no byte-shuffle unit, so (unlike the reference's
  AES-NI/`hn::AESRound` path, `dpf/internal/aes_128_fixed_key_hash_hwy.h`)
  the S-box is computed as a GF(2^8) boolean circuit over eight bit-planes,
  vectorized across all blocks on the VPU. The GF(2^8) inversion uses the
  x^254 square-and-multiply addition chain; squaring matrices and the S-box
  affine map are derived programmatically at import time.

Block convention throughout the framework: a 128-bit block is `uint32[4]`
limbs, little-endian (limb 0 = bits 0..31). Byte j of a block is
`(limbs[j // 4] >> (8 * (j % 4))) & 0xFF`, and AES consumes bytes in index
order b0..b15.

The fixed-key MMO (Matyas-Meyer-Oseas) hash `H(x) = AES_k(sigma(x)) ^ sigma(x)`
with `sigma(x) = (hi ^ lo, hi)` follows the circular-correlation-robust
construction of the reference's `dpf/aes_128_fixed_key_hash.h:28-39`
(Guo et al., eprint 2019/074).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# GF(2^8) tables, S-box, key schedule (numpy, derived at import time)
# ---------------------------------------------------------------------------

_AES_POLY = 0x11B  # x^8 + x^4 + x^3 + x + 1


def _gf_mul(a: int, b: int) -> int:
    """Multiply in GF(2^8) mod x^8+x^4+x^3+x+1."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        a <<= 1
        if a & 0x100:
            a ^= _AES_POLY
        b >>= 1
    return r


def _make_sbox() -> np.ndarray:
    """Generate the AES S-box: GF(2^8) inverse followed by the affine map."""
    # Multiplicative inverses via exhaustive search (256 entries, import-time).
    inv = [0] * 256
    for x in range(1, 256):
        for y in range(1, 256):
            if _gf_mul(x, y) == 1:
                inv[x] = y
                break
    sbox = np.zeros(256, dtype=np.uint8)
    for x in range(256):
        b = inv[x]
        res = 0
        for i in range(8):
            bit = (
                (b >> i)
                ^ (b >> ((i + 4) % 8))
                ^ (b >> ((i + 5) % 8))
                ^ (b >> ((i + 6) % 8))
                ^ (b >> ((i + 7) % 8))
                ^ (0x63 >> i)
            ) & 1
            res |= bit << i
        sbox[x] = res
    return sbox


SBOX = _make_sbox()

# Squaring in GF(2^8) is linear over GF(2): sq(x) = XOR_i bit_i(x) * (x^i)^2.
# _SQ_MAP[i] = (2^i)^2 in GF(2^8); used to build the bitsliced squaring
# circuit.
_SQ_MAP = np.array([_gf_mul(1 << i, 1 << i) for i in range(8)], dtype=np.uint8)

# ShiftRows permutation on flat byte index r + 4*c: row r rotates left by r,
# i.e. output byte position r+4c takes input byte _SHIFT_ROWS[r+4c].
_SHIFT_ROWS = np.array(
    [r + 4 * ((c + r) % 4) for c in range(4) for r in range(4)], dtype=np.int32
)

_RCON = np.array(
    [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36],
    dtype=np.uint8,
)


def key_expansion(key: bytes | np.ndarray) -> np.ndarray:
    """AES-128 key schedule. Returns round keys as uint8[11, 16]."""
    key = np.frombuffer(bytes(key), dtype=np.uint8) if isinstance(key, (bytes, bytearray)) else np.asarray(key, dtype=np.uint8)
    if key.size != 16:
        raise ValueError("AES-128 key must be 16 bytes")
    words = [key[4 * i : 4 * i + 4].copy() for i in range(4)]
    for i in range(4, 44):
        temp = words[i - 1].copy()
        if i % 4 == 0:
            temp = np.roll(temp, -1)  # RotWord
            temp = SBOX[temp]  # SubWord
            temp[0] ^= _RCON[i // 4 - 1]
        words.append(words[i - 4] ^ temp)
    return np.concatenate(words).reshape(11, 16)


# ---------------------------------------------------------------------------
# Numpy oracle
# ---------------------------------------------------------------------------


def _xtime_np(b: np.ndarray) -> np.ndarray:
    return (((b.astype(np.uint16) << 1) ^ ((b >> 7).astype(np.uint16) * 0x1B)) & 0xFF).astype(np.uint8)


def _mix_columns_np(state: np.ndarray) -> np.ndarray:
    """MixColumns on uint8[N, 16] (flat index r + 4c)."""
    s = state.reshape(-1, 4, 4)  # [N, column, row]
    s0, s1, s2, s3 = s[:, :, 0], s[:, :, 1], s[:, :, 2], s[:, :, 3]
    t = s0 ^ s1 ^ s2 ^ s3
    out = np.empty_like(s)
    out[:, :, 0] = s0 ^ t ^ _xtime_np(s0 ^ s1)
    out[:, :, 1] = s1 ^ t ^ _xtime_np(s1 ^ s2)
    out[:, :, 2] = s2 ^ t ^ _xtime_np(s2 ^ s3)
    out[:, :, 3] = s3 ^ t ^ _xtime_np(s3 ^ s0)
    return out.reshape(-1, 16)


def aes_encrypt_np(round_keys: np.ndarray, blocks: np.ndarray) -> np.ndarray:
    """Encrypt uint8[N, 16] blocks with uint8[11, 16] round keys (ECB)."""
    state = blocks.astype(np.uint8) ^ round_keys[0]
    for rnd in range(1, 10):
        state = SBOX[state]
        state = state[:, _SHIFT_ROWS]
        state = _mix_columns_np(state)
        state ^= round_keys[rnd]
    state = SBOX[state]
    state = state[:, _SHIFT_ROWS]
    state ^= round_keys[10]
    return state


# ---------------------------------------------------------------------------
# Limb <-> byte conversions
# ---------------------------------------------------------------------------


def limbs_to_bytes_np(limbs: np.ndarray) -> np.ndarray:
    """uint32[..., 4] -> uint8[..., 16] little-endian."""
    return np.ascontiguousarray(limbs.astype("<u4")).view(np.uint8)


def bytes_to_limbs_np(b: np.ndarray) -> np.ndarray:
    """uint8[..., 16] -> uint32[..., 4] little-endian."""
    b = np.ascontiguousarray(b.astype(np.uint8))
    return b.view("<u4").astype(np.uint32)


def u128_to_limbs(x: int) -> np.ndarray:
    """Python int -> uint32[4] little-endian limbs."""
    return np.array([(x >> (32 * i)) & 0xFFFFFFFF for i in range(4)], dtype=np.uint32)


def limbs_to_u128(limbs) -> int:
    limbs = np.asarray(limbs, dtype=np.uint64)
    return int(sum(int(limbs[..., i]) << (32 * i) for i in range(4)))


# ---------------------------------------------------------------------------
# Bitsliced JAX implementation
# ---------------------------------------------------------------------------
#
# State: uint32[..., 16] byte values (one byte per lane, upper 24 bits zero).
# The S-box unpacks each byte lane into 8 bit-planes of shape [..., 16] and
# evaluates the GF(2^8) inversion circuit; linear steps (ShiftRows,
# MixColumns, AddRoundKey) stay in byte form.


def _planes(bytes_arr):
    return [(bytes_arr >> i) & jnp.uint32(1) for i in range(8)]


def _unplanes(planes):
    out = planes[0]
    for i in range(1, 8):
        out = out | (planes[i] << i)
    return out


def _gf_square_planes(a):
    """Bitsliced GF(2^8) squaring (linear map from _SQ_MAP)."""
    out = []
    for j in range(8):
        acc = None
        for i in range(8):
            if (_SQ_MAP[i] >> j) & 1:
                acc = a[i] if acc is None else acc ^ a[i]
        out.append(acc if acc is not None else jnp.zeros_like(a[0]))
    return out


def _gf_mul_planes(a, b):
    """Bitsliced GF(2^8) schoolbook multiply: acc ^= a_i & (b * x^i)."""
    acc = [None] * 8
    t = list(b)
    for i in range(8):
        ai = a[i]
        for j in range(8):
            term = ai & t[j]
            acc[j] = term if acc[j] is None else acc[j] ^ term
        if i < 7:
            # t *= x (mod 0x11B): shift up, reduce by poly bits {0,1,3,4}.
            t7 = t[7]
            t = [t7, t[0] ^ t7, t[1], t[2] ^ t7, t[3] ^ t7, t[4], t[5], t[6]]
    return acc


def _sbox_planes(x, one=1):
    """AES S-box on bit-planes: inv = x^254, then the affine map.

    `one` is the affine constant's per-plane XOR value (plain Python int so
    import stays device-free): 1 for the single-bit-per-lane layout here,
    0xFFFFFFFF for the packed 32-blocks-per-word layout of `aes_bitslice`."""
    one = jnp.uint32(one)
    a2 = _gf_square_planes(x)  # x^2
    a3 = _gf_mul_planes(a2, x)  # x^3
    a12 = _gf_square_planes(_gf_square_planes(a3))  # x^12
    a15 = _gf_mul_planes(a12, a3)  # x^15
    a240 = a15
    for _ in range(4):  # x^240
        a240 = _gf_square_planes(a240)
    a252 = _gf_mul_planes(a240, a12)  # x^252
    a254 = _gf_mul_planes(a252, a2)  # x^254 = x^-1
    out = []
    for i in range(8):
        v = (
            a254[i]
            ^ a254[(i + 4) % 8]
            ^ a254[(i + 5) % 8]
            ^ a254[(i + 6) % 8]
            ^ a254[(i + 7) % 8]
        )
        if (0x63 >> i) & 1:
            v = v ^ one
        out.append(v)
    return out


def _sub_bytes(state):
    return _unplanes(_sbox_planes(_planes(state)))


def _xtime(b):
    return ((b << 1) ^ ((b >> 7) * jnp.uint32(0x1B))) & jnp.uint32(0xFF)


def _mix_columns(state):
    s = state.reshape(state.shape[:-1] + (4, 4))  # [..., column, row]
    s0, s1, s2, s3 = s[..., 0], s[..., 1], s[..., 2], s[..., 3]
    t = s0 ^ s1 ^ s2 ^ s3
    o0 = s0 ^ t ^ _xtime(s0 ^ s1)
    o1 = s1 ^ t ^ _xtime(s1 ^ s2)
    o2 = s2 ^ t ^ _xtime(s2 ^ s3)
    o3 = s3 ^ t ^ _xtime(s3 ^ s0)
    return jnp.stack([o0, o1, o2, o3], axis=-1).reshape(state.shape)


def _limbs_to_byte_lanes(limbs):
    """uint32[..., 4] -> uint32[..., 16] byte values."""
    parts = [(limbs >> (8 * k)) & jnp.uint32(0xFF) for k in range(4)]
    # byte j = limb[j//4] >> 8*(j%4): interleave so last axis is byte index.
    stacked = jnp.stack(parts, axis=-1)  # [..., 4 limbs, 4 bytes-within-limb]
    return stacked.reshape(limbs.shape[:-1] + (16,))


def _byte_lanes_to_limbs(b):
    b = b.reshape(b.shape[:-1] + (4, 4))
    out = b[..., 0]
    for k in range(1, 4):
        out = out | (b[..., k] << (8 * k))
    return out


def aes_encrypt(round_keys: np.ndarray, blocks: jnp.ndarray) -> jnp.ndarray:
    """Bitsliced AES-128 ECB on uint32[..., 4] limb blocks.

    `round_keys` is a static numpy uint8[11, 16] schedule (fixed framework
    keys), baked into the compiled program as constants.
    """
    rk = jnp.asarray(round_keys.astype(np.uint32))
    state = _limbs_to_byte_lanes(blocks) ^ rk[0]
    for rnd in range(1, 10):
        state = _sub_bytes(state)
        state = state[..., _SHIFT_ROWS]
        state = _mix_columns(state)
        state = state ^ rk[rnd]
    state = _sub_bytes(state)
    state = state[..., _SHIFT_ROWS]
    state = state ^ rk[10]
    return _byte_lanes_to_limbs(state)


def aes_encrypt_select(
    round_keys0: np.ndarray,
    round_keys1: np.ndarray,
    select: jnp.ndarray,
    blocks: jnp.ndarray,
) -> jnp.ndarray:
    """AES-128 with a per-block choice between two fixed key schedules.

    `select` is uint32[...] (0 or 1), broadcast against blocks' batch shape.
    This mirrors the per-lane key-mask trick of the reference's
    `HashOneWithKeyMask` (`dpf/internal/aes_128_fixed_key_hash_hwy.h:123-155`):
    one AES pass, round keys chosen per lane, so path-dependent hashing does
    not double the AES work.
    """
    rk0 = jnp.asarray(round_keys0.astype(np.uint32))
    rk1 = jnp.asarray(round_keys1.astype(np.uint32))
    sel = select[..., None].astype(jnp.uint32)  # [..., 1] over byte axis

    def ark(state, rnd):
        k = jnp.where(sel != 0, rk1[rnd], rk0[rnd])
        return state ^ k

    state = ark(_limbs_to_byte_lanes(blocks), 0)
    for rnd in range(1, 10):
        state = _sub_bytes(state)
        state = state[..., _SHIFT_ROWS]
        state = _mix_columns(state)
        state = ark(state, rnd)
    state = _sub_bytes(state)
    state = state[..., _SHIFT_ROWS]
    state = ark(state, 10)
    return _byte_lanes_to_limbs(state)


# ---------------------------------------------------------------------------
# Fixed-key MMO hash (circular correlation-robust)
# ---------------------------------------------------------------------------


def sigma(blocks: jnp.ndarray) -> jnp.ndarray:
    """sigma(x) = (hi ^ lo, hi) on uint32[..., 4] limbs (low 64 = hi)."""
    lo = blocks[..., 0:2]
    hi = blocks[..., 2:4]
    return jnp.concatenate([hi, hi ^ lo], axis=-1)


def sigma_np(blocks: np.ndarray) -> np.ndarray:
    lo = blocks[..., 0:2]
    hi = blocks[..., 2:4]
    return np.concatenate([hi, hi ^ lo], axis=-1)


def mmo_hash(round_keys: np.ndarray, blocks: jnp.ndarray) -> jnp.ndarray:
    """H(x) = AES_k(sigma(x)) ^ sigma(x) on uint32[..., 4] limbs.

    Dispatches to the fully-bitsliced kernel (32 blocks per word,
    `aes_bitslice.py`); the byte-lane `aes_encrypt` here remains as a
    second implementation for differential testing."""
    from . import aes_bitslice

    s = sigma(blocks)
    return aes_bitslice.aes_encrypt_bs(round_keys, s) ^ s


def mmo_hash_select(rk0, rk1, select, blocks):
    """Per-block key-selected MMO hash (see aes_encrypt_select)."""
    from . import aes_bitslice

    s = sigma(blocks)
    return aes_bitslice.aes_encrypt_select_bs(rk0, rk1, select, s) ^ s


def mmo_hash_np(round_keys: np.ndarray, blocks: np.ndarray) -> np.ndarray:
    """Numpy oracle for mmo_hash, on uint32[..., 4] limbs."""
    s = sigma_np(np.asarray(blocks, dtype=np.uint32))
    shape = s.shape
    enc = aes_encrypt_np(round_keys, limbs_to_bytes_np(s.reshape(-1, 4)))
    return bytes_to_limbs_np(enc).reshape(shape) ^ s
